"""FP16_Optimizer (ref apex/fp16_utils/fp16_optimizer.py).

Master-weight mixed precision around a fused optimizer: the model tree is
half precision, the wrapped optimizer steps fp32 masters, and the updated
masters are cast back into the model tree. Overflow (from the loss scaler)
skips the step and only adjusts the scale — the reference's control flow
(fp16_optimizer.py:step) runs on host; here the whole step is jittable when
used with static scaling, and host-driven with DynamicLossScaler for parity.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.fp16_utils.fp16util import (
    master_params_to_model_params,
    model_grads_to_master_grads,
    prep_param_lists,
    to_python_float,
)
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler


class FP16_Optimizer:
    """Wrap a :class:`apex_tpu.optimizers.FusedOptimizer`
    (ref fp16_optimizer.py:26).

    The wrapped optimizer's ``params`` become the fp32 masters; ``step``
    takes the HALF-precision grads, unscales, checks overflow, steps masters
    and returns the refreshed half model tree.
    """

    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=False, dynamic_loss_args=None,
                 verbose=False):
        self.optimizer = init_optimizer
        self.model_params, master = prep_param_lists(init_optimizer.params)
        self.optimizer.params = master
        self.optimizer.state = self.optimizer.tx.init(master)
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.loss_scaler = LossScaler(static_loss_scale)
        self.overflow = False
        self.verbose = verbose
        self._step_jit = jax.jit(self._master_step)

    # -- functional core ----------------------------------------------------

    def _master_step(self, grads32, state, master, model_params):
        new_master, new_state = self.optimizer._functional_step(
            grads32, state, master)
        model = master_params_to_model_params(model_params, new_master)
        return new_master, new_state, model

    # -- apex-shaped API ----------------------------------------------------

    def scale_loss(self, loss):
        return loss * self.loss_scaler.loss_scale

    def backward(self, loss):  # parity shim: scaling happens in scale_loss
        return self.scale_loss(loss)

    def step(self, grads=None, closure=None):
        if grads is None:
            raise ValueError("pass grads (pytree matching params) to step()")
        del closure
        grads32 = model_grads_to_master_grads(grads)
        inv = 1.0 / self.loss_scaler.loss_scale
        grads32 = jax.tree_util.tree_map(lambda g: g * inv, grads32)
        self.overflow = self.loss_scaler.has_overflow(grads32)
        self.loss_scaler.update_scale(self.overflow)
        if self.overflow:
            if self.verbose:
                print(f"OVERFLOW! Skipping step, reducing loss scale to "
                      f"{self.loss_scaler.loss_scale}")
            return self.model_params
        master, state, model = self._step_jit(
            grads32, self.optimizer.state, self.optimizer.params,
            self.model_params)
        self.optimizer.params = master
        self.optimizer.state = state
        self.model_params = model
        return model

    def clip_master_grads(self, grads, max_norm, norm_type=2):
        """ref fp16_optimizer.py clip_master_grads — clip the (unscaled,
        fp32) master gradients to ``max_norm`` and return the pre-clip
        global norm. Functional divergence from the reference: grads are
        not stored on the optimizer, so pass the tree that will go to
        ``step`` and use the returned clipped tree:

            grads, norm = opt.clip_master_grads(grads, 1.0)
            opt.step(grads=grads)
        """
        from apex_tpu.contrib.clip_grad import clip_grad_norm_

        inv = 1.0 / self.loss_scaler.loss_scale
        grads32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, grads)
        clipped, norm = clip_grad_norm_(grads32, max_norm,
                                        norm_type=norm_type)
        # re-apply the scale: step() divides by it again
        rescaled = jax.tree_util.tree_map(
            lambda g: g * self.loss_scaler.loss_scale, clipped)
        return rescaled, norm

    def inspect_master_grad_data(self):
        """ref fp16_optimizer.py inspect_master_grad_data — grads are
        functional here (never stored), so there is nothing to inspect;
        returns None like the reference does before backward()."""
        if self.verbose:
            print("FP16_Optimizer is functional: gradients are passed to "
                  "step(), not stored; inspect them at the call site")
        return None

    def zero_grad(self, set_to_none=True):
        return None

    def update_master_grads(self):  # parity no-op: done inside step()
        return None

    @property
    def loss_scale(self):
        return self.loss_scaler.loss_scale

    def state_dict(self):
        return {
            "optimizer_state": self.optimizer.state_dict(),
            "cur_scale": self.loss_scaler.cur_scale,
            "overflow": self.overflow,
        }

    def load_state_dict(self, d):
        self.optimizer.load_state_dict(d["optimizer_state"])
        self.loss_scaler.cur_scale = d["cur_scale"]
        self.overflow = d.get("overflow", False)
