"""apex.fp16_utils parity surface (ref apex/fp16_utils/__init__.py)."""

from apex_tpu.fp16_utils.fp16util import (
    BN_convert_float,
    network_to_half,
    prep_param_lists,
    model_grads_to_master_grads,
    master_params_to_model_params,
    tofp16,
    to_python_float,
    clip_grad_norm,
    convert_module,
    convert_network,
    FP16Model,
)
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer
from apex_tpu.fp16_utils.loss_scaler import LossScaler, DynamicLossScaler

__all__ = [
    "BN_convert_float", "network_to_half", "prep_param_lists",
    "model_grads_to_master_grads", "master_params_to_model_params",
    "tofp16", "to_python_float", "clip_grad_norm", "convert_module",
    "convert_network", "FP16Model", "FP16_Optimizer", "LossScaler",
    "DynamicLossScaler",
]
