"""Half-precision utilities (TPU re-design of ``apex.fp16_utils.fp16util``).

The reference mutates torch modules in place (``network_to_half``,
``BN_convert_float`` — ref apex/fp16_utils/fp16util.py:13-60). TPU-native
training is functional over param pytrees, so every helper here maps trees:
"the model" is (apply_fn, params), and half-precision means a low-precision
COPY of the params with fp32 masters kept for the update
(ref fp16util.py:98 prep_param_lists).

bf16-first: ``half_dtype`` defaults to bfloat16 (TPU's native half) but
fp16 is supported for parity.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

_FLOAT_KINDS = ("f",)  # jnp.floating leaves only


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def tofp16(params, half_dtype=jnp.bfloat16):
    """Cast every floating leaf to half (ref fp16util.py:13 tofp16)."""
    return jax.tree_util.tree_map(
        lambda p: p.astype(half_dtype) if _is_float(p) else p, params)


def BN_convert_float(params, is_batchnorm: Optional[Callable] = None):
    """Keep batchnorm leaves fp32 (ref fp16util.py:20). In a pytree the
    batchnorm params are identified by ``is_batchnorm(path_str)`` (default:
    any path segment named bn/batchnorm/batch_stats/BatchNorm...)."""
    if is_batchnorm is None:
        import re

        def is_batchnorm(path: str) -> bool:
            return re.search(
                r"(^|[\[\]'/._])(bn\d*|batchnorm\d*|batch_stats|"
                r"batchnorm|syncbatchnorm)([\]\['/._]|$)",
                path.lower()) is not None

    def fix(path, leaf):
        name = jax.tree_util.keystr(path)
        if _is_float(leaf) and is_batchnorm(name):
            return leaf.astype(jnp.float32)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, params)


def network_to_half(params, half_dtype=jnp.bfloat16):
    """Half-cast params, batchnorm kept fp32 (ref fp16util.py:37)."""
    return BN_convert_float(tofp16(params, half_dtype))


def convert_module(params, dtype):
    """Cast a (sub)tree's float leaves to ``dtype`` (ref fp16util.py:42)."""
    return jax.tree_util.tree_map(
        lambda p: p.astype(dtype) if _is_float(p) else p, params)


def convert_network(params, dtype):
    """ref fp16util.py:56 — batchnorm stays fp32."""
    return BN_convert_float(convert_module(params, dtype))


class FP16Model:
    """Wrap (apply_fn, params) to run in half precision with fp32-held
    batchnorm (ref fp16util.py:72 FP16Model: casts inputs to half, runs the
    half network)."""

    def __init__(self, apply_fn: Callable, params, half_dtype=jnp.bfloat16):
        self.apply_fn = apply_fn
        self.half_dtype = half_dtype
        self.params = network_to_half(params, half_dtype)

    def __call__(self, *inputs, **kw):
        cast = [x.astype(self.half_dtype) if _is_float(x) else x
                for x in inputs]
        return self.apply_fn(self.params, *cast, **kw)


def prep_param_lists(params, flat_master: bool = False):
    """(model_params_half, master_params_fp32) (ref fp16util.py:98).

    ``flat_master=True`` concatenates the master copy into ONE fp32 vector
    (ref uses _flatten_dense_tensors), the layout the flat fused optimizers
    consume.
    """
    master = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.float32) if _is_float(p) else p, params)
    if flat_master:
        leaves = [l.ravel() for l in jax.tree_util.tree_leaves(master)
                  if _is_float(l)]
        master = jnp.concatenate(leaves) if leaves else jnp.zeros((0,))
    return params, master


def model_grads_to_master_grads(model_grads, master_params=None,
                                flat_master: bool = False):
    """Upcast grads to fp32 (+flatten when the master is flat)
    (ref fp16util.py:131)."""
    g32 = jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32) if _is_float(g) else g, model_grads)
    if flat_master:
        leaves = [l.ravel() for l in jax.tree_util.tree_leaves(g32)
                  if _is_float(l)]
        return jnp.concatenate(leaves) if leaves else jnp.zeros((0,))
    return g32


def master_params_to_model_params(model_params, master_params,
                                  flat_master: bool = False):
    """Copy updated fp32 masters back into the half model tree
    (ref fp16util.py:150). Returns the NEW model tree (functional)."""
    if flat_master:
        leaves, treedef = jax.tree_util.tree_flatten(model_params)
        out, off = [], 0
        for l in leaves:
            if _is_float(l):
                n = l.size
                out.append(master_params[off:off + n].reshape(l.shape)
                           .astype(l.dtype))
                off += n
            else:
                out.append(l)
        return jax.tree_util.tree_unflatten(treedef, out)
    return jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype) if _is_float(p) else p,
        master_params, model_params)


def to_python_float(t):
    """ref fp16util.py:184 (handles 0-d arrays and python scalars)."""
    return float(jnp.asarray(t).reshape(()))


def clip_grad_norm(grads, max_norm: float, norm_type: float = 2.0):
    """Global-norm clip over a pytree; returns (clipped, total_norm)
    (ref fp16util.py uses torch.nn.utils.clip_grad_norm_)."""
    leaves = [g for g in jax.tree_util.tree_leaves(grads) if _is_float(g)]
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    else:
        total = jnp.sum(
            jnp.stack([jnp.sum(jnp.abs(g) ** norm_type) for g in leaves])
        ) ** (1.0 / norm_type)
    scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
    clipped = jax.tree_util.tree_map(
        lambda g: g * scale.astype(g.dtype) if _is_float(g) else g, grads)
    return clipped, total
