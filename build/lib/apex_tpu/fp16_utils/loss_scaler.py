"""Host-driven loss scalers (ref apex/fp16_utils/loss_scaler.py).

The reference's fp16_utils scalers are the OLD pre-amp API: the scaler is a
Python object whose ``update_scale(overflow)`` runs on host between steps
(unlike :mod:`apex_tpu.amp.scaler`, which is the in-graph functional design).
Kept for API parity; both delegate the math to the same rules.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _has_overflow(grads) -> bool:
    leaves = jax.tree_util.tree_leaves(grads)
    for l in leaves:
        if not bool(jnp.all(jnp.isfinite(l))):
            return True
    return False


class LossScaler:
    """Static scaler (ref loss_scaler.py:10). ``scale_gradient`` divides."""

    def __init__(self, scale=1.0):
        self.cur_scale = float(scale)

    def has_overflow(self, params):  # parity: static scaler never overflows
        del params
        return False

    def update_scale(self, overflow):
        del overflow

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, grads):
        return jax.tree_util.tree_map(lambda g: g / self.cur_scale, grads)

    def backward(self, loss_fn_or_loss):
        """Scale a loss value (the reference calls scaled_loss.backward())."""
        return loss_fn_or_loss * self.cur_scale


class DynamicLossScaler(LossScaler):
    """ref loss_scaler.py:47 — host-side dynamic scaling."""

    def __init__(self, init_scale=2 ** 32, scale_factor=2.0, scale_window=1000):
        super().__init__(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window

    def has_overflow(self, grads) -> bool:
        return _has_overflow(grads)

    def update_scale(self, overflow: bool):
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor, 1.0)
            self.last_overflow_iter = self.cur_iter
        elif (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
            self.cur_scale *= self.scale_factor
        self.cur_iter += 1
