"""Fused label-smoothing cross entropy (ref apex/contrib/xentropy/
softmax_xentropy.py SoftmaxCrossEntropyLoss).

One fused pass computes per-token losses with label smoothing and
padding-idx masking; the backward reuses the saved log-sum-exp the way the
CUDA kernel reuses ``max_log_sum_exp``. On a vocab-sharded mesh use
:func:`apex_tpu.transformer.tensor_parallel.cross_entropy.
vocab_parallel_cross_entropy`, which implements the same smoothing math
distributed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.custom_vjp
def softmax_cross_entropy_loss(logits, labels, smoothing=0.0, padding_idx=0,
                               half_to_float=False):
    """Per-token losses [N]; logits [N, V] (ref softmax_xentropy.py:5).

    ``smoothing``: eps mass spread uniformly over the vocab;
    tokens equal to ``padding_idx`` contribute 0 loss.
    """
    return _fwd(logits, labels, smoothing, padding_idx, half_to_float)[0]


def _fwd_math(logits, labels, smoothing, padding_idx, half_to_float):
    compute = logits.astype(jnp.float32) if half_to_float else logits
    m = jnp.max(compute, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(compute - m), axis=-1)) + m[..., 0]
    target_logit = jnp.take_along_axis(compute, labels[..., None],
                                       axis=-1)[..., 0]
    nll = lse - target_logit
    if smoothing > 0.0:
        mean_logit = jnp.mean(compute, axis=-1)
        smooth_loss = lse - mean_logit
        loss = (1.0 - smoothing) * nll + smoothing * smooth_loss
    else:
        loss = nll
    pad = labels == padding_idx
    return jnp.where(pad, 0.0, loss), lse, pad


def _fwd(logits, labels, smoothing, padding_idx, half_to_float):
    loss, lse, pad = _fwd_math(logits, labels, smoothing, padding_idx,
                               half_to_float)
    return loss, (logits, labels, lse, pad, smoothing, half_to_float)


def _bwd(res, g):
    logits, labels, lse, pad, smoothing, half_to_float = res
    compute = logits.astype(jnp.float32) if half_to_float else logits
    v = compute.shape[-1]
    softmax = jnp.exp(compute - lse[..., None])
    onehot = jax.nn.one_hot(labels, v, dtype=softmax.dtype)
    target_term = (1.0 - smoothing) * onehot + smoothing / v
    d = (softmax - target_term) * jnp.where(pad, 0.0, g)[..., None]
    return (d.astype(logits.dtype), None, None, None, None)


softmax_cross_entropy_loss.defvjp(_fwd, _bwd)

# O1 boundary cast: cross-entropy is range-sensitive → forced fp32 under an
# active O1 policy (lists.py FP32_OPS; ref functional_overrides FP32_FUNCS)
from apex_tpu.amp.amp import float_function as _float_function  # noqa: E402

softmax_cross_entropy_loss = _float_function(softmax_cross_entropy_loss)


class SoftmaxCrossEntropyLoss:
    """Class-shaped entry (the reference exposes the autograd.Function
    directly; apply == __call__)."""

    apply = staticmethod(softmax_cross_entropy_loss)

    def __call__(self, logits, labels, smoothing=0.0, padding_idx=0,
                 half_to_float=False):
        return softmax_cross_entropy_loss(logits, labels, smoothing,
                                          padding_idx, half_to_float)
