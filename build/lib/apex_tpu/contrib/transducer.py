"""RNN-T transducer joint + loss (ref apex/contrib/transducer/
{transducer.py} TransducerJoint / TransducerLoss, csrc transducer kernels).

TPU-first design notes:
- The joint is the broadcast add f[:, :, None] + g[:, None, :] with optional
  relu/dropout — one XLA fusion. The reference's "packed" layout (valid
  rows only, offsets from cumsum(f_len*g_len)) is supported on both ends
  for API parity — pack_output gathers valid rows out of the padded
  joint, packed_input gathers them back onto the padded lattice — but as
  a LAYOUT, not a compute saving: packing skips don't-care math on GPU,
  while on TPU the fixed-shape lattice is the fast path and dynamic
  shapes would force recompiles.
- The loss's alpha recursion is reformulated so the inner (label) dimension
  runs as a ``lax.associative_scan`` in the log semiring: each time-frame
  row is a first-order linear recurrence
      alpha[t, u] = logaddexp(alpha[t-1, u] + blank[t-1, u],
                              alpha[t, u-1] + emit[t, u-1])
  whose scan element is the affine map X -> E*X + A, composed associatively
  as (log_m, log_a) pairs. The outer time loop is a ``lax.scan``. That
  turns the classic O(T·U) sequential lattice into O(T) steps of O(log U)
  depth — the TPU answer to the reference's warp-parallel CUDA DP.
- Gradients fall out of AD through the scans (exact), so there is no
  hand-written backward kernel to keep in sync.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


# ------------------------------------------------------------------- joint


def transducer_joint(f, g, f_len=None, g_len=None, pack_output: bool = False,
                     relu: bool = False, dropout: float = 0.0,
                     dropout_rng=None, batch_offset=None,
                     packed_batch: int = 0):
    """h[b, t, u, :] = f[b, t, :] + g[b, u, :] (ref TransducerJoint.forward).

    ``pack_output=True`` returns the reference's packed layout
    ``[packed_batch, H]`` — batch b's valid ``f_len[b] x g_len[b]`` block
    flattened row-major at offset ``batch_offset[b-1]`` (``batch_offset``
    is the reference's INCLUSIVE ``cumsum(f_len * g_len)``). On GPU the
    reference packs to SKIP computing don't-care positions; fixed shapes
    being the TPU-friendly layout, this computes the full padded joint in
    one fusion and gathers the valid rows, so the output (and therefore
    everything downstream, e.g. a packed loss) is layout-compatible with
    the reference. ``packed_batch`` must be a static int (the gather's
    output shape).
    """
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jax.nn.relu(h)
    if dropout > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout > 0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout, h.shape)
        h = jnp.where(keep, h / (1.0 - dropout), 0.0)
    if not pack_output:
        return h
    if batch_offset is None or not packed_batch:
        raise ValueError(
            "pack_output=True requires batch_offset and packed_batch")
    if f_len is None or g_len is None:
        raise ValueError("pack_output=True requires f_len and g_len")
    b_of, t_of, u_of = _packed_row_coords(
        jnp.arange(packed_batch), batch_offset, f_len * g_len, g_len)
    return h[b_of, t_of, u_of]


def _packed_row_coords(rows, batch_offset, block_len, g_len):
    """(b, t, u) for each packed row index (reference packed layout)."""
    starts = batch_offset - block_len            # inclusive cumsum -> start
    b = jnp.clip(
        jnp.searchsorted(batch_offset, rows, side="right"), 0,
        batch_offset.shape[0] - 1)
    local = jnp.clip(rows - starts[b], 0, jnp.maximum(block_len[b] - 1, 0))
    g = jnp.maximum(g_len[b], 1)
    return b, local // g, local % g


class TransducerJoint:
    """ref transducer.py:10 TransducerJoint."""

    def __init__(self, pack_output=False, relu=False, dropout=False,
                 dropout_prob=0.0, probe=None):
        del probe
        self.pack_output = pack_output
        self.relu = relu
        self.dropout_prob = dropout_prob if dropout else 0.0

    def __call__(self, f, g, f_len=None, g_len=None, batch_offset=None,
                 packed_batch=0, dropout_rng=None):
        return transducer_joint(f, g, f_len, g_len, self.pack_output,
                                self.relu, self.dropout_prob, dropout_rng,
                                batch_offset=batch_offset,
                                packed_batch=packed_batch)


# -------------------------------------------------------------------- loss


def _row_recurrence(prev_term, emit_row):
    """Solve alpha_row[u] = logaddexp(prev_term[u], alpha_row[u-1] +
    emit_row[u-1]) for all u via associative_scan in the log semiring.

    Element = affine map X -> M*X + A with (log_m, log_a); composition
    (applied left-to-right) is (lm1+lm2, logaddexp(la1 + lm2, la2)).
    """
    u1 = prev_term.shape[-1]
    # shift emit right: multiplier entering position u is emit[u-1]
    log_m = jnp.concatenate(
        [jnp.full(emit_row.shape[:-1] + (1,), _NEG_INF), emit_row[..., :-1]],
        axis=-1)
    log_a = prev_term

    def combine(x, y):
        lm1, la1 = x
        lm2, la2 = y
        return lm1 + lm2, jnp.logaddexp(la1 + lm2, la2)

    _, alpha = jax.lax.associative_scan(combine, (log_m, log_a), axis=-1)
    return alpha


def transducer_loss(logits, targets, f_len, y_len, blank_idx: int = 0,
                    packed_input: bool = False, batch_offset=None,
                    max_f_len: Optional[int] = None):
    """Negative log-likelihood per batch element (ref TransducerLoss).

    logits: [B, T, U+1, V] joint outputs; targets [B, U] label ids;
    f_len [B] valid time frames; y_len [B] valid labels.

    ``packed_input=True`` accepts the reference's packed layout instead:
    logits ``[N, V]`` with batch b's ``f_len[b] x (y_len[b]+1)`` block at
    offset ``batch_offset[b-1]`` (``batch_offset`` = inclusive
    ``cumsum(f_len * (y_len+1))``, ref transducer.py:101) and
    ``max_f_len`` the padded T. The packed rows are gathered back to the
    padded lattice — packing skips don't-care compute on GPU; on TPU the
    static-shape lattice IS the fast path, and the gather keeps the
    reference's calling convention working end-to-end (grads flow back
    to the packed rows through the gather).
    """
    if packed_input:
        if batch_offset is None or max_f_len is None:
            raise ValueError(
                "packed_input=True requires batch_offset and max_f_len")
        U = targets.shape[1]
        T, U1 = int(max_f_len), U + 1
        g_len = y_len + 1
        t_idx = jnp.arange(T)[None, :, None]
        u_idx = jnp.arange(U1)[None, None, :]
        starts = (batch_offset - f_len * g_len)[:, None, None]
        rows = starts + t_idx * g_len[:, None, None] + u_idx
        valid = ((t_idx < f_len[:, None, None])
                 & (u_idx < g_len[:, None, None]))
        rows = jnp.where(valid, rows, 0)
        # [B, T, U+1, V]; invalid positions read row 0 and are zeroed —
        # the lattice only consumes (t, u) inside the valid region
        logits = jnp.where(valid[..., None], logits[rows], 0.0)
    B, T, U1, V = logits.shape
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    blank = lp[..., blank_idx]                       # [B, T, U+1]
    emit = jnp.take_along_axis(
        lp[:, :, :-1, :], targets[:, None, :, None], axis=-1)[..., 0]
    # emit[b, t, u] = lp[t, u, targets[u]]; pad back to U+1 with -inf
    emit = jnp.concatenate(
        [emit, jnp.full((B, T, 1), _NEG_INF)], axis=2)   # [B, T, U+1]
    # labels beyond y_len can never be emitted
    u_pos = jnp.arange(U1)[None, :]
    emit = jnp.where(u_pos[None] < y_len[:, None, None], emit, _NEG_INF)

    alpha0 = jnp.full((B, U1), _NEG_INF).at[:, 0].set(0.0)
    alpha0 = _row_recurrence(
        alpha0.at[:, 1:].set(_NEG_INF).at[:, 0].set(0.0), emit[:, 0])

    def step(alpha_prev, inputs):
        blank_prev, emit_row = inputs  # blank at t-1, emit at t
        prev_term = alpha_prev + blank_prev
        alpha = _row_recurrence(prev_term, emit_row)
        return alpha, alpha

    blanks_t = jnp.moveaxis(blank[:, :-1], 1, 0)    # [T-1, B, U+1]
    emits_t = jnp.moveaxis(emit[:, 1:], 1, 0)
    _, alphas = jax.lax.scan(step, alpha0, (blanks_t, emits_t))
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, U+1]
    alphas = jnp.moveaxis(alphas, 0, 1)             # [B, T, U+1]

    # ll = alpha[f_len-1, y_len] + blank[f_len-1, y_len]
    t_idx = jnp.clip(f_len - 1, 0, T - 1)
    a_final = jnp.take_along_axis(
        alphas, t_idx[:, None, None].repeat(U1, axis=2), axis=1)[:, 0]
    b_final = jnp.take_along_axis(
        blank, t_idx[:, None, None].repeat(U1, axis=2), axis=1)[:, 0]
    ll = jnp.take_along_axis(a_final + b_final, y_len[:, None], axis=1)[:, 0]
    return -ll


class TransducerLoss:
    """ref transducer.py TransducerLoss (Function.apply shape)."""

    def __init__(self, fuse_softmax_backward=True, opt=1,
                 packed_input=False):
        del fuse_softmax_backward, opt
        self.packed_input = packed_input

    def __call__(self, x, label, f_len, y_len, blank_idx=0,
                 batch_offset=None, max_f_len=None, debug_list=None):
        del debug_list
        return transducer_loss(x, label, f_len, y_len, blank_idx,
                               self.packed_input,
                               batch_offset=batch_offset,
                               max_f_len=max_f_len)
