"""NHWC BatchNorm with optional fused ReLU / add+ReLU and cross-replica
groups (ref apex/contrib/groupbn/batch_norm.py BatchNorm2d_NHWC).

The CUDA version is a hand-tiled NHWC kernel with optional peer-device
groups (``bn_group``). On TPU NHWC is the native conv layout, XLA fuses the
normalize+relu chain, and a bn_group maps to a psum over a mesh-axis
subgroup — the same machinery as :class:`apex_tpu.parallel.SyncBatchNorm`.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


class BatchNorm2d_NHWC(nn.Module):
    """ref batch_norm.py:101. ``fuse_relu`` applies relu after normalize;
    ``__call__(x, z)`` with z implements the add+relu fusion
    (bn_addrelu path). ``bn_group > 1`` reduces stats over ``axis_name``.
    """

    num_features: int
    fuse_relu: bool = False
    bn_group: int = 1
    axis_name: Optional[str] = "data"
    momentum: float = 0.9
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x, z=None, train: bool = True):
        if self.bn_group > 1:
            # groups of bn_group consecutive ranks share statistics (ref
            # batch_norm.py bn_group peer groups)
            y = SyncBatchNorm(momentum=1.0 - self.momentum, eps=self.eps,
                              axis_name=self.axis_name,
                              group_size=self.bn_group)(
                x, use_running_average=not train)
        else:
            y = nn.BatchNorm(use_running_average=not train,
                             momentum=self.momentum, epsilon=self.eps,
                             dtype=x.dtype)(x)
        if z is not None:
            y = y + z
        if self.fuse_relu or z is not None:
            y = nn.relu(y)
        return y
