"""Multi-head attention modules (ref apex/contrib/multihead_attn/
{self,encdec}_multihead_attn.py and *_norm_add variants).

The reference offers fused qkv gemms + fused softmax + (optionally) a
fused residual-add+layernorm prologue. Here each module is a flax module
over the same packed-projection layout, with the Pallas flash attention in
the middle and the fused LN from apex_tpu.normalization for the norm-add
variants.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.normalization.fused_layer_norm import fused_layer_norm_affine
from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.transformer.functional.fused_softmax import scaled_masked_softmax


def _masked_attention(q, k, v, key_padding_mask, attn_mask, scale,
                      dropout_p=0.0, dropout_rng=None):
    """[b, s, h, d] attention with torch-style masks (ref
    self_multihead_attn.py:144-156):

    - ``key_padding_mask`` [b, sk], True = pad: padded KEYS are excluded
      from every query's softmax.
    - ``attn_mask`` [sq, sk], bool (True = masked) or additive float
      (-inf = masked), applied to every batch/head.
    - ``dropout_p``/``dropout_rng``: inverted dropout on the softmax
      probabilities (ref self_multihead_attn_func.py:100 fused dropout).
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    b, _, sq, sk = scores.shape
    mask = None  # built lazily: all-additive masks need no bool mask at all
    if key_padding_mask is not None:
        mask = jnp.broadcast_to(key_padding_mask[:, None, None, :],
                                (b, 1, sq, sk))
    if attn_mask is not None:
        if jnp.issubdtype(attn_mask.dtype, jnp.integer):
            # torch-style byte/int mask (nonzero = masked): treat as bool
            # rather than silently ADDING it to the scores
            attn_mask = attn_mask != 0
        if attn_mask.dtype == jnp.bool_:
            am = jnp.broadcast_to(attn_mask[None, None, :, :],
                                  (b, 1, sq, sk))
            mask = am if mask is None else mask | am
        else:  # additive float mask: fold into the (scaled) scores
            scores = scores + attn_mask[None, None, :, :] / scale
    probs = scaled_masked_softmax(scores, mask, scale).astype(v.dtype)
    if dropout_p > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_p,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class SelfMultiheadAttn(nn.Module):
    """ref self_multihead_attn.py:27 (impl='fast').

    Input [s, b, h] (torch MHA layout). ``include_norm_add`` prepends
    residual-add + layernorm (ref self_multihead_attn_norm_add).
    """

    hidden_dim: int
    heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    separate_qkv_params: bool = False

    @nn.compact
    def __call__(self, query, key_padding_mask=None, attn_mask=None,
                 is_training: bool = True, deterministic: Optional[bool] = None):
        s, b, h = query.shape
        d = h // self.heads
        x = query
        if self.include_norm_add:
            w = self.param("lyr_nrm_gamma_weights", nn.initializers.ones, (h,))
            bta = self.param("lyr_nrm_beta_weights", nn.initializers.zeros, (h,))
            x = fused_layer_norm_affine(x, w, bta, (h,))
        if self.separate_qkv_params:
            q = nn.Dense(h, use_bias=self.bias, name="q_proj")(x)
            k = nn.Dense(h, use_bias=self.bias, name="k_proj")(x)
            v = nn.Dense(h, use_bias=self.bias, name="v_proj")(x)
        else:
            qkv = nn.Dense(3 * h, use_bias=self.bias, name="qkv_proj")(x)
            q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads_first(t):
            return t.transpose(1, 0, 2).reshape(b, s, self.heads, d)

        # dropout applies to the softmax PROBS (ref
        # self_multihead_attn_func.py:100), not the output projection
        det = (not is_training) if deterministic is None else deterministic
        drop = 0.0 if det else self.dropout
        rng = self.make_rng("dropout") if drop > 0.0 else None
        if key_padding_mask is not None or attn_mask is not None:
            o = _masked_attention(heads_first(q), heads_first(k),
                                  heads_first(v), key_padding_mask,
                                  attn_mask, d ** -0.5,
                                  dropout_p=drop, dropout_rng=rng)
        else:
            o = flash_attention(heads_first(q), heads_first(k),
                                heads_first(v), causal=False,
                                scale=d ** -0.5, dropout_p=drop,
                                dropout_key=rng, deterministic=det)
        o = o.reshape(b, s, h).transpose(1, 0, 2)
        o = nn.Dense(h, use_bias=self.bias, name="out_proj")(o)
        if self.include_norm_add:
            o = o + query  # fused residual add (ref *_norm_add backward)
        return o


class EncdecMultiheadAttn(nn.Module):
    """ref encdec_multihead_attn.py: q from decoder, k/v from encoder."""

    hidden_dim: int
    heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False

    @nn.compact
    def __call__(self, query, key, is_training: bool = True,
                 deterministic: Optional[bool] = None):
        sq, b, h = query.shape
        sk = key.shape[0]
        d = h // self.heads
        x = query
        if self.include_norm_add:
            w = self.param("lyr_nrm_gamma_weights", nn.initializers.ones, (h,))
            bta = self.param("lyr_nrm_beta_weights", nn.initializers.zeros, (h,))
            x = fused_layer_norm_affine(x, w, bta, (h,))
        q = nn.Dense(h, use_bias=self.bias, name="q_proj")(x)
        kv = nn.Dense(2 * h, use_bias=self.bias, name="kv_proj")(key)
        k, v = jnp.split(kv, 2, axis=-1)

        q4 = q.transpose(1, 0, 2).reshape(b, sq, self.heads, d)
        k4 = k.transpose(1, 0, 2).reshape(b, sk, self.heads, d)
        v4 = v.transpose(1, 0, 2).reshape(b, sk, self.heads, d)
        det = (not is_training) if deterministic is None else deterministic
        drop = 0.0 if det else self.dropout
        rng = self.make_rng("dropout") if drop > 0.0 else None
        o = flash_attention(q4, k4, v4, causal=False, scale=d ** -0.5,
                            dropout_p=drop, dropout_key=rng,
                            deterministic=det)
        o = o.reshape(b, sq, h).transpose(1, 0, 2)
        o = nn.Dense(h, use_bias=self.bias, name="out_proj")(o)
        if self.include_norm_add:
            o = o + query
        return o
