"""Fused ResNet bottleneck (ref apex/contrib/bottleneck/bottleneck.py
Bottleneck/SpatialBottleneck).

The CUDA version hand-fuses conv+bn+relu chains and, for
SpatialBottleneck, overlaps halo exchange with the 3x3 conv. On TPU the
plain Bottleneck IS :class:`apex_tpu.models.resnet.Bottleneck` (XLA fuses
the chain); SpatialBottleneck adds the ppermute halo exchange from
:mod:`apex_tpu.contrib.peer_memory` around the spatially-sharded 3x3.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.contrib.peer_memory import halo_exchange_1d
from apex_tpu.models.resnet import Bottleneck  # re-export (ref Bottleneck)

__all__ = ["Bottleneck", "SpatialBottleneck"]


class SpatialBottleneck(nn.Module):
    """Bottleneck whose feature map is H-sharded across ``axis_name``
    (ref bottleneck.py SpatialBottleneck: spatial group + halo exchange).

    The 3x3 conv needs one halo row from each neighbour; the exchange rides
    ICI via ppermute, then the conv runs on the padded slab and the halo
    rows are dropped again.

    Downsampling always uses the v1 placement (stride on the first 1x1 —
    the reference's spatial path forces ``stride_1x1`` too), so for parity
    with a non-sharded model build its blocks with
    ``Bottleneck(stride_1x1=True)``.
    """

    features: int
    strides: Tuple[int, int] = (1, 1)
    axis_name: str = "spatial"
    sync_bn: bool = False
    bn_axis: Optional[str] = "data"

    @nn.compact
    def __call__(self, x, train: bool = True):
        from apex_tpu.models._common import BatchNorm

        conv = lambda f, k, s=(1, 1): nn.Conv(  # noqa: E731
            f, k, strides=s, use_bias=False, dtype=x.dtype)
        bn = lambda: BatchNorm(sync=self.sync_bn, axis_name=self.bn_axis)  # noqa: E731

        residual = x
        # Downsampling stride lives on the first 1x1 (the reference's
        # spatial path forces stride_1x1, bottleneck.py SpatialBottleneck):
        # a strided per-shard 3x3 would break the residual-add shape and the
        # global stride phase across H-shards.
        y = nn.relu(bn()(conv(self.features, (1, 1), self.strides)(x),
                         train))
        # 3x3 on the H-sharded slab: pad a 1-row halo, exchange, conv VALID
        pad = [(0, 0)] * y.ndim
        pad[1] = (1, 1)
        y_h = jnp.pad(y, pad)
        y_h = halo_exchange_1d(y_h, 1, self.axis_name, h_dim=1)
        y = nn.Conv(self.features, (3, 3), strides=(1, 1),
                    use_bias=False, padding=((0, 0), (1, 1)),
                    dtype=x.dtype)(y_h)
        y = nn.relu(bn()(y, train))
        y = bn()(conv(self.features * 4, (1, 1))(y), train)
        if residual.shape != y.shape:
            residual = bn()(conv(self.features * 4, (1, 1),
                                 self.strides)(residual), train)
        return nn.relu(y + residual)
