"""Focal loss (ref apex/contrib/focal_loss/focal_loss.py focal_loss_cuda).

Per the reference kernel semantics: sigmoid focal loss over one-hot-encoded
class targets (RetinaNet-style), label smoothing supported, normalized by
``num_positives_sum``; a custom_vjp saves the partial grad like the CUDA
kernel's fused backward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def focal_loss(cls_output, cls_targets_at_level, num_positives_sum,
               num_real_classes, alpha: float, gamma: float,
               label_smoothing: float = 0.0):
    """Scalar focal loss (ref focal_loss.py:42 wrapper).

    cls_output: [..., C_padded] raw logits; cls_targets_at_level: [...]
    int class ids with -1 = background/ignore-for-positives (RetinaNet
    convention — targets still produce negative-class loss); only the first
    ``num_real_classes`` channels contribute.
    """
    logits = cls_output[..., :num_real_classes].astype(jnp.float32)
    t = cls_targets_at_level
    onehot = jax.nn.one_hot(jnp.maximum(t, 0), num_real_classes,
                            dtype=jnp.float32)
    onehot = jnp.where((t >= 0)[..., None], onehot, 0.0)
    if label_smoothing > 0.0:
        onehot = onehot * (1.0 - label_smoothing) + 0.5 * label_smoothing

    p = jax.nn.sigmoid(logits)
    ce = (jnp.maximum(logits, 0) - logits * onehot
          + jnp.log1p(jnp.exp(-jnp.abs(logits))))
    p_t = p * onehot + (1.0 - p) * (1.0 - onehot)
    alpha_t = alpha * onehot + (1.0 - alpha) * (1.0 - onehot)
    loss = alpha_t * (1.0 - p_t) ** gamma * ce
    return jnp.sum(loss) / jnp.maximum(num_positives_sum, 1.0)


class FocalLoss:
    """ref focal_loss.py:4 FocalLoss (Function.apply shape)."""

    apply = staticmethod(focal_loss)

    def __call__(self, *a, **kw):
        return focal_loss(*a, **kw)
