"""Fused multi-head attention (ref apex/contrib/fmha/fmha.py FMHAFun +
csrc/fmha cutlass kernels) — backed by the Pallas TPU flash attention
kernel in :mod:`apex_tpu.ops.flash_attention`.

The reference consumes varlen packed sequences (qkv [total, 3, h, d] +
cu_seqlens). TPU-first design uses fixed-shape batches (dynamic shapes
defeat XLA); varlen batches are expressed with a padding mask or by packing
to a common length upstream.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention


def fmha(q, k, v, causal: bool = False, scale: Optional[float] = None,
         dropout_p: float = 0.0, dropout_key=None,
         deterministic: bool = False):
    """[b, s, h, d] fused attention (flash; no s×s HBM materialization).

    ``dropout_p`` drops softmax probs inside the kernel (ref
    fmha.py:35 p_dropout); pass ``dropout_key`` when training.
    """
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           dropout_p=dropout_p, dropout_key=dropout_key,
                           deterministic=deterministic)


def fmha_packed_qkv(qkv, causal: bool = False,
                    scale: Optional[float] = None, seqlens=None,
                    dropout_p: float = 0.0, dropout_key=None,
                    deterministic: bool = False):
    """qkv [b, s, 3, h, d] (the reference's packed layout, batched).

    ``seqlens`` [b] masks per-sequence padding (the reference's varlen
    cu_seqlens semantics on the padded-dense TPU layout) — handled INSIDE
    the flash kernel, so varlen batches keep O(s·d) memory.
    """
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    kv_lens = jnp.asarray(seqlens) if seqlens is not None else None
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           kv_lens=kv_lens, dropout_p=dropout_p,
                           dropout_key=dropout_key,
                           deterministic=deterministic)


class FMHAFun:
    """ref fmha.py FMHAFun.apply shape (padded-dense qkv [b, s, 3, h, d]).

    ``cu_seqlens`` (cumulative, [b+1] — the reference's varlen boundary
    vector) or ``seqlens`` ([b]) mask out each sequence's padding; the
    reference's flat [total, 3, h, d] packing is a CUDA memory layout —
    on TPU batches stay padded-dense (static shapes) and the mask carries
    the varlen semantics.
    """

    @staticmethod
    def apply(qkv, cu_seqlens=None, seqlens=None, p_dropout=0.0,
              max_s=None, is_training=True, zero_tensors=False,
              dropout_key=None):
        """``p_dropout`` drops softmax probs in the kernel (ref
        fmha.py:35). Stateless RNG: pass a FRESH ``dropout_key`` (jax PRNG
        key) every step — the torch reference reads global CUDA RNG state,
        which does not exist in a functional framework, so the key is a
        required training-time argument (same contract as flax ``rngs``).
        """
        del max_s, zero_tensors
        if qkv.ndim != 5:
            raise ValueError(
                "apex_tpu FMHAFun takes padded-dense qkv [b, s, 3, h, d]; "
                "flat varlen packing is a CUDA layout — unpack with "
                "cu_seqlens upstream")
        if seqlens is None and cu_seqlens is not None:
            cu = jnp.asarray(cu_seqlens)
            seqlens = cu[1:] - cu[:-1]
        if p_dropout and is_training and dropout_key is None:
            raise ValueError(
                "FMHAFun.apply with p_dropout in training needs "
                "dropout_key (a jax PRNG key, fresh each step) — a fixed "
                "implicit key would repeat the same dropout mask every "
                "step and silently bias training")
        return fmha_packed_qkv(qkv, seqlens=seqlens, dropout_p=p_dropout,
                               dropout_key=dropout_key,
                               deterministic=not is_training)
