"""Peer-memory halo exchange (ref apex/contrib/peer_memory/
{peer_memory,peer_halo_exchanger_1d}.py).

The reference moves conv halos between GPUs through cudaIpc peer mappings.
On TPU, neighbour transfer IS the ICI collective: a ``ppermute`` pair sends
the top/bottom halo rows to the adjacent rank on the spatial axis. The
PeerMemoryPool (raw device allocations) has no TPU analog — XLA owns
buffers — so the pool here is a thin facade kept for API parity.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


class PeerMemoryPool:
    """API-parity facade (ref peer_memory.py PeerMemoryPool): on TPU there
    are no raw peer mappings to pre-allocate; allocate() hands back shaped
    zeros so reference-ported code keeps running."""

    def __init__(self, static_size: int = 0, dynamic_size: int = 0,
                 peer_ranks=None):
        self.peer_ranks = peer_ranks

    def allocate_peer_tensors(self, shape, dtype, channels_last, dynamic):
        del channels_last, dynamic
        return [jnp.zeros(shape, dtype)]

    def reset(self):
        pass


def halo_exchange_1d(y, half_halo: int, axis_name: str = "spatial",
                     h_dim: int = 1):
    """Exchange ``half_halo`` rows with spatial neighbours over the mesh
    axis (ref peer_halo_exchanger_1d.py:14 __call__, H_split=True).

    y: [N, H_local(+2*half_halo), W, C] with halo margins already in place;
    returns y with the margins filled from the neighbours' edge rows.
    Boundary ranks keep their margins (zero/garbage) like the reference,
    which only exchanges interior halos.
    """
    from apex_tpu.transformer.tensor_parallel.mappings import _to_varying

    n = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    hh = half_halo
    y = _to_varying(y, axis_name)

    def take(lo, hi):
        idx = [slice(None)] * y.ndim
        idx[h_dim] = slice(lo, hi)
        return y[tuple(idx)]

    # my interior edge rows (just inside the halo margins)
    top_edge = take(hh, 2 * hh)           # goes to previous rank's bottom margin
    bot_edge = take(-2 * hh, -hh)         # goes to next rank's top margin

    up = [(i, i - 1) for i in range(1, n)]      # send towards rank 0
    down = [(i, i + 1) for i in range(n - 1)]   # send towards rank n-1
    from_next = jax.lax.ppermute(top_edge, axis_name, up)
    from_prev = jax.lax.ppermute(bot_edge, axis_name, down)

    idx_top = [slice(None)] * y.ndim
    idx_top[h_dim] = slice(0, hh)
    idx_bot = [slice(None)] * y.ndim
    idx_bot[h_dim] = slice(y.shape[h_dim] - hh, y.shape[h_dim])

    y = y.at[tuple(idx_top)].set(
        jnp.where(rank > 0, from_prev, take(0, hh)))
    y = y.at[tuple(idx_bot)].set(
        jnp.where(rank < n - 1, from_next, take(-hh, None)))
    return y


class PeerHaloExchanger1d:
    """ref peer_halo_exchanger_1d.py:5."""

    def __init__(self, rank=None, peer_group_size=None, peer_pool=None,
                 half_halo: int = 1, axis_name: str = "spatial"):
        del rank, peer_group_size, peer_pool
        self.half_halo = half_halo
        self.axis_name = axis_name

    def __call__(self, y, H_split: bool = True, explicit_nhwc: bool = True,
                 numSM: int = 1, diagnostics: bool = False):
        del explicit_nhwc, numSM, diagnostics
        h_dim = 1 if H_split else 2
        return halo_exchange_1d(y, self.half_halo, self.axis_name, h_dim)
