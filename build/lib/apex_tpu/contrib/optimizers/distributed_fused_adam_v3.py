"""DistributedFusedAdam v3 (ref apex/contrib/optimizers/
distributed_fused_adam_v3.py). See distributed_fused_adam_v2 — the NCCL
pipelining variants collapse to one XLA implementation on TPU."""

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    DistributedFusedAdam,
    distributed_fused_adam,
)

DistributedFusedAdamV3 = DistributedFusedAdam

__all__ = ["DistributedFusedAdam", "DistributedFusedAdamV3",
           "distributed_fused_adam"]
