"""ZeRO-style distributed fused LAMB (ref apex/contrib/optimizers/
distributed_fused_lamb.py DistributedFusedLAMB).

The reference (980 lines of chunked NCCL pipelining: reduce-scatter blocks,
L2-norm kernels, all-gather process groups) shards LAMB state across the
data-parallel group, computes the *global* gradient norm and the
*per-tensor* param/update norms over sharded buffers in two stages
(local partial reductions + allreduce), and all-gathers the updated
parameters. On TPU the chunk/process-group scheduling is XLA's job; what
remains — and is implemented here — is the math and the collectives:

    grads --psum_scatter('dp')--> local flat grad shard
    global grad norm  = sqrt(psum(sum(local_shard^2)))      -> clip coeff
    LAMB moments + raw update direction on the local shard
    per-tensor ||p||, ||u||: segment-sum over the shard's slice of each
      tensor, psum'd over 'dp' (the two-stage multi_tensor_l2norm_mp)
    trust ratio per tensor -> elementwise via the segment map
    new master shard --psum-place all-gather--> full updated params

State (fp32 master, m, v) lives only as 1/n-shards: ZeRO-2 memory.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu.optimizers import _math
from apex_tpu.ops.flat import flatten_tree, unflatten_tree
from apex_tpu.transformer.tensor_parallel.mappings import _to_varying


class DistLAMBState(NamedTuple):
    count: jax.Array
    master_shard: dict   # dtype-bucket key -> local fp32 shard
    mu_shard: dict
    nu_shard: dict


def _pad_to(x, k):
    pad = (-x.size) % k
    return jnp.pad(x, (0, pad)) if pad else x


def _segment_ids(spec, pad_size: int) -> np.ndarray:
    """Static per-element tensor index for a padded flat buffer; padding
    elements get segment ``T`` (dropped after reduction)."""
    T = len(spec.sizes)
    ids = np.repeat(np.arange(T, dtype=np.int32), spec.sizes)
    return np.pad(ids, (0, pad_size - ids.size), constant_values=T)


def distributed_fused_lamb(
    lr=1e-3, bias_correction: bool = True, betas=(0.9, 0.999), eps: float = 1e-6,
    weight_decay: float = 0.01, adam_w_mode: bool = True,
    grad_averaging: bool = True, max_grad_norm: float = 1.0,
    use_nvlamb: bool = False, axis_name: str = "dp",
    master_dtype=jnp.float32, fp32_reduce_scatter: bool = True,
) -> optax.GradientTransformation:
    """optax-style transform; MUST run inside shard_map with ``axis_name``
    bound. Each replica passes the FULL grads; state is sharded.

    ``master_dtype`` controls the storage dtype of the sharded
    master/moment buffers (the reference's fp16-master memory knob;
    bf16 halves ZeRO state memory, the step math stays fp32).
    ``fp32_reduce_scatter`` reduces grads in fp32; False reduce-scatters
    in the gradient's own dtype — half the ICI bytes, bf16 summation
    error. (The closest reference analog is DistributedFusedAdam's
    fp16 reduce-scatter path; DistributedFusedLAMB itself has no such
    flag.)"""
    b1, b2 = betas

    def init(params):
        n = jax.lax.axis_size(axis_name)
        r = jax.lax.axis_index(axis_name)
        bufs, _ = flatten_tree(params)
        master, mu, nu = {}, {}, {}
        for k, buf in bufs.items():
            flat = _to_varying(_pad_to(buf.astype(master_dtype), n),
                               axis_name)
            shard = jax.lax.dynamic_slice_in_dim(
                flat, r * (flat.size // n), flat.size // n)
            master[k] = shard
            mu[k] = jnp.zeros_like(shard)
            nu[k] = jnp.zeros_like(shard)
        return DistLAMBState(jnp.zeros([], jnp.int32), master, mu, nu)

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("distributed_fused_lamb requires params")
        n = jax.lax.axis_size(axis_name)
        r = jax.lax.axis_index(axis_name)
        count = state.count + 1
        step = count.astype(jnp.float32)
        lr_t = lr(state.count) if callable(lr) else lr

        pbufs, pmeta = flatten_tree(params)
        _, _, pspecs = pmeta
        g_leaves = jax.tree_util.tree_leaves(grads)

        # ---- stage 1: reduce-scatter grads; two-stage global grad norm
        gshards = {}
        gsq_local = jnp.zeros([], jnp.float32)
        for k, (idxs, spec) in pspecs.items():
            rs_dtype = (jnp.float32 if fp32_reduce_scatter
                        else g_leaves[idxs[0]].dtype)
            gbuf = jnp.concatenate(
                [g_leaves[i].ravel().astype(rs_dtype) for i in idxs])
            gflat = _to_varying(_pad_to(gbuf, n), axis_name)
            gshard = (jax.lax.psum_scatter(
                gflat, axis_name, scatter_dimension=0, tiled=True)
                .astype(jnp.float32) / n)
            gshards[k] = gshard
            gsq_local = gsq_local + jnp.sum(jnp.square(gshard))
        gnorm = jnp.sqrt(jax.lax.psum(gsq_local, axis_name))
        clip_coeff = jnp.where(
            (max_grad_norm > 0.0) & (gnorm > max_grad_norm),
            max_grad_norm / jnp.maximum(gnorm, 1e-30), 1.0)

        # ---- stage 2: shard-local LAMB math + two-stage per-tensor norms
        new_master, new_mu, new_nu, out_bufs = {}, {}, {}, {}
        for k, (idxs, spec) in pspecs.items():
            gshard = gshards[k]
            # step math is always fp32; only the stored shards honor
            # master_dtype (the down-cast happens at state write below)
            p_shard = state.master_shard[k].astype(jnp.float32)
            m, v = _math.lamb_moments(
                gshard, p_shard,
                state.mu_shard[k].astype(jnp.float32),
                state.nu_shard[k].astype(jnp.float32),
                b1=b1, b2=b2, grad_averaging=grad_averaging,
                clip_coeff=clip_coeff, weight_decay=weight_decay,
                adam_w_mode=adam_w_mode)
            u = _math.lamb_update_direction(
                p_shard, m, v, b1=b1, b2=b2, eps=eps,
                weight_decay=weight_decay, adam_w_mode=adam_w_mode,
                step=step, bias_correction=bias_correction)

            # per-tensor ||p||, ||u|| over sharded buffers: local segment
            # sums + psum (ref: multi_tensor_l2norm per block + allreduce)
            T = len(spec.sizes)
            shard_size = p_shard.size
            seg_full = jnp.asarray(_segment_ids(spec, shard_size * n))
            seg = jax.lax.dynamic_slice_in_dim(
                seg_full, r * shard_size, shard_size)
            psq = jax.lax.psum(jax.ops.segment_sum(
                jnp.square(p_shard), seg, num_segments=T + 1), axis_name)
            usq = jax.lax.psum(jax.ops.segment_sum(
                jnp.square(u), seg, num_segments=T + 1), axis_name)
            ratio_t = _math.lamb_trust_ratio(
                jnp.sqrt(psq[:T]), jnp.sqrt(usq[:T]),
                weight_decay=weight_decay, use_nvlamb=use_nvlamb)
            ratio = jnp.concatenate([ratio_t, jnp.ones((1,))])[seg]

            master = p_shard - lr_t * ratio * u
            new_master[k] = master.astype(master_dtype)
            new_mu[k] = m.astype(master_dtype)
            new_nu[k] = v.astype(master_dtype)

            # all-gather updated shards (psum of rank-offset placement —
            # output is vma-invariant, same trick as distributed_fused_adam)
            placed = jnp.zeros((shard_size * n,), master.dtype)
            placed = jax.lax.dynamic_update_slice_in_dim(
                placed, master, r * shard_size, 0)
            full = jax.lax.psum(placed, axis_name)
            out_bufs[k] = full[:pbufs[k].size].astype(pbufs[k].dtype)

        new_params = unflatten_tree(out_bufs, pmeta)
        updates = jax.tree_util.tree_map(
            lambda np_, p: np_ - p, new_params, params)
        return updates, DistLAMBState(count, new_master, new_mu, new_nu)

    return optax.GradientTransformation(init, update)


class DistributedFusedLAMB:
    """Class-shaped wrapper (ref distributed_fused_lamb.py:10). The
    reference's dwu_* chunking/process-group knobs configure NCCL overlap;
    XLA schedules the collectives, so they are accepted and ignored."""

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 grad_averaging=True, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, max_grad_norm=0.0, adam_w_mode=True,
                 use_nvlamb=False, axis_name: str = "dp",
                 master_dtype=jnp.float32, fp32_reduce_scatter=True,
                 **unused):
        self.tx = distributed_fused_lamb(
            lr=lr, bias_correction=bias_correction, betas=betas, eps=eps,
            weight_decay=weight_decay, adam_w_mode=adam_w_mode,
            grad_averaging=grad_averaging, max_grad_norm=max_grad_norm,
            use_nvlamb=use_nvlamb, axis_name=axis_name,
            master_dtype=master_dtype,
            fp32_reduce_scatter=fp32_reduce_scatter)
        self.params = params
        self.state = None  # init must run inside shard_map

    def init(self, params=None):
        self.state = self.tx.init(
            params if params is not None else self.params)
        return self.state
