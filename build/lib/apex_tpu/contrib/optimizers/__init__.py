"""apex.contrib.optimizers parity (ref apex/contrib/optimizers/)."""

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    DistributedFusedAdam,
    distributed_fused_adam,
)
from apex_tpu.contrib.optimizers.distributed_fused_lamb import (
    DistributedFusedLAMB,
    distributed_fused_lamb,
)
from apex_tpu.contrib.optimizers.fp16_optimizer import FP16_Optimizer

__all__ = [
    "DistributedFusedAdam", "distributed_fused_adam",
    "DistributedFusedLAMB", "distributed_fused_lamb",
    "FP16_Optimizer",
]
