"""ZeRO-style distributed fused Adam (ref apex/contrib/optimizers/
distributed_fused_adam.py DistributedFusedAdam).

The reference shards optimizer state across the process group,
reduce-scatters gradients, steps the local shard, and all-gathers updated
params. TPU-first translation over a 'dp' mesh axis inside shard_map:

    grads --psum_scatter('dp')--> local grad shard (flat buffer)
    local fp32 master/m/v shard --adam_step--> local new master shard
    --all_gather('dp')--> full updated params

One flat fp32 buffer per dtype keeps the scatter/gather contiguous (the
multi_tensor_apply layout) and divides evenly across the axis by padding.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers import _math
from apex_tpu.ops.flat import flatten_tree, unflatten_tree
from apex_tpu.transformer.tensor_parallel.mappings import _to_varying


class DistAdamState(NamedTuple):
    count: jax.Array
    master_shard: dict   # key -> local fp32 param shard [pad_size / n]
    mu_shard: dict
    nu_shard: dict


def _pad_to(x, k):
    pad = (-x.size) % k
    return jnp.pad(x, (0, pad)) if pad else x


def distributed_fused_adam(
    lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
    adam_w_mode: bool = True, bias_correction: bool = True,
    axis_name: str = "dp",
) -> optax.GradientTransformation:
    """optax-style transform; MUST run inside shard_map with ``axis_name``
    bound. Each replica passes the FULL grads; state is sharded."""
    b1, b2 = betas

    def axis_n():
        return jax.lax.axis_size(axis_name)

    def init(params):
        n = axis_n()
        r = jax.lax.axis_index(axis_name)
        bufs, meta = flatten_tree(params)
        master, mu, nu = {}, {}, {}
        for k, buf in bufs.items():
            flat = _to_varying(_pad_to(buf.astype(jnp.float32), n), axis_name)
            shard = jax.lax.dynamic_slice_in_dim(
                flat, r * (flat.size // n), flat.size // n)
            master[k] = shard
            mu[k] = jnp.zeros_like(shard)
            nu[k] = jnp.zeros_like(shard)
        return DistAdamState(jnp.zeros([], jnp.int32), master, mu, nu)

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("distributed_fused_adam requires params")
        n = axis_n()
        r = jax.lax.axis_index(axis_name)
        count = state.count + 1
        step = count.astype(jnp.float32)
        pbufs, pmeta = flatten_tree(params)
        # pack grads in the PARAM buckets (grads may differ in dtype, e.g.
        # fp32 grads over bf16 params): same leaf order, cast to fp32
        _, _, pspecs = pmeta
        g_leaves = jax.tree_util.tree_leaves(grads)

        new_master, new_mu, new_nu, out_bufs = {}, {}, {}, {}
        for k, (idxs, spec) in pspecs.items():
            gbuf = jnp.concatenate(
                [g_leaves[i].ravel().astype(jnp.float32) for i in idxs])
            gflat = _to_varying(_pad_to(gbuf, n), axis_name)
            # mean-reduce + scatter: each rank owns 1/n of the gradient
            gshard = jax.lax.psum_scatter(
                gflat, axis_name, scatter_dimension=0, tiled=True) / n
            delta, m, v = _math.adam_step(
                gshard, state.master_shard[k], state.mu_shard[k],
                state.nu_shard[k], lr=lr if not callable(lr) else lr(state.count),
                b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
                adam_w_mode=adam_w_mode, step=step,
                bias_correction=bias_correction)
            master = state.master_shard[k] + delta
            new_master[k], new_mu[k], new_nu[k] = master, m, v
            # gather updated shards in ONE variant->invariant collective:
            # psum of rank-offset-placed shards == all_gather, and the psum
            # output is vma-invariant (no extra claim pass needed)
            pad_size = master.size * n
            placed = jnp.zeros((pad_size,), master.dtype)
            placed = jax.lax.dynamic_update_slice_in_dim(
                placed, master, r * master.size, 0)
            full = jax.lax.psum(placed, axis_name)
            out_bufs[k] = full[:pbufs[k].size].astype(pbufs[k].dtype)

        new_params = unflatten_tree(out_bufs, pmeta)
        updates = jax.tree_util.tree_map(
            lambda np_, p: np_ - p, new_params, params)
        return updates, DistAdamState(count, new_master, new_mu, new_nu)

    return optax.GradientTransformation(init, update)


def dist_adam_partition_specs(params, mesh_axes=("dp",)):
    """PartitionSpecs for carrying :class:`DistAdamState` across jitted
    ``shard_map`` steps (checkpoint/resume of the ZeRO shards).

    The state is one flat fp32 shard per param-dtype bucket per rank; its
    global encoding concatenates every rank's shard along dim 0 in mesh
    order, so a round trip through ``out_specs`` then ``in_specs`` hands
    each rank back exactly the shard it wrote. ``mesh_axes`` should name
    the ZeRO axis plus any mesh axis the params may be sharded over (the
    per-rank shards differ across those too). A bucket that happens to be
    invariant over a listed axis is still fine: shard_map accepts an
    out_spec naming an axis the value is invariant over, and the global
    array just stores that bucket's identical blocks redundantly. Ref
    apex/contrib/optimizers/distributed_fused_adam.py state_dict gather.
    """
    from jax.sharding import PartitionSpec as P

    keys = sorted({jnp.dtype(l.dtype).name
                   for l in jax.tree_util.tree_leaves(params)})
    shard = {k: P(tuple(mesh_axes)) for k in keys}
    return DistAdamState(count=P(), master_shard=shard, mu_shard=shard,
                         nu_shard=shard)


class DistributedFusedAdam:
    """Class-shaped wrapper (ref distributed_fused_adam.py:42); functional
    state, explicit mesh usage."""

    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 adam_w_mode=True, axis_name: str = "dp", **unused):
        self.tx = distributed_fused_adam(
            lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
            adam_w_mode=adam_w_mode, bias_correction=bias_correction,
            axis_name=axis_name)
        self.params = params
        self.state = None  # init must run inside shard_map

    def init(self, params=None):
        self.state = self.tx.init(params if params is not None else self.params)
        return self.state
