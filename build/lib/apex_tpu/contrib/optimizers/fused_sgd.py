"""contrib FusedSGD (ref apex/contrib/optimizers/fused_sgd.py — legacy
duplicate of apex.optimizers.FusedSGD). The TPU FusedSGD already accepts
the legacy knobs (materialize_master_grads), so this is a pure re-export."""

from apex_tpu.optimizers.fused_sgd import FusedSGD, fused_sgd

__all__ = ["FusedSGD", "fused_sgd"]
