"""contrib FusedAdam (ref apex/contrib/optimizers/fused_adam.py — the older
duplicate of apex.optimizers.FusedAdam kept for backward compat; its extra
knobs ``use_mt``/``amp_scale_adjustment`` configured the deprecated
multi-tensor amp path). One implementation on TPU; the legacy kwargs are
accepted and ignored."""

from __future__ import annotations

from apex_tpu.optimizers.fused_adam import FusedAdam as _FusedAdam
from apex_tpu.optimizers.fused_adam import fused_adam


class FusedAdam(_FusedAdam):
    def __init__(self, params, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, eps_inside_sqrt=False,
                 weight_decay=0.0, max_grad_norm=0.0, amsgrad=False,
                 use_mt=False, amp_scale_adjustment=1.0):
        del eps_inside_sqrt, max_grad_norm, use_mt, amp_scale_adjustment
        super().__init__(params, lr=lr, bias_correction=bias_correction,
                         betas=betas, eps=eps, weight_decay=weight_decay,
                         amsgrad=amsgrad, adam_w_mode=False)


__all__ = ["FusedAdam", "fused_adam"]
