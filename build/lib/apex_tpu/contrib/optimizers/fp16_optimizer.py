"""contrib FP16_Optimizer (ref apex/contrib/optimizers/fp16_optimizer.py).

The contrib variant differs from ``apex.fp16_utils.FP16_Optimizer`` only in
assuming a flat-grad fused inner optimizer (it was written for the contrib
FusedAdam/FusedSGD). On TPU both share one implementation — the fp16_utils
version already keeps fp32 masters over a fused optax transform — so this
module re-exports it under the contrib name with the contrib defaults
(dynamic loss scale on by default, ref fp16_optimizer.py:25).
"""

from __future__ import annotations

from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer as _Base


class FP16_Optimizer(_Base):
    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=True, dynamic_loss_args=None,
                 verbose=False):
        super().__init__(init_optimizer, static_loss_scale=static_loss_scale,
                         dynamic_loss_scale=dynamic_loss_scale,
                         dynamic_loss_args=dynamic_loss_args,
                         verbose=verbose)
