"""DistributedFusedAdam v2 (ref apex/contrib/optimizers/
distributed_fused_adam_v2.py).

The reference's v2/v3 differ from v1 only in NCCL overlap strategy
(flat-buffer layout + reduction-pipelining knobs: dwu_num_blocks,
dwu_num_chunks, revert_method...). Under XLA the collective schedule is the
compiler's, so the TPU implementation is shared; the v2/v3 names exist for
import parity and accept (and ignore) the scheduling knobs.
"""

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    DistributedFusedAdam,
    distributed_fused_adam,
)

DistributedFusedAdamV2 = DistributedFusedAdam

__all__ = ["DistributedFusedAdam", "DistributedFusedAdamV2",
           "distributed_fused_adam"]
