"""contrib FusedLAMB (ref apex/contrib/optimizers/fused_lamb.py — legacy
duplicate of apex.optimizers.FusedLAMB). Shared TPU implementation."""

from apex_tpu.optimizers.fused_lamb import FusedLAMB, fused_lamb

__all__ = ["FusedLAMB", "fused_lamb"]
