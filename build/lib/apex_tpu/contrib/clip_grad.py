"""Fused gradient clipping (apex master's apex/contrib/clip_grad — absent
from this reference snapshot but part of the apex surface; semantics follow
torch.nn.utils.clip_grad_norm_ with the multi-tensor fused norm).

Delegates to :func:`apex_tpu.fp16_utils.fp16util.clip_grad_norm` (one
implementation of the global-norm clip) and adds the torch-style
``error_if_nonfinite`` check.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp

from apex_tpu.fp16_utils.fp16util import clip_grad_norm as _clip_grad_norm


def clip_grad_norm_(parameters, max_norm: float,
                    norm_type: Union[float, int] = 2.0,
                    error_if_nonfinite: bool = False):
    """Returns ``(clipped_grads, total_norm)`` — functional: the input tree
    is not mutated (there is no ``.grad`` storage on TPU)."""
    clipped, total_norm = _clip_grad_norm(parameters, max_norm,
                                          float(norm_type))
    if error_if_nonfinite:
        # traced check is impossible without host sync; mirror torch by
        # checking eagerly when the value is concrete
        try:
            if not bool(jnp.isfinite(total_norm)):
                raise RuntimeError(
                    f"the total norm of order {norm_type} is non-finite")
        except jax.errors.TracerBoolConversionError:
            pass
    return clipped, total_norm


clip_grad_norm = clip_grad_norm_
