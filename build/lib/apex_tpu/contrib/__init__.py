"""apex.contrib parity surface (ref apex/contrib/__init__.py)."""

from apex_tpu.contrib import (
    bottleneck,
    clip_grad,
    conv_bias_relu,
    fmha,
    focal_loss,
    groupbn,
    layer_norm,
    multihead_attn,
    optimizers,
    peer_memory,
    sparsity,
    transducer,
    xentropy,
)

__all__ = [
    "bottleneck", "clip_grad", "conv_bias_relu", "fmha", "focal_loss",
    "groupbn", "layer_norm", "multihead_attn", "optimizers", "peer_memory",
    "sparsity", "transducer", "xentropy",
]
