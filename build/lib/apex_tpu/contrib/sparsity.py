"""ASP — automatic 2:4 structured sparsity (ref apex/contrib/sparsity/
{asp.py,sparse_masklib.py}).

The reference computes N:M masks with CUDA permutation-search kernels and
hooks the optimizer to re-apply masks after each step. TPU design: the mask
computation is a vectorized jnp program (magnitude-based m4n2_1d — the
reference's default --whitelist pattern), masks live in the param pytree,
and masking is a pure function applied inside the jitted train step (and
wrapped around any optax transform via :func:`masked_update`).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax


def mn_1d_mask(w, m: int = 4, n: int = 2):
    """Keep the ``n`` largest-magnitude of every ``m`` consecutive weights
    along the last dim (ref sparse_masklib.py:49 m4n2_1d / mn_1d_best).

    Works on any shape with last dim divisible by m; returns a 0/1 mask of
    w's shape and dtype bool.
    """
    if w.shape[-1] % m:
        raise ValueError(f"last dim {w.shape[-1]} not divisible by m={m}")
    groups = w.reshape(*w.shape[:-1], w.shape[-1] // m, m)
    mag = jnp.abs(groups)
    # keep exactly n per group by magnitude rank (deterministic ties)
    order = jnp.argsort(jnp.argsort(-mag, axis=-1), axis=-1)  # rank, 0=largest
    keep = order < n
    return keep.reshape(w.shape)


def create_mask(w, pattern: str = "m4n2_1d"):
    """ref sparse_masklib.py create_mask entry."""
    if pattern == "m4n2_1d":
        return mn_1d_mask(w, 4, 2)
    if pattern == "m4n2_2d_best":
        # 2d pattern: apply 1d along both dims greedily (the reference's
        # exhaustive 2d search is a CUDA kernel; 1d x transpose-1d is the
        # documented greedy fallback, ref sparse_masklib.py:67)
        m_rows = mn_1d_mask(w, 4, 2)
        m_cols = jnp.swapaxes(
            mn_1d_mask(jnp.swapaxes(w, -1, -2), 4, 2), -1, -2)
        return m_rows & m_cols
    raise ValueError(f"unknown pattern {pattern}")


# --------------------------------------------------------------- permutation
# Channel-permutation search (ref apex/contrib/sparsity/permutation_lib.py +
# permutation_search_kernels/): an N:M mask must keep n-of-m CONSECUTIVE
# channels, so when large-magnitude channels cluster in one group the mask
# is forced to drop some of them. Permuting input channels regroups them;
# the reference searches permutations with CUDA kernels, here a host-side
# numpy search (sort+deal seeding, then bounded best-improvement column
# swaps) runs once offline, like the reference's apply-time search.


def _group_retained(cols: "np.ndarray", n: int):
    """Total magnitude kept by n-of-m on [rows, m] group columns."""
    import numpy as np

    s = np.sort(np.abs(cols), axis=1)[:, -n:]
    return float(s.sum())


def find_channel_permutation(w, m: int = 4, n: int = 2, iters: int = 200,
                             pairs_per_iter: int = 2048, seed: int = 0):
    """Permutation of w's LAST dim maximizing n:m retained magnitude.

    Seeding: columns sorted by L1 norm are dealt round-robin across groups
    (spreads heavy channels). Refinement: bounded best-improvement search
    over sampled cross-group column swaps (the reference's
    permutation_search_kernels do the same exchange moves exhaustively on
    GPU). Returns an int array ``perm`` such that ``w[..., perm]`` is the
    permuted layout.
    """
    import numpy as np

    w2 = np.asarray(jax.device_get(w), np.float64).reshape(-1, w.shape[-1])
    # bound the search cost on huge matrices: a deterministic row
    # subsample drives the SEARCH objective (the final mask is computed on
    # the full matrix either way; the reference's GPU kernels bound cost
    # with a time budget instead)
    max_rows = 4096
    if w2.shape[0] > max_rows:
        stride = -(-w2.shape[0] // max_rows)
        w2 = w2[::stride]
    C = w2.shape[1]
    if C % m:
        raise ValueError(f"channels {C} not divisible by m={m}")
    G = C // m

    order = np.argsort(-np.abs(w2).sum(0), kind="stable")
    perm = np.empty(C, dtype=np.int64)
    for i, c in enumerate(order):
        g, slot = i % G, i // G
        perm[g * m + slot] = c

    if G < 2:
        return perm

    rng = np.random.default_rng(seed)
    cur = w2[:, perm]
    ret = np.array([_group_retained(cur[:, g * m:(g + 1) * m], n)
                    for g in range(G)])

    # chunk candidate evaluation so peak memory stays ~[rows, chunk, m]
    chunk = max(1, min(pairs_per_iter,
                       (8 << 20) // max(1, w2.shape[0] * m * 8)))

    misses = 0
    for _ in range(iters):
        # sample cross-group position pairs (i, j)
        i = rng.integers(0, C, pairs_per_iter)
        j = rng.integers(0, C, pairs_per_iter)
        ok = (i // m) != (j // m)
        i, j = i[ok], j[ok]
        if i.size == 0:
            continue
        gi, gj = i // m, j // m

        def retained(cand):
            s = np.sort(np.abs(cand), axis=2)[:, :, -n:]
            return s.sum(axis=(0, 2))                         # [P]

        delta = np.empty(i.size)
        for c0 in range(0, i.size, chunk):
            sl = slice(c0, min(c0 + chunk, i.size))
            idx_i = gi[sl, None] * m + np.arange(m)[None, :]  # [p, m]
            idx_j = gj[sl, None] * m + np.arange(m)[None, :]
            cand_i = cur[:, idx_i].copy()                     # [rows, p, m]
            cand_j = cur[:, idx_j].copy()
            p_n = idx_i.shape[0]
            cand_i[:, np.arange(p_n), i[sl] % m] = cur[:, j[sl]]
            cand_j[:, np.arange(p_n), j[sl] % m] = cur[:, i[sl]]
            delta[sl] = (retained(cand_i) + retained(cand_j)
                         - ret[gi[sl]] - ret[gj[sl]])
        best = int(np.argmax(delta))
        if delta[best] <= 1e-12:
            misses += 1
            if misses >= 3:
                break
            continue
        misses = 0
        bi, bj = int(i[best]), int(j[best])
        perm[bi], perm[bj] = perm[bj], perm[bi]
        cur[:, [bi, bj]] = cur[:, [bj, bi]]
        for g in (bi // m, bj // m):
            ret[g] = _group_retained(cur[:, g * m:(g + 1) * m], n)
    return perm


def permuted_mn_mask(w, m: int = 4, n: int = 2, **search_kw):
    """n:m mask in w's ORIGINAL layout that is n:m-structured under the
    searched channel permutation (ref permutation_lib.py semantics: the
    reference physically permutes the weights and compensates neighboring
    layers; functionally the inverse-permuted mask retains the identical
    magnitude). Returns (mask, perm).

    Guarantee: the result never retains LESS than the naive (identity
    permutation) mask — the search is heuristic (seeded deal + bounded
    swaps on a row subsample), so the identity layout is kept whenever it
    measures better on the FULL matrix."""
    import numpy as np

    perm = find_channel_permutation(w, m, n, **search_kw)
    mask_p = mn_1d_mask(w[..., perm], m, n)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.size)
    mask = mask_p[..., inv]
    naive = mn_1d_mask(w, m, n)
    if retained_magnitude(w, mask) < retained_magnitude(w, naive):
        return naive, np.arange(perm.size)
    return mask, perm


def retained_magnitude(w, mask) -> float:
    """Total |w| kept by the mask (the permutation-search objective)."""
    return float(jnp.sum(jnp.abs(w) * mask.astype(w.dtype)))


def apply_masks(params, masks):
    """w * mask over the tree (the reference's in-place hook, functional)."""
    return jax.tree_util.tree_map(
        lambda p, m: p * m.astype(p.dtype) if m is not None else p,
        params, masks, is_leaf=lambda x: x is None)


def masked_update(tx: optax.GradientTransformation, masks):
    """Wrap an optax transform so updates AND params stay masked — the
    analog of ASP hooking optimizer.step (ref asp.py:init_optimizer_for_pruning)."""

    def init(params):
        return tx.init(apply_masks(params, masks))

    def update(grads, state, params=None):
        grads = apply_masks(grads, masks)
        updates, state = tx.update(grads, state, params)
        updates = apply_masks(updates, masks)
        return updates, state

    return optax.GradientTransformation(init, update)


class ASP:
    """ref asp.py ASP static class; functional equivalents.

    Usage:
        masks = ASP.compute_sparse_masks(params)       # once, post-warmup
        params = ASP.apply(params, masks)
        tx = ASP.init_optimizer_for_pruning(tx, masks) # masked updates
    """

    @staticmethod
    def _eligible(path: str, leaf) -> bool:
        # ref asp.py whitelist: linear/conv weights, ndim>=2, dims % 4 == 0
        return (hasattr(leaf, "ndim") and leaf.ndim >= 2
                and leaf.shape[-1] % 4 == 0)

    @staticmethod
    def compute_sparse_masks(params, pattern: str = "m4n2_1d",
                             eligible: Optional[Callable] = None,
                             allow_permutation: bool = False,
                             **search_kw):
        """``allow_permutation=True`` runs the channel-permutation search
        per eligible weight (ref asp.py allow_permutation +
        permutation_lib.py) — masks retain >= the naive pattern's
        magnitude, at offline search cost."""
        elig = eligible or ASP._eligible

        if allow_permutation and pattern != "m4n2_1d":
            raise ValueError(
                f"allow_permutation is only implemented for the m4n2_1d "
                f"pattern (got {pattern!r}); the 2d patterns constrain "
                f"both dims, so a column permutation alone cannot "
                f"preserve them")

        def mk(path, leaf):
            name = jax.tree_util.keystr(path)
            if not elig(name, leaf):
                return None
            if allow_permutation:
                mask, _ = permuted_mn_mask(leaf, 4, 2, **search_kw)
                return mask
            return create_mask(leaf, pattern)

        return jax.tree_util.tree_map_with_path(mk, params)

    @staticmethod
    def apply(params, masks):
        return apply_masks(params, masks)

    @staticmethod
    def init_optimizer_for_pruning(tx, masks):
        return masked_update(tx, masks)

    @staticmethod
    def init_model_for_pruning(params, mask_calculator: str = "m4n2_1d",
                               **kw):
        """Returns (params, masks) — functional twist on ref asp.py:61."""
        masks = ASP.compute_sparse_masks(params, mask_calculator)
        return apply_masks(params, masks), masks
