"""Fused conv+bias(+relu/+mask) ops (ref apex/contrib/conv_bias_relu/
conv_bias_relu.py via cudnn fused runner). XLA fuses the epilogue into the
conv on TPU; these entry points pin the exact semantics (NHWC, bias over
channels, optional residual mask).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _conv(x, weight, padding, stride):
    """NHWC conv; weight [kh, kw, cin, cout] (TPU-native layout)."""
    return jax.lax.conv_general_dilated(
        x, weight, window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def ConvBias(x, weight, bias, padding: int = 0, stride: int = 1):
    """ref ConvBias_ (conv_bias_relu.py:56)."""
    return _conv(x, weight, padding, stride) + bias


def ConvBiasReLU(x, weight, bias, padding: int = 0, stride: int = 1):
    """ref ConvBiasReLU_ (conv_bias_relu.py:12)."""
    return jax.nn.relu(ConvBias(x, weight, bias, padding, stride))


def ConvBiasMaskReLU(x, weight, bias, mask, padding: int = 0, stride: int = 1):
    """ref ConvBiasMaskReLU_ (conv_bias_relu.py:34): masked residual add
    before the relu."""
    return jax.nn.relu(ConvBias(x, weight, bias, padding, stride) * mask)


def ConvFrozenScaleBiasReLU(x, weight, scale, bias, padding: int = 0,
                            stride: int = 1):
    """ref conv_bias_relu.py ConvFrozenScaleBiasReLU_: conv then frozen-BN
    affine then relu."""
    return jax.nn.relu(_conv(x, weight, padding, stride) * scale + bias)
