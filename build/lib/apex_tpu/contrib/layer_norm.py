"""FastLayerNorm (ref apex/contrib/layer_norm/layer_norm.py FastLayerNorm,
csrc ln_fwd/bwd kernels) — on TPU this IS the Pallas fused layer norm; the
contrib module re-exports it under the contrib names.
"""

from __future__ import annotations

from apex_tpu.normalization.fused_layer_norm import (
    FusedLayerNorm,
    fused_layer_norm_affine,
)


def fast_layer_norm(x, gamma, beta, epsilon=1e-5):
    """ref layer_norm.py FastLayerNormFN.apply."""
    return fused_layer_norm_affine(x, gamma, beta, (x.shape[-1],),
                                   eps=epsilon)


def FastLayerNorm(hidden_size, epsilon: float = 1e-5) -> FusedLayerNorm:
    """ref layer_norm.py:20 FastLayerNorm module (hidden size only on the
    last dim, always affine) — constructs the Pallas-backed module."""
    return FusedLayerNorm(normalized_shape=(hidden_size,), eps=epsilon)
