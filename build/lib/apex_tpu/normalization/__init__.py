"""Fused normalization (TPU re-design of ``apex.normalization``)."""

from apex_tpu.normalization.fused_layer_norm import (
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
    fused_layer_norm,
    fused_layer_norm_affine,
    fused_rms_norm,
    fused_rms_norm_affine,
    mixed_dtype_fused_layer_norm_affine,
    mixed_dtype_fused_rms_norm_affine,
    manual_rms_norm,
)

__all__ = [
    "FusedLayerNorm", "FusedRMSNorm",
    "MixedFusedLayerNorm", "MixedFusedRMSNorm",
    "fused_layer_norm", "fused_layer_norm_affine",
    "fused_rms_norm", "fused_rms_norm_affine",
    "mixed_dtype_fused_layer_norm_affine", "mixed_dtype_fused_rms_norm_affine",
    "manual_rms_norm",
]
