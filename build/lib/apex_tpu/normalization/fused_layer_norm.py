"""FusedLayerNorm / FusedRMSNorm — TPU re-design of ``apex.normalization``.

Ref: apex/normalization/fused_layer_norm.py (+ csrc/layer_norm_cuda_kernel.cu).

Two API layers, mirroring the reference:
- functional: ``fused_layer_norm[_affine]``, ``fused_rms_norm[_affine]`` and
  the ``mixed_dtype_*`` variants (ref fused_layer_norm.py:168-203);
- modules: ``FusedLayerNorm`` / ``FusedRMSNorm`` (flax.linen, ref :204/:300)
  and the Megatron-style ``MixedFusedLayerNorm`` / ``MixedFusedRMSNorm``
  (ref :398/:420) which keep fp32 affine params under bf16 activations.

The compute path is the single-pass Pallas kernel in
``apex_tpu/ops/layer_norm.py`` (fp32 statistics regardless of input dtype,
like the CUDA kernel's float accumulators).
"""

from __future__ import annotations

import numbers
from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops import layer_norm as _ops

Shape = Union[int, Sequence[int]]


def _canon(normalized_shape: Shape):
    if isinstance(normalized_shape, numbers.Integral):
        return (int(normalized_shape),)
    return tuple(int(s) for s in normalized_shape)


# ----------------------------------------------------------- functional API


def fused_layer_norm_affine(input, weight, bias, normalized_shape, eps=1e-6):
    """Ref apex/normalization/fused_layer_norm.py:168."""
    return _ops.layer_norm(input, weight, bias, _canon(normalized_shape), eps)


def fused_layer_norm(input, normalized_shape, eps=1e-6):
    """Ref apex/normalization/fused_layer_norm.py:174."""
    return _ops.layer_norm(input, None, None, _canon(normalized_shape), eps)


def mixed_dtype_fused_layer_norm_affine(input, weight, bias, normalized_shape, eps=1e-6):
    """Ref apex/normalization/fused_layer_norm.py:180 — bf16 input, fp32 affine."""
    return _ops.layer_norm(input, weight, bias, _canon(normalized_shape), eps)


def fused_rms_norm_affine(input, weight, normalized_shape, eps=1e-6):
    """Ref apex/normalization/fused_layer_norm.py:186."""
    return _ops.rms_norm(input, weight, _canon(normalized_shape), eps)


def fused_rms_norm(input, normalized_shape, eps=1e-6):
    """Ref apex/normalization/fused_layer_norm.py:192."""
    return _ops.rms_norm(input, None, _canon(normalized_shape), eps)


def mixed_dtype_fused_rms_norm_affine(input, weight, normalized_shape, eps=1e-6):
    """Ref apex/normalization/fused_layer_norm.py:198."""
    return _ops.rms_norm(input, weight, _canon(normalized_shape), eps)


def manual_rms_norm(input, normalized_shape, weight, eps):
    """Unfused reference path (ref apex/normalization/fused_layer_norm.py:16)."""
    dims = tuple(range(-len(_canon(normalized_shape)), 0))
    variance = jnp.mean(jnp.square(input.astype(jnp.float32)), axis=dims, keepdims=True)
    out = (input.astype(jnp.float32) * (1.0 / jnp.sqrt(variance + eps))).astype(input.dtype)
    if weight is not None:
        out = weight * out
    return out


# ---------------------------------------------------------------- modules


class FusedLayerNorm(nn.Module):
    """LayerNorm module with the fused kernel (ref fused_layer_norm.py:204).

    Args mirror ``torch.nn.LayerNorm`` / apex: ``normalized_shape``, ``eps``,
    ``elementwise_affine``. ``memory_efficient`` recomputes in backward via
    jax.checkpoint composability (statistics are always re-materialized from
    (mu, rstd), so the default is already activation-light).
    """

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _canon(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, shape, self.param_dtype)
            bias = self.param("bias", nn.initializers.zeros, shape, self.param_dtype)
            return fused_layer_norm_affine(x, weight, bias, shape, self.eps)
        return fused_layer_norm(x, shape, self.eps)


class FusedRMSNorm(nn.Module):
    """RMSNorm module with the fused kernel (ref fused_layer_norm.py:300)."""

    normalized_shape: Shape
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        shape = _canon(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, shape, self.param_dtype)
            return fused_rms_norm_affine(x, weight, shape, self.eps)
        return fused_rms_norm(x, shape, self.eps)


class MixedFusedLayerNorm(FusedLayerNorm):
    """Megatron variant: fp32 affine params under low-precision activations
    (ref fused_layer_norm.py:398). In flax this is simply param_dtype=fp32
    with the kernel handling the dtype mix."""

    param_dtype: jnp.dtype = jnp.float32


class MixedFusedRMSNorm(FusedRMSNorm):
    """Ref fused_layer_norm.py:420."""

    param_dtype: jnp.dtype = jnp.float32
