"""Profiling (TPU re-design of ``apex.pyprof``; ref apex/pyprof/*).

The reference has three parts: nvtx instrumentation
(apex/pyprof/nvtx/nvmarker.py), an nvprof-database parser
(apex/pyprof/parse/parse.py) and a per-op flops/bytes report
(apex/pyprof/prof/prof.py). The TPU analogs:

- instrumentation (this module): ``jax.profiler`` annotations under the
  pyprof API names (``init``, ``nvtx.range_push/pop``, ``wrap``) so
  reference-style instrumentation ports unchanged; traces land in
  TensorBoard/Perfetto instead of nvprof;
- :mod:`apex_tpu.pyprof.parse` — xplane capture → per-op records with
  exclusive-time attribution;
- :mod:`apex_tpu.pyprof.prof` — records → per-op / per-category report
  (flops, bytes and roofline bound merged from the capture when a
  device plane is present). CLI: ``tools/trace_report.py``.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Optional

import jax

from apex_tpu.pyprof import parse, prof  # noqa: F401 (re-export)
from apex_tpu.pyprof.prof import Report  # noqa: F401

_enabled = False
_trace_dir: Optional[str] = None


def init(enable_trace: bool = True, trace_dir: str = "/tmp/apex_tpu_trace"):
    """ref apex/pyprof/nvtx/nvmarker.py init: start instrumentation."""
    global _enabled, _trace_dir
    _enabled = enable_trace
    _trace_dir = trace_dir


def start():
    """Begin a profiler trace (analog of cuda profiler start)."""
    if _enabled and _trace_dir:
        jax.profiler.start_trace(_trace_dir)


def stop():
    if _enabled and _trace_dir:
        jax.profiler.stop_trace()


class nvtx:
    """nvtx-shaped annotation API; ranges become XLA trace annotations."""

    _stack = []

    @staticmethod
    def range_push(name: str):
        ctx = jax.profiler.TraceAnnotation(name)
        ctx.__enter__()
        nvtx._stack.append(ctx)

    @staticmethod
    def range_pop():
        if nvtx._stack:
            nvtx._stack.pop().__exit__(None, None, None)


@contextlib.contextmanager
def annotate(name: str):
    with jax.profiler.TraceAnnotation(name):
        yield


def wrap(fn, name: Optional[str] = None):
    """Decorate ``fn`` so every call is an annotated range (ref pyprof wraps
    torch functions module-wide; explicit opt-in here)."""
    label = name or getattr(fn, "__name__", "fn")

    @functools.wraps(fn)
    def wrapped(*a, **kw):
        with jax.profiler.TraceAnnotation(label):
            return fn(*a, **kw)

    return wrapped
