"""GPT-2 family — Megatron-style TP transformer with learned positions.

Corresponds to the reference's GPT-2 345M benchmark config (Apex transformer
primitives assembled Megatron-LM-style: fused softmax + LayerNorm + TP linear
layers — ref apex/transformer/tensor_parallel/layers.py,
apex/transformer/functional/fused_softmax.py). Same functional conventions
as :mod:`apex_tpu.models.llama`: stacked [L, ...] layer params under
``lax.scan``, collectives no-op when the tp axis is unbound.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.models._common import (
    fan_in_normal,
    layer_norm,
    packed_mlp,
    packed_qkv_attention,
)

from apex_tpu.transformer.functional.fused_softmax import (
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    _axis_bound,
)
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    vocab_parallel_embedding,
)


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304  # 50257 padded to a tp/128-friendly multiple
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    max_seq_len: int = 1024
    ln_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def gpt2_345m(**over) -> GPT2Config:
    return GPT2Config(**over)


def tiny(**over) -> GPT2Config:
    kw = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
              max_seq_len=64, dtype=jnp.float32)
    kw.update(over)
    return GPT2Config(**kw)


def init_params(key, cfg: GPT2Config):
    h, L = cfg.hidden_size, cfg.num_layers
    dt = cfg.dtype
    ks = jax.random.split(key, 8)

    def norm(k, *shape, fan_in=None):
        return fan_in_normal(k, *shape, fan_in=fan_in, dtype=dt)

    return {
        "embed": norm(ks[0], cfg.vocab_size, h, fan_in=h),
        "pos_embed": norm(ks[1], cfg.max_seq_len, h, fan_in=h),
        "layers": {
            "ln1_w": jnp.ones((L, h), dt), "ln1_b": jnp.zeros((L, h), dt),
            # packed qkv, [L, h, 3, h] so P(..., 'tp') on the LAST dim
            # shards each of q/k/v by heads (Megatron packing, ref
            # tensor_parallel/layers.py ColumnParallelLinear qkv use)
            "wqkv": norm(ks[2], L, h, 3, h, fan_in=h),
            "bqkv": jnp.zeros((L, 3, h), dt),
            "wo": norm(ks[3], L, h, h), "bo": jnp.zeros((L, h), dt),
            "ln2_w": jnp.ones((L, h), dt), "ln2_b": jnp.zeros((L, h), dt),
            "wfc": norm(ks[4], L, h, 4 * h), "bfc": jnp.zeros((L, 4 * h), dt),
            "wproj": norm(ks[5], L, 4 * h, h), "bproj": jnp.zeros((L, h), dt),
        },
        "lnf_w": jnp.ones((h,), dt), "lnf_b": jnp.zeros((h,), dt),
    }


def param_specs(cfg: GPT2Config, tp_axis: str = "tp"):
    """tp PartitionSpec pytree matching :func:`init_params`."""
    from jax.sharding import PartitionSpec as P

    t = tp_axis
    return {
        "embed": P(t, None), "pos_embed": P(),
        "layers": {
            "ln1_w": P(), "ln1_b": P(),
            "wqkv": P(None, None, None, t), "bqkv": P(None, None, t),
            "wo": P(None, t, None), "bo": P(),
            "ln2_w": P(), "ln2_b": P(),
            "wfc": P(None, None, t), "bfc": P(None, t),
            "wproj": P(None, t, None), "bproj": P(),
        },
        "lnf_w": P(), "lnf_b": P(),
    }


_ln = layer_norm


def _causal_softmax(scores, scale):
    b, n, s, sk = scores.shape
    return scaled_upper_triang_masked_softmax(
        scores.reshape(b * n, s, sk), None, scale
    ).reshape(b, n, s, sk)


def _attention(x, lp, cfg: GPT2Config, tp_axis):
    return packed_qkv_attention(x, lp, cfg.num_heads, cfg.head_dim,
                                _causal_softmax, tp_axis)


def _mlp(x, lp, tp_axis):
    return packed_mlp(x, lp, lambda y: jax.nn.gelu(y, approximate=True),
                      tp_axis)


def decoder_layer(x, lp, cfg: GPT2Config, tp_axis: Optional[str] = "tp"):
    x = x + _attention(_ln(x, lp["ln1_w"], lp["ln1_b"], cfg.ln_eps), lp, cfg,
                       tp_axis)
    x = x + _mlp(_ln(x, lp["ln2_w"], lp["ln2_b"], cfg.ln_eps), lp, tp_axis)
    return x


def hidden_states(params, tokens, cfg: GPT2Config,
                  tp_axis: Optional[str] = "tp", remat: bool = True):
    """Shared trunk: embeddings + layers + final LN (pre-head)."""
    b, s = tokens.shape
    x = vocab_parallel_embedding(tokens, params["embed"], axis_name=tp_axis)
    x = (x + params["pos_embed"][None, :s]).astype(cfg.dtype)

    def body(h, lp):
        return decoder_layer(h, lp, cfg, tp_axis), None

    if remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    return _ln(x, params["lnf_w"], params["lnf_b"], cfg.ln_eps)


def forward(params, tokens, cfg: GPT2Config, tp_axis: Optional[str] = "tp",
            remat: bool = True):
    """tokens [b, s] → vocab-sharded logits [b, s, v_local] (tied head)."""
    x = hidden_states(params, tokens, cfg, tp_axis, remat)
    # tied embedding head → vocab-sharded logits (embed rows are the shard)
    return jnp.matmul(x, params["embed"].T.astype(x.dtype)).astype(jnp.float32)


def loss_fn(params, batch, cfg: GPT2Config, tp_axis: Optional[str] = "tp",
            remat: bool = True, vocab_chunks: Optional[int] = None):
    """Next-token CE; ``vocab_chunks`` streams the tied head + CE so the
    fp32 [b·s, vocab] logits never materialize (functional/chunked_ce.py)."""
    tokens, targets = batch
    if vocab_chunks:
        from apex_tpu.transformer.functional.chunked_ce import (
            chunked_lm_cross_entropy,
        )

        x = hidden_states(params, tokens, cfg, tp_axis, remat)
        losses = chunked_lm_cross_entropy(
            x.reshape(-1, x.shape[-1]), params["embed"].T,
            targets.reshape(-1), vocab_chunks,
            tp_axis=tp_axis if _axis_bound(tp_axis) else None)
        return jnp.mean(losses)
    logits = forward(params, tokens, cfg, tp_axis, remat)
    return jnp.mean(
        vocab_parallel_cross_entropy(logits, targets, axis_name=tp_axis)
    )
