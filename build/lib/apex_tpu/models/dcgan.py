"""DCGAN generator/discriminator — the reference's mixed-precision GAN
example (ref examples/dcgan/main_amp.py), exercising amp with MULTIPLE
models/optimizers/losses (the amp.initialize list-of-models path).

NHWC flax modules; transposed convs for G, strided convs for D.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.models._common import BatchNorm


class Generator(nn.Module):
    latent_dim: int = 100
    width: int = 64
    out_channels: int = 3
    sync_bn: bool = False
    axis_name: Optional[str] = "data"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, z, train: bool = True):
        """z [b, latent] → image [b, 32, 32, c] in (-1, 1)."""
        w = self.width
        x = nn.Dense(4 * 4 * w * 4, dtype=self.dtype)(z.astype(self.dtype))
        x = x.reshape(x.shape[0], 4, 4, w * 4)
        for mult in (2, 1):
            x = nn.relu(BatchNorm(sync=self.sync_bn, axis_name=self.axis_name)(
                x, train))
            x = nn.ConvTranspose(w * mult, (4, 4), strides=(2, 2),
                                 dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(sync=self.sync_bn, axis_name=self.axis_name)(x, train))
        x = nn.ConvTranspose(self.out_channels, (4, 4), strides=(2, 2),
                             dtype=self.dtype)(x)
        return jnp.tanh(x)


class Discriminator(nn.Module):
    width: int = 64
    sync_bn: bool = False
    axis_name: Optional[str] = "data"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        """image [b, 32, 32, c] → logit [b]."""
        x = x.astype(self.dtype)
        for i, mult in enumerate((1, 2, 4)):
            x = nn.Conv(self.width * mult, (4, 4), strides=(2, 2),
                        dtype=self.dtype)(x)
            if i > 0:
                x = BatchNorm(sync=self.sync_bn, axis_name=self.axis_name)(x, train)
            x = nn.leaky_relu(x, 0.2)
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(1, dtype=jnp.float32)(x)[:, 0]
