"""BERT family — bidirectional encoder matching the reference's BERT-base
FusedLAMB + FusedLayerNorm benchmark config (ref BASELINE; primitives from
apex/normalization/fused_layer_norm.py and apex.optimizers.FusedLAMB).

Functional conventions match :mod:`apex_tpu.models.llama`; attention is
bidirectional with an optional padding mask through
``scaled_masked_softmax`` (ref apex/transformer/functional/fused_softmax.py:94).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.models._common import (
    fan_in_normal,
    layer_norm,
    packed_mlp,
    packed_qkv_attention,
)

from apex_tpu.transformer.functional.fused_softmax import scaled_masked_softmax
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    vocab_parallel_embedding,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30528  # 30522 padded for tp/tile divisibility
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 512
    num_types: int = 2
    ln_eps: float = 1e-12
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def bert_base(**over) -> BertConfig:
    return BertConfig(**over)


def tiny(**over) -> BertConfig:
    kw = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
              max_seq_len=64, dtype=jnp.float32)
    kw.update(over)
    return BertConfig(**kw)


def init_params(key, cfg: BertConfig):
    h, L = cfg.hidden_size, cfg.num_layers
    dt = cfg.dtype
    ks = jax.random.split(key, 8)

    def norm(k, *shape, fan_in=None):
        return fan_in_normal(k, *shape, fan_in=fan_in, dtype=dt)

    return {
        "embed": norm(ks[0], cfg.vocab_size, h, fan_in=h),
        "pos_embed": norm(ks[1], cfg.max_seq_len, h, fan_in=h),
        "type_embed": norm(ks[2], cfg.num_types, h, fan_in=h),
        "emb_ln_w": jnp.ones((h,), dt), "emb_ln_b": jnp.zeros((h,), dt),
        "layers": {
            "wqkv": norm(ks[3], L, h, 3, h, fan_in=h),
            "bqkv": jnp.zeros((L, 3, h), dt),
            "wo": norm(ks[4], L, h, h), "bo": jnp.zeros((L, h), dt),
            "ln1_w": jnp.ones((L, h), dt), "ln1_b": jnp.zeros((L, h), dt),
            "wfc": norm(ks[5], L, h, 4 * h), "bfc": jnp.zeros((L, 4 * h), dt),
            "wproj": norm(ks[6], L, 4 * h, h), "bproj": jnp.zeros((L, h), dt),
            "ln2_w": jnp.ones((L, h), dt), "ln2_b": jnp.zeros((L, h), dt),
        },
        "mlm_dense": norm(ks[7], h, h),
        "mlm_bias": jnp.zeros((h,), dt),
        "mlm_ln_w": jnp.ones((h,), dt), "mlm_ln_b": jnp.zeros((h,), dt),
    }


def param_specs(cfg: BertConfig, tp_axis: str = "tp",
                with_decoder_bias: bool = False):
    """tp PartitionSpec pytree matching :func:`init_params`
    (``with_decoder_bias`` adds the HF-imported ``mlm_decoder_bias``
    entry, models/convert.py)."""
    from jax.sharding import PartitionSpec as P

    t = tp_axis
    # the decoder bias adds onto the vocab-LOCAL logits → vocab-sharded
    extra = {"mlm_decoder_bias": P(t)} if with_decoder_bias else {}
    return {**extra,
        "embed": P(t, None), "pos_embed": P(), "type_embed": P(),
        "emb_ln_w": P(), "emb_ln_b": P(),
        "layers": {
            "wqkv": P(None, None, None, t), "bqkv": P(None, None, t),
            "wo": P(None, t, None), "bo": P(),
            "ln1_w": P(), "ln1_b": P(),
            "wfc": P(None, None, t), "bfc": P(None, t),
            "wproj": P(None, t, None), "bproj": P(),
            "ln2_w": P(), "ln2_b": P(),
        },
        "mlm_dense": P(), "mlm_bias": P(),
        "mlm_ln_w": P(), "mlm_ln_b": P(),
    }


_ln = layer_norm


def _attention(x, lp, cfg: BertConfig, pad_mask, tp_axis):
    def padding_softmax(scores, scale):
        # mask: True = masked-out key (ref scaled_masked_softmax semantics)
        mask = None if pad_mask is None else pad_mask[:, None, None, :]
        return scaled_masked_softmax(scores, mask, scale)

    return packed_qkv_attention(x, lp, cfg.num_heads, cfg.head_dim,
                                padding_softmax, tp_axis)


def _mlp(x, lp, tp_axis):
    return packed_mlp(x, lp, lambda y: jax.nn.gelu(y, approximate=False),
                      tp_axis)


def encoder_layer(x, lp, cfg: BertConfig, pad_mask,
                  tp_axis: Optional[str] = "tp"):
    """Post-norm block (original BERT residual order)."""
    x = _ln(x + _attention(x, lp, cfg, pad_mask, tp_axis),
            lp["ln1_w"], lp["ln1_b"], cfg.ln_eps)
    x = _ln(x + _mlp(x, lp, tp_axis), lp["ln2_w"], lp["ln2_b"], cfg.ln_eps)
    return x


def forward(params, tokens, cfg: BertConfig, type_ids=None, pad_mask=None,
            tp_axis: Optional[str] = "tp", remat: bool = True):
    """tokens [b, s] → hidden states [b, s, h]."""
    b, s = tokens.shape
    x = vocab_parallel_embedding(tokens, params["embed"], axis_name=tp_axis)
    x = x + params["pos_embed"][None, :s]
    if type_ids is None:
        x = x + params["type_embed"][0]
    else:
        x = x + jnp.take(params["type_embed"], type_ids, axis=0)
    x = _ln(x.astype(cfg.dtype), params["emb_ln_w"], params["emb_ln_b"],
            cfg.ln_eps)

    def body(h, lp):
        return encoder_layer(h, lp, cfg, pad_mask, tp_axis), None

    if remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    return x


def mlm_transform(params, hidden, cfg: BertConfig):
    """The pre-decoder MLM head transform: dense + gelu + LN."""
    x = jnp.matmul(hidden, params["mlm_dense"].astype(hidden.dtype))
    x = jax.nn.gelu(x + params["mlm_bias"], approximate=False)
    return _ln(x, params["mlm_ln_w"], params["mlm_ln_b"], cfg.ln_eps)


def mlm_logits(params, hidden, cfg: BertConfig,
               tp_axis: Optional[str] = "tp"):
    """Masked-LM head: dense+gelu+LN, tied decoder → [b, s, v_local].
    An optional ``mlm_decoder_bias`` [vocab] (HF BERT's
    cls.predictions.bias) adds per-vocab offsets when present."""
    x = mlm_transform(params, hidden, cfg)
    logits = jnp.matmul(
        x, params["embed"].T.astype(x.dtype)).astype(jnp.float32)
    if "mlm_decoder_bias" in params:
        logits = logits + params["mlm_decoder_bias"].astype(jnp.float32)
    return logits


def loss_fn(params, batch, cfg: BertConfig, type_ids=None, pad_mask=None,
            tp_axis: Optional[str] = "tp", remat: bool = True,
            vocab_chunks: Optional[int] = None):
    """MLM loss; ``batch = (tokens, targets, loss_mask)`` — loss_mask selects
    the masked positions (targets elsewhere are ignored). ``pad_mask``
    (True = padding) masks attention; the loss_mask only masks the CE sum.
    ``vocab_chunks`` streams the tied decoder + CE without materializing
    the fp32 [b·s, vocab] logits (functional/chunked_ce.py)."""
    tokens, targets, loss_mask = batch
    hidden = forward(params, tokens, cfg, type_ids=type_ids,
                     pad_mask=pad_mask, tp_axis=tp_axis, remat=remat)
    if vocab_chunks:
        from apex_tpu.transformer.functional.chunked_ce import (
            chunked_lm_cross_entropy,
        )
        from apex_tpu.transformer.tensor_parallel.mappings import (
            _axis_bound,
        )

        x = mlm_transform(params, hidden, cfg)
        losses = chunked_lm_cross_entropy(
            x.reshape(-1, x.shape[-1]), params["embed"].T,
            targets.reshape(-1), vocab_chunks,
            tp_axis=tp_axis if _axis_bound(tp_axis) else None,
            bias=params.get("mlm_decoder_bias"))
        losses = losses.reshape(targets.shape)
    else:
        logits = mlm_logits(params, hidden, cfg, tp_axis)
        losses = vocab_parallel_cross_entropy(logits, targets,
                                              axis_name=tp_axis)
    denom = jnp.maximum(jnp.sum(loss_mask), 1.0)
    return jnp.sum(losses * loss_mask) / denom
