"""Model zoo for the reference's benchmark configs (SURVEY.md §2 #52):
llama (flagship), gpt2, bert, resnet, mlp, dcgan."""

from apex_tpu.models import bert, dcgan, gpt2, llama, mlp, resnet

__all__ = ["bert", "dcgan", "gpt2", "llama", "mlp", "resnet"]
