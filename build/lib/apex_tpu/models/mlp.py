"""Simple MLP — the reference's `apex.mlp.MLP` benchmark model and the O1
"simple" example config (ref apex/mlp/mlp.py, examples/simple).

The fused forward lives in :mod:`apex_tpu.mlp` (dense-bias-act chain); this
module is the model-zoo wrapper used by tests/bench/examples.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from apex_tpu.models._common import fan_in_normal


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    sizes: Sequence[int] = (784, 1024, 1024, 10)
    activation: str = "relu"  # relu | sigmoid | none (ref mlp.py activation)
    bias: bool = True
    dtype: jnp.dtype = jnp.float32


def init_params(key, cfg: MLPConfig):
    ks = jax.random.split(key, len(cfg.sizes) - 1)
    layers = []
    for k, fan_in, fan_out in zip(ks, cfg.sizes[:-1], cfg.sizes[1:]):
        w = fan_in_normal(k, fan_in, fan_out, dtype=cfg.dtype)
        layer = {"w": w}
        if cfg.bias:
            layer["b"] = jnp.zeros((fan_out,), cfg.dtype)
        layers.append(layer)
    return {"layers": layers}


def _act(x, name: str):
    if name == "relu":
        return jax.nn.relu(x)
    if name == "sigmoid":
        return jax.nn.sigmoid(x)
    if name == "none":
        return x
    raise ValueError(f"unknown activation {name!r} (relu|sigmoid|none)")


def forward(params, x, cfg: MLPConfig):
    n = len(params["layers"])
    for i, layer in enumerate(params["layers"]):
        x = jnp.matmul(x, layer["w"])
        if "b" in layer:
            x = x + layer["b"]
        if i < n - 1:
            x = _act(x, cfg.activation)
    return x


def loss_fn(params, batch, cfg: MLPConfig):
    """Softmax CE on integer labels; ``batch = (x, y)``."""
    x, y = batch
    logits = forward(params, x, cfg).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
