"""HuggingFace checkpoint import for the model zoo.

No reference analog (apex assumes you already hold torch modules); on
TPU the practical entry point to real weights is a HF checkpoint, so
each LM family gets a converter from ``transformers`` state dicts to the
apex_tpu functional param trees. Conventions verified by logit-parity
tests against the torch reference implementations
(tests/run_models/test_hf_convert.py):

- llama: HF ``rotate_half`` RoPE == functional/rope.py; torch Linear
  stores [out, in] → kernels transpose; per-layer tensors stack on dim 0.
- gpt2: HF Conv1D already stores [in, out] → no transpose; c_attn's
  packed q|k|v [h, 3h] reshapes straight into our wqkv [h, 3, h].

Pass a ``transformers`` model (weights read via ``state_dict()``) or any
mapping of parameter names to array-likes.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from apex_tpu.models import gpt2 as _gpt2
from apex_tpu.models import llama as _llama

__all__ = [
    "bert_config_from_hf",
    "bert_from_hf",
    "llama_config_from_hf",
    "llama_from_hf",
    "gpt2_config_from_hf",
    "gpt2_from_hf",
]


def _state_dict(model_or_sd) -> Mapping[str, Any]:
    sd = (model_or_sd.state_dict() if hasattr(model_or_sd, "state_dict")
          else model_or_sd)

    def to_np(t):
        if hasattr(t, "detach"):
            t = t.detach().cpu().float().numpy()
        return np.asarray(t)

    return {k: to_np(v) for k, v in sd.items()}


def _stack(sd, fmt, n_layers, transpose=False):
    mats = [sd[fmt.format(i)] for i in range(n_layers)]
    if transpose:
        mats = [m.T for m in mats]
    return np.stack(mats)


# ------------------------------------------------------------------ llama


def llama_config_from_hf(hf_config) -> "_llama.LlamaConfig":
    return _llama.LlamaConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=(hf_config.num_key_value_heads
                      or hf_config.num_attention_heads),
        max_seq_len=hf_config.max_position_embeddings,
        rms_eps=hf_config.rms_norm_eps,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings",
                                    False)),
    )


def llama_from_hf(model_or_sd, cfg: "_llama.LlamaConfig" = None,
                  dtype=None):
    """HF ``LlamaForCausalLM`` (or its state dict) → ``(params, cfg)``."""
    if cfg is None:
        cfg = llama_config_from_hf(model_or_sd.config)
    if dtype is not None:
        import dataclasses

        cfg = dataclasses.replace(cfg, dtype=dtype)
    sd = _state_dict(model_or_sd)
    L = cfg.num_layers
    p = "model.layers.{}."
    layers = {
        "attn_norm": _stack(sd, p + "input_layernorm.weight", L),
        "wq": _stack(sd, p + "self_attn.q_proj.weight", L, transpose=True),
        "wk": _stack(sd, p + "self_attn.k_proj.weight", L, transpose=True),
        "wv": _stack(sd, p + "self_attn.v_proj.weight", L, transpose=True),
        "wo": _stack(sd, p + "self_attn.o_proj.weight", L, transpose=True),
        "mlp_norm": _stack(sd, p + "post_attention_layernorm.weight", L),
        "wg": _stack(sd, p + "mlp.gate_proj.weight", L, transpose=True),
        "wu": _stack(sd, p + "mlp.up_proj.weight", L, transpose=True),
        "wd": _stack(sd, p + "mlp.down_proj.weight", L, transpose=True),
    }
    params = {
        "embed": sd["model.embed_tokens.weight"],
        "layers": layers,
        "final_norm": sd["model.norm.weight"],
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = sd["lm_head.weight"].T
    import jax

    params = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, cfg.dtype), params)
    return params, cfg


# ------------------------------------------------------------------- gpt2


def gpt2_config_from_hf(hf_config) -> "_gpt2.GPT2Config":
    return _gpt2.GPT2Config(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.n_embd,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        max_seq_len=hf_config.n_positions,
        ln_eps=hf_config.layer_norm_epsilon,
    )


def gpt2_from_hf(model_or_sd, cfg: "_gpt2.GPT2Config" = None, dtype=None):
    """HF ``GPT2LMHeadModel`` (or its state dict) → ``(params, cfg)``."""
    import jax

    if cfg is None:
        cfg = gpt2_config_from_hf(model_or_sd.config)
    if dtype is not None:
        import dataclasses

        cfg = dataclasses.replace(cfg, dtype=dtype)
    sd = _state_dict(model_or_sd)
    sd = {k.removeprefix("transformer."): v for k, v in sd.items()}
    L, h = cfg.num_layers, cfg.hidden_size
    p = "h.{}."
    layers = {
        "ln1_w": _stack(sd, p + "ln_1.weight", L),
        "ln1_b": _stack(sd, p + "ln_1.bias", L),
        # Conv1D stores [in, out]: c_attn [h, 3h] → [h, 3, h] is exactly
        # our packed q|k|v layout
        "wqkv": _stack(sd, p + "attn.c_attn.weight", L).reshape(L, h, 3, h),
        "bqkv": _stack(sd, p + "attn.c_attn.bias", L).reshape(L, 3, h),
        "wo": _stack(sd, p + "attn.c_proj.weight", L),
        "bo": _stack(sd, p + "attn.c_proj.bias", L),
        "ln2_w": _stack(sd, p + "ln_2.weight", L),
        "ln2_b": _stack(sd, p + "ln_2.bias", L),
        "wfc": _stack(sd, p + "mlp.c_fc.weight", L),
        "bfc": _stack(sd, p + "mlp.c_fc.bias", L),
        "wproj": _stack(sd, p + "mlp.c_proj.weight", L),
        "bproj": _stack(sd, p + "mlp.c_proj.bias", L),
    }
    params = {
        "embed": sd["wte.weight"],
        "pos_embed": sd["wpe.weight"],
        "layers": layers,
        "lnf_w": sd["ln_f.weight"],
        "lnf_b": sd["ln_f.bias"],
    }
    params = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, cfg.dtype), params)
    return params, cfg


# ------------------------------------------------------------------- bert


def bert_config_from_hf(hf_config):
    from apex_tpu.models import bert as _bert

    return _bert.BertConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        max_seq_len=hf_config.max_position_embeddings,
        num_types=hf_config.type_vocab_size,
        ln_eps=hf_config.layer_norm_eps,
    )


def bert_from_hf(model_or_sd, cfg=None, dtype=None):
    """HF ``BertForMaskedLM`` (or its state dict) → ``(params, cfg)``.
    The decoder bias (cls.predictions.bias) lands as
    ``mlm_decoder_bias``."""
    import jax

    if cfg is None:
        cfg = bert_config_from_hf(model_or_sd.config)
    if dtype is not None:
        import dataclasses

        cfg = dataclasses.replace(cfg, dtype=dtype)
    sd = _state_dict(model_or_sd)
    L = cfg.num_layers
    p = "bert.encoder.layer.{}."

    def qkv(i):
        mats = [sd[p.format(i) + f"attention.self.{n}.weight"].T
                for n in ("query", "key", "value")]
        return np.stack(mats, axis=1)            # [h, 3, h]

    def bqkv(i):
        return np.stack([sd[p.format(i) + f"attention.self.{n}.bias"]
                         for n in ("query", "key", "value")])

    layers = {
        "wqkv": np.stack([qkv(i) for i in range(L)]),
        "bqkv": np.stack([bqkv(i) for i in range(L)]),
        "wo": _stack(sd, p + "attention.output.dense.weight", L,
                     transpose=True),
        "bo": _stack(sd, p + "attention.output.dense.bias", L),
        "ln1_w": _stack(sd, p + "attention.output.LayerNorm.weight", L),
        "ln1_b": _stack(sd, p + "attention.output.LayerNorm.bias", L),
        "wfc": _stack(sd, p + "intermediate.dense.weight", L,
                      transpose=True),
        "bfc": _stack(sd, p + "intermediate.dense.bias", L),
        "wproj": _stack(sd, p + "output.dense.weight", L, transpose=True),
        "bproj": _stack(sd, p + "output.dense.bias", L),
        "ln2_w": _stack(sd, p + "output.LayerNorm.weight", L),
        "ln2_b": _stack(sd, p + "output.LayerNorm.bias", L),
    }
    params = {
        "embed": sd["bert.embeddings.word_embeddings.weight"],
        "pos_embed": sd["bert.embeddings.position_embeddings.weight"],
        "type_embed": sd["bert.embeddings.token_type_embeddings.weight"],
        "emb_ln_w": sd["bert.embeddings.LayerNorm.weight"],
        "emb_ln_b": sd["bert.embeddings.LayerNorm.bias"],
        "layers": layers,
        "mlm_dense": sd["cls.predictions.transform.dense.weight"].T,
        "mlm_bias": sd["cls.predictions.transform.dense.bias"],
        "mlm_ln_w": sd["cls.predictions.transform.LayerNorm.weight"],
        "mlm_ln_b": sd["cls.predictions.transform.LayerNorm.bias"],
        "mlm_decoder_bias": sd["cls.predictions.bias"],
    }
    params = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, cfg.dtype), params)
    return params, cfg
