"""ResNet family — the reference's imagenet example model (ResNet-50 with
amp O2 + DDP + optional SyncBatchNorm; ref examples/imagenet/main_amp.py,
apex/parallel/sync_batchnorm.py).

Flax linen modules (convs are stateful-ish with BN running stats, so the
module abstraction earns its keep here, unlike the functional transformer
families). NHWC layout — the TPU-native conv layout XLA tiles best.
``sync_bn=True`` swaps plain BatchNorm for the cross-replica Welford
:class:`apex_tpu.parallel.SyncBatchNorm` over the 'data'/'dp' mesh axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.models._common import BatchNorm


class Bottleneck(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck (the reference's contrib/bottleneck fused
    block is the CUDA fusion of exactly this; XLA fuses it on TPU).

    ``stride_1x1`` moves the downsampling stride from the 3x3 (ResNet
    v1.5, the default here) onto the first 1x1 (v1 — ref
    contrib/bottleneck/bottleneck.py ``stride_1x1``). The spatially-sharded
    :class:`apex_tpu.contrib.bottleneck.SpatialBottleneck` always uses the
    v1 placement (a strided per-shard 3x3 would break the halo phase), so
    build the plain block with ``stride_1x1=True`` when parity with the
    spatial variant matters.
    """
    features: int
    strides: Tuple[int, int] = (1, 1)
    sync_bn: bool = False
    axis_name: Optional[str] = "data"
    stride_1x1: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        bn = partial(BatchNorm, sync=self.sync_bn, axis_name=self.axis_name)
        conv = partial(nn.Conv, use_bias=False, dtype=x.dtype)
        residual = x
        s1 = self.strides if self.stride_1x1 else (1, 1)
        s3 = (1, 1) if self.stride_1x1 else self.strides
        y = conv(self.features, (1, 1), strides=s1)(x)
        y = nn.relu(bn()(y, train))
        y = conv(self.features, (3, 3), strides=s3)(y)
        y = nn.relu(bn()(y, train))
        y = conv(self.features * 4, (1, 1))(y)
        y = bn()(y, train)
        if residual.shape != y.shape:
            residual = conv(self.features * 4, (1, 1),
                            strides=self.strides)(residual)
            residual = bn()(residual, train)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    num_classes: int = 1000
    width: int = 64
    sync_bn: bool = False
    axis_name: Optional[str] = "data"
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.relu(BatchNorm(sync=self.sync_bn,
                               axis_name=self.axis_name)(x, train))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = Bottleneck(self.width * 2 ** i, strides,
                               self.sync_bn, self.axis_name)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def resnet50(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), **kw)


def resnet101(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 23, 3), **kw)


def tiny(**kw) -> ResNet:
    """Test-scale: one block per stage, width 8, fp32."""
    kw.setdefault("stage_sizes", (1, 1))
    kw.setdefault("width", 8)
    kw.setdefault("num_classes", 10)
    kw.setdefault("dtype", jnp.float32)
    return ResNet(**kw)
