"""Shared model-zoo scaffolding: init helpers and the BatchNorm switch."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


def fan_in_normal(key, *shape, fan_in=None, dtype=jnp.float32):
    """N(0, 1/fan_in) init (fan_in defaults to the second-to-last dim)."""
    scale = (fan_in if fan_in is not None else shape[-2]) ** -0.5
    return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)


class BatchNorm(nn.Module):
    """Plain flax BatchNorm or cross-replica :class:`SyncBatchNorm`.

    ``momentum`` uses the flax convention (fraction of the running stat
    KEPT each step); SyncBatchNorm follows the torch convention (fraction
    REPLACED, ref apex/parallel/sync_batchnorm.py), so it gets ``1 - m`` —
    the same inversion ``convert_syncbn_model`` applies.
    """

    sync: bool = False
    axis_name: Optional[str] = "data"
    momentum: float = 0.9
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool):
        if self.sync:
            return SyncBatchNorm(momentum=1.0 - self.momentum, eps=self.eps,
                                 axis_name=self.axis_name)(
                x, use_running_average=not train)
        return nn.BatchNorm(use_running_average=not train,
                            momentum=self.momentum, epsilon=self.eps,
                            dtype=x.dtype)(x)


# --------------------------------------------------- shared transformer bits

from apex_tpu.normalization.fused_layer_norm import fused_layer_norm_affine
from apex_tpu.transformer.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
)
from apex_tpu.transformer.tensor_parallel.mappings import _axis_bound


def layer_norm(x, w, b, eps):
    return fused_layer_norm_affine(x, w, b, (x.shape[-1],), eps=eps)


def tp_size(tp_axis) -> int:
    import jax.lax

    return jax.lax.axis_size(tp_axis) if _axis_bound(tp_axis) else 1


def packed_qkv_attention(x, lp, num_heads, head_dim, softmax_fn, tp_axis):
    """Megatron packed-qkv attention shared by the gpt2/bert families.

    ``lp`` carries wqkv [h, 3, h] / bqkv [3, h] / wo / bo; sharding the LAST
    dim of wqkv with P(..., 'tp') gives each rank its heads of all of q, k
    and v, so the flattened local kernel is q|k|v blocks and a thirds-split
    of the local gemm output is exact. ``softmax_fn(scores, scale) -> probs``
    injects the mask flavour (causal for gpt2, padding for bert).
    """
    b, s, h = x.shape
    n = num_heads // tp_size(tp_axis)
    d = head_dim

    w = lp["wqkv"].reshape(h, -1)   # local [h, 3·h/tp]: q|k|v blocks
    qkv = column_parallel_linear(x, w, lp["bqkv"].reshape(-1),
                                 gather_output=False, axis_name=tp_axis)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, n, d)
    k = k.reshape(b, s, n, d)
    v = v.reshape(b, s, n, d)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    probs = softmax_fn(scores, d ** -0.5).astype(v.dtype)
    o = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, s, n * d)
    return row_parallel_linear(o, lp["wo"], lp["bo"], input_is_parallel=True,
                               axis_name=tp_axis)


def packed_mlp(x, lp, act_fn, tp_axis):
    """fc -> act -> proj with column/row tensor parallelism."""
    y = column_parallel_linear(x, lp["wfc"], lp["bfc"], gather_output=False,
                               axis_name=tp_axis)
    return row_parallel_linear(act_fn(y), lp["wproj"], lp["bproj"],
                               input_is_parallel=True, axis_name=tp_axis)
