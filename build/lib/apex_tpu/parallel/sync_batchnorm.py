"""SyncBatchNorm — TPU re-design of ``apex.parallel.sync_batchnorm``.

Ref: apex/parallel/{sync_batchnorm,optimized_sync_batchnorm}.py +
csrc/{syncbn.cpp,welford.cu}.

The reference's optimized path fuses a per-GPU Welford reduction with an
NCCL allreduce of (mean, var, count) — ``welford.cu`` exists precisely
because E[x²]−E[x]² cancels catastrophically for large-mean activations.
The TPU formulation keeps that numerics guarantee: each replica computes
its local (count, mean, M2 = Σ(x−mean)²), and the replicas merge with
Chan's parallel update expressed over two ``psum``s —
``M = Σnᵢmᵢ/N`` then ``M2 = Σ(M2ᵢ + nᵢ(mᵢ−M)²)`` — never forming a
sum-of-squares. Running stats use the unbiased variance exactly as the
reference does (sync_batchnorm.py:87).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


class SyncBatchNorm(nn.Module):
    """Cross-replica BatchNorm over ``axis_name`` (default ``data``).

    Mirrors ``apex.parallel.SyncBatchNorm(num_features, eps, momentum,
    affine, track_running_stats, process_group, channel_last)`` — the
    process group is a mesh axis name here. Drop-in for ``flax.linen
    .BatchNorm`` with ``use_running_average`` semantics.

    Channel axis: flax convention is NHWC, so ``channel_last`` defaults to
    True (channels = last dim). Pass ``channel_last=False`` for torch-style
    NCHW parity with the reference's default.
    """

    num_features: Optional[int] = None
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    process_group: Optional[str] = None  # mesh axis name
    channel_last: bool = True
    axis_name: Optional[str] = "data"
    group_size: Optional[int] = None  # stats groups of N consecutive ranks
    dtype: Any = jnp.float32
    # flax.linen.BatchNorm conversion fidelity (convert_syncbn_model):
    # None defers to ``affine`` / the call-time argument respectively
    use_scale: Optional[bool] = None
    use_bias: Optional[bool] = None
    use_running_average: Optional[bool] = None
    scale_init: Any = nn.initializers.ones
    bias_init: Any = nn.initializers.zeros
    result_dtype: Any = None  # None = return in x.dtype (flax: bn.dtype)

    def _group_merge(self, axis_name, g, local_count, local_mean,
                     local_m2):
        """Merge (count, mean, M2) within groups of ``group_size``
        consecutive ranks (ref distributed/synced_batchnorm/test_groups.py;
        the reference builds NCCL subgroups). shard_map's psum does not
        support axis_index_groups, so gather the tiny per-channel stats and
        reduce this rank's group slice locally — Chan's merge unchanged."""
        n = jax.lax.axis_size(axis_name)
        if n % g:
            raise ValueError(f"group_size={g} must divide axis size {n}")
        start = (jax.lax.axis_index(axis_name) // g) * g
        counts = jax.lax.dynamic_slice_in_dim(
            jax.lax.all_gather(local_count, axis_name), start, g)
        means = jax.lax.dynamic_slice_in_dim(
            jax.lax.all_gather(local_mean, axis_name), start, g)
        m2s = jax.lax.dynamic_slice_in_dim(
            jax.lax.all_gather(local_m2, axis_name), start, g)
        total_count = jnp.sum(counts)
        mean = jnp.sum(counts[:, None] * means, 0) / total_count
        m2 = jnp.sum(m2s + counts[:, None] * jnp.square(means - mean[None]),
                     0)
        return total_count, mean, m2

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        if use_running_average is None:
            # the module field supplies the default when the call site
            # doesn't pass one. Divergence from flax (which RAISES when
            # both are None): both-None means training mode here, matching
            # the reference apex SyncBatchNorm, whose implicit
            # module.training default is train
            use_running_average = bool(self.use_running_average)
        axis_name = self.process_group or self.axis_name
        group_size = self.group_size
        if isinstance(axis_name, tuple):
            # create_syncbn_process_group's (axis_name, group_size) pair,
            # passed straight through process_group= like the reference's
            # group object
            axis_name, tuple_size = axis_name
            group_size = tuple_size if group_size is None else group_size
        ch_axis = (x.ndim - 1) if (self.channel_last or x.ndim == 2) else 1
        reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
        c = x.shape[ch_axis]

        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), jnp.float32))

        stat_shape = [1] * x.ndim
        stat_shape[ch_axis] = c

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            x32 = x.astype(jnp.float32)
            local_count = jnp.asarray(x.size / c, jnp.float32)
            local_mean = jnp.mean(x32, axis=reduce_axes)
            # Welford M2: centered sum of squares — no E[x²]−E[x]²
            # cancellation (ref csrc/welford.cu)
            local_m2 = jnp.sum(
                jnp.square(x32 - local_mean.reshape(stat_shape)),
                axis=reduce_axes)
            try:
                if group_size is not None:
                    total_count, mean, m2 = self._group_merge(
                        axis_name, group_size, local_count, local_mean,
                        local_m2)
                else:
                    total_count = jax.lax.psum(local_count, axis_name)
                    mean = jax.lax.psum(local_count * local_mean,
                                        axis_name) / total_count
                    # Chan's parallel merge of per-replica (mean, M2, count)
                    m2 = jax.lax.psum(
                        local_m2
                        + local_count * jnp.square(local_mean - mean),
                        axis_name)
            except NameError:
                # outside pmap/shard_map: plain (single-replica) batch norm
                total_count, mean, m2 = local_count, local_mean, local_m2
            var = m2 / total_count
            if self.track_running_stats and not self.is_initializing():
                unbiased = var * total_count / jnp.maximum(total_count - 1.0, 1.0)
                ra_mean.value = (1 - self.momentum) * ra_mean.value + self.momentum * mean
                ra_var.value = (1 - self.momentum) * ra_var.value + self.momentum * unbiased

        shape = stat_shape
        y = (x.astype(jnp.float32) - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + self.eps)
        scale_on = (self.affine if self.use_scale is None
                    else self.use_scale)
        bias_on = self.affine if self.use_bias is None else self.use_bias
        if scale_on:
            weight = self.param("scale", self.scale_init, (c,), self.dtype)
            y = y * weight.astype(jnp.float32).reshape(shape)
        if bias_on:
            bias = self.param("bias", self.bias_init, (c,), self.dtype)
            y = y + bias.astype(jnp.float32).reshape(shape)
        return y.astype(self.result_dtype or x.dtype)


def convert_syncbn_model(module, process_group=None, channel_last=None):
    """Analog of ``apex.parallel.convert_syncbn_model`` (ref
    apex/parallel/__init__.py): recursively replace every
    ``flax.linen.BatchNorm`` in a module tree with :class:`SyncBatchNorm`.

    flax modules are frozen dataclasses, so the "surgery" is a functional
    rebuild: dataclass fields (including lists/tuples/dicts of
    submodules) are walked and modules containing conversions are
    ``clone()``d. Like the reference, a tree with no BatchNorm passes
    through unchanged. Limitation vs torch's in-place mutation: children
    created inside ``setup()``/``__call__`` bodies are invisible to
    dataclass traversal — declare them as attributes (flax's own
    convention) or construct with ``sync_bn=True`` where the model
    supports it (``apex_tpu.models.resnet`` / ``dcgan``).

    ``channel_last=None`` infers the channel axis from each BatchNorm's
    ``axis`` field (flax default -1 → channel-last)."""

    def convert_bn(bn):
        if channel_last is None:
            # only axis == -1 (flax default, channel-last for any rank)
            # and axis == 1 (torch-style NCHW) map onto SyncBatchNorm's
            # two layouts rank-independently; anything else would
            # silently normalize the wrong axis
            if bn.axis in (-1, None):
                ch_last = True
            elif bn.axis == 1:
                ch_last = False
            else:
                raise ValueError(
                    f"cannot infer channel layout from BatchNorm axis="
                    f"{bn.axis}; pass channel_last= explicitly")
        else:
            ch_last = channel_last
        groups = getattr(bn, "axis_index_groups", None)
        group_size = None
        if groups is not None:
            # SyncBatchNorm models subgroups as consecutive-rank blocks of
            # one size; map exactly that shape, refuse anything else
            # rather than silently syncing over the whole axis
            sizes = {len(g) for g in groups}
            flat = [r for g in groups for r in g]
            if len(sizes) == 1 and flat == list(range(len(flat))):
                group_size = sizes.pop()
            else:
                raise ValueError(
                    f"cannot map axis_index_groups={groups!r} onto "
                    f"group_size (needs equal-size consecutive-rank "
                    f"blocks); construct SyncBatchNorm directly")
        return SyncBatchNorm(
            eps=bn.epsilon, momentum=1.0 - bn.momentum,
            affine=bn.use_scale or bn.use_bias,
            use_scale=bn.use_scale, use_bias=bn.use_bias,
            use_running_average=bn.use_running_average,
            scale_init=bn.scale_init, bias_init=bn.bias_init,
            result_dtype=bn.dtype,
            process_group=process_group,
            # a BN already syncing over its own axis keeps that axis
            axis_name=getattr(bn, "axis_name", None) or "data",
            group_size=group_size,
            channel_last=ch_last,
            dtype=bn.param_dtype)

    def walk(v):
        if isinstance(v, SyncBatchNorm):
            return v
        if isinstance(v, nn.BatchNorm):
            return convert_bn(v)
        if isinstance(v, nn.Module):
            changes = {}
            for f in dataclasses.fields(v):
                if f.name in ("parent", "name"):
                    continue
                old = getattr(v, f.name, None)
                new = walk(old)
                if new is not old:
                    changes[f.name] = new
            return v.clone(**changes) if changes else v
        if isinstance(v, (list, tuple)):
            items = [walk(i) for i in v]
            if all(a is b for a, b in zip(items, v)):
                return v
            if hasattr(v, "_fields"):          # NamedTuple
                return type(v)(*items)
            return type(v)(items)
        if isinstance(v, dict):
            items = {k: walk(i) for k, i in v.items()}
            if all(items[k] is v[k] for k in v):
                return v
            return items
        return v

    return walk(module)
