"""LARC — TPU re-design of ``apex.parallel.LARC``.

Ref: apex/parallel/LARC.py. The reference wraps an optimizer and rescales
each parameter's gradient by the layerwise adaptive rate before the inner
step. Here that is an optax-style transform wrapper (``larc(inner_tx)``)
plus an apex-shaped class wrapping a FusedOptimizer.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class LARCState(NamedTuple):
    inner: optax.OptState
    count: jnp.ndarray


def larc(inner_tx: optax.GradientTransformation, lr,
         trust_coefficient: float = 0.02, clip: bool = True, eps: float = 1e-8,
         weight_decay: float = 0.0) -> optax.GradientTransformation:
    """Wrap ``inner_tx`` with LARC gradient rescaling (ref LARC.py:75 step).

    ``lr`` is the inner optimizer's learning rate — a float or an optax
    schedule (evaluated at the wrapper's own step count) — needed for the
    clipping form ``min(adaptive_lr / lr, 1)``.
    """

    def init(params):
        return LARCState(inner=inner_tx.init(params),
                         count=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None):
        lr_now = lr(state.count) if callable(lr) else lr

        def rescale(g, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
            adaptive_lr = trust_coefficient * p_norm / (
                g_norm + p_norm * weight_decay + eps)
            if clip:
                adaptive_lr = jnp.minimum(adaptive_lr / lr_now, 1.0)
            scale = jnp.where((p_norm > 0) & (g_norm > 0), adaptive_lr, 1.0)
            if weight_decay:
                g32 = g32 + weight_decay * p32
            return (g32 * scale).astype(g.dtype)

        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        p_leaves = jax.tree_util.tree_leaves(params)
        scaled = treedef.unflatten(
            [rescale(g, p) for g, p in zip(g_leaves, p_leaves)])
        updates, inner = inner_tx.update(scaled, state.inner, params)
        return updates, LARCState(inner=inner, count=state.count + 1)

    return optax.GradientTransformation(init, update)


class LARC:
    """apex-shaped wrapper over a FusedOptimizer (ref LARC.py:LARC).

    ``opt = LARC(FusedSGD(params, lr=0.1, momentum=0.9))``
    """

    def __init__(self, optimizer, trust_coefficient: float = 0.02,
                 clip: bool = True, eps: float = 1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps
        lr = optimizer.defaults.get("lr", 1e-3)
        wd = optimizer.defaults.get("weight_decay", 0.0)
        # the larc wrapper owns weight decay (it must enter the adaptive-lr
        # denominator and be scaled); zero it in the inner optimizer, like the
        # reference temporarily zeroes group['weight_decay'] (ref LARC.py:88)
        inner_tx = optimizer.tx
        if wd and optimizer._tx_factory is not None:
            inner_tx = optimizer._tx_factory(weight_decay=0.0)
        self._inner_tx = inner_tx
        self._built_lr, self._built_wd = lr, wd
        self._tx = larc(inner_tx, lr=lr, trust_coefficient=trust_coefficient,
                        clip=clip, eps=eps, weight_decay=wd)
        self._state = LARCState(inner=optimizer.state,
                                count=jnp.zeros((), jnp.int32))
        self._jit_step = jax.jit(self._functional_step)

    def _refresh_hparams(self):
        """Honor scheduler-style pokes of ``param_groups[0]['lr']``
        (and weight_decay): larc() bakes both into its closure, so a
        change rebuilds the transformation. A float-lr poke therefore
        recompiles — for per-step schedules pass an optax schedule as
        the inner optimizer's lr instead."""
        group = self.optim.param_groups[0] if self.optim.param_groups else {}
        lr = group.get("lr", self._built_lr)
        wd = group.get("weight_decay", self._built_wd)
        if lr == self._built_lr and wd == self._built_wd:
            return
        self._built_lr, self._built_wd = lr, wd
        # the inner transform bakes its own lr too — rebuild it when the
        # optimizer exposes a factory (larc's lr only sets the clip ratio)
        if self.optim._tx_factory is not None:
            overrides = {"lr": lr}
            if wd:
                overrides["weight_decay"] = 0.0  # larc owns weight decay
            self._inner_tx = self.optim._tx_factory(**overrides)
        self._tx = larc(self._inner_tx, lr=lr,
                        trust_coefficient=self.trust_coefficient,
                        clip=self.clip, eps=self.eps, weight_decay=wd)
        self._jit_step = jax.jit(self._functional_step)

    def _functional_step(self, grads, state, params):
        updates, new_state = self._tx.update(grads, state, params)
        return optax.apply_updates(params, updates), new_state

    @property
    def params(self):
        return self.optim.params

    @property
    def state(self):
        return self._state

    @property
    def param_groups(self):
        """ref LARC.py param_groups — proxied to the wrapped optimizer
        so schedulers that poke group['lr'] keep working."""
        return self.optim.param_groups

    @param_groups.setter
    def param_groups(self, value):
        self.optim.param_groups = value

    def step(self, grads=None, closure=None):
        loss = closure() if closure is not None else None
        if grads is None:
            raise ValueError("pass grads to step()")
        self._refresh_hparams()
        self.optim.params, self._state = self._jit_step(
            grads, self._state, self.optim.params)
        self.optim.state = self._state.inner
        return loss if loss is not None else self.optim.params

    @property
    def defaults(self):
        return self.optim.defaults

    def zero_grad(self):
        return None

    def state_dict(self):
        return self.optim.state_dict()

    def load_state_dict(self, d):
        self.optim.load_state_dict(d)
        self._state = LARCState(inner=self.optim.state,
                                count=jnp.zeros((), jnp.int32))
