"""Checkpoint/resume (SURVEY.md §5): orbax-backed save/restore of
params + optimizer state + amp/loss-scaler state + RNG.

The reference has no checkpoint layer of its own (torch.save in examples,
plus ``amp.state_dict()`` — ref apex/amp/frontend.py state_dict); here the
whole training state round-trips through one API, sharding-aware via orbax
(restores land on the same Mesh/PartitionSpec layout they were saved from).

Async saves (``AsyncCheckpointWriter`` / ``CheckpointManager(
async_save=True)``) copy device arrays to host, then write in a
background thread while the TPU keeps training — on a chip whose step
time is milliseconds, a blocking multi-GB write is the difference
between checkpointing every 15 minutes and every minute.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def save_checkpoint(path: str, state: Any, step: Optional[int] = None,
                    overwrite: bool = True):
    """Save a pytree (params / opt state / amp state / rng — anything).

    ``step`` appends a step subdirectory (``path/step_000010``).
    """
    ocp = _ocp()
    if step is not None:
        path = os.path.join(path, f"step_{step:08d}")
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, state, force=overwrite)
    return path


def restore_checkpoint(path: str, target: Optional[Any] = None,
                       step: Optional[int] = None):
    """Restore; ``target`` (a matching pytree of arrays/ShapeDtypeStructs)
    pins structure, dtypes and shardings."""
    ocp = _ocp()
    if step is None:
        # resume semantics: a stepped checkpoint dir restores its newest step
        step = latest_step(path)
    if step is not None:
        path = os.path.join(path, f"step_{step:08d}")
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    if target is None:
        return ckptr.restore(path)
    return ckptr.restore(path, item=target)


class AsyncCheckpointWriter:
    """Background checkpoint writer over ``ocp.AsyncCheckpointer``.

    ``save`` returns as soon as device arrays are snapshotted to host;
    the serialization/write runs concurrently with subsequent training
    steps. A second ``save`` (or ``wait``) blocks until the previous
    write lands — at most one write is ever in flight.
    """

    def __init__(self):
        ocp = _ocp()
        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())

    def save(self, path: str, state: Any, step: Optional[int] = None,
             overwrite: bool = True) -> str:
        if step is not None:
            path = os.path.join(path, f"step_{step:08d}")
        path = os.path.abspath(path)
        self._ckptr.save(path, state, force=overwrite)
        return path

    def wait(self):
        """Block until the in-flight write (if any) is durable."""
        self._ckptr.wait_until_finished()

    def close(self):
        self.wait()
        self._ckptr.close()


def latest_step(path: str) -> Optional[int]:
    """Largest ``step_*`` subdirectory, or None."""
    if not os.path.isdir(path):
        return None
    steps = []
    for d in os.listdir(path):
        if d.startswith("step_"):
            try:
                steps.append(int(d[5:]))
            except ValueError:
                pass
    return max(steps) if steps else None


class CheckpointManager:
    """Thin rotation/bookkeeping wrapper (orbax CheckpointManager analog
    with the apex-era torch.save ergonomics).

    Async mode (``async_save=True``): retention runs *before* the
    just-issued write lands, so up to ``max_to_keep + 1`` finalized step
    dirs can transiently exist between saves — that is by design, not a
    leak. Call :meth:`wait_until_finished` at the end of the training
    loop: it flushes the in-flight write AND applies final retention; a
    caller that skips it only gets the last write flushed at interpreter
    exit (orbax's atexit hook) and keeps the extra step dir on disk."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = False):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)
        self._writer = AsyncCheckpointWriter() if async_save else None

    def save(self, step: int, state: Any):
        if self._writer is not None:
            # AsyncCheckpointer.save fences the PREVIOUS write internally,
            # so by the time the new write is issued every older step has
            # landed — retention can run immediately (the in-flight step
            # is the newest and always survives _gc)
            p = self._writer.save(self.directory, state, step=step)
            self._gc()
            return p
        p = save_checkpoint(self.directory, state, step=step)
        self._gc()
        return p

    def wait_until_finished(self):
        """Async mode: block until pending writes land, then apply
        retention. No-op in blocking mode."""
        if self._writer is not None:
            self._writer.wait()
            self._gc()

    def restore(self, target: Optional[Any] = None,
                step: Optional[int] = None):
        step = step if step is not None else latest_step(self.directory)
        if step is None:
            return None
        return restore_checkpoint(self.directory, target, step=step)

    def latest_step(self) -> Optional[int]:
        return latest_step(self.directory)

    def _gc(self):
        import shutil

        steps = []
        for d in os.listdir(self.directory):
            # skip orbax in-flight temp dirs
            # (step_X.orbax-checkpoint-tmp-*) and anything non-numeric —
            # a crash can leave them behind and they must not kill _gc
            if not d.startswith("step_"):
                continue
            try:
                steps.append(int(d[5:]))
            except ValueError:
                continue
        for s in sorted(steps)[:-self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory, f"step_{s:08d}"),
                          ignore_errors=True)
