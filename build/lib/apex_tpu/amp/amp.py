"""O1 boundary casting — the mechanism behind the casting lists.

TPU re-design of apex/amp/amp.py:1-177 (half/float/promote function
registration) + apex/amp/wrap.py (cast-before-call wrappers). The reference
monkeypatches torch functions at ``amp.initialize`` time; under XLA nothing
can (or should) be patched, so the same classification
(:mod:`apex_tpu.amp.lists`) is applied *at the call boundary*:
library entry points (mlp, fused_dense, xentropy, multihead_attn) route
their calls through :func:`amp_call`, which casts floating-point array
arguments per the active O1 policy. With no active policy every wrapper is
an exact identity, so O0 code traces to the unchanged jaxpr.

Casting decisions are made at *trace* time (they read the process-global
amp handle), so — as with every JAX configuration — ``amp.initialize``
must run before the first jit trace of the functions it should affect.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp import lists
from apex_tpu.amp._amp_state import _amp_state

_policy_override = None


def current_policy():
    """The active O1 policy, or None when boundary casting is off.

    An explicit :func:`casting` context beats the process-global handle;
    the handle applies only when its opt level enables function patching
    (O1 — ``patch_jax_functions``).
    """
    if _policy_override is not None:
        return _policy_override
    h = _amp_state.handle
    if (h is not None and h.props.enabled and h.props.patch_jax_functions):
        return h.policy
    return None


@contextlib.contextmanager
def casting(policy):
    """Force an O1 policy for the duration (tests / local overrides)."""
    global _policy_override
    prev = _policy_override
    _policy_override = policy
    try:
        yield
    finally:
        _policy_override = prev


def _is_float_array(x) -> bool:
    return (hasattr(x, "dtype") and hasattr(x, "astype")
            and jnp.issubdtype(x.dtype, jnp.floating))


def _cast_tree(tree, dtype):
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if _is_float_array(x) else x, tree)


def _widest_float_dtype(trees) -> Optional[jnp.dtype]:
    dtype = None
    for leaf in jax.tree_util.tree_leaves(trees):
        if _is_float_array(leaf):
            dtype = leaf.dtype if dtype is None else jnp.promote_types(
                dtype, leaf.dtype)
    return dtype


def _cast_call(category, fn, args, kwargs):
    policy = current_policy()
    if policy is None:
        return fn(*args, **kwargs)
    if category == "compute":
        dtype = policy.compute_dtype
    elif category == "fp32":
        dtype = jnp.float32
    else:  # promote: widest floating input wins (ref tensor_overrides CASTS)
        dtype = _widest_float_dtype((args, kwargs))
        if dtype is None:
            return fn(*args, **kwargs)
    return fn(*_cast_tree(args, dtype), **_cast_tree(kwargs, dtype))


def amp_call(op_name: str, fn, *args, **kwargs):
    """Call ``fn`` with inputs cast per the O1 policy and the op's
    classification in :mod:`apex_tpu.amp.lists` (the wrap.py analog)."""
    return _cast_call(lists.classify(op_name), fn, args, kwargs)


def _wrap(fn, category):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return _cast_call(category, fn, args, kwargs)

    wrapper.__wrapped_amp_category__ = category
    return wrapper


def half_function(fn):
    """Inputs cast to the compute (bf16/fp16) dtype under O1
    (ref apex/amp/amp.py:half_function)."""
    return _wrap(fn, "compute")


def float_function(fn):
    """Inputs forced to fp32 under O1 (ref amp.py:float_function)."""
    return _wrap(fn, "fp32")


def promote_function(fn):
    """Inputs widened to the widest floating input dtype under O1
    (ref amp.py:promote_function)."""
    return _wrap(fn, "promote")


def _register(module, name, category):
    fn = getattr(module, name)
    if getattr(fn, "__wrapped_amp_category__", None) == category:
        return  # idempotent
    setattr(module, name, _wrap(fn, category))


def register_half_function(module, function_name):
    """Wrap ``module.function_name`` for compute-precision casting
    (ref amp.py:register_half_function — but only apex_tpu's own modules
    can be registered; jax itself is never patched)."""
    _register(module, function_name, "compute")


def register_float_function(module, function_name):
    _register(module, function_name, "fp32")


def register_promote_function(module, function_name):
    _register(module, function_name, "promote")
