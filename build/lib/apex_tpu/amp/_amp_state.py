"""Process-level amp registry (ref apex/amp/_amp_state.py).

Holds the active :class:`~apex_tpu.amp.handle.AmpHandle` so module-level
``amp.state_dict()`` / ``amp.load_state_dict()`` work like the reference.
"""

from __future__ import annotations


class AmpState:
    def __init__(self):
        self.handle = None
        self.opt_properties = None
        self.verbosity = 1


_amp_state = AmpState()


def maybe_print(s: str, verbose: bool = False) -> None:
    if _amp_state.verbosity > (0 if verbose else 1) or (verbose and _amp_state.verbosity > 0):
        print(s)


def warn_or_err(msg: str) -> None:
    raise RuntimeError("\n".join(["", msg]))


def master_params(optimizer):
    """ref _amp_state.py:60 — iterate the (master, fp32 when O2) param
    leaves owned by ``optimizer``. Works on a FusedOptimizer (yields the
    master tree's leaves when amp attached fp32 masters, else the model
    params), an FP16_Optimizer wrapper (whose masters live on the inner
    optimizer), or a bare params tree."""
    import jax

    tree = getattr(optimizer, "master_params", None)
    if tree is None and hasattr(optimizer, "optimizer"):
        # FP16_Optimizer shape: the wrapped optimizer's params ARE the
        # fp32 masters
        tree = getattr(optimizer.optimizer, "params", None)
    if tree is None:
        tree = getattr(optimizer, "params", optimizer)
    if tree is optimizer and not isinstance(
            tree, (dict, list, tuple)) and not hasattr(tree, "shape"):
        raise TypeError(
            f"master_params: {type(optimizer).__name__} carries no "
            "params/master_params tree")
    yield from jax.tree_util.tree_leaves(tree)
