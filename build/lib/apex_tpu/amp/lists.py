"""Op-category precision tables — TPU re-design of ``apex.amp.lists``.

Ref: apex/amp/lists/{functional_overrides,torch_overrides,tensor_overrides}.py.

The reference monkeypatches torch functions at O1 so MXU-friendly ops run
fp16 and range-sensitive ops run fp32. Under XLA nothing can (or should) be
patched — casting is decided where the op is *called*. These tables encode
the same classification for JAX ops; ``Policy.run_fp32`` /
``Policy.cast_to_compute`` (frontend.py) and the fused kernels consume them:
every apex_tpu fused kernel (layer_norm, softmax, cross-entropy) already
computes fp32 internally regardless of storage dtype, which is exactly the
behavior the FP32_FUNCS list enforces on GPU.
"""

# MXU-friendly: run in compute (bf16/fp16) precision — ref functional_overrides.py FP16_FUNCS
COMPUTE_PRECISION_OPS = frozenset({
    "dot", "dot_general", "conv", "conv_general_dilated", "einsum", "matmul",
    "dense", "linear", "attention_qk", "attention_av",
})

# Range-sensitive: force fp32 math — ref functional_overrides.py FP32_FUNCS
FP32_OPS = frozenset({
    "softmax", "log_softmax", "layer_norm", "rms_norm", "batch_norm",
    "group_norm", "cross_entropy", "nll_loss", "mse_loss", "cosine_similarity",
    "exp", "log", "pow", "sum", "mean", "var", "std", "norm", "cumsum",
    "erf", "erfinv", "softplus", "sigmoid_focal_loss",
})

# Type-promotion ops: widest input dtype wins — ref tensor_overrides.py CASTS
PROMOTE_OPS = frozenset({
    "add", "sub", "mul", "div", "where", "concatenate", "stack", "maximum",
    "minimum",
})


def classify(op_name: str) -> str:
    """Return 'compute', 'fp32', or 'promote' for an op name."""
    if op_name in COMPUTE_PRECISION_OPS:
        return "compute"
    if op_name in FP32_OPS:
        return "fp32"
    return "promote"
