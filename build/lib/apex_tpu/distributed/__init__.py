"""Distributed communication backend (SURVEY.md §2 #54 — the NCCL analog).

The reference's comms layer is torch.distributed process groups over NCCL.
On TPU the transport is XLA collectives over ICI/DCN and the "process
group" is a named mesh axis; multi-host init is ``jax.distributed``.
This module is the process-group-shaped surface over that machinery.
"""

from apex_tpu.distributed.backend import (
    all_gather,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    get_rank,
    get_world_size,
    init_process_group,
    is_initialized,
    new_group,
    reduce_scatter,
    ReduceOp,
)
from apex_tpu.distributed.divergence import (
    DivergenceMonitor,
    assert_replicas_equal,
    replica_divergence,
)

__all__ = [
    "all_gather", "all_reduce", "all_to_all", "barrier", "broadcast",
    "get_rank", "get_world_size", "init_process_group", "is_initialized",
    "new_group", "reduce_scatter", "ReduceOp",
    "DivergenceMonitor", "assert_replicas_equal", "replica_divergence",
]
