"""Replica-divergence detection — the TPU analog of race detection.

CUDA race detection guards against unsynchronized writes; in SPMD there
are no shared-memory races, but the equivalent silent failure exists:
values that SHOULD be identical on every rank of an axis (replicated
params after a dp step, the loss scaler state, RNG-derived masks) drift
apart — from a missed grad allreduce, nondeterministic reductions, or a
flaky interconnect — and training silently diverges long before NaNs.

These helpers run IN-GRAPH (no host sync): a rank's fingerprint is
compared against the axis-wide min/max, so a pair of scalar collectives
verifies agreement across the whole axis. Detection is EXACT: the digest
is an integer hash of the raw bits (position-weighted uint32 wraparound
arithmetic), so a single 1-ulp drift in a billion-parameter tree flips
the digest — a float accumulator would drown that delta in rounding. A
secondary f32 magnitude digest sizes the drift for logging.

- :func:`replica_divergence` — traced scalar: 0.0 iff every rank's tree
  is bit-identical; otherwise the spread of the magnitude digest
  (floored at a tiny positive value so exact detection is never lost).
- :func:`assert_replicas_equal` — hard in-graph check; callers branch on
  the returned traced bool (the amp scaler's overflow-skip pattern).
- :class:`DivergenceMonitor` — periodic wrapper for train loops: the
  digest computes every ``every`` steps (lax.cond-gated — the scalar
  collectives run unconditionally to keep SPMD analysis simple).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from apex_tpu.transformer.tensor_parallel.mappings import (
    make_varying,
    tree_vma,
)

Axes = Union[str, Sequence[str]]


def _axes_tuple(axis_name: Axes):
    return (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)


def _spread(h, mag, axis_name: Axes) -> jax.Array:
    """Axis-wide digest comparison: exact integer hash decides WHETHER
    replicas diverge; the f32 magnitude spread (floored to stay nonzero)
    estimates HOW MUCH."""
    h_hi = h_lo = h.astype(jnp.int32)
    m_hi = m_lo = mag
    for ax in _axes_tuple(axis_name):
        h_hi = jax.lax.pmax(make_varying(h_hi, ax), ax)
        h_lo = jax.lax.pmin(make_varying(h_lo, ax), ax)
        m_hi = jax.lax.pmax(make_varying(m_hi, ax), ax)
        m_lo = jax.lax.pmin(make_varying(m_lo, ax), ax)
    return jnp.where(h_hi != h_lo,
                     jnp.maximum(jnp.abs(m_hi - m_lo),
                                 jnp.float32(1e-30)), 0.0)


def _leaf_bits(leaf) -> jax.Array:
    """Raw bits of a leaf as a flat uint32 vector (exact, dtype-agnostic)."""
    x = leaf.ravel()
    if x.dtype.itemsize == 4:
        return jax.lax.bitcast_convert_type(x, jnp.uint32)
    if x.dtype.itemsize == 2:
        return jax.lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
    if x.dtype.itemsize == 1:
        return jax.lax.bitcast_convert_type(x, jnp.uint8).astype(jnp.uint32)
    # 8-byte dtypes bitcast to a trailing pair of u32 words
    return jax.lax.bitcast_convert_type(x, jnp.uint32).ravel()


def _fingerprint(tree):
    """(exact_hash uint32, magnitude f32) digest of a pytree.

    The hash multiplies each element's bits by an odd position constant
    (bijective in uint32) and sums with wraparound — exact integer math,
    so bitwise-identical trees agree and any single-bit drift disagrees
    (up to a ~2^-32 collision). The magnitude digest is a cheap f32 sum
    for sizing the drift; it plays no part in detection.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    h = jnp.zeros((), jnp.uint32)
    mag = jnp.zeros((), jnp.float32)
    for i, leaf in enumerate(leaves):
        bits = _leaf_bits(leaf)
        pos = jax.lax.iota(jnp.uint32, bits.size)
        w = pos * jnp.uint32(2654435761) + jnp.uint32(2 * i + 1)
        h = h + jnp.sum(bits * (2 * w + 1))  # odd multiplier: bijective
        mag = mag + jnp.sum(leaf.astype(jnp.float32))
    return h, mag


def replica_divergence(tree, axis_name: Axes) -> jax.Array:
    """Traced scalar: 0.0 iff every rank on ``axis_name`` holds a
    bit-identical copy of ``tree``; otherwise the spread of the f32
    magnitude digest, floored at a tiny positive value (exact integer
    detection decides WHETHER, the float spread estimates HOW MUCH).

    Runs inside ``shard_map`` with the axis bound. Cost: four scalar
    collectives plus one pass over the tree.
    """
    h, mag = _fingerprint(tree)
    return _spread(h, mag, axis_name)


def assert_replicas_equal(tree, axis_name: Axes, atol: float = 0.0):
    """In-graph divergence check. Returns ``(ok, divergence)`` — ``ok`` is
    a traced bool, identical on every rank, suitable for ``lax.cond`` (the
    same pattern the amp scaler uses for overflow skips) or for poisoning
    the loss (``loss = jnp.where(ok, loss, jnp.nan)``) so the failure is
    visible at the host without a per-step sync."""
    div = replica_divergence(tree, axis_name)
    return div <= atol, div


class DivergenceState(NamedTuple):
    step: jax.Array        # i32 steps seen
    checks: jax.Array      # i32 checks performed
    max_divergence: jax.Array  # f32 worst spread observed
    diverged: jax.Array    # bool latch


class DivergenceMonitor:
    """Periodic replicated-state checker for jitted train loops.

    ``state = monitor.init()``; inside the (shard_mapped) train step:
    ``state = monitor.update(state, params, axis_name='dp')`` — every
    ``every`` steps it fingerprints ``params`` across the axis and latches
    any disagreement. Read ``state.diverged`` / ``state.max_divergence``
    at the host whenever convenient (e.g. with checkpoint cadence).
    """

    def __init__(self, every: int = 100, atol: float = 0.0):
        self.every = every
        self.atol = atol

    def init(self) -> DivergenceState:
        return DivergenceState(
            step=jnp.zeros((), jnp.int32),
            checks=jnp.zeros((), jnp.int32),
            max_divergence=jnp.zeros((), jnp.float32),
            diverged=jnp.zeros((), jnp.bool_),
        )

    def update(self, state: DivergenceState, tree,
               axis_name: Axes = "dp",
               force: Optional[jax.Array] = None) -> DivergenceState:
        step = state.step + 1
        due = (step % self.every) == 0
        if force is not None:
            # a rank-local force would make the cond predicate differ
            # across ranks and latch a false positive (one rank digests,
            # the others produce zeros) — make it axis-uniform: ANY rank
            # forcing forces everyone
            f = force.astype(jnp.int32)
            for ax in _axes_tuple(axis_name):
                f = jax.lax.pmax(make_varying(f, ax), ax)
            due = jnp.logical_or(due, f > 0)

        # the expensive full-tree digest only computes on due steps
        # (lax.cond with no collectives inside); the cheap SCALAR
        # collectives in _spread run unconditionally — `due` is uniform
        # across the axis (step-derived, or pmax'd force), so both
        # branches agree axis-wide and the off-step zeros trivially match
        def digest(_):
            return _fingerprint(tree)

        def skip(_):
            # fresh zeros must match the digest branch's vma (the union
            # of the tree leaves' varying axes) or the cond types disagree
            h0 = jnp.zeros((), jnp.uint32)
            m0 = jnp.zeros((), jnp.float32)
            for ax in sorted(tree_vma(tree)):
                h0 = make_varying(h0, ax)
                m0 = make_varying(m0, ax)
            return h0, m0

        h, mag = jax.lax.cond(due, digest, skip, None)
        div = _spread(h, mag, axis_name)
        bad = div > self.atol
        return DivergenceState(
            step=step,
            checks=state.checks + due.astype(jnp.int32),
            max_divergence=jnp.where(
                due, jnp.maximum(state.max_divergence, div),
                state.max_divergence),
            diverged=jnp.logical_or(state.diverged,
                                    jnp.logical_and(due, bad)),
        )
