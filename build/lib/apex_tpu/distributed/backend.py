"""torch.distributed-shaped API over XLA collectives + jax.distributed.

Two layers, mirroring how the reference splits host coordination from
device collectives:

- HOST side: :func:`init_process_group` wraps ``jax.distributed.initialize``
  (the NCCL-bootstrap analog — rendezvous, health, failure detection are
  owned by the JAX runtime over DCN).
- DEVICE side: the collectives take ``group`` = a mesh axis name (or tuple
  of names) and must run inside ``shard_map``/``pjit`` where the axis is
  bound — the analog of issuing NCCL ops on a process group's stream; XLA
  schedules them on ICI and overlaps with compute.
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from apex_tpu.transformer.tensor_parallel.mappings import _to_varying

_INITIALIZED = False
Group = Union[str, Sequence[str]]


class ReduceOp(enum.Enum):
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "product"


def init_process_group(backend: str = "ici", init_method: Optional[str] = None,
                       world_size: Optional[int] = None,
                       rank: Optional[int] = None, **kw):
    """Multi-host bootstrap (ref torch.distributed.init_process_group).

    On a single-host run (the common test path) this is a no-op success;
    multi-host passes coordinator address/process counts through to
    ``jax.distributed.initialize``.
    """
    global _INITIALIZED
    del backend
    if world_size is not None and world_size > 1 and init_method:
        addr = init_method.replace("tcp://", "")
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=world_size,
                                   process_id=rank, **kw)
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def get_world_size(group: Optional[Group] = None) -> int:
    if group is None:
        return jax.device_count()
    axes = (group,) if isinstance(group, str) else tuple(group)
    try:
        n = 1
        for a in axes:
            n *= jax.lax.axis_size(a)
        return n
    except NameError:
        return jax.device_count()


def get_rank(group: Optional[Group] = None):
    if group is None:
        return jax.process_index()
    axes = (group,) if isinstance(group, str) else tuple(group)
    r = jax.lax.axis_index(axes[0])
    for a in axes[1:]:
        r = r * jax.lax.axis_size(a) + jax.lax.axis_index(a)
    return r


def new_group(axis_name: str) -> str:
    """Groups ARE mesh axes; kept for call-site parity."""
    return axis_name


def _vary_group(x, group: Group):
    """pvary over EVERY axis of the group — a tuple group's collective
    needs the value varying over all of its axes, not just the first."""
    axes = (group,) if isinstance(group, str) else tuple(group)
    for ax in axes:
        x = _to_varying(x, ax)
    return x


def all_reduce(x, op: ReduceOp = ReduceOp.SUM, group: Group = "dp"):
    x = _vary_group(x, group)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        y = jax.lax.psum(x, group)
        if op == ReduceOp.AVG:
            y = y / get_world_size(group)
        return y
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, group)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, group)
    if op == ReduceOp.PRODUCT:
        # exact and sign-correct for any dtype (exp(psum(log)) would NaN on
        # negatives); PRODUCT is never bandwidth-critical, so the gather is
        # fine
        return jnp.prod(jax.lax.all_gather(x, group, axis=0), axis=0)
    raise ValueError(op)


def all_gather(x, group: Group = "dp", axis: int = 0, tiled: bool = True):
    x = _vary_group(x, group)
    return jax.lax.all_gather(x, group, axis=axis, tiled=tiled)


def reduce_scatter(x, group: Group = "dp", axis: int = 0,
                   op: ReduceOp = ReduceOp.SUM):
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError("reduce_scatter supports SUM/AVG")
    x = _vary_group(x, group)
    y = jax.lax.psum_scatter(x, group, scatter_dimension=axis, tiled=True)
    if op == ReduceOp.AVG:
        y = y / get_world_size(group)
    return y


def all_to_all(x, group: Group = "cp", split_axis: int = 0,
               concat_axis: int = 0):
    x = _vary_group(x, group)
    return jax.lax.all_to_all(x, group, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def broadcast(x, src: int = 0, group: Group = "dp"):
    """Every rank gets rank ``src``'s value (psum of the masked value —
    variant→invariant, so the result is replicated like NCCL bcast).
    ``src`` is the COMPOSITE rank for tuple groups (get_rank's order)."""
    axes = (group,) if isinstance(group, str) else tuple(group)
    rank = get_rank(group)
    contrib = jnp.where(rank == src, _vary_group(x, group),
                        jnp.zeros_like(x))
    return jax.lax.psum(contrib, axes if len(axes) > 1 else axes[0])


def barrier(group: Group = "dp"):
    """Collective no-op fence (NCCL barrier analog): a tiny psum every rank
    must reach. Returns the axis size so the dependency is real."""
    return jax.lax.psum(jnp.ones(()), group)
