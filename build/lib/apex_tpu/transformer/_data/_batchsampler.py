"""Megatron-style batch samplers for dynamic / rampup batch sizes.

Capability parity with ref apex/transformer/_data/_batchsampler.py:1-181
(MegatronPretrainingSampler / MegatronPretrainingRandomSampler), re-designed
for the TPU input pipeline: pure-numpy index generation (no torch dependency
in the data path), deterministic per-epoch shuffling via a seeded Generator,
and resumable via ``consumed_samples`` — the same contract the reference's
checkpoint/resume uses.

Yields *local minibatches* of indices (global_batch // dp_size) for one
data-parallel rank; feed them to any indexable dataset, then shard the
resulting array over the 'dp' mesh axis.
"""

from __future__ import annotations

import abc

import numpy as np

__all__ = [
    "MegatronPretrainingSampler",
    "MegatronPretrainingRandomSampler",
]


class _Base(abc.ABC):
    """Base class for Megatron-style batch samplers (ref _batchsampler.py:16)."""

    @abc.abstractmethod
    def __len__(self) -> int:
        ...

    @abc.abstractmethod
    def __iter__(self):
        ...

    @property
    def local_minibatch_size(self) -> int:
        return self._local_minibatch_size

    @local_minibatch_size.setter
    def local_minibatch_size(self, new_size: int) -> None:
        self._local_minibatch_size = new_size
        self.local_minibatch_times_data_parallel_size = (
            new_size * self.data_parallel_size)


def _check_args(total_samples, local_minibatch_size, data_parallel_rank,
                data_parallel_size):
    if total_samples <= 0:
        raise ValueError(f"no sample to consume: {total_samples}")
    if local_minibatch_size <= 0:
        raise ValueError(
            f"local minibatch size must be greater than 0: "
            f"{local_minibatch_size}")
    if data_parallel_size <= 0:
        raise ValueError(
            f"data parallel size must be greater than 0: "
            f"{data_parallel_size}")
    if data_parallel_rank >= data_parallel_size:
        raise ValueError(
            f"data_parallel_rank should be smaller than data parallel size: "
            f"{data_parallel_rank}, {data_parallel_size}")


class MegatronPretrainingSampler(_Base):
    """Sequential sampler (ref _batchsampler.py:38-100).

    Walks ``[consumed_samples, total_samples)`` in order, accumulating one
    *global* minibatch (``local_minibatch_size * data_parallel_size``) at a
    time and yielding this rank's slice of it. (The reference accumulates
    only ``local_minibatch_size`` before slicing — ref _batchsampler.py:88-93
    — which hands every rank > 0 an empty slice; we follow the upstream
    Megatron-LM semantics the reference's docstring points at instead.)

    .. warning:: With ``drop_last=False``, a final tail shorter than
       ``data_parallel_size`` is padded by REPEATING the last sample index
       so every rank stays non-empty (an empty per-rank batch kills SPMD
       consumers). Eval/metric loops that must not double-count the
       repeated sample should pass ``with_validity=True``, which makes the
       sampler yield ``(indices, valid)`` pairs where ``valid`` is a
       boolean list marking padding entries ``False``.
    """

    def __init__(self, total_samples: int, consumed_samples: int,
                 local_minibatch_size: int, data_parallel_rank: int,
                 data_parallel_size: int, drop_last: bool = True,
                 with_validity: bool = False):
        _check_args(total_samples, local_minibatch_size, data_parallel_rank,
                    data_parallel_size)
        if consumed_samples >= total_samples:
            raise ValueError(
                f"no samples left to consume: {consumed_samples}, "
                f"{total_samples}")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        self.drop_last = drop_last
        self.with_validity = with_validity

    def __len__(self) -> int:
        return self.total_samples

    def get_start_end_idx(self):
        start = self.data_parallel_rank * self.local_minibatch_size
        return start, start + self.local_minibatch_size

    def _emit(self, indices, valid=None):
        if self.with_validity:
            return indices, ([True] * len(indices) if valid is None
                             else valid)
        return indices

    def __iter__(self):
        batch = []
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.local_minibatch_times_data_parallel_size:
                start, end = self.get_start_end_idx()
                yield self._emit(batch[start:end])
                batch = []
        if batch and not self.drop_last:
            # split the short tail evenly (sizes differ by at most 1) instead
            # of the reference's fixed-offset slice, which hands every rank
            # past the remainder an empty list (ref _batchsampler.py:97-100);
            # consumers must still expect a ragged final batch. A tail with
            # fewer samples than ranks is padded by REPEATING the last index
            # so drop_last=False keeps its contract (every sample yielded,
            # every rank non-empty) — an empty batch kills SPMD consumers.
            # with_validity=True marks those repeats False (class warning).
            n_real = len(batch)
            if len(batch) < self.data_parallel_size:
                batch = batch + [batch[-1]] * (
                    self.data_parallel_size - len(batch))
            valid = [True] * n_real + [False] * (len(batch) - n_real)
            base, rem = divmod(len(batch), self.data_parallel_size)
            r = self.data_parallel_rank
            start = r * base + min(r, rem)
            end = start + base + (1 if r < rem else 0)
            yield self._emit(batch[start:end], valid[start:end])


class MegatronPretrainingRandomSampler(_Base):
    """Per-epoch-shuffled sampler (ref _batchsampler.py:103-181).

    Each rank owns a contiguous bucket of ``total // (local_mb * dp)``
    ``local_minibatch_size``-sized groups; the bucket is shuffled with a
    generator seeded by the epoch number so every rank (and every resume
    from ``consumed_samples``) sees the same permutation. Incomplete
    trailing batches are dropped.
    """

    def __init__(self, total_samples: int, consumed_samples: int,
                 local_minibatch_size: int, data_parallel_rank: int,
                 data_parallel_size: int):
        _check_args(total_samples, local_minibatch_size, data_parallel_rank,
                    data_parallel_size)
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        if total_samples < self.local_minibatch_times_data_parallel_size:
            raise ValueError(
                f"total_samples ({total_samples}) must be >= one global "
                f"minibatch (local_minibatch_size * data_parallel_size = "
                f"{self.local_minibatch_times_data_parallel_size})")
        self.last_batch_size = (
            self.total_samples % self.local_minibatch_times_data_parallel_size)

    def __len__(self) -> int:
        return self.total_samples

    def __iter__(self):
        active_total_samples = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // active_total_samples
        current_epoch_samples = self.consumed_samples % active_total_samples

        bucket_size = (
            self.total_samples // self.local_minibatch_times_data_parallel_size
        ) * self.local_minibatch_size
        bucket_offset = current_epoch_samples // self.data_parallel_size
        start_idx = self.data_parallel_rank * bucket_size

        rng = np.random.Generator(np.random.PCG64(self.epoch))
        random_idx = rng.permutation(bucket_size).tolist()
        idx_range = [start_idx + x for x in random_idx[bucket_offset:]]

        batch = []
        for idx in idx_range:
            batch.append(idx)
            if len(batch) == self.local_minibatch_size:
                self.consumed_samples += (
                    self.local_minibatch_times_data_parallel_size)
                yield batch
                batch = []
