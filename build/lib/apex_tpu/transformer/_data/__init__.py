"""Megatron-style batch samplers (ref apex/transformer/_data/__init__.py)."""

from apex_tpu.transformer._data._batchsampler import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)

__all__ = ["MegatronPretrainingRandomSampler", "MegatronPretrainingSampler"]
