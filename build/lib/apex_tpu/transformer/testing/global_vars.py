"""Global singletons for the test harness
(ref apex/transformer/testing/global_vars.py).

``set_global_variables`` parses args once and builds the num-microbatches
calculator; ``get_args``/``get_num_microbatches``/``get_timers`` read the
singletons with the reference's initialized/not-initialized assertions.
Timers block on device work (``block_until_ready``) the way the
reference's timers ``cuda.synchronize`` (ref global_vars.py:191).
"""

from __future__ import annotations

from typing import Optional

import jax

from apex_tpu.transformer.pipeline_parallel import _timers as _shared_timers
from apex_tpu.transformer.microbatches import (
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.testing.arguments import parse_args

_GLOBAL_ARGS = None
_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TIMERS = None


def _ensure_initialized(var, name):
    assert var is not None, f"{name} is not initialized."
    return var


def _ensure_not_initialized(var, name):
    assert var is None, f"{name} is already initialized."


def get_args():
    """Return arguments (ref global_vars.py:34)."""
    return _ensure_initialized(_GLOBAL_ARGS, "args")


def get_num_microbatches() -> int:
    return _ensure_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    ).get()


def get_current_global_batch_size() -> int:
    return _ensure_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    ).get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int, *,
                            consistency_check: bool = True) -> None:
    _ensure_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator"
    ).update(consumed_samples, consistency_check)


def get_timers():
    return _ensure_initialized(_GLOBAL_TIMERS, "timers")


def set_global_variables(extra_args_provider=None, args_defaults=None,
                         ignore_unknown_args: bool = True,
                         data_parallel_size: Optional[int] = None,
                         args=None):
    """Parse args and set every singleton (ref global_vars.py:87)."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR, _GLOBAL_TIMERS
    _ensure_not_initialized(_GLOBAL_ARGS, "args")
    parsed = parse_args(extra_args_provider, args_defaults,
                        ignore_unknown_args, args=args)
    _GLOBAL_ARGS = parsed
    dp = data_parallel_size if data_parallel_size is not None else 1
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank=0,
        rampup_batch_size=parsed.rampup_batch_size,
        global_batch_size=parsed.global_batch_size,
        micro_batch_size=parsed.micro_batch_size,
        data_parallel_size=dp,
    )
    _GLOBAL_TIMERS = Timers()
    return parsed


def destroy_global_vars():
    """Reset for the next test (the reference leaks these across tests)."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR, _GLOBAL_TIMERS
    _GLOBAL_ARGS = None
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
    _GLOBAL_TIMERS = None


class _Timer(_shared_timers._Timer):
    """Shared timer + an up-front device drain: start/stop first flush
    ALL pending async dispatches (jax.device_put round-trip), so the
    bracket excludes work queued before the region — the strictest
    reading of the reference's cuda.synchronize placement
    (ref global_vars.py:191)."""

    def _drain(self):
        jax.device_put(0.0).block_until_ready()

    def start(self):
        self._drain()
        super().start()

    def stop(self, block_on=None):
        self._drain()
        super().stop(block_on)


class Timers(_shared_timers.Timers):
    """ref global_vars.py:236 — named registry over the draining timer."""

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]
