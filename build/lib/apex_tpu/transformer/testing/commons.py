"""Shared fixtures for transformer tests
(ref apex/transformer/testing/commons.py).

The reference's commons builds a toy ``MyModel`` (one linear per pipeline
stage), a forward-step function in the schedule's expected shape, seeded
RNG, and NCCL setup. The TPU analogs: a toy stage function + params for
the collective pipeline, mesh construction over the virtual CPU devices,
and `fold_in`-seeded keys.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from apex_tpu.transformer import parallel_state


# ------------------------------------------------------------- toy model
# ref commons.py:34-67 — MyLayer (square weight + bias) and MyModel.


def init_toy_stage_params(key, hidden_size: int, layers_per_stage: int = 1):
    """Per-stage params of the reference's MyModel shape."""
    ws, bs = [], []
    for i in range(layers_per_stage):
        kw, kb, key = jax.random.split(key, 3)
        ws.append(jax.random.normal(kw, (hidden_size, hidden_size)) * 0.1)
        bs.append(jax.random.normal(kb, (hidden_size,)) * 0.1)
    return {"w": jnp.stack(ws), "b": jnp.stack(bs)}


def toy_stage_fn(stage_params, x):
    """The reference MyLayer fwd (x @ w + b per layer), scan over layers."""

    def body(h, lp):
        w, b = lp
        return h @ w + b, None

    out, _ = jax.lax.scan(body, x, (stage_params["w"], stage_params["b"]))
    return out


def model_provider_func(hidden_size, pre_process=True, post_process=True):
    """ref commons.py:70 — returns (init_fn, stage_fn) for one stage."""
    del pre_process, post_process  # stage io is uniform in the TPU design

    def init_fn(key, layers_per_stage=1):
        return init_toy_stage_params(key, hidden_size, layers_per_stage)

    return init_fn, toy_stage_fn


def process_batch(batch):
    """ref commons.py:74 — unpack (x,) or x."""
    if isinstance(batch, (list, tuple)):
        return batch[0]
    return batch


def fwd_step_func(batch, stage_params):
    """ref commons.py:82 — forward + loss closure in the schedule shape."""
    x = process_batch(batch)
    y = toy_stage_fn(stage_params, x)

    def loss_func(y):
        loss = jnp.mean(y * y)
        return loss, {"avg": loss}

    return y, loss_func


class IdentityLayer:
    """ref commons.py:96 — a trainable tensor behind an identity call."""

    def __init__(self, key, shape, scale=1.0):
        self.weight = scale * jax.random.normal(key, shape)

    def __call__(self):
        return self.weight


# ------------------------------------------- stage splitting (model zoo)


def split_stages(params, n_stages: int):
    """Split a model-zoo params tree's [L, ...] layer stack into
    [n_stages, L/n_stages, ...] (shared by the standalone GPT/BERT
    builders; the stacked-layer convention is uniform across the zoo)."""
    layers = params["layers"]
    L = jax.tree_util.tree_leaves(layers)[0].shape[0]
    if L % n_stages:
        raise ValueError(f"{L} layers not divisible by {n_stages} stages")
    return jax.tree_util.tree_map(
        lambda a: a.reshape(n_stages, L // n_stages, *a.shape[1:]), layers)


def io_params(params):
    """Stage-replicated non-layer params (embeddings, final norms, heads)."""
    return {k: v for k, v in params.items() if k != "layers"}


# ------------------------------------------------------------ environment


def set_random_seed(seed: int):
    """ref commons.py:105 — one seed for model and data streams."""
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def build_mesh(shape: Sequence[int], axis_names: Sequence[str],
               devices=None) -> Mesh:
    """Mesh over the first prod(shape) devices (tests: virtual CPU mesh)."""
    n = int(np.prod(shape))
    devices = list(jax.devices() if devices is None else devices)[:n]
    if len(devices) < n:
        raise RuntimeError(f"need {n} devices, have {len(devices)}")
    return Mesh(np.array(devices).reshape(*shape), tuple(axis_names))


def initialize_distributed(tp: int = 1, pp: int = 1, cp: int = 1,
                           backend: Optional[str] = None) -> Mesh:
    """ref commons.py:113 initialize_distributed — here: build the mesh and
    register it with parallel_state (no process groups to create)."""
    del backend  # XLA collectives; kept for call-site parity
    n = len(jax.devices())
    dp = n // (tp * pp * cp)
    if dp * tp * pp * cp != n:
        raise RuntimeError(
            f"tp*pp*cp ({tp * pp * cp}) must divide device count ({n})")
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=tp,
        pipeline_model_parallel_size_=pp,
        context_parallel_size_=cp,
    )
    return parallel_state.get_mesh()


def print_separator(message: str):
    """ref commons.py:148."""
    print("\n" + "-" * 31 + f" {message} " + "-" * 31, flush=True)
