"""Standalone GPT for pipeline-parallel tests
(ref apex/transformer/testing/standalone_gpt.py).

The reference carries a 1.5k-line Megatron GPT to test its schedules
without importing Megatron-LM; ``apex_tpu.models.gpt2`` already is that
model, so this module adapts it to the harness contract: build from
``get_args`` flags, split layer params into pipeline stages, and expose
embed / stage_fn / head pieces in the shape the collective pipeline
schedules consume.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.models import gpt2
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    vocab_parallel_embedding,
)


def gpt_config_from_args(args) -> gpt2.GPT2Config:
    """Map harness args (ref arguments.py flags) onto GPT2Config."""
    dtype = (jnp.bfloat16 if args.params_dtype == "bfloat16"
             else jnp.float16 if args.params_dtype == "float16"
             else jnp.float32)
    return gpt2.GPT2Config(
        vocab_size=args.padded_vocab_size,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_heads=args.num_attention_heads,
        max_seq_len=args.max_position_embeddings,
        ln_eps=args.layernorm_epsilon,
        dtype=dtype,
    )


from apex_tpu.transformer.testing.commons import io_params, split_stages  # noqa: E402,F401 - re-export (harness contract)


def embed(io, tokens, cfg: gpt2.GPT2Config, tp_axis: Optional[str] = "tp"):
    """First-stage input: token + positional embedding."""
    s = tokens.shape[-1]
    x = vocab_parallel_embedding(tokens, io["embed"], axis_name=tp_axis)
    return (x + io["pos_embed"][None, :s]).astype(cfg.dtype)


def stage_fn(stage_params, x, cfg: gpt2.GPT2Config,
             tp_axis: Optional[str] = "tp"):
    """One pipeline stage: scan this stage's decoder layers."""

    def body(h, lp):
        return gpt2.decoder_layer(h, lp, cfg, tp_axis), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out


def head_loss(io, x, targets, cfg: gpt2.GPT2Config,
              tp_axis: Optional[str] = "tp"):
    """Last-stage output: final LN + tied-embedding head + vocab-parallel CE."""
    x = gpt2._ln(x, io["lnf_w"], io["lnf_b"], cfg.ln_eps)
    logits = jnp.matmul(
        x, io["embed"].T.astype(x.dtype)).astype(jnp.float32)
    return jnp.mean(
        vocab_parallel_cross_entropy(logits, targets, axis_name=tp_axis))


def gpt_model_provider(args=None):
    """ref standalone_gpt.py:gpt_model_provider — returns
    (cfg, init_fn, split_stages, embed, stage_fn, head_loss)."""
    if args is None:
        from apex_tpu.transformer.testing.global_vars import get_args

        args = get_args()
    cfg = gpt_config_from_args(args)
    return cfg, gpt2.init_params, split_stages, embed, stage_fn, head_loss
