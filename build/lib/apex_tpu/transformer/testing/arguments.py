"""Megatron-style argument parser (ref apex/transformer/testing/arguments.py).

The reference carries the full 800-line Megatron-LM parser; tests consume
a small core of it (model shape, batch/microbatch sizing, parallel sizes,
mixed precision, seed). This parser keeps those flags under the same names
and validation rules so scripts written against the reference's harness
parse unchanged; CUDA-only knobs are accepted and ignored via
``parse_known_args`` rather than enumerated.
"""

from __future__ import annotations

import argparse


def parse_args(extra_args_provider=None, defaults=None,
               ignore_unknown_args: bool = True, args=None):
    """Ref arguments.py:parse_args (core subset, same flag spellings)."""
    parser = argparse.ArgumentParser(description="apex_tpu testing args",
                                     allow_abbrev=False)

    g = parser.add_argument_group("model")
    g.add_argument("--num-layers", type=int, default=4)
    g.add_argument("--hidden-size", type=int, default=64)
    g.add_argument("--num-attention-heads", type=int, default=4)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--seq-length", type=int, default=32)
    g.add_argument("--max-position-embeddings", type=int, default=None)
    g.add_argument("--padded-vocab-size", type=int, default=128)
    g.add_argument("--layernorm-epsilon", type=float, default=1e-5)

    g = parser.add_argument_group("training")
    g.add_argument("--micro-batch-size", type=int, default=2)
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", nargs="*", default=None)
    g.add_argument("--train-iters", type=int, default=10)
    g.add_argument("--lr", type=float, default=1e-3)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--seed", type=int, default=1234)

    g = parser.add_argument_group("parallelism")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument(
        "--virtual-pipeline-model-parallel-size", type=int, default=None)
    g.add_argument("--context-parallel-size", type=int, default=1)
    g.add_argument("--sequence-parallel", action="store_true")
    g.add_argument("--use-cpu-initialization", action="store_true")

    g = parser.add_argument_group("mixed precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None)
    g.add_argument("--initial-loss-scale", type=float, default=2.0 ** 16)
    g.add_argument("--loss-scale-window", type=int, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)

    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        parsed, _ = parser.parse_known_args(args)
    else:
        parsed = parser.parse_args(args)

    for key, value in (defaults or {}).items():
        key = key.replace("-", "_")
        if getattr(parsed, key, None) is None or key not in vars(parsed):
            setattr(parsed, key, value)

    # derived values + validation (ref arguments.py post-parse block)
    if parsed.ffn_hidden_size is None:
        parsed.ffn_hidden_size = 4 * parsed.hidden_size
    if parsed.kv_channels is None:
        if parsed.hidden_size % parsed.num_attention_heads:
            raise ValueError(
                "num_attention_heads must divide hidden_size evenly")
        parsed.kv_channels = parsed.hidden_size // parsed.num_attention_heads
    if parsed.max_position_embeddings is None:
        parsed.max_position_embeddings = parsed.seq_length
    if parsed.fp16 and parsed.bf16:
        raise ValueError("fp16 and bf16 are mutually exclusive")
    parsed.params_dtype = ("float16" if parsed.fp16
                           else "bfloat16" if parsed.bf16 else "float32")

    mp = (parsed.tensor_model_parallel_size
          * parsed.pipeline_model_parallel_size)
    parsed.model_parallel_size = mp
    if parsed.global_batch_size is None:
        parsed.global_batch_size = parsed.micro_batch_size
    if parsed.virtual_pipeline_model_parallel_size is not None:
        if parsed.num_layers % (
                parsed.pipeline_model_parallel_size
                * parsed.virtual_pipeline_model_parallel_size):
            raise ValueError(
                "num_layers must divide pp_size * virtual_pp_size")
    return parsed
