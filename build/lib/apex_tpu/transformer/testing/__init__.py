"""Standalone test harness (ref apex/transformer/testing/).

The reference ships a mini-Megatron (argument parser, global singletons,
toy + standalone GPT/BERT models, a distributed unittest base) so its
transformer tests run without Megatron-LM. The TPU form serves the same
role for mesh-based tests: argument parsing with the same flag names,
`get_args`/`get_num_microbatches` singletons, timers, mesh fixtures, and
standalone model builders over ``apex_tpu.models``.
"""

from apex_tpu.transformer.testing import global_vars
from apex_tpu.transformer.testing.commons import (
    build_mesh,
    fwd_step_func,
    initialize_distributed,
    model_provider_func,
    print_separator,
    set_random_seed,
)

__all__ = [
    "global_vars",
    "build_mesh",
    "fwd_step_func",
    "initialize_distributed",
    "model_provider_func",
    "print_separator",
    "set_random_seed",
]
