"""Distributed test base (ref apex/transformer/testing/distributed_test_base.py).

The reference subclasses a multi-process NCCL test harness; on TPU the
"distributed" environment is the device mesh inside one process, so the
base class manages parallel_state setup/teardown around each test and
skips when the device count can't fit the requested topology.
"""

from __future__ import annotations

import unittest

import jax

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import global_vars


class DistributedTestBase(unittest.TestCase):
    """ref distributed_test_base.py:DistributedTestBase.

    Subclasses set ``TP``/``PP``/``CP`` (defaults 1) and get a live
    parallel_state mesh in every test; state is torn down afterwards.
    """

    TP = 1
    PP = 1
    CP = 1

    @property
    def world_size(self) -> int:
        return len(jax.devices())

    def setUp(self):
        super().setUp()
        need = self.TP * self.PP * self.CP
        if self.world_size % need:
            self.skipTest(
                f"needs a multiple of {need} devices, have {self.world_size}")
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=self.TP,
            pipeline_model_parallel_size_=self.PP,
            context_parallel_size_=self.CP,
        )
        self.mesh = parallel_state.get_mesh()

    def tearDown(self):
        parallel_state.destroy_model_parallel()
        global_vars.destroy_global_vars()
        super().tearDown()


class NcclDistributedTestBase(DistributedTestBase):
    """Name-parity alias (ref uses NCCL; the TPU mesh needs no backend)."""
