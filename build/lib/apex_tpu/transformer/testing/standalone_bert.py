"""Standalone BERT for pipeline-parallel tests
(ref apex/transformer/testing/standalone_bert.py).

Adapts ``apex_tpu.models.bert`` to the harness contract (see
standalone_gpt.py): config from ``get_args``, stage splitting, and
embed / stage_fn / head pieces for the collective pipeline schedules.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.models import bert
from apex_tpu.transformer.tensor_parallel.layers import (
    vocab_parallel_embedding,
)


def bert_config_from_args(args) -> bert.BertConfig:
    dtype = (jnp.bfloat16 if args.params_dtype == "bfloat16"
             else jnp.float16 if args.params_dtype == "float16"
             else jnp.float32)
    return bert.BertConfig(
        vocab_size=args.padded_vocab_size,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_heads=args.num_attention_heads,
        max_seq_len=args.max_position_embeddings,
        ln_eps=args.layernorm_epsilon,
        dtype=dtype,
    )


from apex_tpu.transformer.testing.commons import io_params, split_stages  # noqa: E402,F401 - re-export (harness contract)


def embed(io, tokens, cfg: bert.BertConfig, type_ids=None,
          tp_axis: Optional[str] = "tp"):
    s = tokens.shape[-1]
    x = vocab_parallel_embedding(tokens, io["embed"], axis_name=tp_axis)
    x = x + io["pos_embed"][None, :s]
    if type_ids is None:
        x = x + io["type_embed"][0]
    else:
        x = x + jnp.take(io["type_embed"], type_ids, axis=0)
    return bert._ln(x.astype(cfg.dtype), io["emb_ln_w"], io["emb_ln_b"],
                    cfg.ln_eps)


def stage_fn(stage_params, x, cfg: bert.BertConfig, pad_mask=None,
             tp_axis: Optional[str] = "tp"):
    def body(h, lp):
        return bert.encoder_layer(h, lp, cfg, pad_mask, tp_axis), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out


def head_loss(io, x, targets, loss_mask, cfg: bert.BertConfig,
              tp_axis: Optional[str] = "tp"):
    """MLM head over the final hidden states + masked CE."""
    # mlm_logits reads only io params + the tied embedding
    logits = bert.mlm_logits(io, x, cfg, tp_axis=tp_axis)
    from apex_tpu.transformer.tensor_parallel.cross_entropy import (
        vocab_parallel_cross_entropy,
    )

    ce = vocab_parallel_cross_entropy(logits, targets, axis_name=tp_axis)
    denom = jnp.maximum(jnp.sum(loss_mask), 1.0)
    return jnp.sum(ce * loss_mask) / denom


def bert_model_provider(args=None):
    """ref standalone_bert.py:bert_model_provider."""
    if args is None:
        from apex_tpu.transformer.testing.global_vars import get_args

        args = get_args()
    cfg = bert_config_from_args(args)
    return cfg, bert.init_params, split_stages, embed, stage_fn, head_loss
