"""Context (sequence) parallelism — first-class long-context support.

No reference-file analog (the CUDA reference scales sequence length with
megatron context parallelism + flash attention at the framework level; see
SURVEY.md §2 #53): sequences are sharded over the 'cp' mesh axis and
attention runs as **ring attention** — each step computes one K/V block's
contribution with an online-softmax accumulator (flash-attention algebra in
fp32) and ``ppermute``s the K/V block around the ring, so peak memory is
O(s_local²/P) and the ICI transfer overlaps the block matmul. Backward is
autodiff through the scan: the transposed ppermutes run the ring in reverse.

Alternative: :func:`ulysses_attention` (DeepSpeed-Ulysses-style) swaps
sequence↔head sharding with two ``all_to_all``s and runs plain attention
locally — cheaper at moderate sequence lengths when heads ≥ cp.

All functions run inside ``shard_map`` with 'cp' bound; layouts are
``[batch, seq_local, heads, head_dim]``.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state

_NEG_INF = -1e30


def _axis(axis_name: Optional[str]) -> str:
    return axis_name if axis_name is not None else parallel_state.CONTEXT_AXIS


def _vary_like(x, axis, *like):
    """pvary ``x`` over ``axis`` plus every mesh axis any of ``like`` varies
    over. Fresh-zeros scan carries and cond branches must match the vma of
    values computed from the real inputs — when cp composes with tp/pp/dp
    in one shard_map (the 4-axis dryrun), q/k/v vary over MORE than the
    ring axis and a carry marked only {cp} trips the scan vma check."""
    from apex_tpu.transformer.tensor_parallel.mappings import (
        _to_varying,
        tree_vma,
    )

    for ax in sorted({axis} | tree_vma(like)):
        x = _to_varying(x, ax)
    return x


def ring_attention(
    q,
    k,
    v,
    axis_name: Optional[str] = None,
    causal: bool = True,
    scale: Optional[float] = None,
    remat: bool = True,
):
    """Exact attention over a cp-sharded sequence.

    q/k/v: [b, s_local, h, d] — this rank's sequence shard. Returns the
    attention output for the local queries, identical (up to fp roundoff) to
    full attention over the gathered sequence.

    On TPU (Pallas enabled) each ring step runs the flash-attention kernel
    on the resident K/V block and per-block results merge by logsumexp —
    peak memory O(s_local·d), never a score matrix in HBM (see
    :func:`_ring_flash`); elsewhere the jnp online-softmax path below runs.
    """
    from apex_tpu.ops import pallas_config

    if pallas_config.use_pallas("flash_attention"):
        b, s_local, h, d = q.shape
        h_kv = k.shape[2]
        if h % h_kv:
            raise ValueError(
                f"query heads {h} not a multiple of kv heads {h_kv}")
        sc = float(scale if scale is not None else 1.0 / (d ** 0.5))
        qt = q.transpose(0, 2, 1, 3).reshape(b * h, s_local, d)
        kt = k.transpose(0, 2, 1, 3).reshape(b * h_kv, s_local, d)
        vt = v.transpose(0, 2, 1, 3).reshape(b * h_kv, s_local, d)
        o = _ring_flash(_axis(axis_name), causal, sc, qt, kt, vt)
        return (o.reshape(b, h, s_local, d).transpose(0, 2, 1, 3)
                .astype(q.dtype))
    axis = _axis(axis_name)
    n = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    b, s_local, h, d = q.shape
    h_kv = k.shape[2]
    if h % h_kv:
        raise ValueError(f"query heads {h} not a multiple of kv heads {h_kv}")
    rep = h // h_kv  # GQA: k/v ride the ring at h_kv heads, never repeated
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    q32 = q.astype(jnp.float32) * scale
    if rep > 1:
        q32 = q32.reshape(b, s_local, h_kv, rep, d)
    row_pos = rank * s_local + jnp.arange(s_local)  # global query positions

    def block(carry_kv, src_rank):
        """One K/V block's contribution given its originating rank."""
        k_blk, v_blk = carry_kv
        k32 = k_blk.astype(jnp.float32)
        if rep > 1:
            s = jnp.einsum("bqgrd,bkgd->bgrqk", q32, k32)
            s = s.reshape(b, h, s_local, -1)
        else:
            s = jnp.einsum("bqhd,bkhd->bhqk", q32, k32)
        if causal:
            col_pos = src_rank * s_local + jnp.arange(s_local)
            allowed = col_pos[None, :] <= row_pos[:, None]  # [q, k]
            s = jnp.where(allowed[None, None], s, _NEG_INF)
        return s

    def step(carry, i):
        k_blk, v_blk, m, l, o = carry
        src = (rank - i) % n
        s = block((k_blk, v_blk), src)  # [b, h, q, k]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # fully-masked rows have s == m_new == _NEG_INF; exp(0)=1 would leak
        # weight onto masked keys, so zero them explicitly
        p = jnp.where(
            s <= _NEG_INF * 0.5, 0.0, jnp.exp(s - m_new[..., None])
        )
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        v32 = v_blk.astype(jnp.float32)
        if rep > 1:
            pv = jnp.einsum(
                "bgrqk,bkgd->bgrqd",
                p.reshape(b, h_kv, rep, s_local, -1), v32
            ).reshape(b, h, s_local, d)
        else:
            pv = jnp.einsum("bhqk,bkhd->bhqd", p, v32)
        o = o * alpha[..., None] + pv
        # rotate K/V around the ring (rank r's block moves to r+1)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        return (k_blk, v_blk, m_new, l, o), None

    step_fn = jax.checkpoint(step) if remat else step
    # accumulators become device-varying inside the loop; start them that way
    m0 = _vary_like(jnp.full((b, h, s_local), _NEG_INF, jnp.float32), axis,
                    q, k, v)
    l0 = _vary_like(jnp.zeros((b, h, s_local), jnp.float32), axis, q, k, v)
    o0 = _vary_like(jnp.zeros((b, h, s_local, d), jnp.float32), axis,
                    q, k, v)
    (_, _, m, l, o), _ = jax.lax.scan(
        step_fn, (k, v, m0, l0, o0), jnp.arange(n)
    )
    out = o / jnp.maximum(l, 1e-20)[..., None]  # [b, h, q, d]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


# ------------------------------------------------------ ring flash (Pallas)
# Each ring step runs the flash-attention TPU kernel on the resident K/V
# block; per-block (out, lse) pairs merge by logsumexp. Backward re-runs
# the ring calling the flash dq/dk/dv kernels with the GLOBAL (out, lse) —
# block probabilities recompute exactly, and the circulating dK/dV
# accumulators arrive home after a full rotation (the ring-flash-attention
# algorithm; same design as the fwd/bwd kernels in ops/flash_attention).


def _rotate(x, axis):
    n = jax.lax.axis_size(axis)
    return jax.lax.ppermute(x, axis, [(j, (j + 1) % n) for j in range(n)])


def _merge_lse(o_acc, lse_acc, o_i, lse_i):
    """Merge normalized block outputs by their logsumexps (fp32)."""
    lse_new = jnp.logaddexp(lse_acc, lse_i)
    safe = jnp.where(jnp.isfinite(lse_new), lse_new, 0.0)
    w_a = jnp.exp(lse_acc - safe)[..., None]
    w_i = jnp.exp(lse_i - safe)[..., None]
    return o_acc * w_a + o_i.astype(jnp.float32) * w_i, lse_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _ring_flash(axis, causal, scale, q, k, v):
    """Flattened flash ring: q [bh, s, d], k/v [bh_kv, s, d] (GQA via
    fewer kv rows, kv-major head order as in ops.flash_attention)."""
    return _ring_flash_fwd(axis, causal, scale, q, k, v)[0]


def _ring_flash_block_fwd(q, kb, vb, src, rank, causal, scale, axis, interp):
    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.flash_attention import _flash_fwd_pallas

    bh, s, d = q.shape
    bq, bk = pallas_config.flash_blocks("fwd", s, s, d)

    def diag(_):
        return _flash_fwd_pallas(q, kb, vb, True, scale, bq, bk, interp)

    def full(_):
        return _flash_fwd_pallas(q, kb, vb, False, scale, bq, bk, interp)

    def skip(_):
        # zeros must carry the same vma as the kernel outputs
        return (_vary_like(jnp.zeros((bh, s, d), q.dtype), axis, q, kb, vb),
                _vary_like(jnp.full((bh, s), -jnp.inf, jnp.float32), axis,
                           q, kb, vb))

    if not causal:
        return full(None)
    return jax.lax.cond(
        src == rank, diag,
        lambda _: jax.lax.cond(src < rank, full, skip, None), None)


def _ring_flash_fwd(axis, causal, scale, q, k, v):
    from apex_tpu.ops import pallas_config

    interp = pallas_config.interpret()
    n = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    bh, s, d = q.shape

    def step(carry, i):
        kb, vb, o_acc, lse_acc = carry
        src = (rank - i) % n
        o_i, lse_i = _ring_flash_block_fwd(q, kb, vb, src, rank, causal,
                                           scale, axis, interp)
        o_acc, lse_acc = _merge_lse(o_acc, lse_acc, o_i, lse_i)
        return (_rotate(kb, axis), _rotate(vb, axis), o_acc, lse_acc), None

    o0 = _vary_like(jnp.zeros((bh, s, d), jnp.float32), axis, q, k, v)
    lse0 = _vary_like(jnp.full((bh, s), -jnp.inf, jnp.float32), axis,
                      q, k, v)
    (_, _, o, lse), _ = jax.lax.scan(step, (k, v, o0, lse0), jnp.arange(n))
    o = o.astype(q.dtype)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd(axis, causal, scale, res, do):
    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.flash_attention import _flash_bwd_pallas

    q, k, v, o, lse = res
    interp = pallas_config.interpret()
    n = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    bh, s, d = q.shape
    bh_kv = k.shape[0]
    bq, bk = pallas_config.flash_blocks("bwd", s, s, d)

    def block_bwd(kb, vb, src):
        def diag(_):
            return _flash_bwd_pallas(q, kb, vb, o, lse, do, True, scale,
                                     bq, bk, interp)

        def full(_):
            return _flash_bwd_pallas(q, kb, vb, o, lse, do, False, scale,
                                     bq, bk, interp)

        def skip(_):
            return (_vary_like(jnp.zeros((bh, s, d), q.dtype), axis,
                               q, kb, vb, do),
                    _vary_like(jnp.zeros((bh_kv, s, d), k.dtype), axis,
                               q, kb, vb, do),
                    _vary_like(jnp.zeros((bh_kv, s, d), v.dtype), axis,
                               q, kb, vb, do))

        if not causal:
            return full(None)
        return jax.lax.cond(
            src == rank, diag,
            lambda _: jax.lax.cond(src < rank, full, skip, None), None)

    def step(carry, i):
        kb, vb, dkb, dvb, dq_acc = carry
        src = (rank - i) % n
        dq_i, dk_i, dv_i = block_bwd(kb, vb, src)
        dq_acc = dq_acc + dq_i.astype(jnp.float32)
        dkb = dkb + dk_i.astype(jnp.float32)
        dvb = dvb + dv_i.astype(jnp.float32)
        # dK/dV accumulators travel WITH their block; after the full
        # rotation they are home with every rank's contribution
        return (_rotate(kb, axis), _rotate(vb, axis), _rotate(dkb, axis),
                _rotate(dvb, axis), dq_acc), None

    z_kv = _vary_like(jnp.zeros((bh_kv, s, d), jnp.float32), axis,
                      q, k, v, do)
    z_q = _vary_like(jnp.zeros((bh, s, d), jnp.float32), axis, q, k, v, do)
    (_, _, dk_out, dv_out, dq_out), _ = jax.lax.scan(
        step, (k, v, z_kv, z_kv, z_q), jnp.arange(n))
    return (dq_out.astype(q.dtype), dk_out.astype(k.dtype),
            dv_out.astype(v.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ulysses_attention(
    q,
    k,
    v,
    attn_fn: Optional[Callable] = None,
    axis_name: Optional[str] = None,
    causal: bool = True,
    scale: Optional[float] = None,
):
    """All-to-all sequence parallelism: trade seq sharding for head sharding,
    attend locally over the FULL sequence, swap back.

    Requires heads % cp == 0. ``attn_fn(q, k, v)`` (full-sequence layouts)
    defaults to plain softmax attention with the usual 1/√d scale.
    """
    axis = _axis(axis_name)
    n = jax.lax.axis_size(axis)

    def seq_to_heads(x):
        # [b, s_local, h, d] -> [b, s_full, h/n, d]
        x = jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                               tiled=True)
        return x

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    qf, kf, vf = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)

    if attn_fn is None:
        d = q.shape[-1]
        sc = scale if scale is not None else 1.0 / (d ** 0.5)

        def attn_fn(q, k, v):
            s = jnp.einsum(
                "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
            ) * sc
            if causal:
                sq, sk = s.shape[-2], s.shape[-1]
                rows = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
                cols = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
                s = jnp.where((cols > rows)[None, None], _NEG_INF, s)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
            return o.astype(q.dtype)

    of = attn_fn(qf, kf, vf)
    return heads_to_seq(of)


def split_sequence(x, axis_name: Optional[str] = None, seq_dim: int = 1):
    """Take this rank's sequence chunk (delegates to the tensor_parallel
    mapping; the cp default axis and [b, s, ...] seq_dim=1 differ)."""
    from apex_tpu.transformer.tensor_parallel import mappings

    return mappings.scatter_to_sequence_parallel_region(
        x, _axis(axis_name), seq_dim=seq_dim)


def gather_sequence(x, axis_name: Optional[str] = None, seq_dim: int = 1):
    """Inverse of :func:`split_sequence`."""
    from apex_tpu.transformer.tensor_parallel import mappings

    return mappings.gather_from_sequence_parallel_region(
        x, _axis(axis_name), seq_dim=seq_dim)


def context_parallel_positions(s_local: int, axis_name: Optional[str] = None):
    """Global position ids for this rank's shard (feed to RoPE)."""
    axis = _axis(axis_name)
    rank = jax.lax.axis_index(axis)
    return rank * s_local + jnp.arange(s_local)
