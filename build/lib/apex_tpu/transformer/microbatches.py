"""Number-of-microbatches calculators (ref apex/transformer/microbatches.py).

Pure host-side bookkeeping (it feeds the pipeline schedule's static loop
bounds, so it must be Python ints, never traced values).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional

from apex_tpu.transformer.utils import divide


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
):
    """ref microbatches.py:26 — pick constant vs rampup calculator."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size
        )
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "rampup_batch_size must be [start_batch_size, increment, "
            f"ramp-up samples], got {rampup_batch_size}"
        )
    start, incr, samples = (int(v) for v in rampup_batch_size)
    return RampupBatchsizeNumMicroBatches(
        start,
        incr,
        samples,
        global_batch_size,
        micro_batch_size,
        data_parallel_size,
    )


class NumMicroBatchesCalculator(ABC):
    """ref microbatches.py:77."""

    def __init__(self):
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    @abstractmethod
    def update(self, consumed_samples, consistency_check) -> None:
        ...


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """ref microbatches.py:93."""

    def __init__(self, global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        self.num_micro_batches = divide(global_batch_size, micro_batch_times_dp)
        if self.num_micro_batches < 1:
            raise ValueError("global batch smaller than one microbatch per replica")
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def update(self, consumed_samples, consistency_check) -> None:
        del consumed_samples, consistency_check


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Linear batch-size ramp-up (ref microbatches.py:112)."""

    def __init__(
        self,
        start_batch_size,
        batch_size_increment,
        ramup_samples,
        global_batch_size,
        micro_batch_size,
        data_parallel_size,
    ):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size
        )
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size

        diff = global_batch_size - start_batch_size
        if diff < 0:
            raise ValueError(
                "global batch size must be ≥ start batch size for ramp-up"
            )
        if diff % batch_size_increment != 0:
            raise ValueError(
                "(global - start) batch size must be divisible by the increment"
            )
        num_increments = diff // batch_size_increment
        self.rampup_samples_per_increment = (
            self.ramup_samples / num_increments if num_increments > 0 else 0
        )
        self.update(0, False)

    def update(self, consumed_samples, consistency_check) -> None:
        if (
            consumed_samples > self.ramup_samples
            or self.rampup_samples_per_increment == 0
        ):
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment
            )
            self.current_global_batch_size = min(
                self.current_global_batch_size, self.global_batch_size
            )
        if consistency_check:
            divide(
                self.current_global_batch_size,
                self.micro_batch_times_data_parallel_size,
            )
        self.num_micro_batches = (
            self.current_global_batch_size
            // self.micro_batch_times_data_parallel_size
        )
