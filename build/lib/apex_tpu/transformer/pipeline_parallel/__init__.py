"""Pipeline parallelism over the 'pp' mesh axis
(ref apex/transformer/pipeline_parallel/__init__.py)."""

from apex_tpu.transformer.pipeline_parallel import p2p
from apex_tpu.transformer.pipeline_parallel import utils
from apex_tpu.transformer.pipeline_parallel._timers import Timers
from apex_tpu.transformer.pipeline_parallel.schedules import (
    ExperimentalWarning,
    build_model,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    get_params_for_weight_decay_optimization,
    pipelined_forward,
    pipelined_forward_interleaved,
)

# parity alias for the reference module name
p2p_communication = p2p

__all__ = [
    "p2p",
    "p2p_communication",
    "utils",
    "Timers",
    "ExperimentalWarning",
    "build_model",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_with_interleaving",
    "forward_backward_pipelining_without_interleaving",
    "get_forward_backward_func",
    "get_params_for_weight_decay_optimization",
    "pipelined_forward",
    "pipelined_forward_interleaved",
]
