"""Stage-to-stage communication (ref apex/transformer/pipeline_parallel/p2p_communication.py).

The reference posts paired NCCL isend/irecv ops between pipeline neighbours
(ref p2p_communication.py:29 ``_run_p2pops``). On TPU, neighbour exchange is
one collective: ``lax.ppermute`` over the 'pp' mesh axis moves every stage's
tensor to its neighbour in a single ICI hop, and XLA overlaps it with
compute. Each "send X recv Y" pair from the reference API is therefore a
single ppermute here; ranks with no sender receive **zeros** (ppermute
semantics), which is exactly what the schedules want for warmup bubbles.

Shape negotiation (``_communicate``'s tensor_shape exchange) does not exist:
shapes are static under jit.

All functions must run inside ``shard_map`` with 'pp' bound.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state


def _axis(axis_name: Optional[str]) -> str:
    return axis_name if axis_name is not None else parallel_state.PIPELINE_AXIS


def _shift(x, delta: int, axis_name: Optional[str] = None):
    """ppermute every stage's ``x`` to rank+delta (non-cyclic: edge ranks
    receive zeros)."""
    axis = _axis(axis_name)
    n = jax.lax.axis_size(axis)
    perm = [
        (i, i + delta) for i in range(n) if 0 <= i + delta < n
    ]
    return jax.lax.ppermute(x, axis, perm)


def _shift_cyclic(x, delta: int, axis_name: Optional[str] = None):
    """Cyclic ppermute (used by the interleaved schedule's ring)."""
    axis = _axis(axis_name)
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + delta) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def send_forward_recv_forward(output_tensor, axis_name: Optional[str] = None):
    """Push activations one stage downstream; returns what arrived from the
    previous stage (ref p2p_communication.py:337)."""
    return _shift(output_tensor, +1, axis_name)


def send_backward_recv_backward(input_grad, axis_name: Optional[str] = None):
    """Push gradients one stage upstream (ref p2p_communication.py:361)."""
    return _shift(input_grad, -1, axis_name)


def send_forward(output_tensor, axis_name: Optional[str] = None):
    """Collective alias: on TPU a lone send is still the paired shift —
    the result is meaningful on the receiving ranks
    (ref p2p_communication.py:237)."""
    return _shift(output_tensor, +1, axis_name)


def recv_forward(output_tensor, axis_name: Optional[str] = None):
    """Alias of :func:`send_forward` from the receiver's point of view
    (ref p2p_communication.py:187): pass the tensor being sent by the
    upstream stages; every stage gets its predecessor's copy."""
    return _shift(output_tensor, +1, axis_name)


def send_backward(input_grad, axis_name: Optional[str] = None):
    """ref p2p_communication.py:263."""
    return _shift(input_grad, -1, axis_name)


def recv_backward(input_grad, axis_name: Optional[str] = None):
    """ref p2p_communication.py:213."""
    return _shift(input_grad, -1, axis_name)


def send_forward_recv_backward(output_tensor, input_grad,
                               axis_name: Optional[str] = None):
    """Both directions in one step (ref p2p_communication.py:287); XLA
    schedules the two ppermutes concurrently on opposite ICI directions."""
    return (_shift(input_grad, -1, axis_name),
            _shift(output_tensor, +1, axis_name))


def send_backward_recv_forward(input_grad, output_tensor,
                               axis_name: Optional[str] = None):
    """ref p2p_communication.py:312."""
    return (_shift(output_tensor, +1, axis_name),
            _shift(input_grad, -1, axis_name))


def send_forward_backward_recv_forward_backward(
    output_tensor, input_grad, axis_name: Optional[str] = None
):
    """ref p2p_communication.py:385."""
    return (_shift(output_tensor, +1, axis_name),
            _shift(input_grad, -1, axis_name))


def embedding_allreduce(grad, axis_name: Optional[str] = None):
    """Sum embedding grads between first and last stage (the reference's
    embedding group allreduce; ref parallel_state.py:301 + Megatron's
    allreduce_word_embedding_grads): contribute zero unless first/last."""
    axis = _axis(axis_name)
    n = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    is_member = (r == 0) | (r == n - 1)
    masked = jnp.where(is_member, grad, jnp.zeros_like(grad))
    total = jax.lax.psum(masked, axis)
    return jnp.where(is_member, total, grad)
