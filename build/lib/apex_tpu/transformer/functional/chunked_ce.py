"""Chunked fused lm-head + cross-entropy: the ``[N, vocab]`` logits are
never materialized.

No reference-file analog (the CUDA reference predates this pattern; its
closest relative is contrib/xentropy's fused CE over *existing* logits).
TPU-first rationale: for an LLM loss the fp32 logits are often the
single largest live buffer (B·S·V·4 bytes — 1 GiB at the bench.py Llama
shapes), bigger than any activation. Streaming the vocab dimension in
``num_chunks`` slices with an online logsumexp (the flash-attention
trick applied to the classifier) caps that at ``B·S·V/num_chunks`` and
lets a larger batch fit HBM — more MXU work per step, higher MFU. The
backward recomputes each chunk's logits from the saved row statistics
instead of saving them.

All math is fp32 regardless of input dtypes (CE is range-sensitive;
same policy as contrib.xentropy).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["chunked_lm_cross_entropy"]


def _chunk_weights(weight, bias, num_chunks):
    h, v = weight.shape
    if v % num_chunks:
        raise ValueError(
            f"vocab {v} must divide into num_chunks={num_chunks}")
    vc = v // num_chunks
    w = weight.reshape(h, num_chunks, vc).transpose(1, 0, 2)  # [C, h, Vc]
    b = bias.astype(jnp.float32).reshape(num_chunks, vc)      # [C, Vc]
    los = (jnp.arange(num_chunks) * vc).astype(jnp.int32)
    return w, b, los, vc


def _rank_offset(tp_axis, v_local):
    if tp_axis is None:
        return jnp.int32(0)
    return (jax.lax.axis_index(tp_axis) * v_local).astype(jnp.int32)


def _carry_axes(tp_axis, *operands):
    """Mesh axes the scan carries become varying over: every axis any
    operand already varies over (e.g. 'cp'-sharded hidden states), plus
    the explicit vocab-parallel axis."""
    from apex_tpu.transformer.tensor_parallel.mappings import tree_vma

    axes = set(tree_vma(*operands))
    if tp_axis is not None:
        axes.add(tp_axis)
    return sorted(axes)


def _vary(x, axes):
    from apex_tpu.transformer.tensor_parallel.mappings import make_varying

    for ax in axes:
        x = make_varying(x, ax)
    return x


def chunked_lm_cross_entropy(hidden, weight, labels, num_chunks=8,
                             tp_axis=None, bias=None):
    """Per-token CE of ``hidden @ weight (+ bias)`` vs ``labels`` without
    the ``[N, V]`` logits: ``hidden`` [N, h], ``weight`` [h, V] (the
    lm-head kernel; pass ``embed.T`` for tied embeddings), ``labels``
    [N] int, optional ``bias`` [V] (e.g. HF BERT's decoder bias — it
    streams in the same vocab chunks). Returns per-token losses [N]
    (fp32).

    ``tp_axis``: inside ``shard_map`` with a vocab-sharded weight
    ([h, V/tp] per rank, Megatron layout; bias shards the same way),
    composes the chunked pass with the vocab-parallel reduction — local
    online logsumexp per rank, then pmax/psum across ranks (the
    vocab_parallel_cross_entropy math, streamed). The backward psums the
    partial ``d_hidden`` the way the column-parallel matmul transpose
    would."""
    if bias is None:
        bias = jnp.zeros((weight.shape[1],), jnp.float32)
    return _ce(hidden, weight, bias, labels, num_chunks, tp_axis)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _ce(hidden, weight, bias, labels, num_chunks, tp_axis):
    return _fwd(hidden, weight, bias, labels, num_chunks, tp_axis)[0]


def _fwd(hidden, weight, bias, labels, num_chunks, tp_axis):
    w, bch, los, vc = _chunk_weights(weight, bias, num_chunks)
    x32 = hidden.astype(jnp.float32)
    n = x32.shape[0]
    lo_rank = _rank_offset(tp_axis, weight.shape[1])
    axes = _carry_axes(tp_axis, hidden, weight, bias, labels)

    def body(carry, inp):
        m, s, tgt = carry
        w_c, b_c, lo = inp
        logits = x32 @ w_c.astype(jnp.float32) + b_c      # [N, Vc]
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = (s * jnp.exp(m - m_new)
             + jnp.sum(jnp.exp(logits - m_new[:, None]), axis=-1))
        idx = labels.astype(jnp.int32) - lo_rank - lo
        in_c = (idx >= 0) & (idx < vc)
        tl = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, vc - 1)[:, None], axis=1)[:, 0]
        tgt = jnp.where(in_c, tl, tgt)
        return (m_new, s, tgt), None

    init = (_vary(jnp.full((n,), -jnp.inf, jnp.float32), axes),
            _vary(jnp.zeros((n,), jnp.float32), axes),
            _vary(jnp.zeros((n,), jnp.float32), axes))
    (m, s, tgt), _ = jax.lax.scan(body, init, (w, bch, los))
    if tp_axis is not None:
        # vocab-parallel merge of the per-rank streams (the stable
        # cross-rank max/sum of tensor_parallel/cross_entropy.py)
        m_g = jax.lax.pmax(m, tp_axis)
        s = jax.lax.psum(s * jnp.exp(m - m_g), tp_axis)
        tgt = jax.lax.psum(tgt, tp_axis)  # exactly one rank contributed
        m = m_g
    lse = jnp.log(s) + m
    return lse - tgt, (hidden, weight, bias, labels, lse)


def _bwd(num_chunks, tp_axis, res, g):
    hidden, weight, bias, labels, lse = res
    w, bch, los, vc = _chunk_weights(weight, bias, num_chunks)
    x32 = hidden.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    lo_rank = _rank_offset(tp_axis, weight.shape[1])
    axes = _carry_axes(tp_axis, hidden, weight, bias, labels, g)

    def body(dx, inp):
        w_c, b_c, lo = inp
        w32 = w_c.astype(jnp.float32)
        logits = x32 @ w32 + b_c                          # recompute [N, Vc]
        p = jnp.exp(logits - lse[:, None])                # softmax slice
        idx = labels.astype(jnp.int32) - lo_rank - lo
        in_c = (idx >= 0) & (idx < vc)
        onehot = (jax.nn.one_hot(jnp.clip(idx, 0, vc - 1), vc,
                                 dtype=jnp.float32)
                  * in_c[:, None].astype(jnp.float32))
        d = (p - onehot) * g32[:, None]                   # [N, Vc]
        dx = dx + d @ w32.T
        dw_c = x32.T @ d                                  # [h, Vc]
        db_c = jnp.sum(d, axis=0)                         # [Vc]
        return dx, (dw_c, db_c)

    dx, (dws, dbs) = jax.lax.scan(
        body, _vary(jnp.zeros_like(x32), axes), (w, bch, los))
    if tp_axis is not None:
        # each rank's dx covers only its vocab shard's columns — the
        # column-parallel transpose is an allreduce
        dx = jax.lax.psum(dx, tp_axis)
    dweight = dws.transpose(1, 0, 2).reshape(weight.shape)
    dbias = dbs.reshape(bias.shape).astype(bias.dtype)
    return (dx.astype(hidden.dtype), dweight.astype(weight.dtype), dbias,
            None)


_ce.defvjp(_fwd, _bwd)
