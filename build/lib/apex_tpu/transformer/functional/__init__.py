"""Fused functional ops (ref apex/transformer/functional/__init__.py)."""

from apex_tpu.transformer.functional.fused_softmax import (
    FusedScaleMaskSoftmax,
    scaled_masked_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.functional.chunked_ce import (
    chunked_lm_cross_entropy,
)
from apex_tpu.transformer.functional.rope import (
    apply_rotary_pos_emb,
    apply_rotary_qk,
    fused_apply_rotary_pos_emb,
    rotary_freqs,
)

__all__ = [
    "chunked_lm_cross_entropy",
    "FusedScaleMaskSoftmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "apply_rotary_pos_emb",
    "apply_rotary_qk",
    "fused_apply_rotary_pos_emb",
    "rotary_freqs",
]
