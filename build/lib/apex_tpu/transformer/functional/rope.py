"""Fused rotary position embedding (ref csrc/megatron fused_rotary_positional_embedding
via apex.transformer.functional.fused_rope API surface).

The CUDA kernel fuses the rotate-half multiply-add; on TPU the whole
expression is a single XLA fusion already, so the value here is the exact
Megatron semantics (interleaved halves, fp32 trig, optional partial rotary
dim) in one place, shared by the model families.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp


def rotary_freqs(
    seq_len: int,
    dim: int,
    base: float = 10000.0,
    dtype=jnp.float32,
) -> jnp.ndarray:
    """[seq, dim] angle table θ_{t,i} (Megatron RotaryEmbedding analog)."""
    inv = 1.0 / (
        base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim)
    )
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)  # [seq, dim/2]
    return jnp.concatenate([freqs, freqs], axis=-1).astype(dtype)


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def fused_apply_rotary_pos_emb(t, freqs) -> jnp.ndarray:
    """Apply rotary embedding: t·cos + rotate_half(t)·sin, fp32 trig.

    ``t``: [..., seq, ..., dim] with ``freqs`` broadcastable [seq, dim] →
    callers reshape freqs to line up (Megatron uses [sq, 1, 1, hn]).
    Partial rotary (freqs dim < t dim) rotates the leading slice and passes
    the rest through, like the reference kernel.
    """
    rot_dim = freqs.shape[-1]
    t_rot, t_pass = t[..., :rot_dim], t[..., rot_dim:]
    f32 = jnp.float32
    cos, sin = jnp.cos(freqs.astype(f32)), jnp.sin(freqs.astype(f32))
    out = t_rot.astype(f32) * cos + _rotate_half(t_rot.astype(f32)) * sin
    out = out.astype(t.dtype)
    if t_pass.shape[-1] == 0:
        return out
    return jnp.concatenate([out, t_pass], axis=-1)


def apply_rotary_pos_emb(t, freqs) -> jnp.ndarray:
    """Megatron-shaped entry: t [sq, b, np, hn], freqs [sq, 1, 1, hn]."""
    return fused_apply_rotary_pos_emb(t, freqs)


def apply_rotary_qk(
    q,
    k,
    freqs: Optional[jnp.ndarray] = None,
    *,
    positions: Optional[jnp.ndarray] = None,
    base: float = 10000.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Convenience for [b, seq, heads, dim] layouts (our model families).

    ``positions`` ([b, seq] int) selects rows of the angle table for packed /
    shifted sequences (context-parallel shards pass their global offsets).
    """
    dim = q.shape[-1]
    if freqs is None:
        if positions is not None:
            # Compute angles straight from positions — no table, no
            # data-dependent bound, traceable under jit/shard_map.
            inv = 1.0 / (
                base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim)
            )
            half = positions.astype(jnp.float32)[..., None] * inv  # [b,s,d/2]
            freqs = jnp.concatenate([half, half], axis=-1)
        else:
            freqs = rotary_freqs(q.shape[1], dim, base)
    if freqs.ndim == 2:  # [seq, dim] -> [1, seq, 1, dim]
        freqs = freqs[None, :, None, :]
    elif freqs.ndim == 3:  # [b, seq, dim] -> [b, seq, 1, dim]
        freqs = freqs[:, :, None, :]
    return (
        fused_apply_rotary_pos_emb(q, freqs),
        fused_apply_rotary_pos_emb(k, freqs),
    )
