"""Model-parallel RNG state tracking (ref apex/transformer/tensor_parallel/random.py).

The reference snapshots/restores CUDA RNG states so that dropout inside
tensor-parallel regions differs per tp rank while everything else matches
(ref random.py:120 CudaRNGStatesTracker). JAX keys are explicit and
functional, so the tracker holds named PRNG keys; per-rank divergence is a
``fold_in`` of the tp axis index — deterministic, trace-friendly, and exactly
reproducible on replay, which is also why activation checkpointing needs no
special RNG save/restore here: ``jax.checkpoint`` replays the same folded
keys (vs the reference's CheckpointFunction manually stashing CUDA states,
ref random.py:233-305).
"""

from __future__ import annotations

import contextlib
from typing import Dict

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel.mappings import _axis_bound

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


class RNGStatesTracker:
    """Named PRNG keys with fork semantics (ref random.py:120)."""

    def __init__(self):
        self.states_: Dict[str, jax.Array] = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        if not isinstance(states, dict):
            raise TypeError("states must be a dict of name -> PRNG key")
        self.states_ = dict(states)

    def add(self, name: str, seed: int):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already present")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"rng state {name} already present")
        self.states_[name] = jax.random.PRNGKey(seed)

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Yield a fresh subkey from the named stream and advance it.

        The reference swaps the global CUDA state in/out; here the caller
        gets an explicit key to pass to its dropout/init.
        """
        if name not in self.states_:
            raise KeyError(f"rng state {name} is not added")
        key = self.states_[name]
        key, sub = jax.random.split(key)
        self.states_[name] = key
        yield sub


# Parity alias (the reference class name).
CudaRNGStatesTracker = RNGStatesTracker

_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    return _RNG_STATE_TRACKER


# Parity alias (ref random.py:195).
get_cuda_rng_tracker = get_rng_tracker


def model_parallel_rng_seed(seed: int) -> None:
    """Seed the default + tensor-parallel streams (ref random.py:200
    ``model_parallel_cuda_manual_seed``): tp stream = seed + 2718 + tp_rank,
    default stream = seed (same across tp, differs per dp via the caller's
    data sharding)."""
    offset = seed + 2718
    tracker = get_rng_tracker()
    tracker.reset()
    tracker.add("default", seed)
    tracker.add(_MODEL_PARALLEL_RNG_TRACKER_NAME, offset)
    # Per-rank divergence happens at use time via fold_in (trace-friendly).


model_parallel_cuda_manual_seed = model_parallel_rng_seed


def tp_rank_key(key, axis_name=None):
    """Fold the tensor-parallel rank into a key (per-rank dropout streams)."""
    axis = axis_name if axis_name is not None else parallel_state.TENSOR_AXIS
    if not _axis_bound(axis):
        return key
    return jax.random.fold_in(key, jax.lax.axis_index(axis))


def checkpoint(function, *args, **kwargs):
    """Activation-checkpointed call (ref random.py:306 ``checkpoint``).

    ``jax.checkpoint`` rematerializes the forward during backward; explicit
    PRNG keys replay identically, so no RNG state stashing is needed.
    """
    return jax.checkpoint(function)(*args, **kwargs)


def init_checkpointed_activations_memory_buffer(*args, **kwargs):
    """No-op: XLA owns activation memory; remat policy replaces the
    reference's hand-managed buffer (ref random.py:45)."""
    del args, kwargs


def reset_checkpointed_activations_memory_buffer():
    """No-op (ref random.py:80)."""
