"""Cross-rank data broadcast (ref apex/transformer/tensor_parallel/data.py).

The reference broadcasts tokenized batches from tp-rank-0 to the rest of the
tp group so every rank sees identical data. Under single-controller JAX the
host hands the same global arrays to every device by construction, so
``broadcast_data`` reduces to dtype checking + casting; under multi-host
(multi-controller) it broadcasts host-0's arrays with
``multihost_utils.broadcast_one_to_all``.
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp


def _check_data_types(keys, data, target_dtype):
    """ref data.py:25."""
    for key in keys:
        if jnp.asarray(data[key]).dtype != target_dtype:
            raise ValueError(
                f"{key} has data type {jnp.asarray(data[key]).dtype}, "
                f"expected {target_dtype}"
            )


def _build_key_size_numel_dictionaries(keys, data):
    """ref data.py:34 — shapes/sizes bookkeeping."""
    key_size = {}
    key_numel = {}
    total_numel = 0
    for key in keys:
        arr = jnp.asarray(data[key])
        key_size[key] = arr.shape
        numel = int(arr.size)
        key_numel[key] = numel
        total_numel += numel
    return key_size, key_numel, total_numel


def broadcast_data(keys: Sequence[str], data: Dict, datatype) -> Dict:
    """Return ``{key: array}`` identical on every rank (ref data.py:80)."""
    _check_data_types(keys, data, datatype)
    key_size, _, _ = _build_key_size_numel_dictionaries(keys, data)
    out = {}
    multi_process = jax.process_count() > 1
    for key in keys:
        arr = jnp.asarray(data[key], dtype=datatype)
        if multi_process:
            from jax.experimental import multihost_utils

            arr = multihost_utils.broadcast_one_to_all(arr)
        out[key] = arr.reshape(key_size[key])
    return out
