"""Tensor-parallel helpers (ref apex/transformer/tensor_parallel/utils.py)."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

from apex_tpu.transformer.utils import divide


def split_tensor_along_last_dim(
    tensor, num_partitions: int, contiguous_split_chunks: bool = False
):
    """Split along the last dim (ref utils.py:20). Chunks are always
    "contiguous" on TPU — XLA owns layout — so the flag is accepted and
    ignored."""
    del contiguous_split_chunks
    last_dim_size = divide(tensor.shape[-1], num_partitions)
    return jnp.split(
        tensor,
        [last_dim_size * i for i in range(1, num_partitions)],
        axis=tensor.ndim - 1,
    )


class VocabUtility:
    """Vocab range bookkeeping for vocab-parallel embeddings/CE
    (ref utils.py:40)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
        per_partition_vocab_size: int, rank, world_size: int
    ) -> Tuple[int, int]:
        index_f = rank * per_partition_vocab_size
        index_l = index_f + per_partition_vocab_size
        return index_f, index_l

    @staticmethod
    def vocab_range_from_global_vocab_size(
        global_vocab_size: int, rank, world_size: int
    ) -> Tuple[int, int]:
        per_partition_vocab_size = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_partition_vocab_size, rank, world_size
        )
