"""Vocab-parallel cross entropy (ref apex/transformer/tensor_parallel/cross_entropy.py).

The logits' vocab dim is sharded across the tensor-parallel axis; the loss is
computed without ever materializing the full-vocab logits on one device:

    1. global max  — pmax over tp (numerical stability)
    2. sum of exp  — local row-sum, then psum
    3. target logit — each rank masks targets outside its vocab slice,
       gathers its local value, psum combines (exactly one rank contributes)

Backward is a custom_vjp: d logits = (softmax - onehot_local) * g, computed
from the saved (exp_logits, sum_exp, target_mask) — the same memory shape the
reference saves (ref cross_entropy.py:23-99 _VocabParallelCrossEntropy).

Runs inside ``shard_map`` with the tp axis bound and per-shard logits
``[..., vocab/tp]``; with tp=1 it degrades to plain stable CE, so the same
model code works unsharded.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel.mappings import _axis_bound


@functools.lru_cache(maxsize=None)
def _make_vocab_parallel_ce(axis: Optional[str]):
    """Build the custom_vjp CE for a fixed (hashable) axis name."""

    def pmax(x):
        return jax.lax.pmax(x, axis) if axis else x

    def psum(x):
        return jax.lax.psum(x, axis) if axis else x

    def rank():
        return jax.lax.axis_index(axis) if axis else 0

    def fwd_math(logits, target, label_smoothing):
        # logits: [..., v_local]; target: [...] global vocab ids.
        v_local = logits.shape[-1]
        logits_max = pmax(jnp.max(logits, axis=-1))
        logits = logits - jax.lax.stop_gradient(logits_max)[..., None]
        exp_logits = jnp.exp(logits)
        sum_exp = psum(jnp.sum(exp_logits, axis=-1))

        vocab_start = rank() * v_local
        local_target = target - vocab_start
        in_range = (local_target >= 0) & (local_target < v_local)
        safe_target = jnp.where(in_range, local_target, 0)
        predicted = jnp.take_along_axis(
            logits, safe_target[..., None], axis=-1
        )[..., 0]
        predicted = psum(jnp.where(in_range, predicted, 0.0))

        loss = jnp.log(sum_exp) - predicted
        if label_smoothing > 0.0:
            # Smoothed CE = (1-eps)·CE + eps·mean over vocab of -log p
            # (ref contrib/xentropy semantics; vocab mean needs the global
            # sum of logits).
            vocab_size = v_local * (
                jax.lax.axis_size(axis) if axis else 1
            )
            mean_logit = psum(jnp.sum(logits, axis=-1)) / vocab_size
            smooth_loss = jnp.log(sum_exp) - mean_logit
            loss = (1.0 - label_smoothing) * loss + label_smoothing * smooth_loss
        residuals = (exp_logits, sum_exp, in_range, safe_target)
        return loss, residuals

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def ce(logits, target, label_smoothing=0.0):
        return fwd_math(logits, target, label_smoothing)[0]

    def ce_fwd(logits, target, label_smoothing):
        loss, res = fwd_math(logits, target, label_smoothing)
        return loss, (res, target, logits.shape[-1])

    def ce_bwd(label_smoothing, carry, g):
        (exp_logits, sum_exp, in_range, safe_target), target, v_local = carry
        del target
        softmax = exp_logits / sum_exp[..., None]
        onehot = jax.nn.one_hot(
            safe_target, v_local, dtype=softmax.dtype
        ) * in_range[..., None].astype(softmax.dtype)
        if label_smoothing > 0.0:
            vocab_size = v_local * (
                jax.lax.axis_size(axis) if axis else 1
            )
            grad = softmax - (1.0 - label_smoothing) * onehot
            grad = grad - label_smoothing / vocab_size
        else:
            grad = softmax - onehot
        d_logits = grad * g[..., None]
        return (d_logits.astype(exp_logits.dtype), None)

    ce.defvjp(ce_fwd, ce_bwd)
    return ce


def vocab_parallel_cross_entropy(
    vocab_parallel_logits,
    target,
    label_smoothing: float = 0.0,
    axis_name: Optional[str] = None,
):
    """Per-token CE over vocab-sharded logits (ref cross_entropy.py:101)."""
    axis = axis_name if axis_name is not None else parallel_state.TENSOR_AXIS
    if not _axis_bound(axis):
        axis = None
    return _make_vocab_parallel_ce(axis)(
        vocab_parallel_logits, target, label_smoothing
    )
