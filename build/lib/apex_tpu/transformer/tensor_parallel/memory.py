"""Flat memory buffers (ref apex/transformer/tensor_parallel/memory.py).

The reference pre-allocates big flat CUDA buffers and hands out zero-copy
views to dodge the caching allocator's fragmentation. XLA owns device memory
under jit, so the TPU analog keeps the *packing* semantics — a flat array
plus offset bookkeeping, useful for fused multi-tensor updates and bucketed
collectives — with buffer donation (``jax.jit(donate_argnums=...)``) playing
the role of in-place reuse.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

_MEM_BUFFS: Dict[str, "MemoryBuffer"] = {}


def allocate_mem_buff(name, numel, dtype, track_usage):
    """ref memory.py:23."""
    if name in _MEM_BUFFS:
        raise ValueError(f"memory buffer {name} already allocated")
    _MEM_BUFFS[name] = MemoryBuffer(name, numel, dtype, track_usage)
    return _MEM_BUFFS[name]


def get_mem_buff(name):
    """ref memory.py:30."""
    return _MEM_BUFFS.get(name)


def reset_mem_buffs():
    _MEM_BUFFS.clear()


class MemoryBuffer:
    """Flat buffer with bump-pointer allocation (ref memory.py:35)."""

    def __init__(self, name, numel, dtype, track_usage=False):
        self.name = name
        self.numel = numel
        self.dtype = dtype
        self.data = jnp.zeros((numel,), dtype=dtype)
        self._start = 0
        self.track_usage = track_usage
        self.in_use_value = 0.0
        self.total_value = 0.0

    def reset(self):
        self._start = 0
        if self.track_usage:
            self.total_value += float(self.numel)
            self.in_use_value = 0.0

    def is_in_use(self) -> bool:
        return self._start > 0

    def allocated(self) -> int:
        return self._start

    def add(self, shape):
        """Reserve a region; returns (start, stop) flat offsets."""
        numel = 1
        for s in shape:
            numel *= int(s)
        if self._start + numel > self.numel:
            raise MemoryError(
                f"buffer {self.name} out of space "
                f"({self._start}+{numel} > {self.numel})"
            )
        start = self._start
        self._start += numel
        if self.track_usage:
            self.in_use_value += float(numel)
        return start, start + numel

    def get(self, shape, start: int):
        """Slice of the flat buffer viewed as ``shape`` (functional: a copy
        under jit; XLA elides it when possible)."""
        numel = 1
        for s in shape:
            numel *= int(s)
        return jnp.reshape(
            jnp.asarray(self.data)[start : start + numel], shape
        )

    def put(self, value, start: int):
        """Write ``value`` into the region (returns the updated buffer)."""
        flat = jnp.ravel(value).astype(self.dtype)
        self.data = self.data.at[start : start + flat.size].set(flat)
        return self.data

    def print_average_usage(self):
        if not self.track_usage:
            return
        if self.total_value:
            print(
                f"buffer {self.name} average usage: "
                f"{100.0 * self.in_use_value / self.total_value:.2f}%"
            )


class RingMemBuffer:
    """Round-robin set of memory buffers (ref memory.py:133)."""

    def __init__(self, name, num_buffers, numel, dtype, track_usage):
        self.num_buffers = num_buffers
        self.buffers = [
            allocate_mem_buff(f"{name}-{i}", numel, dtype, track_usage)
            for i in range(num_buffers)
        ]
        self._index = -1

    def get_next_buffer(self):
        self._index = (self._index + 1) % self.num_buffers
        buff = self.buffers[self._index]
        if buff.is_in_use():
            raise RuntimeError("next ring buffer is still in use")
        return buff
