"""Gradient scaler with model-parallel inf check
(ref apex/transformer/amp/grad_scaler.py GradScaler).

The reference subclasses ``torch.cuda.amp.GradScaler`` and all-reduces
``found_inf`` (MAX) over the model-parallel group before deciding to step
or back off — a rank seeing a local overflow must make EVERY tp/pp rank
skip, or the replicas diverge. The TPU form subclasses the in-graph
:class:`apex_tpu.amp.LossScaler`: :meth:`unscale` ORs the overflow flag
across the model-parallel mesh axes with ``pmax`` inside the jitted step.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler


def _axis_bound(axis: str) -> bool:
    """True iff ``axis`` is a bound named axis in the current trace.

    Probing the axis env directly (rather than catching pmax's unbound-axis
    error) keeps genuine pmax failures loud — swallowing them would silently
    drop the cross-rank overflow sync this class exists to guarantee.
    """
    try:
        from jax._src.core import get_axis_env

        return bool(get_axis_env().axis_exists(axis))
    except Exception:  # private API moved: probe with a cheap axis_size
        try:
            jax.lax.axis_size(axis)
            return True
        except (NameError, AssertionError):
            return False


class GradScaler(LossScaler):
    """ref grad_scaler.py:21. ``model_parallel_axes`` are the mesh axes the
    overflow decision must agree across (tp and pp by default); axes not
    bound in the current shard_map are skipped, so the same scaler works
    under any mesh subset."""

    def __init__(self, init_scale=2.0 ** 16, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000, enabled=True,
                 model_parallel_axes: Sequence[str] = ("tp", "pp")):
        super().__init__(
            loss_scale="dynamic", init_scale=init_scale,
            scale_factor=growth_factor, scale_window=growth_interval,
            enabled=enabled, backoff_factor=backoff_factor)
        self.model_parallel_axes = tuple(model_parallel_axes)

    def unscale(self, grads, state):
        unscaled, overflow = super().unscale(grads, state)
        if not self.enabled:  # disabled scaler compiles to nothing
            return unscaled, overflow
        # sync the decision across model-parallel ranks (ref
        # _maybe_opt_step's MAX allreduce over get_model_parallel_group())
        flag = overflow.astype(jnp.int32)
        for axis in self.model_parallel_axes:
            if not _axis_bound(axis):
                continue
            flag = jax.lax.pmax(flag, axis)
        return unscaled, flag > 0
