"""Model/tensor/pipeline-parallel state over a global ``jax.sharding.Mesh``.

TPU re-design of ``apex/transformer/parallel_state.py`` (which builds NCCL
process groups per parallel dimension). On TPU there are no process groups:
one global device mesh carries named axes, collectives name the axis they
ride, and XLA lowers them onto ICI. This module keeps the reference's exact
getter API (ref parallel_state.py:73 ``initialize_model_parallel`` and the
getters at :250-555) but the underlying object is a Mesh with axes

    ('pp', 'dp', 'cp', 'tp')    # pipeline, data, context, tensor

laid out so tensor-parallel neighbours are adjacent devices (innermost axis ⇒
fastest-varying ⇒ nearest on the ICI torus), matching the reference's rank
ordering where tp ranks are consecutive (ref parallel_state.py:93-117).

"Groups" become axis names: passing the result of
``get_tensor_model_parallel_group()`` to ``psum``/``all_gather`` inside
``shard_map`` is the analog of passing an NCCL group to ``dist.all_reduce``.

Rank getters are dual-mode:
- inside ``shard_map`` (axis bound) they return the traced ``lax.axis_index``;
- outside, they return the value injected via the ``set_*_rank`` overrides
  (used by tests and by host-side schedule construction, same as the
  reference's ``set_tensor_model_parallel_rank`` test hooks), defaulting to 0.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names.
PIPELINE_AXIS = "pp"
DATA_AXIS = "dp"
CONTEXT_AXIS = "cp"
TENSOR_AXIS = "tp"

_MESH: Optional[Mesh] = None
_VIRTUAL_PIPELINE_WORLD_SIZE: Optional[int] = None
_VIRTUAL_PIPELINE_RANK: Optional[int] = None
_PIPELINE_SPLIT_RANK: Optional[int] = None

# Host-side overrides (ref parallel_state.py:378-443 set_* hooks).
_OVERRIDES = {}


def is_unitialized() -> bool:
    """(sic — the reference misspells it too, ref parallel_state.py:68)"""
    return _MESH is None


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    *,
    context_parallel_size_: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build and install the global mesh (ref parallel_state.py:73).

    The data-parallel size is inferred: world // (tp * pp * cp). ``devices``
    defaults to ``jax.devices()``; pass an explicit list to subset or reorder
    (e.g. to align tp with an ICI axis on a real slice).
    """
    global _MESH, _VIRTUAL_PIPELINE_WORLD_SIZE, _VIRTUAL_PIPELINE_RANK
    global _PIPELINE_SPLIT_RANK
    devices = list(devices if devices is not None else jax.devices())
    world = len(devices)
    tp = tensor_model_parallel_size_
    pp = pipeline_model_parallel_size_
    cp = context_parallel_size_
    if world % (tp * pp * cp) != 0:
        raise RuntimeError(
            f"world size {world} not divisible by tp({tp})*pp({pp})*cp({cp})"
        )
    dp = world // (tp * pp * cp)
    # Reference rank order (parallel_state.py:93): tp consecutive, then dp,
    # then pp outermost — reshape preserves it.
    arr = np.asarray(devices, dtype=object).reshape(pp, dp, cp, tp)
    _MESH = Mesh(arr, (PIPELINE_AXIS, DATA_AXIS, CONTEXT_AXIS, TENSOR_AXIS))
    if virtual_pipeline_model_parallel_size_ is not None:
        _VIRTUAL_PIPELINE_WORLD_SIZE = virtual_pipeline_model_parallel_size_
        _VIRTUAL_PIPELINE_RANK = 0
    else:
        _VIRTUAL_PIPELINE_WORLD_SIZE = None
        _VIRTUAL_PIPELINE_RANK = None
    _PIPELINE_SPLIT_RANK = pipeline_model_parallel_split_rank_
    return _MESH


def destroy_model_parallel() -> None:
    """Tear down global state (ref parallel_state.py:555)."""
    global _MESH, _VIRTUAL_PIPELINE_WORLD_SIZE, _VIRTUAL_PIPELINE_RANK
    global _PIPELINE_SPLIT_RANK
    _MESH = None
    _VIRTUAL_PIPELINE_WORLD_SIZE = None
    _VIRTUAL_PIPELINE_RANK = None
    _PIPELINE_SPLIT_RANK = None
    _OVERRIDES.clear()


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError(
            "model parallel mesh is not initialized "
            "(call initialize_model_parallel first)"
        )
    return _MESH


# ------------------------------------------------------------------ groups
# A "group" is the axis name (or tuple of names) collectives should ride.


def get_model_parallel_group() -> Tuple[str, str]:
    """tp+pp combined (ref parallel_state.py:273)."""
    get_mesh()
    return (PIPELINE_AXIS, TENSOR_AXIS)


def get_tensor_model_parallel_group() -> str:
    get_mesh()
    return TENSOR_AXIS


def get_pipeline_model_parallel_group() -> str:
    get_mesh()
    return PIPELINE_AXIS


def get_data_parallel_group() -> str:
    get_mesh()
    return DATA_AXIS


def get_context_parallel_group() -> str:
    get_mesh()
    return CONTEXT_AXIS


def get_embedding_group() -> str:
    """First+last pipeline stage share embedding grads (ref
    parallel_state.py:301). On the mesh this is a masked psum over 'pp'
    (see pipeline_parallel.p2p.embedding_allreduce); the axis is still 'pp'.
    """
    get_mesh()
    return PIPELINE_AXIS


def get_position_embedding_group() -> str:
    get_mesh()
    return PIPELINE_AXIS


# ------------------------------------------------------------- world sizes


def _axis_size(axis: str) -> int:
    return get_mesh().shape[axis]


def get_tensor_model_parallel_world_size() -> int:
    ov = _OVERRIDES.get("tp_world")
    return ov if ov is not None else _axis_size(TENSOR_AXIS)


def get_pipeline_model_parallel_world_size() -> int:
    ov = _OVERRIDES.get("pp_world")
    return ov if ov is not None else _axis_size(PIPELINE_AXIS)


def get_data_parallel_world_size() -> int:
    ov = _OVERRIDES.get("dp_world")
    return ov if ov is not None else _axis_size(DATA_AXIS)


def get_context_parallel_world_size() -> int:
    ov = _OVERRIDES.get("cp_world")
    return ov if ov is not None else _axis_size(CONTEXT_AXIS)


def set_tensor_model_parallel_world_size(world_size) -> None:
    _OVERRIDES["tp_world"] = world_size


def set_pipeline_model_parallel_world_size(world_size) -> None:
    _OVERRIDES["pp_world"] = world_size


# ------------------------------------------------------------------- ranks


def _axis_rank(axis: str, override_key: str):
    ov = _OVERRIDES.get(override_key)
    if ov is not None:
        return ov
    try:
        # Traced value when the axis is bound (inside shard_map).
        return jax.lax.axis_index(axis)
    except (NameError, ValueError, KeyError, TypeError):
        return 0


def get_tensor_model_parallel_rank():
    return _axis_rank(TENSOR_AXIS, "tp_rank")


def get_pipeline_model_parallel_rank():
    return _axis_rank(PIPELINE_AXIS, "pp_rank")


def get_data_parallel_rank():
    return _axis_rank(DATA_AXIS, "dp_rank")


def get_context_parallel_rank():
    return _axis_rank(CONTEXT_AXIS, "cp_rank")


def set_tensor_model_parallel_rank(rank) -> None:
    _OVERRIDES["tp_rank"] = rank


def set_pipeline_model_parallel_rank(rank) -> None:
    _OVERRIDES["pp_rank"] = rank


def get_rank_info() -> Tuple:
    """(tp_rank, pp_rank, dp_rank) for debug logging (ref :250)."""
    return (
        get_tensor_model_parallel_rank(),
        get_pipeline_model_parallel_rank(),
        get_data_parallel_rank(),
    )


# -------------------------------------------------------- pipeline helpers


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """ref parallel_state.py:449. Traced bool inside shard_map."""
    if not ignore_virtual:
        if (
            _VIRTUAL_PIPELINE_WORLD_SIZE is not None
            and get_virtual_pipeline_model_parallel_rank() != 0
        ):
            return False
    return get_pipeline_model_parallel_rank() == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    """ref parallel_state.py:460."""
    if not ignore_virtual:
        vws = _VIRTUAL_PIPELINE_WORLD_SIZE
        if vws is not None and get_virtual_pipeline_model_parallel_rank() != (
            vws - 1
        ):
            return False
    return (
        get_pipeline_model_parallel_rank()
        == get_pipeline_model_parallel_world_size() - 1
    )


def get_virtual_pipeline_model_parallel_rank():
    return _VIRTUAL_PIPELINE_RANK


def set_virtual_pipeline_model_parallel_rank(rank) -> None:
    global _VIRTUAL_PIPELINE_RANK
    _VIRTUAL_PIPELINE_RANK = rank


def get_virtual_pipeline_model_parallel_world_size():
    return _VIRTUAL_PIPELINE_WORLD_SIZE


def get_pipeline_model_parallel_split_rank():
    return _PIPELINE_SPLIT_RANK


def set_pipeline_model_parallel_split_rank(rank: int) -> None:
    global _PIPELINE_SPLIT_RANK
    _PIPELINE_SPLIT_RANK = rank


def is_pipeline_stage_before_split(rank=None):
    """Encoder side of an encoder-decoder split (ref :338)."""
    if get_pipeline_model_parallel_world_size() == 1:
        return True
    if rank is None:
        rank = get_pipeline_model_parallel_rank()
    if _PIPELINE_SPLIT_RANK is None:
        return True
    return rank < _PIPELINE_SPLIT_RANK


def is_pipeline_stage_after_split(rank=None):
    """Decoder side (ref :353)."""
    if get_pipeline_model_parallel_world_size() == 1:
        return True
    if rank is None:
        rank = get_pipeline_model_parallel_rank()
    if _PIPELINE_SPLIT_RANK is None:
        return True
    return rank >= _PIPELINE_SPLIT_RANK


def is_pipeline_stage_at_split():
    """ref :368 — the stage feeding encoder output into the decoder."""
    rank = get_pipeline_model_parallel_rank()
    return is_pipeline_stage_before_split(rank) & is_pipeline_stage_after_split(
        rank + 1
    )


def is_rank_in_embedding_group(ignore_virtual: bool = False):
    """First or last pp stage (ref :315)."""
    del ignore_virtual
    return is_pipeline_first_stage(ignore_virtual=True) | is_pipeline_last_stage(
        ignore_virtual=True
    )


def is_rank_in_position_embedding_group():
    return is_pipeline_first_stage(ignore_virtual=True)


# ------------------------------------------------- global-rank conversions
# The reference exposes flat global ranks for src-rank broadcasts
# (ref :493-541). With a single-controller mesh these index into
# mesh.devices; they're mostly useful for logging / multihost launch.


def get_tensor_model_parallel_src_rank():
    """Global rank of tp-rank-0 in this rank's tp group (ref :493)."""
    world = get_tensor_model_parallel_world_size()
    # With tp innermost, the group leader is the floor to a multiple of tp.
    return (_flat_rank() // world) * world


def get_data_parallel_src_rank():
    """ref :501."""
    tp = get_tensor_model_parallel_world_size()
    cp = get_context_parallel_world_size()
    rank = _flat_rank()
    # dp varies over blocks of (cp*tp) within a pp stage.
    stage = rank % (get_data_parallel_world_size() * cp * tp)
    return (rank - stage) + stage % (cp * tp)


def get_pipeline_model_parallel_first_rank():
    return _flat_rank() % _stage_stride()


def get_pipeline_model_parallel_last_rank():
    return get_pipeline_model_parallel_first_rank() + _stage_stride() * (
        get_pipeline_model_parallel_world_size() - 1
    )


def get_pipeline_model_parallel_next_rank():
    stride = _stage_stride()
    world = get_pipeline_model_parallel_world_size()
    rank = _flat_rank()
    return rank % stride + stride * ((rank // stride + 1) % world)


def get_pipeline_model_parallel_prev_rank():
    stride = _stage_stride()
    world = get_pipeline_model_parallel_world_size()
    rank = _flat_rank()
    return rank % stride + stride * ((rank // stride - 1) % world)


def _stage_stride() -> int:
    return (
        get_data_parallel_world_size()
        * get_context_parallel_world_size()
        * get_tensor_model_parallel_world_size()
    )


def _flat_rank():
    ov = _OVERRIDES.get("flat_rank")
    if ov is not None:
        return ov
    pp = get_pipeline_model_parallel_rank()
    dp = get_data_parallel_rank()
    cp = get_context_parallel_rank()
    tp = get_tensor_model_parallel_rank()
    cpw = get_context_parallel_world_size()
    tpw = get_tensor_model_parallel_world_size()
    dpw = get_data_parallel_world_size()
    return ((pp * dpw + dp) * cpw + cp) * tpw + tp


def set_flat_rank(rank) -> None:
    _OVERRIDES["flat_rank"] = rank
