"""Logging controls (ref apex/transformer/log_util.py)."""

import logging
import os

_LOGGER_NAME = "apex_tpu.transformer"


def get_transformer_logger(name: str = _LOGGER_NAME) -> logging.Logger:
    return logging.getLogger(name)


def set_logging_level(verbosity) -> None:
    """Set the transformer-subsystem logging level (ref log_util.py
    set_logging_level)."""
    logging.getLogger(_LOGGER_NAME).setLevel(verbosity)


# Same env knob the reference honors for one-time warnings.
_warned = set()


def warn_once(logger: logging.Logger, msg: str) -> None:
    if msg not in _warned and not os.environ.get("APEX_TPU_SILENCE_WARNINGS"):
        _warned.add(msg)
        logger.warning(msg)
