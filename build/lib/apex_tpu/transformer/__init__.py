"""Megatron-style model parallelism, TPU-native
(ref apex/transformer/__init__.py).

Axes ride a global ``jax.sharding.Mesh`` ('pp','dp','cp','tp','ep'); see
``parallel_state`` for the group/rank API, ``tensor_parallel`` for TP
layers/collectives, ``pipeline_parallel`` for collective 1F1B schedules,
``context_parallel`` for ring-attention sequence parallelism, and ``moe``
for expert parallelism (GShard/Switch dispatch over 'ep').
"""

from apex_tpu.transformer import enums
from apex_tpu.transformer import functional
from apex_tpu.transformer import microbatches
from apex_tpu.transformer import moe
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel
from apex_tpu.transformer import utils
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType, ModelType
from apex_tpu.transformer.log_util import set_logging_level

__all__ = [
    "enums",
    "functional",
    "microbatches",
    "moe",
    "parallel_state",
    "tensor_parallel",
    "utils",
    "AttnMaskType",
    "AttnType",
    "LayerType",
    "ModelType",
    "set_logging_level",
]
