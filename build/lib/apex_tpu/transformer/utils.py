"""Transformer-wide utilities (ref apex/transformer/utils.py).

The reference's ``split_tensor_into_1d_equal_chunks`` / ``gather_split_1d_tensor``
move flat shards between tensor-parallel ranks; here they are expressed as
per-shard ops usable under ``shard_map`` over the tensor-parallel axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ensure_divisibility(numerator: int, denominator: int) -> None:
    """Raise unless numerator is divisible by denominator (ref utils.py:7)."""
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    """Exact integer division (ref utils.py:14)."""
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_into_1d_equal_chunks(tensor, axis_name: str = "tp"):
    """Return this rank's equal flat chunk of ``tensor`` (ref utils.py:21).

    Must run inside ``shard_map`` with ``axis_name`` bound; the input is the
    (replicated) full tensor, the output is the local 1-D shard.
    """
    n = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    flat = tensor.reshape(-1)
    chunk = flat.shape[0] // n
    return jax.lax.dynamic_slice(flat, (rank * chunk,), (chunk,))


def gather_split_1d_tensor(tensor, axis_name: str = "tp"):
    """All-gather flat shards back into the full 1-D tensor (ref utils.py:32)."""
    return jax.lax.all_gather(tensor, axis_name, axis=0, tiled=True)


def cast_if_needed(x, dtype):
    """Cast ``x`` to ``dtype`` when set (mirrors torch.Tensor.to semantics
    used throughout the reference's mixed-precision paths)."""
    return x if dtype is None else jnp.asarray(x, dtype)
