"""Multi-tensor fused elementwise ops (TPU re-design of ``apex.multi_tensor_apply``).

Ref: apex/multi_tensor_apply/multi_tensor_apply.py + csrc/multi_tensor_*.cu.
On TPU there are no per-tensor kernel launches to amortize: a list of tensors
is packed into one flat buffer and the op compiles to a single fused XLA
kernel, which is the same end state the CUDA chunking machinery fights for.
"""

from apex_tpu.multi_tensor_apply.multi_tensor_apply import (
    MultiTensorApply,
    multi_tensor_applier,
    multi_tensor_scale,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_l2norm_mp,
    multi_tensor_l2norm_scale,
)
