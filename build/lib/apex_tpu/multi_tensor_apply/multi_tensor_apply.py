"""Fused multi-tensor elementwise ops.

Functional equivalents of the reference CUDA kernels:

- ``multi_tensor_scale``        ref csrc/multi_tensor_scale_kernel.cu
- ``multi_tensor_axpby``        ref csrc/multi_tensor_axpby_kernel.cu
- ``multi_tensor_l2norm``       ref csrc/multi_tensor_l2norm_kernel.cu
- ``multi_tensor_l2norm_mp``    ref csrc/multi_tensor_l2norm_kernel_mp.cu
- ``multi_tensor_l2norm_scale`` ref csrc/multi_tensor_l2norm_scale_kernel.cu

Semantics notes vs the reference:
- The CUDA kernels write into an ``overflow_buf`` int flag when they see
  inf/nan. Here every op *returns* a boolean ``overflow`` scalar (computed in
  the same fused pass), which callers fold into jit-compatible control flow
  (``lax.cond`` / ``jnp.where``) instead of a host-side check.
- Chunking is irrelevant under XLA (one executable regardless of tensor
  count), so chunk_size is accepted and ignored by the applier shim.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from apex_tpu.ops.flat import FlatSpec, flatten_tensors, unflatten_tensors


def _flat(tensors: Sequence[jax.Array]):
    return flatten_tensors(tensors)


def _nonfinite(x: jax.Array) -> jax.Array:
    return jnp.logical_not(jnp.all(jnp.isfinite(x)))


def multi_tensor_scale(src_list, scale, out_dtype=None):
    """out[i] = src[i] * scale, plus overflow flag.

    Ref csrc/multi_tensor_scale_kernel.cu (used by amp unscale + O2 master-grad
    copy). ``out_dtype`` supports the fp16<-fp32 copy-with-scale use.
    """
    flat, spec = _flat(src_list)
    scaled = flat.astype(jnp.float32) * scale
    overflow = _nonfinite(scaled)
    out = scaled.astype(out_dtype or spec.dtype)
    return unflatten_tensors(out, FlatSpec(spec.shapes, out.dtype, spec.offsets, spec.sizes, spec.total)), overflow


def multi_tensor_axpby(x_list, y_list, a=1.0, b=1.0, out_dtype=None):
    """out[i] = a*x[i] + b*y[i] with overflow detection.

    Ref csrc/multi_tensor_axpby_kernel.cu (used by amp master-grad blending).
    """
    fx, spec = _flat(x_list)
    fy, _ = _flat(y_list)
    out = a * fx.astype(jnp.float32) + b * fy.astype(jnp.float32)
    overflow = _nonfinite(out)
    out = out.astype(out_dtype or spec.dtype)
    return unflatten_tensors(out, FlatSpec(spec.shapes, out.dtype, spec.offsets, spec.sizes, spec.total)), overflow


def multi_tensor_l2norm(tensor_list, per_tensor=False):
    """Global (and optionally per-tensor) L2 norm in one fused pass.

    Ref csrc/multi_tensor_l2norm_kernel.cu. Returns
    ``(global_norm, per_tensor_norms | None)`` as fp32 scalars.
    """
    flat, spec = _flat(tensor_list)
    sq = jnp.square(flat.astype(jnp.float32))
    total = jnp.sqrt(jnp.sum(sq))
    if not per_tensor:
        return total, None
    seg_ids = jnp.repeat(
        jnp.arange(len(spec.sizes)), jnp.asarray(spec.sizes), total_repeat_length=spec.total
    )
    per = jnp.sqrt(jax.ops.segment_sum(sq, seg_ids, num_segments=len(spec.sizes)))
    return total, per


def multi_tensor_l2norm_mp(tensor_list, per_tensor=False):
    """Mixed-precision variant: accumulates in fp32 regardless of input dtype.

    Ref csrc/multi_tensor_l2norm_kernel_mp.cu. Identical accumulation here
    (we always accumulate fp32), kept as a distinct entry point for parity.
    """
    return multi_tensor_l2norm(tensor_list, per_tensor=per_tensor)


def multi_tensor_l2norm_scale(src_list, scale, per_tensor=False):
    """Fused l2norm + scale in one pass (ref csrc/multi_tensor_l2norm_scale_kernel.cu)."""
    flat, spec = _flat(src_list)
    f32 = flat.astype(jnp.float32)
    scaled = f32 * scale
    norm = jnp.sqrt(jnp.sum(jnp.square(scaled)))
    overflow = _nonfinite(scaled)
    per = None
    if per_tensor:
        seg_ids = jnp.repeat(
            jnp.arange(len(spec.sizes)), jnp.asarray(spec.sizes), total_repeat_length=spec.total
        )
        per = jnp.sqrt(jax.ops.segment_sum(jnp.square(scaled), seg_ids, num_segments=len(spec.sizes)))
    out = unflatten_tensors(scaled.astype(spec.dtype), spec)
    return out, norm, per, overflow


class MultiTensorApply:
    """API-parity shim for ``apex.multi_tensor_apply.multi_tensor_applier``.

    Ref apex/multi_tensor_apply/multi_tensor_apply.py: callable taking
    ``(op, overflow_buf, tensor_lists, *args)``. Chunking is a no-op under
    XLA and the overflow flag is *returned* by the op instead of written
    into ``overflow_buf``.

    Apex's calling convention passes input and output lists together in
    ``tensor_lists`` (scale: ``[src, dst]``; axpby: ``[x, y, out]``). JAX
    arrays are immutable, so the trailing output lists cannot be written
    in place — they are accepted for parity, ignored, and the results
    returned. Each functional op declares how many leading lists are
    inputs via its ``n_input_lists`` attribute.
    """

    available = True
    warned = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size  # accepted for parity; XLA needs no chunking

    @classmethod
    def check_avail(cls):
        """ref multi_tensor_apply.py check_avail — the reference raises
        when the amp_C extension is missing; the XLA path is always
        compiled in, so this never raises."""
        return None

    def __call__(self, op, noop_flag_buffer, tensor_lists, *args):
        del noop_flag_buffer
        n_in = getattr(op, "n_input_lists", len(tensor_lists))
        return op(*tensor_lists[:n_in], *args)


# Leading-input-list counts for the apex [inputs..., outputs...] convention.
multi_tensor_scale.n_input_lists = 1          # [src, dst]
multi_tensor_axpby.n_input_lists = 2          # [x, y, out]
multi_tensor_l2norm.n_input_lists = 1
multi_tensor_l2norm_mp.n_input_lists = 1
multi_tensor_l2norm_scale.n_input_lists = 1

multi_tensor_applier = MultiTensorApply(2048 * 32)
