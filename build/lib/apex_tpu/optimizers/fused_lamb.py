"""FusedLAMB — TPU re-design of ``apex.optimizers.FusedLAMB``.

Ref: apex/optimizers/fused_lamb.py + csrc/multi_tensor_lamb.cu.

Pipeline (one jitted executable, matching the reference's two fused stages):
1. global grad norm over the whole tree (one fused reduction — ref computes
   it with multi_tensor_l2norm over fp16+fp32 lists);
2. clip grads by ``max_grad_norm``;
3. Adam-style moments; raw update direction ``u``;
4. per-tensor trust ratio ||p|| / ||u|| (NVLAMB gating via ``use_nvlamb``);
5. ``p -= lr * ratio * u``.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers import _math
from apex_tpu.optimizers._base import FusedOptimizer
from apex_tpu.optimizers.fused_adam import ScalarOrSchedule, _lr_at
from apex_tpu.multi_tensor_apply import multi_tensor_l2norm


class FusedLAMBState(NamedTuple):
    count: jax.Array
    mu: Any
    nu: Any


def fused_lamb(
    lr: ScalarOrSchedule = 1e-3,
    bias_correction: bool = True,
    betas=(0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    adam_w_mode: bool = True,
    grad_averaging: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
) -> optax.GradientTransformation:
    b1, b2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return FusedLAMBState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(zeros, params),
            nu=jax.tree_util.tree_map(zeros, params),
        )

    def update(grads, state, params=None):
        count = state.count + 1
        step = count.astype(jnp.float32)
        lr_t = _lr_at(lr, state.count)  # optax convention: schedule sees pre-increment count

        # global grad norm via the fused multi-tensor reduction, per-dtype
        # lists blended like the reference's g_16/g_32 split
        # (ref fused_lamb.py:123-135)
        by_dtype: dict = {}
        for l in jax.tree_util.tree_leaves(grads):
            by_dtype.setdefault(jnp.dtype(l.dtype).name, []).append(l)
        norms = [multi_tensor_l2norm(ls)[0] for ls in by_dtype.values()]
        gnorm = jnp.sqrt(sum(jnp.square(n) for n in norms))
        # max_grad_norm <= 0 disables clipping (ref fused_lamb.py: the norm
        # kernel only runs when defaults['max_grad_norm'] > 0)
        clip_coeff = jnp.where(
            (max_grad_norm > 0.0) & (gnorm > max_grad_norm),
            max_grad_norm / jnp.maximum(gnorm, 1e-30), 1.0
        )

        def leaf(g, p, m, v):
            m, v = _math.lamb_moments(
                g, p, m, v, b1=b1, b2=b2, grad_averaging=grad_averaging,
                clip_coeff=clip_coeff, weight_decay=weight_decay,
                adam_w_mode=adam_w_mode)
            u = _math.lamb_update_direction(
                p, m, v, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
                adam_w_mode=adam_w_mode, step=step, bias_correction=bias_correction)
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
            u_norm = jnp.sqrt(jnp.sum(jnp.square(u)))
            ratio = _math.lamb_trust_ratio(
                p_norm, u_norm, weight_decay=weight_decay, use_nvlamb=use_nvlamb)
            return (-lr_t * ratio * u).astype(p.dtype), m, v

        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        p_leaves = jax.tree_util.tree_leaves(params)
        m_leaves = jax.tree_util.tree_leaves(state.mu)
        v_leaves = jax.tree_util.tree_leaves(state.nu)
        results = [leaf(g, p, m, v)
                   for g, p, m, v in zip(g_leaves, p_leaves, m_leaves, v_leaves)]
        updates = treedef.unflatten([r[0] for r in results])
        mu = treedef.unflatten([r[1] for r in results])
        nu = treedef.unflatten([r[2] for r in results])
        return updates, FusedLAMBState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


class FusedLAMB(FusedOptimizer):
    """Stateful apex-style API (ref apex/optimizers/fused_lamb.py:66)."""

    def __init__(self, params, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-6, weight_decay=0.01, amsgrad=False, adam_w_mode=True,
                 grad_averaging=True, set_grad_none=True, max_grad_norm=1.0,
                 use_nvlamb=False):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        del set_grad_none
        kw = dict(lr=lr, bias_correction=bias_correction, betas=betas, eps=eps,
                  weight_decay=weight_decay, adam_w_mode=adam_w_mode,
                  grad_averaging=grad_averaging, max_grad_norm=max_grad_norm,
                  use_nvlamb=use_nvlamb)
        super().__init__(params, fused_lamb(**kw), dict(
            lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
            max_grad_norm=max_grad_norm),
            tx_factory=lambda **ov: fused_lamb(**{**kw, **ov}))
