"""Fused optimizer update math.

Each function is the elementwise body the reference implements as a CUDA
multi-tensor kernel (csrc/multi_tensor_{adam,lamb,sgd,novograd,adagrad}*.cu),
expressed over arrays so it can run either per-leaf (tree mode — preserves
shardings, XLA fuses the chain per leaf) or over a packed flat buffer (flat
mode — one kernel for the whole model, the multi-tensor-apply end state).

All state math is fp32; params may be any float dtype (cast in/out at the
edges, matching the mixed-precision kernels' fp32 math on fp16 storage).
"""

from __future__ import annotations

import jax.numpy as jnp


def adam_step(g, p, m, v, *, lr, b1, b2, eps, weight_decay, adam_w_mode, step, bias_correction):
    """One Adam/AdamW update. Ref csrc/multi_tensor_adam.cu (ADAM_MODE_0/1).

    Returns (delta, new_m, new_v) with delta = new_p - p in fp32.
    """
    g32 = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if not adam_w_mode and weight_decay:  # L2 mode: decay folded into the gradient
        g32 = g32 + weight_decay * p32
    m = b1 * m + (1.0 - b1) * g32
    v = b2 * v + (1.0 - b2) * jnp.square(g32)
    if bias_correction:
        m_hat = m / (1.0 - b1 ** step)
        v_hat = v / (1.0 - b2 ** step)
    else:
        m_hat, v_hat = m, v
    update = m_hat / (jnp.sqrt(v_hat) + eps)
    if adam_w_mode and weight_decay:
        update = update + weight_decay * p32
    return -lr * update, m, v


def adagrad_step(g, p, h, *, lr, eps, weight_decay, adagrad_w_mode):
    """One Adagrad update. Ref csrc/multi_tensor_adagrad.cu (MODE_0 = L2, MODE_1 = decoupled)."""
    g32 = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if not adagrad_w_mode and weight_decay:
        g32 = g32 + weight_decay * p32
    h = h + jnp.square(g32)
    update = g32 / (jnp.sqrt(h) + eps)
    if adagrad_w_mode and weight_decay:
        update = update + weight_decay * p32
    return -lr * update, h


def sgd_step(g, p, buf, *, lr, momentum, dampening, nesterov, weight_decay,
             wd_after_momentum, first_run):
    """One (momentum) SGD update. Ref csrc/multi_tensor_sgd_kernel.cu.

    ``first_run`` seeds the momentum buffer with the raw gradient the way the
    reference's ``get_momentums`` first-touch path does.
    """
    g32 = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if weight_decay and not wd_after_momentum:
        g32 = g32 + weight_decay * p32
    if momentum:
        buf = jnp.where(first_run, g32, momentum * buf + (1.0 - dampening) * g32)
        d = g32 + momentum * buf if nesterov else buf
    else:
        d = g32
    if weight_decay and wd_after_momentum:
        d = d + weight_decay * p32
    return -lr * d, buf


def lamb_moments(g, p, m, v, *, b1, b2, grad_averaging, clip_coeff, weight_decay, adam_w_mode):
    """LAMB stage 1: clipped-grad moment update (ref csrc/multi_tensor_lamb.cu).

    In L2 mode (MOMENT_MODE_0) the decay enters the gradient *before* the
    moments, so it flows into m, v, and the trust-ratio numerator.
    """
    g32 = g.astype(jnp.float32) * clip_coeff
    if not adam_w_mode and weight_decay:
        g32 = g32 + weight_decay * p.astype(jnp.float32)
    beta1_coeff = (1.0 - b1) if grad_averaging else 1.0
    m = b1 * m + beta1_coeff * g32
    v = b2 * v + (1.0 - b2) * jnp.square(g32)
    return m, v


def lamb_update_direction(p, m, v, *, b1, b2, eps, weight_decay, adam_w_mode, step, bias_correction):
    """LAMB raw update direction u (before the trust-ratio scaling).

    AdamW mode (MOMENT_MODE_1) adds decoupled decay here; L2 mode already
    folded decay into the moments in :func:`lamb_moments`.
    """
    if bias_correction:
        m_hat = m / (1.0 - b1 ** step)
        v_hat = v / (1.0 - b2 ** step)
    else:
        m_hat, v_hat = m, v
    u = m_hat / (jnp.sqrt(v_hat) + eps)
    if adam_w_mode and weight_decay:
        u = u + weight_decay * p.astype(jnp.float32)
    return u


def lamb_trust_ratio(p_norm, u_norm, *, weight_decay, use_nvlamb):
    """Per-tensor trust ratio (ref csrc/multi_tensor_lamb.cu reduction epilogue)."""
    ratio = jnp.where(
        (p_norm > 0.0) & (u_norm > 0.0), p_norm / jnp.maximum(u_norm, 1e-30), 1.0
    )
    if not use_nvlamb and not weight_decay:
        # NVLAMB off: parameters with no weight decay skip the adaptive rate.
        ratio = jnp.ones_like(ratio)
    return ratio


def novograd_step(g, p, m, v_norm, *, lr, b1, b2, eps, weight_decay,
                  grad_averaging, reg_inside_moment, step, bias_correction, norm_type):
    """One NovoGrad update. Ref csrc/multi_tensor_novograd.cu.

    ``v_norm`` is a per-tensor scalar EMA of the gradient *norm* (the
    reference stores the norm, not its square, to unify L2/Linf handling:
    L2 blends root-of-squares ``sqrt(b2*v^2 + (1-b2)*n^2)``, Linf blends
    linearly — ref csrc/multi_tensor_novograd.cu norm comment). With
    ``bias_correction`` both moments are corrected: m by ``(1-b1^t)`` and
    the norm by ``sqrt(1-b2^t)``.
    """
    g32 = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if norm_type == 0:
        gnorm = jnp.max(jnp.abs(g32))
        v_new = b2 * v_norm + (1.0 - b2) * gnorm
    else:
        gnorm = jnp.sqrt(jnp.sum(jnp.square(g32)))
        v_new = jnp.sqrt(b2 * jnp.square(v_norm) + (1.0 - b2) * jnp.square(gnorm))
    v_hat = v_new / jnp.sqrt(1.0 - b2 ** step) if bias_correction else v_new
    scaled = g32 / (v_hat + eps)
    if weight_decay and reg_inside_moment:
        scaled = scaled + weight_decay * p32
    beta1_coeff = (1.0 - b1) if grad_averaging else 1.0
    m = b1 * m + beta1_coeff * scaled
    m_hat = m / (1.0 - b1 ** step) if bias_correction else m
    update = m_hat
    if weight_decay and not reg_inside_moment:
        update = update + weight_decay * p32
    return -lr * update, m, v_new
