"""Stateful optimizer shim over functional (optax-style) transforms.

The reference optimizers subclass ``torch.optim.Optimizer`` (mutable state,
``.step()``). TPU-native training is functional — the transform's ``update``
runs inside the user's jitted train step. ``FusedOptimizer`` wraps a
transform with an apex-flavoured stateful API for drop-in familiarity and for
the eager-ish scripting path; serious training should use the transform
directly (``tx.init`` / ``tx.update``).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import optax


class FusedOptimizer:
    """Apex-style stateful wrapper: holds params + opt state, ``step(grads)``.

    Unlike torch there are no ``.grad`` attributes: gradients are passed to
    ``step`` explicitly (a pytree matching params). ``zero_grad`` exists for
    API parity and is a no-op (ref e.g. apex/optimizers/fused_adam.py:85
    ``zero_grad``).
    """

    def __init__(self, params, tx: optax.GradientTransformation, defaults: dict,
                 tx_factory: Optional[Callable] = None):
        self.defaults = dict(defaults)
        self.tx = tx
        # rebuild hook: tx_factory(**overrides) -> GradientTransformation with
        # the same hyperparams except the overrides (used by e.g. LARC to zero
        # the inner weight decay, ref apex/parallel/LARC.py step()).
        self._tx_factory = tx_factory
        self.params = params
        self.state = tx.init(params)
        self._jit_step = jax.jit(self._functional_step)
        # torch-style param groups: group 0 aliases (params, state) above;
        # groups added later carry their own transform + state. Hyperparams
        # in these dicts are LIVE: mutating param_groups[i]['lr'] (the
        # torch LR-scheduler idiom) rebuilds that group's transform at the
        # next step() via tx_factory.
        self.param_groups = [{"params": params, **self.defaults}]
        self._group_hparams = [dict(self.defaults)]
        self._extra_groups = []

    def _functional_step(self, grads, state, params):
        updates, new_state = self.tx.update(grads, state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_state

    def add_param_group(self, group: dict) -> None:
        """Add a parameter group with its own hyperparameters (ref
        torch.optim.Optimizer.add_param_group; tested by the reference's
        L0/run_amp/test_add_param_group.py).

        ``group`` is ``{"params": pytree, **hyperparam_overrides}``; unknown
        hyperparameters are rejected. With extra groups present, ``step``
        takes a sequence of grad pytrees, one per group in order.
        """
        if not isinstance(group, dict) or "params" not in group:
            raise ValueError("param group must be a dict with a 'params' key")
        overrides = {k: v for k, v in group.items() if k != "params"}
        unknown = set(overrides) - set(self.defaults)
        if unknown:
            raise ValueError(f"unknown hyperparameters for this optimizer: "
                             f"{sorted(unknown)}")
        if overrides and self._tx_factory is None:
            raise ValueError(
                "this optimizer does not support per-group overrides")
        tx = self._tx_factory(**overrides) if overrides else self.tx
        gparams = group["params"]
        self._extra_groups.append({
            "params": gparams, "state": tx.init(gparams), "tx": tx,
            "jit_step": jax.jit(
                lambda g, s, p, _tx=tx: self._group_step(_tx, g, s, p)),
        })
        self.param_groups.append({**self.defaults, **group})
        self._group_hparams.append({**self.defaults, **overrides})

    @staticmethod
    def _group_step(tx, grads, state, params):
        updates, new_state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), new_state

    def _sync_group_hparams(self) -> None:
        """Honor torch-style in-place edits of ``param_groups[i]`` (e.g. an
        LR scheduler writing ``group['lr']``): rebuild the affected group's
        transform with the new hyperparameters. State layouts are shared
        across hyperparam values, so the existing state carries over."""
        for i, pg in enumerate(self.param_groups):
            current = {k: pg[k] for k in self.defaults if k in pg}
            if current == self._group_hparams[i]:
                continue
            if self._tx_factory is None:
                raise ValueError(
                    "param_groups hyperparameters changed but this "
                    "optimizer has no tx_factory to rebuild from")
            changed = {k: v for k, v in current.items()
                       if v != self.defaults.get(k)}
            tx = self._tx_factory(**changed)
            # the carried state is only valid if the rebuilt transform has
            # the same state LAYOUT (a tx_factory whose overrides toggle
            # state structure, e.g. momentum on/off, would silently
            # mismatch at the next jit step)
            group_params = self.param_groups[i]["params"]
            old_state = (self.state if i == 0
                         else self._extra_groups[i - 1]["state"])
            new_struct = jax.tree_util.tree_structure(
                jax.eval_shape(tx.init, group_params))
            old_struct = jax.tree_util.tree_structure(old_state)
            if new_struct != old_struct:
                raise ValueError(
                    f"param_groups[{i}] hyperparameter change altered the "
                    f"optimizer state structure ({old_struct} -> "
                    f"{new_struct}); carried state cannot be reused — "
                    f"rebuild the optimizer instead")
            if i == 0:
                self.tx = tx
                self._jit_step = jax.jit(self._functional_step)
            else:
                grp = self._extra_groups[i - 1]
                grp["tx"] = tx
                grp["jit_step"] = jax.jit(
                    lambda g, s, p, _tx=tx: self._group_step(_tx, g, s, p))
            self._group_hparams[i] = current

    def step(self, grads=None, closure: Optional[Callable] = None):
        """Apply one fused update. Returns the new params (also stored on
        self). With extra param groups, ``grads`` is a sequence of pytrees
        (one per group) and the returned params are a list in group order."""
        loss = closure() if closure is not None else None
        if grads is None:
            raise ValueError(
                "apex_tpu optimizers are functional: pass grads to step() "
                "(there is no .grad attribute to read on TPU)."
            )
        self._sync_group_hparams()
        if not self._extra_groups:
            self.params, self.state = self._jit_step(
                grads, self.state, self.params)
            self.param_groups[0]["params"] = self.params
            return loss if loss is not None else self.params
        if not isinstance(grads, (list, tuple)):
            raise ValueError(
                f"optimizer has {1 + len(self._extra_groups)} param groups: "
                "pass a list of grad trees, one per group")
        grads = list(grads)
        if len(grads) != 1 + len(self._extra_groups):
            raise ValueError(
                f"expected {1 + len(self._extra_groups)} grad trees "
                f"(one per param group), got {len(grads)}")
        self.params, self.state = self._jit_step(
            grads[0], self.state, self.params)
        for g, grp in zip(grads[1:], self._extra_groups):
            grp["params"], grp["state"] = grp["jit_step"](
                g, grp["state"], grp["params"])
        all_params = [self.params] + [g["params"] for g in self._extra_groups]
        self.param_groups[0]["params"] = self.params
        for pg, grp in zip(self.param_groups[1:], self._extra_groups):
            pg["params"] = grp["params"]
        return loss if loss is not None else all_params

    def zero_grad(self, set_to_none: bool = True):  # noqa: ARG002 - parity no-op
        return None

    def state_dict(self) -> dict:
        d = {"state": self.state, "defaults": self.defaults}
        if self._extra_groups:
            d["group_states"] = [g["state"] for g in self._extra_groups]
        return d

    def load_state_dict(self, state_dict: dict) -> None:
        new_state = state_dict["state"]
        have = jax.tree_util.tree_structure(self.state)
        got = jax.tree_util.tree_structure(new_state)
        if have != got:
            raise ValueError(
                f"loaded optimizer state structure {got} does not match "
                f"current optimizer structure {have}")
        self.state = new_state
        group_states = state_dict.get("group_states", [])
        if len(group_states) != len(self._extra_groups):
            raise ValueError(
                f"loaded state has {len(group_states)} extra param groups, "
                f"optimizer has {len(self._extra_groups)}")
        for i, (grp, s) in enumerate(zip(self._extra_groups, group_states)):
            have = jax.tree_util.tree_structure(grp["state"])
            got = jax.tree_util.tree_structure(s)
            if have != got:
                raise ValueError(
                    f"loaded state for param group {i + 1} has structure "
                    f"{got}, optimizer has {have}")
            grp["state"] = s
        self.defaults.update(state_dict.get("defaults", {}))


def opt_partition_specs(tx, params, param_specs):
    """PartitionSpec tree for ``tx.init(params)`` state whose moment trees
    mirror the param sharding (the Fused* ``(count, mu, nu)`` NamedTuples;
    any other state replicates). The standard companion to sharding a
    fused optimizer's state under ``shard_map``/``jit``.
    """
    from jax.sharding import PartitionSpec as P

    shapes = jax.eval_shape(tx.init, params)
    specs = jax.tree_util.tree_map(
        lambda _: P(), shapes,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))
    if hasattr(specs, "_replace") and hasattr(specs, "mu"):
        # flat=True Fused* state packs mu/nu into dtype-keyed flat buffers
        # whose tree structure does NOT mirror the params; grafting
        # param_specs onto them would build a structure-mismatched spec
        # tree that fails much later inside jit/shard_map. Leave flat
        # moment buffers replicated (P()) instead.
        mirrors = (jax.tree_util.tree_structure(shapes.mu)
                   == jax.tree_util.tree_structure(params))
        if mirrors:
            specs = specs._replace(mu=param_specs, nu=param_specs)
    return specs
