"""FusedSGD — TPU re-design of ``apex.optimizers.FusedSGD``.

Ref: apex/optimizers/fused_sgd.py + csrc/multi_tensor_sgd_kernel.cu.
Momentum/nesterov/dampening/weight-decay semantics match torch SGD with the
reference's extra ``wd_after_momentum`` knob. ``materialize_master_grads``
is a CUDA master-weight detail with no TPU analog (amp handles master
params); accepted and ignored.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers import _math
from apex_tpu.optimizers._base import FusedOptimizer
from apex_tpu.optimizers.fused_adam import ScalarOrSchedule, _lr_at


class FusedSGDState(NamedTuple):
    count: jax.Array
    momentum_buffer: Any


def fused_sgd(
    lr: ScalarOrSchedule,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    wd_after_momentum: bool = False,
) -> optax.GradientTransformation:
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires a momentum and zero dampening")

    def init(params):
        buf = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return FusedSGDState(count=jnp.zeros([], jnp.int32), momentum_buffer=buf)

    def update(grads, state, params=None):
        count = state.count + 1
        first_run = state.count == 0  # seeds buf with raw grad (ref get_momentums)
        lr_t = _lr_at(lr, state.count)  # optax convention: schedule sees pre-increment count
        kw = dict(lr=lr_t, momentum=momentum, dampening=dampening, nesterov=nesterov,
                  weight_decay=weight_decay, wd_after_momentum=wd_after_momentum,
                  first_run=first_run)
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        p_leaves = jax.tree_util.tree_leaves(params)
        b_leaves = jax.tree_util.tree_leaves(state.momentum_buffer)
        results = [_math.sgd_step(g, p, b, **kw)
                   for g, p, b in zip(g_leaves, p_leaves, b_leaves)]
        updates = treedef.unflatten(
            [r[0].astype(p.dtype) for r, p in zip(results, p_leaves)])
        buf = treedef.unflatten([r[1] for r in results])
        return updates, FusedSGDState(count=count, momentum_buffer=buf)

    return optax.GradientTransformation(init, update)


class FusedSGD(FusedOptimizer):
    """Stateful apex-style API (ref apex/optimizers/fused_sgd.py:76)."""

    def __init__(self, params, lr, momentum=0.0, dampening=0.0, weight_decay=0.0,
                 nesterov=False, wd_after_momentum=False,
                 materialize_master_grads=True, set_grad_none=False):
        del materialize_master_grads, set_grad_none  # no TPU analog / parity no-op
        kw = dict(lr=lr, momentum=momentum, dampening=dampening,
                  weight_decay=weight_decay, nesterov=nesterov,
                  wd_after_momentum=wd_after_momentum)
        super().__init__(params, fused_sgd(**kw), dict(
            lr=lr, momentum=momentum, dampening=dampening,
            weight_decay=weight_decay, nesterov=nesterov),
            tx_factory=lambda **ov: fused_sgd(**{**kw, **ov}))
