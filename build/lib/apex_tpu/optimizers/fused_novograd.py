"""FusedNovoGrad — TPU re-design of ``apex.optimizers.FusedNovoGrad``.

Ref: apex/optimizers/fused_novograd.py + csrc/multi_tensor_novograd.cu.

The second moment is a per-tensor scalar EMA of the gradient *norm* (the
reference stores the norm, not its square, to unify L2 / Linf handling;
see fused_novograd.py:160). ``init_zero=False`` seeds it with the first
step's norm so the first blend is a no-op, matching fused_novograd.py:168.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers import _math
from apex_tpu.optimizers._base import FusedOptimizer
from apex_tpu.optimizers.fused_adam import ScalarOrSchedule, _lr_at


class FusedNovoGradState(NamedTuple):
    count: jax.Array
    mu: Any
    v_norm: Any  # per-tensor scalar norm EMA


def fused_novograd(
    lr: ScalarOrSchedule = 1e-3,
    bias_correction: bool = True,
    betas=(0.95, 0.98),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_averaging: bool = True,
    reg_inside_moment: bool = False,
    norm_type: int = 2,
    init_zero: bool = False,
) -> optax.GradientTransformation:
    if norm_type not in (0, 2):
        raise RuntimeError("FusedNovoGrad only support l2/inf norm now.")
    b1, b2 = betas

    def init(params):
        return FusedNovoGradState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            v_norm=jax.tree_util.tree_map(lambda p: jnp.zeros([], jnp.float32), params),
        )

    def update(grads, state, params=None):
        count = state.count + 1
        step = count.astype(jnp.float32)
        lr_t = _lr_at(lr, state.count)  # optax convention: schedule sees pre-increment count

        def leaf(g, p, m, v):
            g32 = g.astype(jnp.float32)
            if norm_type == 0:
                gnorm = jnp.max(jnp.abs(g32))
            else:
                gnorm = jnp.sqrt(jnp.sum(jnp.square(g32)))
            # first step with init_zero=False: v <- gnorm (blend is a no-op)
            v_eff = v if init_zero else jnp.where(state.count == 0, gnorm, v)
            d, m, v_new = _math.novograd_step(
                g, p, m, v_eff, lr=lr_t, b1=b1, b2=b2, eps=eps,
                weight_decay=weight_decay, grad_averaging=grad_averaging,
                reg_inside_moment=reg_inside_moment, step=step,
                bias_correction=bias_correction, norm_type=norm_type)
            return d.astype(p.dtype), m, v_new

        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        p_leaves = jax.tree_util.tree_leaves(params)
        m_leaves = jax.tree_util.tree_leaves(state.mu)
        v_leaves = jax.tree_util.tree_leaves(state.v_norm)
        results = [leaf(g, p, m, v)
                   for g, p, m, v in zip(g_leaves, p_leaves, m_leaves, v_leaves)]
        updates = treedef.unflatten([r[0] for r in results])
        mu = treedef.unflatten([r[1] for r in results])
        v = treedef.unflatten([r[2] for r in results])
        return updates, FusedNovoGradState(count=count, mu=mu, v_norm=v)

    return optax.GradientTransformation(init, update)


class FusedNovoGrad(FusedOptimizer):
    """Stateful apex-style API (ref apex/optimizers/fused_novograd.py:67)."""

    def __init__(self, params, lr=1e-3, bias_correction=True, betas=(0.95, 0.98),
                 eps=1e-8, weight_decay=0.0, amsgrad=False, reg_inside_moment=False,
                 grad_averaging=True, norm_type=2, init_zero=False,
                 set_grad_none=True):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant.")
        del set_grad_none
        kw = dict(lr=lr, bias_correction=bias_correction, betas=betas,
                  eps=eps, weight_decay=weight_decay,
                  grad_averaging=grad_averaging,
                  reg_inside_moment=reg_inside_moment,
                  norm_type=norm_type, init_zero=init_zero)
        super().__init__(params, fused_novograd(**kw),
                         dict(lr=lr, betas=betas, eps=eps,
                              weight_decay=weight_decay),
                         tx_factory=lambda **ov: fused_novograd(**{**kw, **ov}))
