"""FusedMixedPrecisionLamb — TPU re-design of
``apex.optimizers.FusedMixedPrecisionLamb``.

Ref: apex/optimizers/fused_mixed_precision_lamb.py. The reference keeps fp32
master weights plus a reduced-precision model copy, with lr/step living on
device for sync-free execution. Here the fp32 master lives *inside the
optimizer state*; ``update`` runs LAMB on the master and returns deltas in
the model's (possibly bf16/fp16) dtype. lr/step are traced scalars, so the
whole step is sync-free by construction under jit.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers._base import FusedOptimizer
from apex_tpu.optimizers.fused_adam import ScalarOrSchedule
from apex_tpu.optimizers.fused_lamb import fused_lamb


class FusedMPLambState(NamedTuple):
    master: Any  # fp32 master params
    inner: Any   # FusedLAMBState over the master tree


def fused_mixed_precision_lamb(
    lr: ScalarOrSchedule = 1e-3,
    bias_correction: bool = True,
    betas=(0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    adam_w_mode: bool = True,
    grad_averaging: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    reduced_precision_dtype=None,
) -> optax.GradientTransformation:
    del reduced_precision_dtype  # model dtype is whatever params carry
    inner_tx = fused_lamb(lr=lr, bias_correction=bias_correction, betas=betas,
                          eps=eps, weight_decay=weight_decay,
                          adam_w_mode=adam_w_mode, grad_averaging=grad_averaging,
                          max_grad_norm=max_grad_norm, use_nvlamb=use_nvlamb)

    def init(params):
        master = jax.tree_util.tree_map(lambda p: p.astype(jnp.float32), params)
        return FusedMPLambState(master=master, inner=inner_tx.init(master))

    def update(grads, state, params=None):
        g32 = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
        deltas, inner = inner_tx.update(g32, state.inner, state.master)
        master = optax.apply_updates(state.master, deltas)
        # model-precision update = round(master) - old model params
        updates = jax.tree_util.tree_map(
            lambda new_m, p: new_m.astype(p.dtype) - p, master, params)
        return updates, FusedMPLambState(master=master, inner=inner)

    return optax.GradientTransformation(init, update)


class FusedMixedPrecisionLamb(FusedOptimizer):
    """Stateful apex-style API (ref apex/optimizers/fused_mixed_precision_lamb.py:10)."""

    def __init__(self, params, lr=1e-3, step=0, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01, amsgrad=False,
                 adam_w_mode=True, grad_averaging=True, max_grad_norm=1.0,
                 use_nvlamb=False, reduced_precision_dtype=None):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        del step
        tx = fused_mixed_precision_lamb(
            lr=lr, bias_correction=bias_correction, betas=betas, eps=eps,
            weight_decay=weight_decay, adam_w_mode=adam_w_mode,
            grad_averaging=grad_averaging, max_grad_norm=max_grad_norm,
            use_nvlamb=use_nvlamb, reduced_precision_dtype=reduced_precision_dtype)
        super().__init__(params, tx, dict(lr=lr, betas=betas, eps=eps,
                                          weight_decay=weight_decay))
