"""FusedAdagrad — TPU re-design of ``apex.optimizers.FusedAdagrad``.

Ref: apex/optimizers/fused_adagrad.py + csrc/multi_tensor_adagrad.cu.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optimizers import _math
from apex_tpu.optimizers._base import FusedOptimizer
from apex_tpu.optimizers.fused_adam import ScalarOrSchedule, _lr_at


class FusedAdagradState(NamedTuple):
    count: jax.Array
    sum: Any  # accumulated squared gradients ("h" in the kernel)


def fused_adagrad(
    lr: ScalarOrSchedule = 1e-2,
    eps: float = 1e-10,
    weight_decay: float = 0.0,
    adagrad_w_mode: bool = False,
) -> optax.GradientTransformation:
    def init(params):
        h = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return FusedAdagradState(count=jnp.zeros([], jnp.int32), sum=h)

    def update(grads, state, params=None):
        count = state.count + 1
        lr_t = _lr_at(lr, state.count)  # optax convention: schedule sees pre-increment count
        kw = dict(lr=lr_t, eps=eps, weight_decay=weight_decay,
                  adagrad_w_mode=adagrad_w_mode)
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        p_leaves = jax.tree_util.tree_leaves(params)
        h_leaves = jax.tree_util.tree_leaves(state.sum)
        results = [_math.adagrad_step(g, p, h, **kw)
                   for g, p, h in zip(g_leaves, p_leaves, h_leaves)]
        updates = treedef.unflatten(
            [r[0].astype(p.dtype) for r, p in zip(results, p_leaves)])
        h = treedef.unflatten([r[1] for r in results])
        return updates, FusedAdagradState(count=count, sum=h)

    return optax.GradientTransformation(init, update)


class FusedAdagrad(FusedOptimizer):
    """Stateful apex-style API (ref apex/optimizers/fused_adagrad.py:43)."""

    def __init__(self, params, lr=1e-2, eps=1e-10, weight_decay=0.0,
                 set_grad_none=True, adagrad_w_mode=False):
        del set_grad_none
        kw = dict(lr=lr, eps=eps, weight_decay=weight_decay,
                  adagrad_w_mode=adagrad_w_mode)
        super().__init__(params, fused_adagrad(**kw),
                         dict(lr=lr, eps=eps, weight_decay=weight_decay),
                         tx_factory=lambda **ov: fused_adagrad(**{**kw, **ov}))
