"""Fused optimizers (TPU re-design of ``apex.optimizers``).

Each optimizer exists in two forms:
- a functional, optax-compatible transform (``fused_adam(...)``) for jitted
  functional training loops — the native TPU path;
- an apex-style stateful class (``FusedAdam(params, ...)``) for drop-in
  familiarity with the reference API (ref apex/optimizers/__init__.py).
"""

from apex_tpu.optimizers._base import opt_partition_specs
from apex_tpu.optimizers.fused_adam import FusedAdam, fused_adam
from apex_tpu.optimizers.fused_sgd import FusedSGD, fused_sgd
from apex_tpu.optimizers.fused_lamb import FusedLAMB, fused_lamb
from apex_tpu.optimizers.fused_adagrad import FusedAdagrad, fused_adagrad
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad, fused_novograd
from apex_tpu.optimizers.fused_mixed_precision_lamb import (
    FusedMixedPrecisionLamb,
    fused_mixed_precision_lamb,
)

__all__ = [
    "opt_partition_specs",
    "FusedAdam", "fused_adam",
    "FusedSGD", "fused_sgd",
    "FusedLAMB", "fused_lamb",
    "FusedAdagrad", "fused_adagrad",
    "FusedNovoGrad", "fused_novograd",
    "FusedMixedPrecisionLamb", "fused_mixed_precision_lamb",
]
