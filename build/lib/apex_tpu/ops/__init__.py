"""Kernel layer: Pallas TPU kernels + flat-buffer fused tree ops.

TPU analog of the reference's ``csrc/`` CUDA kernels (see SURVEY.md §2).
"""

from apex_tpu.ops.flat import (
    FlatSpec,
    flatten_tensors,
    unflatten_tensors,
    flatten_tree,
    unflatten_tree,
)
