"""Flat-buffer packing: the TPU analog of Apex's tensor flattening.

The reference relies on ``csrc/flatten_unflatten.cpp`` (torch's
``_flatten_dense_tensors``) plus the ``multi_tensor_apply`` chunking machinery
(``csrc/multi_tensor_apply.cuh``) so that elementwise updates over hundreds of
small tensors become a handful of kernel launches. On TPU the same goal —
one fused pass over all parameters — is met by packing leaves into a single
1-D buffer per dtype and letting XLA/Pallas run one fused elementwise kernel
over it.

Everything here is jit-compatible: specs are static python metadata, pack and
unpack are pure functions of arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class FlatSpec:
    """Static metadata describing how a list of arrays packs into one buffer."""

    shapes: tuple  # tuple of shape-tuples
    dtype: Any
    offsets: tuple  # start offset of each leaf in the flat buffer
    sizes: tuple
    total: int

    @staticmethod
    def of(tensors: Sequence[jax.Array]) -> "FlatSpec":
        shapes = tuple(tuple(t.shape) for t in tensors)
        dtypes = {jnp.dtype(t.dtype) for t in tensors}
        if len(dtypes) > 1:
            raise ValueError(
                f"flatten_tensors requires a uniform dtype, got {dtypes}; "
                "split into per-dtype lists first (as the reference does with "
                "its g_16/g_32 lists)."
            )
        sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
        offsets = tuple(int(o) for o in np.cumsum((0,) + sizes)[:-1])
        return FlatSpec(
            shapes=shapes,
            dtype=dtypes.pop() if dtypes else jnp.float32,
            offsets=offsets,
            sizes=sizes,
            total=int(sum(sizes)),
        )


def flatten_tensors(tensors: Sequence[jax.Array], spec: FlatSpec | None = None):
    """Pack a list of same-dtype arrays into one 1-D buffer.

    Mirrors ``apex.parallel.distributed.flatten`` /
    ``csrc/flatten_unflatten.cpp:flatten`` but stays inside jit (the concat
    compiles to one fused copy).
    """
    if spec is None:
        spec = FlatSpec.of(tensors)
    if not tensors:
        return jnp.zeros((0,), dtype=spec.dtype), spec
    flat = jnp.concatenate([jnp.ravel(t) for t in tensors])
    return flat, spec


def unflatten_tensors(flat: jax.Array, spec: FlatSpec):
    """Inverse of :func:`flatten_tensors` (ref csrc/flatten_unflatten.cpp:unflatten)."""
    return [
        jax.lax.dynamic_slice_in_dim(flat, off, size).reshape(shape)
        for off, size, shape in zip(spec.offsets, spec.sizes, spec.shapes)
    ]


def flatten_tree(tree):
    """Pack an arbitrary pytree into per-dtype flat buffers.

    Returns ``(buffers, (treedef, leaf_dtypes, specs))`` where ``buffers`` is a
    dict mapping dtype name -> 1-D buffer. Used by the flat-path optimizers to
    run a single fused update per dtype regardless of how many parameters the
    model has (the multi-tensor-apply analog).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    by_dtype: dict[str, list[int]] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(leaf.dtype).name, []).append(i)
    buffers = {}
    specs = {}
    for name, idxs in by_dtype.items():
        buf, spec = flatten_tensors([leaves[i] for i in idxs])
        buffers[name] = buf
        specs[name] = (tuple(idxs), spec)
    return buffers, (treedef, len(leaves), specs)


def unflatten_tree(buffers, meta):
    """Inverse of :func:`flatten_tree`."""
    treedef, n_leaves, specs = meta
    leaves: list = [None] * n_leaves
    for name, (idxs, spec) in specs.items():
        parts = unflatten_tensors(buffers[name], spec)
        for i, part in zip(idxs, parts):
            leaves[i] = part
    return jax.tree_util.tree_unflatten(treedef, leaves)
