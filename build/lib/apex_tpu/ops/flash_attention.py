"""Pallas TPU flash attention (the kernel behind ``apex_tpu.contrib.fmha``;
ref apex/contrib/fmha/fmha.py + csrc/fmha cutlass kernels).

Design (TPU-first, not a CUDA port):
- grid = (batch*heads, q_blocks, k_blocks), k innermost so the online
  softmax state (m, l, acc) lives in VMEM scratch across the k sweep.
- one q tile is [BLOCK_Q, d] in VMEM; each step streams one [BLOCK_K, d]
  k/v tile through the MXU (q @ k^T then p @ v), fp32 accumulation.
- causal masking is positional (iota compare) — no mask tensor ever
  materializes in HBM (the reference's kernels read a cu_seqlens array;
  fixed-shape batched input is the TPU-friendly layout).

Backward (FlashAttention-2 style, TPU-blocked): the forward additionally
writes the per-row logsumexp; the backward recomputes p-blocks from (q, k,
lse) in VMEM — dq accumulates over a k sweep, dk/dv accumulate over a q
sweep (and, for GQA, over the query heads sharing each kv head) — so
training, like inference, never materializes an [sq, sk] matrix in HBM
(ref apex/contrib/fmha csrc dgrad kernels). Non-TPU backends fall back to
the jnp reference VJP.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops import pallas_config

_NEG_INF = -1e30


def _keep_mask(seed, bh, q_pos, k_pos, p_drop):
    """Counter-based Bernoulli keep mask for attention dropout.

    Deterministic in the ABSOLUTE (head, query, key) coordinates — the
    forward and backward kernels run different block grids, so a stateful
    per-block PRNG could not reproduce the same mask; a murmur3-finalized
    hash of the position counter can, from any tiling (ref
    apex/contrib/fmha/fmha.py:35 threads p_dropout through the fused
    kernel; philox counters play this role in the CUDA kernels).
    Pure elementwise uint32 math: runs identically inside a Pallas kernel
    and in the jnp fallback path.
    """
    x = (k_pos.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         + q_pos.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)
         + bh.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35)
         + seed.astype(jnp.uint32))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    # compare in the positive-int31 domain: a logical >>1 makes the value
    # fit signed int32, so the threshold test never depends on how the
    # backend treats unsigned comparisons (Mosaic-safe)
    x31 = (x >> jnp.uint32(1)).astype(jnp.int32)
    return x31 > jnp.int32(min(int(p_drop * 2147483648.0), 2147483647))


def _fwd_kernel(causal, scale, block_q, block_k, sq, sk, varlen, p_drop,
                q_ref, k_ref, v_ref, *refs):
    refs = list(refs)
    kvlen_ref = refs.pop(0) if varlen else None
    seed_ref = refs.pop(0) if p_drop else None
    o_ref, lse_ref, m_sc, l_sc, acc_sc = refs
    bh_idx = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    run = True
    if causal:
        # whole block above the diagonal ⇒ nothing to do
        run = (ki * block_k) <= (qi * block_q + block_q - 1)
    if varlen:
        # whole block past this sequence's keys ⇒ nothing to do
        run = run & ((ki * block_k) < kvlen_ref[0, 0, 0])

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
        k = k_ref[0].astype(jnp.float32)                  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        if causal:
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        # mask key padding (sk not multiple of block_k)
        if sk % block_k:
            s = jnp.where(k_pos < sk, s, _NEG_INF)
        if varlen:
            s = jnp.where(k_pos < kvlen_ref[0, 0, 0], s, _NEG_INF)

        m_prev = m_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        # rows with nothing allowed yet: keep p exact zero
        p = jnp.where(s <= _NEG_INF * 0.5, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_sc[:, 0] = l_sc[:, 0] * alpha + jnp.sum(p, axis=-1)
        # dropout applies to the NORMALIZED probs (torch semantics:
        # dropout(softmax) @ v), so the numerator is masked+rescaled while
        # the normalizer l accumulates the raw probs
        pv = p
        if p_drop:
            keep = _keep_mask(seed_ref[0, 0], bh_idx.astype(jnp.uint32),
                              q_pos, k_pos, p_drop)
            pv = jnp.where(keep, p / (1.0 - p_drop), 0.0)
        acc_sc[:] = acc_sc[:] * alpha[:, None] + jax.lax.dot_general(
            pv, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_sc[:, 0] = m_new

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_sc[:, 0], 1e-30)
        o_ref[0] = (acc_sc[:] / l[:, None]).astype(o_ref.dtype)
        # exact per-row logsumexp — the backward's p-block recompute key.
        # lse rides as [bh, sq, 1]: a (1, bq) block over [bh, sq] violates
        # Mosaic's last-two-dims rule (second-to-last must divide 8 or
        # equal the array dim); the trailing singleton makes the block
        # (1, bq, 1) legal (bq % 8 == 0, 1 == full dim)
        lse_ref[0, :, 0] = (m_sc[:, 0] + jnp.log(l)).astype(jnp.float32)


def _pick_block(s, target):
    b = min(target, s)
    while s % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret",
                                             "p_drop"))
def _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                      interpret=False, kv_lens=None, p_drop=0.0, seed=None):
    """q [bh, sq, d], k/v [bh_kv, sk, d] → o [bh, sq, d].

    GQA: when bh_kv < bh, ``rep = bh // bh_kv`` query heads read the SAME
    k/v block via the BlockSpec index map — no repeated copy in HBM.
    Layout requirement: q heads grouped kv-major (head g*rep+r shares kv
    head g), which :func:`flash_attention` arranges.

    ``kv_lens`` [bh] int32 (varlen): row b attends only to its first
    kv_lens[b] keys; blocks entirely past the bound are skipped. The
    length rides as a [bh, 1, 1] array with a (1, 1, 1) VMEM block per
    row (the last two block dims must equal the array dims or divide the
    (8, 128) tile — CI pins this via tests/run_pallas/test_tpu_lowering);
    scalar prefetch (SMEM via PrefetchScalarGridSpec) would let Mosaic
    skip the block FETCH too, but needs per-shape grid plumbing —
    revisit if varlen profiles hot.
    """
    bh, sq, d = q.shape
    bh_kv, sk, _ = k.shape
    rep = bh // bh_kv
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    grid = (bh, pl.cdiv(sq, bq), pl.cdiv(sk, bk))
    varlen = kv_lens is not None

    kernel = functools.partial(_fwd_kernel, causal, scale, bq, bk, sq, sk,
                               varlen, p_drop)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b // rep, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b // rep, j, 0)),
    ]
    args = (q, k, v)
    if varlen:
        # [bh, 1, 1] with a (1, 1, 1) block: last two dims equal the
        # array's, which Mosaic accepts ((1, 1) over [bh, 1] does not)
        in_specs.append(
            pl.BlockSpec((1, 1, 1), lambda b, i, j: (b, 0, 0)))
        args = args + (kv_lens.astype(jnp.int32).reshape(bh, 1, 1),)
    if p_drop:
        in_specs.append(pl.BlockSpec((1, 1), lambda b, i, j: (0, 0)))
        args = args + (seed.astype(jnp.uint32).reshape(1, 1),)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            pallas_config.out_struct((bh, sq, d), q.dtype, q, k, v),
            pallas_config.out_struct((bh, sq, 1), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    # public lse stays [bh, sq]; the singleton is a kernel-layout detail
    return o, lse[:, :, 0]


def _reference_attention(q, k, v, causal, scale, kv_lens=None, p_drop=0.0,
                         seed=None):
    """jnp reference — also the VJP path (rematerialized). GQA-aware:
    q [bh, sq, d] with k/v [bh_kv, sk, d]; grouped einsum, no kv copy.
    ``kv_lens`` [bh]: varlen key bound per row (finite fill — empty
    sequences stay NaN-free through autodiff). Dropout uses the SAME
    counter-based mask as the Pallas kernels, so both backends produce
    bit-identical masks for a given seed."""
    bh, sq, d = q.shape
    bh_kv, sk, _ = k.shape
    rep = bh // bh_kv
    qg = q.reshape(bh_kv, rep, sq, d).astype(jnp.float32)
    s = jnp.einsum("grqd,gkd->grqk", qg, k.astype(jnp.float32)) * scale
    if causal:
        qpos = jnp.arange(sq)[:, None]
        kpos = jnp.arange(sk)[None, :]
        s = jnp.where(kpos <= qpos, s, _NEG_INF)
    if kv_lens is not None:
        ok = (jnp.arange(sk)[None, None, None, :]
              < kv_lens.reshape(bh_kv, rep)[:, :, None, None])  # [g,r,1,sk]
        s = jnp.where(ok, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if p_drop:
        bh_idx = (jnp.arange(bh_kv, dtype=jnp.uint32)[:, None]
                  * jnp.uint32(rep)
                  + jnp.arange(rep, dtype=jnp.uint32)[None, :])
        keep = _keep_mask(
            seed, bh_idx[:, :, None, None],
            jnp.arange(sq, dtype=jnp.uint32)[None, None, :, None],
            jnp.arange(sk, dtype=jnp.uint32)[None, None, None, :], p_drop)
        p = jnp.where(keep, p / (1.0 - p_drop), 0.0)
    o = jnp.einsum("grqk,gkd->grqd", p, v.astype(jnp.float32))
    return o.reshape(bh, sq, d).astype(q.dtype)


# ------------------------------------------------------------ backward
# FlashAttention-2-style blocked backward: p-blocks are recomputed in VMEM
# from (q, k, lse); dq accumulates over the k sweep, dk/dv over the q sweep
# (innermost, so scratch accumulation per kv block is contiguous) and, for
# GQA, over the `rep` query heads sharing each kv head. No [sq, sk] array
# ever exists in HBM (ref csrc/fmha dgrad kernels).


def _bwd_dq_kernel(causal, scale, bq, bk, varlen, p_drop,
                   q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                   *refs):
    refs = list(refs)
    kvlen_ref = refs.pop(0) if varlen else None
    seed_ref = refs.pop(0) if p_drop else None
    dq_ref, acc_sc = refs
    bh_idx = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    run = True
    if causal:
        run = (ki * bk) <= (qi * bq + bq - 1)
    if varlen:
        run = run & ((ki * bk) < kvlen_ref[0, 0, 0])

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        p = jnp.exp(s - lse_ref[0])
        if causal or varlen or p_drop:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
        if causal:
            p = jnp.where(k_pos <= q_pos, p, 0.0)
        if varlen:
            p = jnp.where(k_pos < kvlen_ref[0, 0, 0], p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if p_drop:
            # o = (p∘m)@v with m = keep/(1-pd): dL/dp = m∘(do@vᵀ), and the
            # softmax-backward row term stays D = rowsum(do∘o) because
            # Σ_k p_k m_k (do·v_k) = do·o — only dp gets masked
            keep = _keep_mask(seed_ref[0, 0], bh_idx.astype(jnp.uint32),
                              q_pos, k_pos, p_drop)
            dp = jnp.where(keep, dp / (1.0 - p_drop), 0.0)
        ds = p * (dp - dl_ref[0]) * scale
        acc_sc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = acc_sc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(causal, scale, bq, bk, rep, nq, varlen, p_drop,
                    q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                    *refs):
    refs = list(refs)
    kvlen_ref = refs.pop(0) if varlen else None
    seed_ref = refs.pop(0) if p_drop else None
    dk_ref, dv_ref, dk_sc, dv_sc = refs
    g_idx = pl.program_id(0)
    ki = pl.program_id(1)
    r = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when((r == 0) & (qi == 0))
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    run = True
    if causal:
        run = (qi * bq + bq - 1) >= (ki * bk)
    if varlen:
        run = run & ((ki * bk) < kvlen_ref[0, 0, 0])

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        p = jnp.exp(s - lse_ref[0])
        if causal or varlen or p_drop:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
        if causal:
            p = jnp.where(k_pos <= q_pos, p, 0.0)
        if varlen:
            p = jnp.where(k_pos < kvlen_ref[0, 0, 0], p, 0.0)
        if p_drop:
            # same counter-based mask as the forward: bh = g*rep + r here
            bh_idx = (g_idx * rep + r).astype(jnp.uint32)
            keep = _keep_mask(seed_ref[0, 0], bh_idx, q_pos, k_pos, p_drop)
            pm = jnp.where(keep, p / (1.0 - p_drop), 0.0)
        else:
            pm = p
        dv_sc[:] += jax.lax.dot_general(
            pm, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if p_drop:
            dp = jnp.where(keep, dp / (1.0 - p_drop), 0.0)
        ds = p * (dp - dl_ref[0]) * scale
        dk_sc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]

    @pl.when((r == rep - 1) & (qi == nq - 1))
    def _finish():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret",
                                             "p_drop"))
def _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                      interpret=False, kv_lens=None, p_drop=0.0, seed=None):
    bh, sq, d = q.shape
    bh_kv, sk, _ = k.shape
    rep = bh // bh_kv
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    nq, nk = sq // bq, sk // bk
    varlen = kv_lens is not None

    # D_i = rowsum(dO * O): elementwise, O(s·d) — fine as fused XLA.
    # lse/delta ride as [bh, sq, 1] (same Mosaic block-shape rule as the
    # forward's lse output)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, :, None]
    lse3 = lse.reshape(bh, sq, 1)

    dq_in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b // rep, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b // rep, j, 0)),
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
    ]
    dq_args = (q, k, v, do, lse3, delta)
    dkv_in_specs = [
        pl.BlockSpec((1, bq, d), lambda g, j, r, i: (g * rep + r, i, 0)),
        pl.BlockSpec((1, bk, d), lambda g, j, r, i: (g, j, 0)),
        pl.BlockSpec((1, bk, d), lambda g, j, r, i: (g, j, 0)),
        pl.BlockSpec((1, bq, d), lambda g, j, r, i: (g * rep + r, i, 0)),
        pl.BlockSpec((1, bq, 1), lambda g, j, r, i: (g * rep + r, i, 0)),
        pl.BlockSpec((1, bq, 1), lambda g, j, r, i: (g * rep + r, i, 0)),
    ]
    dkv_args = (q, k, v, do, lse3, delta)
    if varlen:
        kvl = kv_lens.astype(jnp.int32).reshape(bh, 1, 1)
        dq_in_specs.append(
            pl.BlockSpec((1, 1, 1), lambda b, i, j: (b, 0, 0)))
        dq_args = dq_args + (kvl,)
        dkv_in_specs.append(
            pl.BlockSpec((1, 1, 1), lambda g, j, r, i: (g * rep + r, 0, 0)))
        dkv_args = dkv_args + (kvl,)
    if p_drop:
        sd = seed.astype(jnp.uint32).reshape(1, 1)
        dq_in_specs.append(pl.BlockSpec((1, 1), lambda b, i, j: (0, 0)))
        dq_args = dq_args + (sd,)
        dkv_in_specs.append(
            pl.BlockSpec((1, 1), lambda g, j, r, i: (0, 0)))
        dkv_args = dkv_args + (sd,)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal, scale, bq, bk, varlen,
                          p_drop),
        grid=(bh, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=pallas_config.out_struct((bh, sq, d), q.dtype, q, k, v,
                                           do),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(*dq_args)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal, scale, bq, bk, rep, nq,
                          varlen, p_drop),
        grid=(bh_kv, nk, rep, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda g, j, r, i: (g, j, 0)),
            pl.BlockSpec((1, bk, d), lambda g, j, r, i: (g, j, 0)),
        ],
        out_shape=[
            pallas_config.out_struct((bh_kv, sk, d), k.dtype, q, k, v, do),
            pallas_config.out_struct((bh_kv, sk, d), v.dtype, q, k, v, do),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


def _use_pallas() -> bool:
    return pallas_config.use_pallas("flash_attention")


def _blocks(kind, q, k):
    return pallas_config.flash_blocks(kind, q.shape[1], k.shape[1],
                                      q.shape[2])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    if _use_pallas():
        bq, bk = _blocks("fwd", q, k)
        return _flash_fwd_pallas(q, k, v, causal, scale, bq, bk,
                                 pallas_config.interpret())[0]
    return _reference_attention(q, k, v, causal, scale)


def _flash_fwd(q, k, v, causal, scale):
    if _use_pallas():
        bq, bk = _blocks("fwd", q, k)
        o, lse = _flash_fwd_pallas(q, k, v, causal, scale, bq, bk,
                                   pallas_config.interpret())
        return o, (q, k, v, o, lse)
    return _reference_attention(q, k, v, causal, scale), (q, k, v, None, None)


def _flash_bwd(causal, scale, res, g):
    q, k, v, o, lse = res
    if lse is not None:
        bq, bk = _blocks("bwd", q, k)
        return _flash_bwd_pallas(q, k, v, o, lse, g, causal, scale, bq, bk,
                                 pallas_config.interpret())
    _, vjp = jax.vjp(
        lambda q, k, v: _reference_attention(q, k, v, causal, scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


# dropout flavor (ref apex/contrib/fmha/fmha.py:35 p_dropout): the seed
# rides as a traced uint32 so changing it does NOT retrace; the mask is
# recomputed in the backward kernels from the same counter hash.


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_dropout(q, k, v, seed, causal, scale, p_drop):
    return _flash_dropout_fwd(q, k, v, seed, causal, scale, p_drop)[0]


def _flash_dropout_fwd(q, k, v, seed, causal, scale, p_drop):
    if _use_pallas():
        bq, bk = _blocks("fwd", q, k)
        o, lse = _flash_fwd_pallas(q, k, v, causal, scale, bq, bk,
                                   pallas_config.interpret(),
                                   p_drop=p_drop, seed=seed)
        return o, (q, k, v, seed, o, lse)
    o = _reference_attention(q, k, v, causal, scale, p_drop=p_drop,
                             seed=seed)
    return o, (q, k, v, seed, None, None)


def _flash_dropout_bwd(causal, scale, p_drop, res, g):
    import numpy as _np

    q, k, v, seed, o, lse = res
    if lse is not None:
        bq, bk = _blocks("bwd", q, k)
        dq, dk, dv = _flash_bwd_pallas(q, k, v, o, lse, g, causal, scale,
                                       bq, bk, pallas_config.interpret(),
                                       p_drop=p_drop, seed=seed)
    else:
        _, vjp = jax.vjp(
            lambda q, k, v: _reference_attention(
                q, k, v, causal, scale, p_drop=p_drop, seed=seed), q, k, v)
        dq, dk, dv = vjp(g)
    dseed = _np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dseed


_flash_dropout.defvjp(_flash_dropout_fwd, _flash_dropout_bwd)


# varlen (kv_lens-bounded) flavor: same kernels, masked to each row's key
# count — the reference's cu_seqlens semantics with flash memory behavior.


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash_varlen(causal, scale, p_drop, q, k, v, kv_lens, seed):
    return _flash_varlen_fwd(causal, scale, p_drop, q, k, v, kv_lens,
                             seed)[0]


def _flash_varlen_fwd(causal, scale, p_drop, q, k, v, kv_lens, seed):
    if _use_pallas():
        bq, bk = _blocks("fwd", q, k)
        o, lse = _flash_fwd_pallas(q, k, v, causal, scale, bq, bk,
                                   pallas_config.interpret(),
                                   kv_lens=kv_lens, p_drop=p_drop,
                                   seed=seed)
        return o, (q, k, v, kv_lens, seed, o, lse)
    o = _reference_attention(q, k, v, causal, scale, kv_lens=kv_lens,
                             p_drop=p_drop, seed=seed)
    return o, (q, k, v, kv_lens, seed, None, None)


def _flash_varlen_bwd(causal, scale, p_drop, res, g):
    import numpy as _np

    q, k, v, kv_lens, seed, o, lse = res
    if lse is not None:
        bq, bk = _blocks("bwd", q, k)
        dq, dk, dv = _flash_bwd_pallas(q, k, v, o, lse, g, causal, scale,
                                       bq, bk, pallas_config.interpret(),
                                       kv_lens=kv_lens, p_drop=p_drop,
                                       seed=seed)
    else:
        _, vjp = jax.vjp(
            lambda q, k, v: _reference_attention(q, k, v, causal, scale,
                                                 kv_lens=kv_lens,
                                                 p_drop=p_drop, seed=seed),
            q, k, v)
        dq, dk, dv = vjp(g)
    dlens = _np.zeros(kv_lens.shape, dtype=jax.dtypes.float0)
    dseed = _np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, dlens, dseed


_flash_varlen.defvjp(_flash_varlen_fwd, _flash_varlen_bwd)


def _dropout_seed(dropout_key):
    """uint32 kernel seed from a jax PRNG key (traced, so a fresh key per
    step does not retrace)."""
    try:
        return jax.random.bits(dropout_key, (), jnp.uint32)
    except (AttributeError, TypeError):  # older jax without random.bits
        return jax.random.randint(
            dropout_key, (), 0, jnp.iinfo(jnp.int32).max).astype(jnp.uint32)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, kv_lens=None,
                    dropout_p: float = 0.0, dropout_key=None,
                    deterministic: bool = False):
    """Fused attention on [b, s, h, d] (heads may differ for k/v — GQA).

    Returns [b, sq, h, d]; fp32 softmax internally, output in q's dtype.
    ``kv_lens`` [b] int32 bounds each sequence's keys (varlen batching —
    ref fmha cu_seqlens); padded QUERY rows of the output are zeroed.
    The varlen path is SELF-attention only (one shared length per row
    bounds both queries and keys, so it requires sq == sk); cross-attention
    with separate q/kv lengths is not expressible with a single kv_lens.

    ``dropout_p`` drops SOFTMAX PROBABILITIES inside the kernel (inverted
    dropout, ref apex/contrib/fmha/fmha.py:35 p_dropout) — requires
    ``dropout_key`` (jax PRNG key) unless ``deterministic`` is set, in
    which case dropout is a no-op (eval mode).
    """
    b, sq, h, d = q.shape
    h_kv = k.shape[2]
    if h % h_kv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {h_kv}")
    sk = k.shape[1]
    if kv_lens is not None and sq != sk:
        raise ValueError(
            f"kv_lens implies self-attention (shared per-row length) but "
            f"sq={sq} != sk={sk}; cross-attention varlen needs separate "
            f"q_lens/kv_lens, which this kernel does not support")
    scale = scale if scale is not None else 1.0 / d ** 0.5
    p_drop = 0.0 if deterministic else float(dropout_p)
    if p_drop and dropout_key is None:
        raise ValueError(
            "dropout_p > 0 in training needs dropout_key (jax PRNG key); "
            "pass deterministic=True for eval")

    # heads-major flatten; q head g*rep+r shares kv head g (standard GQA
    # head order), matching the kernel's b//rep kv indexing
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h_kv, sk, d)
    if kv_lens is None:
        if p_drop:
            o = _flash_dropout(qt, kt, vt, _dropout_seed(dropout_key),
                               causal, float(scale), p_drop)
        else:
            o = _flash(qt, kt, vt, causal, float(scale))
        return o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    kv_lens = jnp.asarray(kv_lens, jnp.int32)
    seed = (_dropout_seed(dropout_key) if p_drop
            else jnp.zeros((), jnp.uint32))
    o = _flash_varlen(causal, float(scale), p_drop, qt, kt, vt,
                      jnp.repeat(kv_lens, h), seed)
    o = o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    # zero meaningless padded-query rows (and their gradients)
    q_ok = jnp.arange(sq)[None, :] < kv_lens[:, None]
    return jnp.where(q_ok[:, :, None, None], o, 0.0)
