"""Pallas TPU kernel for the flat-buffer fused Adam update.

The reference's ``csrc/multi_tensor_adam.cu`` is ONE kernel over chunked
tensor lists; the TPU flat path packs the whole model into a 1-D buffer
per dtype, and this kernel is the single fused elementwise pass over it
(SURVEY §1 kernel layer: "fused adam/lamb on flat buffers"). XLA's own
fusion of the jnp chain is the fallback and the baseline ``bench.py``
races this kernel against — elementwise chains are XLA's home turf, so
the kernel must EARN its default (``use_kernel=None`` defers to the
pallas gate; the bench reports both).

Layout: the 1-D buffer pads to a (rows, 1024) fp32-tileable slab and the
grid walks row blocks; traced scalars (lr_t and the bias-correction
denominators — step-dependent) ride a (1, 4) block, static hyperparams
close over the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops import pallas_config

_COLS = 1024
_BLOCK_ROWS = 512


def _adam_kernel(b1, b2, eps, weight_decay, adam_w_mode, bias_correction,
                 sc_ref, g_ref, p_ref, m_ref, v_ref,
                 d_ref, mo_ref, vo_ref):
    lr_t = sc_ref[0, 0]
    c1 = sc_ref[0, 1]
    c2 = sc_ref[0, 2]
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    m = m_ref[...]
    v = v_ref[...]
    if not adam_w_mode and weight_decay:
        g = g + weight_decay * p
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * jnp.square(g)
    if bias_correction:
        m_hat = m / c1
        v_hat = v / c2
    else:
        m_hat, v_hat = m, v
    update = m_hat / (jnp.sqrt(v_hat) + eps)
    if adam_w_mode and weight_decay:
        update = update + weight_decay * p
    d_ref[...] = (-lr_t * update).astype(d_ref.dtype)
    mo_ref[...] = m
    vo_ref[...] = v


def _pad_to_slab(x, block_rows):
    n = x.size
    per = _COLS * block_rows
    rows = -(-n // _COLS)
    rows = -(-rows // block_rows) * block_rows
    pad = rows * _COLS - n
    if pad:
        x = jnp.pad(x.ravel(), (0, pad))
    return x.reshape(rows, _COLS), n


@functools.partial(jax.jit, static_argnames=(
    "b1", "b2", "eps", "weight_decay", "adam_w_mode", "bias_correction",
    "interpret"))
def adam_flat_pallas(g, p, m, v, lr_t, step, *, b1, b2, eps, weight_decay,
                     adam_w_mode, bias_correction, interpret=False):
    """One fused Adam pass over 1-D buffers.

    ``g``/``m``/``v`` fp32, ``p`` any float dtype; ``lr_t``/``step``
    traced scalars. Returns ``(delta, m', v')`` with delta in p's dtype.
    """
    block = _BLOCK_ROWS if g.size >= _COLS * _BLOCK_ROWS else 8
    g2, n = _pad_to_slab(g.astype(jnp.float32), block)
    p2, _ = _pad_to_slab(p, block)
    m2, _ = _pad_to_slab(m, block)
    v2, _ = _pad_to_slab(v, block)
    rows = g2.shape[0]
    step = step.astype(jnp.float32)
    scalars = jnp.stack([
        jnp.asarray(lr_t, jnp.float32),
        1.0 - b1 ** step if bias_correction else jnp.float32(1.0),
        1.0 - b2 ** step if bias_correction else jnp.float32(1.0),
        jnp.float32(0.0),
    ]).reshape(1, 4)

    row_spec = pl.BlockSpec((block, _COLS), lambda i: (i, 0))
    sc_spec = pl.BlockSpec((1, 4), lambda i: (0, 0))
    d2, mo2, vo2 = pl.pallas_call(
        functools.partial(_adam_kernel, b1, b2, eps, weight_decay,
                          adam_w_mode, bias_correction),
        grid=(rows // block,),
        in_specs=[sc_spec, row_spec, row_spec, row_spec, row_spec],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=[
            pallas_config.out_struct((rows, _COLS), p.dtype, g, p, m, v),
            pallas_config.out_struct((rows, _COLS), jnp.float32, g, p, m, v),
            pallas_config.out_struct((rows, _COLS), jnp.float32, g, p, m, v),
        ],
        interpret=interpret,
    )(scalars, g2, p2, m2, v2)
    return (d2.ravel()[:n], mo2.ravel()[:n], vo2.ravel()[:n])
