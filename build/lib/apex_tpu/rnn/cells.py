"""RNN cells (TPU re-design of ``apex.RNN.cells`` + the fused pointwise
cells in RNNBackend; ref apex/RNN/cells.py, apex/RNN/RNNBackend.py).

The reference's "fused" cells rely on torch's rnnFusedPointwise CUDA kernel;
under XLA the gate pointwise math fuses automatically, so the cells are pure
functions ``cell(params, carry, x) -> (new_carry, output)`` designed for
``jax.lax.scan`` over time.

Weights follow the torch convention: w_ih [gates*h, in], w_hh [gates*h, h],
gate order (i, f, g, o) for LSTM and (r, z, n) for GRU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_cell_params(key, input_size, hidden_size, gate_multiplier,
                     bias=True, extra_m=False, output_size=None, dtype=jnp.float32):
    """Uniform(-1/sqrt(h), 1/sqrt(h)) init (ref RNNBackend.py reset_parameters)."""
    out = output_size if output_size is not None else hidden_size
    g = gate_multiplier
    bound = 1.0 / hidden_size ** 0.5
    ks = jax.random.split(key, 6)

    def u(k, *shape):
        return jax.random.uniform(k, shape, dtype, -bound, bound)

    p = {"w_ih": u(ks[0], g * hidden_size, input_size),
         "w_hh": u(ks[1], g * hidden_size, out)}
    if bias:
        p["b_ih"] = u(ks[2], g * hidden_size)
        p["b_hh"] = u(ks[3], g * hidden_size)
    if extra_m:  # mLSTM multiplicative weights (ref cells.py:21-25)
        p["w_mih"] = u(ks[4], out, input_size)
        p["w_mhh"] = u(ks[5], out, out)
    if out != hidden_size:
        # output projection h_out = w_ho @ h (ref RNNBackend.py RNNCell:
        # "if output_size != hidden_size: h = F.linear(h, w_ho)")
        key, k = jax.random.split(ks[5])
        p["w_ho"] = u(k, out, hidden_size)
    return p


def _gates(p, x, h):
    y = x @ p["w_ih"].T + h @ p["w_hh"].T
    if "b_ih" in p:
        y = y + p["b_ih"] + p["b_hh"]
    return y


def lstm_cell(p, carry, x):
    """Fused-pointwise LSTM (ref RNNBackend fusedBackend.LSTMFused)."""
    h, c = carry
    i, f, g, o = jnp.split(_gates(p, x, h), 4, axis=-1)
    c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return (h_new, c_new), h_new


def mlstm_cell(p, carry, x):
    """Multiplicative LSTM (ref cells.py:61 mLSTMCell): the hidden input to
    the gates is modulated m = (W_mih x) * (W_mhh h)."""
    h, c = carry
    m = (x @ p["w_mih"].T) * (h @ p["w_mhh"].T)
    i, f, g, o = jnp.split(_gates(p, x, m), 4, axis=-1)
    c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return (h_new, c_new), h_new


def gru_cell(p, carry, x):
    """GRU with torch gate layout (r, z, n) (ref fusedBackend.GRUFused)."""
    (h,) = carry
    gi = x @ p["w_ih"].T + (p["b_ih"] if "b_ih" in p else 0.0)
    gh = h @ p["w_hh"].T + (p["b_hh"] if "b_hh" in p else 0.0)
    ir, iz, in_ = jnp.split(gi, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(in_ + r * hn)
    h_new = (1.0 - z) * n + z * h
    return (h_new,), h_new


def relu_cell(p, carry, x):
    """Vanilla ReLU RNN (ref RNNBackend RNNReLUCell)."""
    (h,) = carry
    h_new = jax.nn.relu(_gates(p, x, h))
    return (h_new,), h_new


def tanh_cell(p, carry, x):
    """Vanilla tanh RNN (ref RNNBackend RNNTanhCell)."""
    (h,) = carry
    h_new = jnp.tanh(_gates(p, x, h))
    return (h_new,), h_new


CELLS = {
    "LSTM": (lstm_cell, 4, 2, False),
    "mLSTM": (mlstm_cell, 4, 2, True),
    "GRU": (gru_cell, 3, 1, False),
    "ReLU": (relu_cell, 1, 1, False),
    "Tanh": (tanh_cell, 1, 1, False),
}
