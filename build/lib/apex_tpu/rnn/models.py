"""Stacked RNN models over lax.scan (ref apex/RNN/models.py which wires
cells into stackedRNN/bidirectionalRNN containers).

``LSTM(input_size, hidden_size, num_layers)`` returns a model object with
``.params`` and ``__call__(x, params=None, h0=None)``; x is [seq, batch, in]
(the torch RNN layout the reference uses; ``batch_first=True`` accepts
[batch, seq, in]). ``bidirectional=True`` runs a second cell per layer over
reversed time and concatenates the two outputs on the feature dim
(ref RNNBackend.py:25 bidirectionalRNN: fwd + reversed scan, cat(-1)).
Dropout between layers matches ref RNNBackend.stackedRNN.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.rnn.cells import CELLS, init_cell_params


class _RNNModel:
    def __init__(self, mode: str, input_size: int, hidden_size: int,
                 num_layers: int = 1, bias: bool = True, dropout: float = 0.0,
                 bidirectional: bool = False, batch_first: bool = False,
                 output_size: Optional[int] = None,
                 seed: int = 0, dtype=jnp.float32):
        self.mode = mode
        self.cell, self.gate_multiplier, self.n_states, self.extra_m = CELLS[mode]
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.dropout = dropout
        self.bidirectional = bidirectional
        self.batch_first = batch_first
        self.n_directions = 2 if bidirectional else 1
        self.output_size = output_size if output_size is not None else hidden_size
        key = jax.random.PRNGKey(seed)
        self.params = []
        for layer in range(num_layers):
            in_sz = (input_size if layer == 0
                     else self.output_size * self.n_directions)
            dirs = []
            for _ in range(self.n_directions):
                key, k = jax.random.split(key)
                dirs.append(init_cell_params(
                    k, in_sz, hidden_size, self.gate_multiplier, bias=bias,
                    extra_m=self.extra_m, output_size=self.output_size,
                    dtype=dtype))
            self.params.append(dirs[0] if not bidirectional
                               else {"fwd": dirs[0], "rev": dirs[1]})

    def init_hidden(self, batch: int, dtype=jnp.float32):
        """Zero states per layer (ref RNNBackend init_hidden): h carries
        output_size, extra states (LSTM c) carry hidden_size. Bidirectional
        layers carry a ``(fwd_states, rev_states)`` pair."""
        sizes = [self.output_size] + [self.hidden_size] * (self.n_states - 1)

        def one():
            return tuple(jnp.zeros((batch, s), dtype) for s in sizes)

        return [
            (one(), one()) if self.bidirectional else one()
            for _ in range(self.num_layers)
        ]

    def _scan_dir(self, lp, state, xs, reverse: bool):
        def body(carry, xt):
            new_carry, y = self.cell(lp, carry, xt)
            if "w_ho" in lp:
                # project hidden -> output_size (ref RNNBackend RNNCell
                # forward); the projected h is what the carry stores
                y = y @ lp["w_ho"].T
                new_carry = (y,) + tuple(new_carry[1:])
            return new_carry, y

        return jax.lax.scan(body, state, xs, reverse=reverse)

    def __call__(self, x, params=None, h0=None, dropout_rng=None):
        """x [seq, batch, in] ([batch, seq, in] when ``batch_first``) →
        (outputs [seq, batch, h·dirs] (resp. batch-first), final_states)."""
        if self.batch_first:
            x = jnp.swapaxes(x, 0, 1)
        p = params if params is not None else self.params
        states = h0 if h0 is not None else self.init_hidden(x.shape[1], x.dtype)
        outs = x
        finals = []
        for layer in range(self.num_layers):
            lp = p[layer]
            if self.bidirectional:
                s_f, s_r = states[layer]
                final_f, out_f = self._scan_dir(lp["fwd"], s_f, outs, False)
                # reverse=True consumes time back-to-front and emits ys in
                # original order — the reversed-scan half of the ref's
                # bidirectionalRNN without materializing x[::-1]
                final_r, out_r = self._scan_dir(lp["rev"], s_r, outs, True)
                outs = jnp.concatenate([out_f, out_r], axis=-1)
                finals.append((final_f, final_r))
            else:
                final, outs = self._scan_dir(lp, states[layer], outs, False)
                finals.append(final)
            if self.dropout > 0.0 and layer < self.num_layers - 1:
                if dropout_rng is None:
                    raise ValueError(
                        "dropout > 0 requires dropout_rng (pass None-free "
                        "rng, or construct with dropout=0.0 for eval)")
                dropout_rng, k = jax.random.split(dropout_rng)
                keep = jax.random.bernoulli(
                    k, 1.0 - self.dropout, outs.shape)
                outs = jnp.where(keep, outs / (1.0 - self.dropout), 0.0)
        if self.batch_first:
            outs = jnp.swapaxes(outs, 0, 1)
        return outs, finals


def LSTM(input_size, hidden_size, num_layers=1, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, **kw):
    """ref RNN/models.py:34 LSTM."""
    return _RNNModel("LSTM", input_size, hidden_size, num_layers, bias,
                     dropout, bidirectional, batch_first, **kw)


def GRU(input_size, hidden_size, num_layers=1, bias=True, batch_first=False,
        dropout=0.0, bidirectional=False, **kw):
    return _RNNModel("GRU", input_size, hidden_size, num_layers, bias,
                     dropout, bidirectional, batch_first, **kw)


def ReLU(input_size, hidden_size, num_layers=1, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, **kw):
    return _RNNModel("ReLU", input_size, hidden_size, num_layers, bias,
                     dropout, bidirectional, batch_first, **kw)


def Tanh(input_size, hidden_size, num_layers=1, bias=True, batch_first=False,
         dropout=0.0, bidirectional=False, **kw):
    return _RNNModel("Tanh", input_size, hidden_size, num_layers, bias,
                     dropout, bidirectional, batch_first, **kw)


def mLSTM(input_size, hidden_size, num_layers=1, bias=True, batch_first=False,
          dropout=0.0, bidirectional=False, **kw):
    """ref RNN/models.py:22 mLSTM."""
    return _RNNModel("mLSTM", input_size, hidden_size, num_layers, bias,
                     dropout, bidirectional, batch_first, **kw)
