"""apex.RNN parity surface (ref apex/RNN/__init__.py)."""

from apex_tpu.rnn.models import LSTM, GRU, ReLU, Tanh, mLSTM
from apex_tpu.rnn import cells, models

__all__ = ["LSTM", "GRU", "ReLU", "Tanh", "mLSTM", "cells", "models"]
