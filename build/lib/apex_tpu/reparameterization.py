"""Weight normalization (TPU re-design of ``apex.reparameterization``;
ref apex/reparameterization/{__init__,weight_norm,reparameterization}.py).

The reference installs forward-pre hooks that recompute w = g * v/||v||
before each forward. Functionally, the reparameterized model simply stores
(g, v) in its param tree and materializes w inside the (jitted) forward —
XLA fuses the norm into the consuming matmul, which is the whole point of
the CUDA "fused norm" path.

API: :func:`apply_weight_norm` walks a pytree, replacing selected leaves
``w`` with ``{name_g, name_v}`` subtrees; :func:`compute_weights` /
:func:`remove_weight_norm` invert it.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_G_SUFFIX = "_g"
_V_SUFFIX = "_v"


def _norm(v, dim: Optional[int]):
    """2-norm over all dims except ``dim`` (ref weight_norm.py:8 _norm)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(v.astype(jnp.float32) ** 2))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    n = jnp.sqrt(jnp.sum(v.astype(jnp.float32) ** 2, axis=axes,
                         keepdims=True))
    return n


class WeightNorm:
    """w = g * v / ||v|| (ref weight_norm.py:22)."""

    @staticmethod
    def reparameterize(weight, dim: Optional[int] = 0):
        """weight → (g, v) (ref weight_norm.py:62)."""
        g = _norm(weight, dim).astype(weight.dtype)
        return g, weight

    @staticmethod
    def compute_weight(g, v, dim: Optional[int] = 0):
        """(g, v) → w (ref weight_norm.py:39); fp32 norm, origin dtype out."""
        w = v.astype(jnp.float32) * (
            g.astype(jnp.float32) / (_norm(v, dim) + 1e-12))
        return w.astype(v.dtype)


Reparameterization = WeightNorm  # ref reparameterization.py base class


def _eligible(leaf) -> bool:
    # ref __init__.py: skip 1-d vectors and scalars
    return hasattr(leaf, "ndim") and leaf.ndim >= 2


def apply_weight_norm(params, name: str = "", dim: int = 0):
    """Replace eligible leaves (or the one named ``name``) with
    ``{leaf + '_g', leaf + '_v'}`` pairs (ref __init__.py:7)."""

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if isinstance(v, dict):
                out[k] = walk(v)
            elif (_eligible(v) and (name == "" or k == name)):
                g, vv = WeightNorm.reparameterize(v, dim)
                out[k + _G_SUFFIX] = g
                out[k + _V_SUFFIX] = vv
            else:
                out[k] = v
        return out

    return walk(params)


def compute_weights(params, dim: int = 0):
    """Materialize every (g, v) pair back into w — call INSIDE the forward
    so the norm fuses into the consumer."""

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if isinstance(v, dict):
                out[k] = walk(v)
            elif k.endswith(_G_SUFFIX) and k[:-len(_G_SUFFIX)] + _V_SUFFIX in node:
                base = k[:-len(_G_SUFFIX)]
                out[base] = WeightNorm.compute_weight(
                    v, node[base + _V_SUFFIX], dim)
            elif k.endswith(_V_SUFFIX) and k[:-len(_V_SUFFIX)] + _G_SUFFIX in node:
                pass  # consumed with its _g partner
            else:
                out[k] = v
        return out

    return walk(params)


def remove_weight_norm(params, name: str = "", dim: int = 0):
    """Collapse (g, v) back to plain weights (ref __init__.py:64)."""
    del name
    return compute_weights(params, dim)


def apply_reparameterization(params, reparameterization=None, name: str = "",
                             dim: int = 0, hook_child: bool = True):
    """ref reparameterization/__init__.py:67 — apply a reparameterization
    (WeightNorm is the only one the reference ships, and the default) to
    one named weight or every eligible weight. Functional: returns the
    transformed params tree instead of installing forward hooks
    (``hook_child`` is accepted for parity; there are no hooks to place)."""
    del hook_child
    if reparameterization is not None and reparameterization is not WeightNorm:
        raise ValueError(
            f"unknown reparameterization {reparameterization!r}; "
            "WeightNorm is the supported kind (as in the reference)")
    return apply_weight_norm(params, name=name, dim=dim)


def remove_reparameterization(params, reparameterization=None, name: str = "",
                              remove_all: bool = False):
    """ref reparameterization/__init__.py:99 — collapse (g, v) pairs back
    to plain weights. ``remove_all``/``name`` narrow which weights in the
    reference; the functional tree walk collapses every pair it finds, so
    both spellings converge here."""
    del remove_all
    if reparameterization is not None and reparameterization is not WeightNorm:
        raise ValueError(
            f"unknown reparameterization {reparameterization!r}; "
            "WeightNorm is the supported kind (as in the reference)")
    return remove_weight_norm(params, name=name)
