"""Long-context Llama training with ring-attention context parallelism.

The sequence dimension is sharded over the 'cp' mesh axis: each device
holds seq/cp tokens, and attention runs as a ring — K/V blocks circulate
via ``ppermute`` while each device accumulates its queries' online
softmax (apex_tpu/transformer/context_parallel.py). Peak activation
memory per device is O(seq/cp · d): no device ever materializes a score
matrix for the full sequence, which is what makes 100k+-token contexts
fit. Optionally composes with dp (data parallelism) on the same mesh.

This is the capability Apex's users reach for Megatron-LM's context
parallelism for; the reference itself has no single-file analog (its
pieces live in apex/transformer). TPU-native shape: one ``shard_map``
carries the ring attention, the dp gradient mean, and the fused-Adam
update in a single jitted step.

    python examples/long_context.py --cp 4 --dp 2 --seq 512 --steps 10
"""

from __future__ import annotations

import argparse
import functools
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cp", type=int, default=4)
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--seq", type=int, default=512,
                   help="GLOBAL sequence length (seq/cp per device)")
    p.add_argument("--batch", type=int, default=4,
                   help="global batch (batch/dp per dp rank)")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=1e-3)
    args = p.parse_args()

    n_dev = args.cp * args.dp
    from examples._common import ensure_devices

    ensure_devices(n_dev)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    from apex_tpu.models import llama
    from apex_tpu.optimizers import fused_adam

    if args.seq % args.cp:
        raise SystemExit(f"--seq {args.seq} must divide by --cp {args.cp}")
    if args.batch % args.dp:
        raise SystemExit(f"--batch {args.batch} must divide by --dp "
                         f"{args.dp}")

    cfg = llama.tiny(max_seq_len=args.seq)
    mesh = Mesh(np.array(jax.devices()[:n_dev]).reshape(args.dp, args.cp),
                ("dp", "cp"))
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tx = fused_adam(lr=args.lr)
    opt_state = tx.init(params)

    # one fixed batch (overfit => deterministic decrease); tokens are
    # sharded [batch/dp, seq/cp] per device
    tokens = jax.random.randint(jax.random.PRNGKey(1),
                                (args.batch, args.seq), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=-1)

    def step(params, opt_state, tokens, targets):
        def loss_fn(p):
            # ring attention makes the ACTIVATIONS globally correct over
            # cp, but llama.loss_fn's CE mean covers only this device's
            # seq shard — average it over cp (and dp) to the global loss
            loss = llama.loss_fn(p, (tokens, targets), cfg, tp_axis=None,
                                 cp_axis="cp")
            return jax.lax.pmean(jax.lax.pmean(loss, "cp"), "dp")

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # params are replicated over BOTH axes, so their grads must be
        # averaged over both — each rank's backward pass contributes only
        # its own tokens' share
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(jax.lax.pmean(g, "cp"), "dp"), grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, opt_state, loss

    jstep = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P("dp", "cp"), P("dp", "cp")),
        out_specs=(P(), P(), P())))

    # ground truth: the sharded global loss at init must equal the
    # single-device loss on the full batch — catches any missing cp/dp
    # reduction that mere loss-decrease would hide
    ref = float(llama.loss_fn(params, (tokens, targets), cfg,
                              tp_axis=None, cp_axis=None))
    _, _, l0 = jstep(params, opt_state, tokens, targets)
    if abs(float(l0) - ref) > 5e-3 * max(1.0, abs(ref)):
        raise SystemExit(f"cp-sharded loss {float(l0):.5f} != "
                         f"single-device loss {ref:.5f}")
    print(f"parity: sharded loss {float(l0):.5f} == single-device "
          f"{ref:.5f} OK")

    losses = []
    for i in range(args.steps):
        t0 = time.perf_counter()
        params, opt_state, loss = jstep(params, opt_state, tokens, targets)
        losses.append(float(loss))
        print(f"step {i:3d}  loss {losses[-1]:.4f}  "
              f"({(time.perf_counter() - t0) * 1e3:.0f} ms)", flush=True)

    verdict = "decreased" if losses[-1] < losses[0] else "NOT decreased"
    print(f"ring-attention cp={args.cp} dp={args.dp} seq={args.seq}: "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f} ({verdict})")
    if losses[-1] >= losses[0]:
        raise SystemExit(1)


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
