"""Llama training with composed TP x PP x DP (+ sequence parallelism) —
the 3D-parallel example the reference enables through apex.transformer
(ref apex/transformer/parallel_state.py + pipeline_parallel/schedules;
the reference itself ships no end-to-end transformer example — this is the
Megatron-LM composition its pieces exist for).

TPU-native shape: one ``shard_map`` over a (pp, dp, tp) mesh contains the
whole train step — collective-1F1B pipeline via scan+ppermute, tensor- and
sequence-parallel layers, vocab-parallel cross entropy, fused Adam, and the
cross-axis gradient reductions (dp mean everywhere; pp psum of the shared
embedding/head grads — the reference's embedding-group allreduce; tp psum
of sequence-parallel norm grads). XLA overlaps the collectives with
compute; there is no NCCL-style schedule code.

The step loop is driven by ``apex_tpu.resilience.ResilientTrainLoop``
(ISSUE 5): auto-resume from the newest *valid* checkpoint, periodic +
emergency saves, retry/rollback on transient failures, SIGTERM/env
preemption handling — and ``APEX_TPU_FAULT_PLAN=preempt@7,...`` turns
any invocation into a chaos run (docs/resilience.md).

    python examples/llama_train.py --pp 2 --dp 2 --tp 2 --steps 10
"""

from __future__ import annotations

import argparse
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    t_main0 = time.perf_counter()
    p = argparse.ArgumentParser()
    p.add_argument("--pp", type=int, default=2)
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--microbatches", type=int, default=4)
    p.add_argument("--microbatch-size", type=int, default=2)
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--layers-per-stage", type=int, default=2)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--no-sequence-parallel", action="store_true")
    p.add_argument("--fixed-data", action="store_true",
                   help="overfit one fixed batch (deterministic decrease)")
    p.add_argument("--checkpoint-dir", default="",
                   help="save sharded train state here (orbax)")
    p.add_argument("--save-every", type=int, default=5)
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest step in --checkpoint-dir")
    p.add_argument("--auto-shard", action="store_true",
                   help="let the analysis planner pick pp/dp/tp and the "
                        "PartitionSpec layout for the device budget "
                        "(--pp*--dp*--tp devices) instead of the "
                        "hand-written tables (docs/planner.md)")
    p.add_argument("--opt-level", default="O0", choices=["O0", "O4"],
                   help="O4 (ISSUE 13): run the lm_head matmul in fp8 "
                        "(E4M3 fwd / E5M2 grad) under delayed per-tensor "
                        "scaling; the Fp8ScalingState rides the train "
                        "state through checkpoints, so scales resume "
                        "bit-identical (docs/amp.md). The O1-O3 amp "
                        "levels apply to the apex-shaped examples "
                        "(imagenet/main_amp style); this 3D-parallel "
                        "demo exposes the fp8 tier.")
    args = p.parse_args()

    n_dev = args.pp * args.dp * args.tp
    from examples._common import ensure_devices, opt_partition_specs

    ensure_devices(n_dev)

    plan = None
    if args.auto_shard:
        # the flag's CLI contract: --pp/--dp/--tp still size the DEVICE
        # budget (so invocations stay comparable), but the planner
        # decides how to factor it and which dims shard (ISSUE 8)
        from apex_tpu.parallel import auto_shard

        # min tp=2: this step's vocab-parallel CE / sequence-parallel
        # collectives assume a bound tp axis, and jax 0.4.37's shard_map
        # cannot statically infer out_specs replication over a tp=1
        # mesh — the executability floor rides the plan request so the
        # search never emits a mesh this runtime cannot execute.
        # The run-derived knobs that shape the cost model's comms and
        # bubble terms ride along (seq scales activation bytes,
        # microbatches the pipeline bubble; batch/layers anchored at
        # the device budget so every dp|pp factorization divides them).
        # hidden/heads/vocab stay the planner's defaults because this
        # demo scales those dims WITH the chosen tp below.
        plan = auto_shard.plan_for(
            "llama", devices=n_dev, min_mesh={"tp": 2},
            seq=args.seq, microbatches=args.microbatches,
            batch=args.microbatches * args.microbatch_size * n_dev,
            layers=args.layers_per_stage * n_dev)
        args.pp, args.dp, args.tp = (plan.mesh["pp"], plan.mesh["dp"],
                                     plan.mesh["tp"])
        print(f"auto-shard plan: pp={args.pp} dp={args.dp} tp={args.tp} "
              f"layout={plan.layout} "
              f"(predicted {plan.predicted['step_ms']:.3f} ms/step, "
              f"comms {plan.predicted['comms_bytes']} B/step, "
              f"verified {plan.predicted['findings']} findings)")

    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    from apex_tpu.models import llama
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pipelined_forward,
    )
    from apex_tpu.transformer.tensor_parallel.cross_entropy import (
        vocab_parallel_cross_entropy,
    )
    from apex_tpu.transformer.tensor_parallel.mappings import _to_varying

    pp, dp, tp = args.pp, args.dp, args.tp
    mesh = Mesh(np.array(jax.devices()[:n_dev]).reshape(pp, dp, tp),
                ("pp", "dp", "tp"))
    sp = tp > 1 and not args.no_sequence_parallel

    cfg = llama.tiny(
        num_layers=args.layers_per_stage * pp, num_heads=2 * tp,
        num_kv_heads=tp, hidden_size=32 * tp, intermediate_size=64 * tp,
        vocab_size=128 * tp, max_seq_len=args.seq)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    stage_params = llama.split_stages(params, pp)
    io_params = {k: v for k, v in params.items() if k != "layers"}

    M, mb, s = args.microbatches, args.microbatch_size, args.seq
    tx = fused_adam(lr=args.lr)

    # O4 fp8 tier (ISSUE 13): one registered site — the lm_head
    # projection, the biggest single matmul in the step (hidden x
    # vocab). Decoder-layer matmuls live inside the lax.scan over
    # layers, where the delayed-scaling context deliberately falls back
    # to the fp32-accum path (a collected amax may not escape a
    # transform); registering only "lm_head" makes that explicit.
    fp8 = None
    if args.opt_level == "O4":
        from apex_tpu.amp import Fp8DelayedScaler

        fp8 = Fp8DelayedScaler(["lm_head"], history=16)
        print("opt-level O4: lm_head in fp8 (E4M3/E5M2, delayed "
              "scaling, history=16)")

    def psum(t, ax):
        return jax.lax.psum(_to_varying(t, ax), ax)

    def pmean(t, ax):
        return jax.lax.pmean(_to_varying(t, ax), ax)

    def train_step(stage_params, io_params, opt_state, tokens, targets,
                   fp8_state=None):
        pp_rank = jax.lax.axis_index("pp")
        pp_size = jax.lax.axis_size("pp")

        def vary_all(t):
            for ax in ("pp", "dp", "tp"):
                t = jax.tree_util.tree_map(
                    lambda a, ax=ax: _to_varying(a, ax), t)
            return t

        def total_loss(trees):
            stage, io = trees
            stage = jax.tree_util.tree_map(lambda a: a[0], stage)
            stage, io = vary_all(stage), vary_all(io)

            x_mb = vary_all(jax.vmap(
                lambda tok: llama.embed(io, tok, cfg, tp_axis="tp",
                                        sequence_parallel=sp))(tokens))
            positions = llama._positions(mb, s, None)

            def stage_fn(sp_params, x):
                return llama.stage_fn(sp_params, x, cfg, positions,
                                      tp_axis="tp", cp_axis=None,
                                      sequence_parallel=sp)

            outs = pipelined_forward(stage_fn, stage, x_mb, axis_name="pp",
                                     remat=True)

            if fp8 is not None:
                # O4: fold the microbatch dim into the batch and run ONE
                # lm_head call outside any vmap — the fp8 context's amax
                # collection cannot cross a transform boundary, and the
                # folded gemm is the same math (equal-sized microbatches
                # mean mean-of-means == global mean)
                o2 = outs.reshape((M * mb,) + outs.shape[2:])
                t2 = targets.reshape((M * mb,) + targets.shape[2:])
                logits = llama.lm_head(io, o2, cfg, tp_axis="tp",
                                       sequence_parallel=sp)
                losses = jnp.mean(vocab_parallel_cross_entropy(
                    logits, t2, axis_name="tp"))
            else:
                def mb_loss(o, t):
                    logits = llama.lm_head(io, o, cfg, tp_axis="tp",
                                           sequence_parallel=sp)
                    return jnp.mean(vocab_parallel_cross_entropy(
                        logits, t, axis_name="tp"))

                losses = jnp.mean(jax.vmap(mb_loss)(outs, targets))
            local = jnp.where(pp_rank == pp_size - 1, losses, 0.0)
            return jax.lax.psum(local, "pp")

        if fp8 is not None:
            with fp8.step(fp8_state) as fp8_ctx:
                loss, (g_stage, g_io) = fp8_ctx.value_and_grad(
                    total_loss)((stage_params, io_params))
            # pmax the observations over EVERY mesh axis so all ranks
            # write identical ring columns and the delayed scales stay
            # replicated (non-last pp stages observe their bubble
            # activations too — a conservative over-estimate that only
            # lowers the scale)
            new_fp8 = fp8.update(fp8_state, fp8_ctx,
                                 reduce_axes=("pp", "dp", "tp"))
        else:
            loss, (g_stage, g_io) = jax.value_and_grad(total_loss)(
                (stage_params, io_params))
            new_fp8 = fp8_state

        g_stage = jax.tree_util.tree_map(lambda g: pmean(g, "dp"), g_stage)
        g_io = jax.tree_util.tree_map(
            lambda g: pmean(psum(g, "pp"), "dp"), g_io)
        if sp:  # sequence-parallel norm grads are tp-partial (Megatron SP)
            g_stage = {k: (psum(v, "tp") if k.endswith("norm") else v)
                       for k, v in g_stage.items()}
            g_io = {k: (psum(v, "tp") if k == "final_norm" else v)
                    for k, v in g_io.items()}

        grads = {"stage": g_stage, "io": g_io}
        updates, opt_state = tx.update(
            grads, opt_state, {"stage": stage_params, "io": io_params})
        new_stage = jax.tree_util.tree_map(
            jnp.add, stage_params, updates["stage"])
        new_io = jax.tree_util.tree_map(jnp.add, io_params, updates["io"])
        loss = jax.lax.pmean(jax.lax.pmean(loss, "dp"), "tp")
        if fp8 is not None:
            return new_stage, new_io, opt_state, new_fp8, loss
        return new_stage, new_io, opt_state, loss

    if plan is not None:
        # the plan's spec tables replace the hand-written layout: layer
        # specs gain the leading stage dim, io specs apply as-is (at
        # tp=1 the planner's entries degenerate to replicated, which is
        # exactly what a tp=1 mesh needs)
        from apex_tpu.parallel import auto_shard

        lp = auto_shard.spec_group(plan, "layers")
        io_specs = auto_shard.spec_group(plan, "io")
    else:
        lp = llama.param_specs(cfg)["layers"]
        io_specs = {"embed": P("tp", None), "final_norm": P(),
                    "lm_head": P(None, "tp")}
    stage_specs = {k: P("pp", *lp[k]) for k in lp}

    with mesh:
        opt_state = tx.init({"stage": stage_params, "io": io_params})
        opt_specs = opt_partition_specs(
            tx, {"stage": stage_params, "io": io_params},
            {"stage": stage_specs, "io": io_specs})

        if fp8 is not None:
            # the Fp8ScalingState is replicated (every leaf P()): the
            # pmax'd updates keep all ranks' rings bit-identical, and a
            # replicated spec is what lets the restored state resume
            # bit-identical after preempt/crash-restart
            fp8_state0 = fp8.init()
            fp8_specs = jax.tree_util.tree_map(lambda _: P(), fp8_state0)
            step = jax.jit(shard_map(
                train_step, mesh=mesh,
                in_specs=(stage_specs, io_specs, opt_specs,
                          P(None, "dp", None), P(None, "dp", None),
                          fp8_specs),
                out_specs=(stage_specs, io_specs, opt_specs, fp8_specs,
                           P()),
            ))
        else:
            step = jax.jit(shard_map(
                train_step, mesh=mesh,
                in_specs=(stage_specs, io_specs, opt_specs,
                          P(None, "dp", None), P(None, "dp", None)),
                out_specs=(stage_specs, io_specs, opt_specs, P()),
            ))

        # per-step telemetry through the shared layer: structured step
        # records (step time, tokens/s, loss) land in the process
        # registry; APEX_TPU_METRICS=<path> dumps the run as JSONL for
        # `python -m apex_tpu.observability report`
        from apex_tpu import observability as obs
        from apex_tpu import resilience

        reporter = obs.StepReporter("llama_train",
                                    tokens_per_step=M * mb * dp * s)
        # per-step phase attribution (ISSUE 7): every step runs inside a
        # span window; the data/compute/comms/host fractions land on the
        # StepReporter record, so the step log says WHERE the time went
        phases = obs.StepPhases(name="llama_train/step")
        # numerics tier (ISSUE 9): a decimated fused stats pass over the
        # param tree (amax/l2/underflow/finite, ONE host fetch every 8
        # steps) rides the step record's numerics block, and the health
        # monitor turns loss trajectories into numerics/* events before
        # the resilience ladder has to act
        collector = obs.StatsCollector("llama_train", every=8)
        health = obs.HealthMonitor("llama_train")
        # memory tier (ISSUE 15): a decimated live-HBM snapshot (one
        # host-side walk of the live buffers every 8 steps) rides the
        # step record's memory block; the monitor's watermark + top-k
        # buffers feed the OOM forensics verdict the resilience loop
        # attaches when a step dies RESOURCE_EXHAUSTED
        memmon = obs.MemoryMonitor("llama_train", every=8)
        key = jax.random.PRNGKey(1)
        stats = {"first": None, "last": None}

        def make_batch(it):
            # the data stream is a pure function of the step index
            # (fold_in) — the property the loop's bit-identical
            # resume-replay guarantee rests on
            sub = jax.random.fold_in(key, 0 if args.fixed_data else it)
            tokens = jax.random.randint(sub, (M, mb * dp, s), 0,
                                        cfg.vocab_size)
            return tokens, jnp.roll(tokens, -1, axis=-1)

        def train_step_fn(state, it):
            with phases.step():
                # t0 before make_batch: step_time_ms must cover the same
                # window as the phase fractions, or step_time × phases
                # misattributes the excluded data time
                t0 = time.perf_counter()
                with obs.span("data/batch"):
                    tokens, targets = make_batch(it)
                if fp8 is not None:
                    new_stage, new_io, new_opt, new_fp8, loss = step(
                        state["stage"], state["io"], state["opt"],
                        tokens, targets, state["fp8"])
                else:
                    new_stage, new_io, new_opt, loss = step(
                        state["stage"], state["io"], state["opt"],
                        tokens, targets)
                loss = float(loss)  # host pull: syncs the step chain
                dt = time.perf_counter() - t0
            collector.observe({"stage": new_stage, "io": new_io}, it)
            health.observe(it, loss=loss)
            memmon.observe(it)
            rec = reporter.step(dt, loss=loss, numerics=collector.last,
                                memory=memmon.last,
                                **phases.last_fields())
            if stats["first"] is None:
                stats["first"] = loss
            stats["last"] = loss
            print(f"step {it:3d}  loss {loss:.4f}  "
                  f"({rec['step_time_ms']:.0f} ms  "
                  f"{rec['tokens_per_sec']:.0f} tok/s)")
            new_state = {"stage": new_stage, "io": new_io,
                         "opt": new_opt}
            if fp8 is not None:
                new_state["fp8"] = new_fp8
            return new_state, {"loss": loss}

        # resilient driver (ISSUE 5): the ref-style epoch checkpointing
        # of main_amp.py upgraded to the production contract — sharded
        # train state round-trips through orbax with commit markers,
        # SIGTERM/APEX_TPU_PREEMPT forces an emergency save + exit 75,
        # checkpoint I/O is retried, APEX_TPU_FAULT_PLAN injects chaos
        fault_spec = os.environ.get("APEX_TPU_FAULT_PLAN")
        # stall flight recorder (ISSUE 7): a step that runs past 3x the
        # trailing median (or APEX_TPU_STALL_DEADLINE seconds) dumps the
        # span ring, all thread stacks and the last registry events to a
        # flightrec_*.json post-mortem; its sensor feeds the preemption
        # watcher so a hung fleet ALSO takes the emergency-checkpoint +
        # exit-75 path instead of burning its allocation
        deadline = os.environ.get("APEX_TPU_STALL_DEADLINE")
        try:
            deadline_s = float(deadline) if deadline else None
        except ValueError:
            raise SystemExit(
                f"APEX_TPU_STALL_DEADLINE={deadline!r} is not a number "
                f"(wall-deadline seconds, e.g. 120)")
        recorder = obs.FlightRecorder(
            directory=args.checkpoint_dir or None,
            # 10x median, not the default 3x: a contended CI host can
            # jitter a CPU step 3x without anything being wedged, and a
            # false stall here escalates to exit 75 via the sensor
            stall_factor=10.0,
            deadline_s=deadline_s).install()
        watcher = resilience.PreemptionWatcher(
            sensors=[resilience.env_sensor(), recorder.sensor()]).install()
        loop = resilience.ResilientTrainLoop(
            train_step_fn,
            flight_recorder=recorder,
            directory=args.checkpoint_dir or None,
            save_every=args.save_every, max_to_keep=2,
            retry_policy=resilience.Policy(max_attempts=3, name="llama"),
            fault_plan=(resilience.FaultPlan.parse(fault_spec)
                        if fault_spec else None),
            watcher=watcher, auto_resume=args.resume,
            memory_monitor=memmon,  # OOM forensics read its watermark
            check_state_every=0,  # loss is the health signal; skip the
            # per-step full-state device fetch on the 3D-sharded tree
            exit_on_preempt=True,  # the scheduler-facing contract:
            # emergency checkpoint, then exit 75 (EX_TEMPFAIL) = rerun me
            on_resume=lambda it: print(f"=> resumed from step {it}"))
        init_state = {"stage": stage_params, "io": io_params,
                      "opt": opt_state}
        if fp8 is not None:
            # the fp8 scaling state checkpoints/restores with the rest
            # of the train state — delayed scales are replay-stable
            init_state["fp8"] = fp8_state0
        try:
            loop.run(init_state, args.steps)
        finally:
            watcher.uninstall()
            recorder.uninstall()

    if stats["first"] is None:
        print(f"nothing to do: resumed step + 1 "
              f"({(loop.resumed_from or 0) + 1}) >= --steps {args.steps}")
    else:
        print(f"mesh pp={pp} dp={dp} tp={tp} sp={sp}: "
              f"loss {stats['first']:.4f} -> {stats['last']:.4f} "
              f"({'decreased' if stats['last'] < stats['first'] else 'NOT decreased'})")

    if os.environ.get("APEX_TPU_METRICS"):
        reg = obs.get_registry()
        # goodput accounting (ISSUE 17): publish the goodput/* gauge
        # family before the dump so the run's JSONL carries its own
        # accounting (re-derivable offline:
        # `python -m apex_tpu.observability goodput <dump>`)
        try:
            ledger = obs.ledger_from_records(reg.to_records())
            acc = obs.account_goodput(
                ledger, wall_s=time.perf_counter() - t_main0)
            obs.goodput.publish(acc, reg)
            print(f"goodput {acc['goodput_ratio']:.4f} "
                  f"(productive {acc['productive_s']:.2f}s of "
                  f"{acc['wall_s']:.2f}s wall)")
        except Exception as e:  # telemetry must not cost the run
            print(f"goodput accounting failed: {e!r}")
        reg.dump(os.environ["APEX_TPU_METRICS"])
        print(f"metrics -> {os.environ['APEX_TPU_METRICS']}")


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
