"""ResNet training with amp O2 + DDP + SyncBatchNorm — the TPU analog of the
reference's flagship example (ref examples/imagenet/main_amp.py:1).

The reference flow: ``amp.initialize(model, opt, opt_level="O2")`` →
``DistributedDataParallel(model)`` → optional ``convert_syncbn_model`` →
loop { fwd, ``with amp.scale_loss(...)``, backward, step }. The TPU-native
flow below is the same recipe made functional: bf16 model params with fp32
master weights, dynamic loss scaling with in-graph overflow skip, gradient
sync as a ``pmean`` over the 'data' mesh axis inside one jitted train step,
SyncBatchNorm via cross-replica Welford stats.

Runs on any device count (virtual CPU mesh by default); synthetic data so
it runs without an imagenet tree. Try::

    python examples/imagenet_resnet50.py --steps 20
    python examples/imagenet_resnet50.py --arch resnet50 --image-size 224
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--arch", default="tiny", choices=["tiny", "resnet50"])
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=32, help="global batch")
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--opt-level", default="O2", choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--no-sync-bn", action="store_true")
    p.add_argument("--devices", type=int, default=8)
    args = p.parse_args()

    from examples._common import ensure_devices, synthetic_images

    ensure_devices(args.devices)

    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    import apex_tpu.amp as amp
    from apex_tpu.models import resnet
    from apex_tpu.optimizers import fused_sgd
    from apex_tpu.parallel import average_reduced

    n_dev = args.devices
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
    assert args.batch % n_dev == 0, "global batch must divide the mesh"

    build = resnet.resnet50 if args.arch == "resnet50" else resnet.tiny
    model = build(num_classes=args.classes,
                  sync_bn=not args.no_sync_bn, axis_name="data",
                  dtype=jnp.bfloat16 if args.opt_level in ("O2", "O3")
                  else jnp.float32)

    x0, _ = synthetic_images(jax.random.PRNGKey(0), 2, args.image_size,
                             args.classes)
    variables = model.init(jax.random.PRNGKey(1), x0, train=False)
    params32 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.float32), variables["params"])
    batch_stats = variables["batch_stats"]

    # amp.initialize resolves the opt level into a dtype policy + scaler
    # (ref main_amp.py: amp.initialize(model, optimizer, opt_level=...))
    _, handle = amp.initialize(params32, opt_level=args.opt_level,
                               verbosity=0)
    policy, scaler = handle.policy, handle.scaler
    sstate = handle.scaler_state

    tx = fused_sgd(lr=args.lr, momentum=0.9, weight_decay=1e-4)
    opt_state = tx.init(params32)  # fp32 master state (O2 master weights)

    def train_step(master, opt_state, sstate, batch_stats, x, y):
        """Per-shard body under shard_map; 'data' axis bound."""

        def loss_fn(master):
            model_params = policy.cast_model(master)  # bf16, norms fp32 (O2)
            logits, mut = model.apply(
                {"params": model_params, "batch_stats": batch_stats},
                x, train=True, mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y).mean()
            return scaler.scale_loss(loss, sstate), (loss, mut["batch_stats"])

        grads, (loss, new_stats) = jax.grad(loss_fn, has_aux=True)(master)
        # DDP: master is replicated, so shard_map's transpose already
        # psummed the local grads (the allreduce); divide by the axis size
        # for the global-batch mean (ref apex DDP gradient_average=True)
        grads = average_reduced(grads, axis_name="data")
        updates, opt_state, sstate, overflow = amp.scaled_update(
            tx, scaler, grads, opt_state, master, sstate)
        master = optax.apply_updates(master, updates)
        loss = jax.lax.pmean(loss, "data")
        return master, opt_state, sstate, new_stats, loss, overflow

    stats_specs = jax.tree_util.tree_map(lambda _: P(), batch_stats)
    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), stats_specs, P("data"), P("data")),
        out_specs=(P(), P(), P(), stats_specs, P(), P()),
    ))

    # a small fixed dataset (cycled) so the loss-decrease verdict is
    # deterministic — fresh random labels every step would be unlearnable
    batches = [synthetic_images(jax.random.PRNGKey(100 + i), args.batch,
                                args.image_size, args.classes)
               for i in range(4)]
    t0 = time.perf_counter()
    for it in range(args.steps):
        x, y = batches[it % len(batches)]
        (params32, opt_state, sstate, batch_stats, loss,
         overflow) = step(params32, opt_state, sstate, batch_stats, x, y)
        if it == 0:
            first_loss = float(loss)
            t0 = time.perf_counter()  # exclude compile
        if it % 5 == 0 or it == args.steps - 1:
            print(f"step {it:4d}  loss {float(loss):.4f}  "
                  f"scale {float(sstate.loss_scale):.0f}  "
                  f"overflow {bool(overflow)}")
    dt = (time.perf_counter() - t0) / max(args.steps - 1, 1)
    print(f"{args.batch / dt:.1f} images/s  ({dt * 1e3:.1f} ms/step)")
    final_loss = float(loss)
    print(f"loss {first_loss:.4f} -> {final_loss:.4f} "
          f"({'decreased' if final_loss < first_loss else 'NOT decreased'})")


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
