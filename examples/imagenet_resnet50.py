"""ImageNet-style ResNet trainer — TPU re-design of the reference's
flagship example (ref examples/imagenet/main_amp.py:1-543), feature for
feature: amp opt levels with loss-scale / keep-batchnorm-fp32 overrides,
DDP over the 'data' mesh axis, SyncBatchNorm, epoch loop with step-decay
+ warmup LR schedule, top-1/top-5 validation, checkpoint/save/resume
with best-accuracy tracking, and a prefetching input pipeline (the
DataLoader-workers analog, backed by the C++ host ring when built).

Data: ``--data DIR`` reads ``*.npz`` shards holding ``x`` [N,H,W,3]
float and ``y`` [N] int arrays; without it a deterministic synthetic
dataset is generated (so the example runs anywhere, ref uses fake_data
similarly). Try::

    python examples/imagenet_resnet50.py --smoke
    python examples/imagenet_resnet50.py --epochs 3 --steps-per-epoch 30
    python examples/imagenet_resnet50.py --resume auto --evaluate
    python examples/imagenet_resnet50.py --arch resnet50 --image-size 224
"""

from __future__ import annotations

import argparse
import collections
import os
import threading
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def parse_args():
    p = argparse.ArgumentParser(
        description="apex_tpu imagenet trainer (ref main_amp.py)")
    p.add_argument("--val-data", default="", metavar="DIR",
                   help="held-out shards for validation; without it the "
                        "val metrics are measured on the TRAINING shards "
                        "(a warning is printed)")
    p.add_argument("--data", default="", metavar="DIR",
                   help="dir of .npz shards (x,y); synthetic if empty")
    p.add_argument("--arch", "-a", default="tiny",
                   choices=["tiny", "resnet50", "resnet101"])
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--start-epoch", type=int, default=0)
    p.add_argument("--steps-per-epoch", type=int, default=20)
    p.add_argument("-b", "--batch", type=int, default=32,
                   help="global batch size")
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", "--wd", type=float, default=1e-4)
    p.add_argument("--warmup-epochs", type=float, default=1.0)
    p.add_argument("--decay-epochs", type=int, nargs="*", default=[30, 60, 80],
                   help="epochs at which lr steps down 10x (ref "
                        "adjust_learning_rate)")
    p.add_argument("--print-freq", "-p", type=int, default=10)
    p.add_argument("--workers", "-j", type=int, default=2,
                   help="prefetch worker threads (DataLoader analog)")
    p.add_argument("--resume", default="", metavar="PATH",
                   help="checkpoint dir to resume from ('auto' = "
                        "--checkpoint-dir)")
    p.add_argument("--checkpoint-dir", default="",
                   help="save checkpoints here each epoch (empty = no "
                        "saving)")
    p.add_argument("-e", "--evaluate", action="store_true",
                   help="validate only, no training")
    p.add_argument("--deterministic", action="store_true")
    p.add_argument("--opt-level", default="O2",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--keep-batchnorm-fp32", default=None,
                   choices=[None, "True", "False"])
    p.add_argument("--loss-scale", default=None,
                   help="float or 'dynamic' (default: opt-level policy)")
    p.add_argument("--no-sync-bn", action="store_true")
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--smoke", action="store_true",
                   help="tiny 1-epoch run that asserts the loss decreased "
                        "(CI path)")
    args = p.parse_args()
    if args.smoke:
        # shrink everything NOT explicitly overridden on the CLI (a value
        # equal to the default is indistinguishable from unset, so check
        # the argv flags themselves)
        given = set(sys.argv[1:])

        def absent(*flags):
            return not (given & set(flags))

        if absent("--arch", "-a"):
            args.arch = "tiny"
        if absent("--steps-per-epoch"):
            args.steps_per_epoch = 10
        if absent("--batch", "-b"):
            args.batch = 32
        if absent("--image-size"):
            args.image_size = 32
        if absent("--epochs"):
            args.epochs = 1
    if args.loss_scale not in (None, "dynamic"):
        args.loss_scale = float(args.loss_scale)
    return args


# ------------------------------------------------------------------- data


class ShardDataset:
    """npz shards or deterministic synthetic batches; one sample row =
    [pixels..., label] so the prefetch ring carries a single buffer."""

    def __init__(self, data_dir, n_batches, batch, image_size, classes,
                 seed):
        self.batch, self.hw, self.classes = batch, image_size, classes
        self.n_batches = n_batches
        self.seed = seed
        self.row = image_size * image_size * 3 + 1
        self._cache = collections.OrderedDict()
        self._cache_lock = threading.Lock()
        self.files = []
        if data_dir:
            self.files = sorted(
                os.path.join(data_dir, f) for f in os.listdir(data_dir)
                if f.endswith(".npz"))
            if not self.files:
                raise FileNotFoundError(f"no .npz shards in {data_dir}")

    # shard access is sequential/cyclic, so a tiny LRU suffices; unbounded
    # caching would grow host memory to the whole dataset on an
    # ImageNet-scale --data dir
    _CACHE_SHARDS = 4

    def _shard(self, path):
        """Cache decompressed shards: np.load + array access per batch
        would re-decompress the whole file on the prefetch hot path.
        fill() runs on multiple prefetch worker threads — the lock keeps
        the evicting LRU consistent (and the decompress single-flight)."""
        with self._cache_lock:
            if path in self._cache:
                self._cache.move_to_end(path)
                return self._cache[path]
            f = np.load(path)
            shard = (np.asarray(f["x"]), np.asarray(f["y"]))
            self._cache[path] = shard
            while len(self._cache) > self._CACHE_SHARDS:
                self._cache.popitem(last=False)
            return shard

    def fill(self, batch_idx, out):
        """Prefetch callback: writes batch ``batch_idx`` into ``out``
        [batch, row] float32 (runs on a worker thread)."""
        if self.files:
            xs, ys = self._shard(self.files[batch_idx % len(self.files)])
            n = len(ys)
            idx = (np.arange(self.batch) + batch_idx * self.batch) % n
            x = xs[idx].astype(np.float32).reshape(self.batch, -1)
            y = ys[idx].astype(np.float32)[:, None]
        else:
            rng = np.random.default_rng(self.seed + batch_idx)
            y_int = rng.integers(0, self.classes, self.batch)
            # class-dependent means make synthetic data learnable
            x = (rng.standard_normal((self.batch, self.row - 1)) * 0.5
                 + (y_int[:, None] / self.classes - 0.5) * 2.0)
            x, y = x.astype(np.float32), y_int.astype(np.float32)[:, None]
        out[:] = np.concatenate([x, y], axis=1)

    def unpack(self, rows):
        x = rows[:, :-1].reshape(self.batch, self.hw, self.hw, 3)
        y = rows[:, -1].astype(np.int32)
        return x, y

    def loader(self, n_slots, n_workers):
        from apex_tpu.runtime.host import PrefetchLoader

        return PrefetchLoader(
            self.fill, self.n_batches, (self.batch, self.row),
            np.float32, n_slots=n_slots, n_workers=max(n_workers, 1))


# ------------------------------------------------------------------ meters


def accuracy_counts(logits, y, topk=(1, 5)):
    """Per-shard correct counts for top-k (ref main_amp.py accuracy())."""
    out = []
    for k in topk:
        k = min(k, logits.shape[-1])
        top = jax.lax.top_k(logits, k)[1]
        out.append(jnp.sum(jnp.any(top == y[:, None], axis=-1)))
    return out


def main():
    args = parse_args()
    if args.deterministic:
        np.random.seed(0)

    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from examples._common import ensure_devices

    ensure_devices(args.devices)

    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    import apex_tpu.amp as amp
    from apex_tpu.checkpoint import CheckpointManager
    from apex_tpu.models import resnet
    from apex_tpu.optimizers import fused_sgd

    n_dev = args.devices
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
    assert args.batch % n_dev == 0, "global batch must divide the mesh"

    build = {"tiny": resnet.tiny, "resnet50": resnet.resnet50,
             "resnet101": resnet.resnet101}[args.arch]
    model = build(num_classes=args.classes,
                  sync_bn=not args.no_sync_bn, axis_name="data",
                  dtype=jnp.bfloat16 if args.opt_level in ("O2", "O3")
                  else jnp.float32)

    ds = ShardDataset(args.data, args.steps_per_epoch, args.batch,
                      args.image_size, args.classes, seed=100)
    # validation needs HELD-OUT shards (ref main_amp.py's separate val
    # dir); measuring on the training shards inflates top-1/top-5 and
    # corrupts best-checkpoint selection
    if args.data and not args.val_data:
        print("WARNING: no --val-data given; validation metrics are "
              "measured on the TRAINING shards and overstate accuracy",
              file=sys.stderr)
    val_ds = ShardDataset(args.val_data or args.data, 4, args.batch,
                          args.image_size, args.classes, seed=9000)

    x0 = jnp.zeros((2, args.image_size, args.image_size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(1), x0, train=False)
    params32 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.float32), variables["params"])
    batch_stats = variables["batch_stats"]

    # amp.initialize resolves opt level + user overrides into the dtype
    # policy and scaler (ref main_amp.py amp.initialize(model, optimizer,
    # opt_level, keep_batchnorm_fp32, loss_scale))
    _, handle = amp.initialize(
        params32, opt_level=args.opt_level,
        keep_batchnorm_fp32=args.keep_batchnorm_fp32,
        loss_scale=args.loss_scale, verbosity=0)
    policy, scaler = handle.policy, handle.scaler
    sstate = handle.scaler_state

    # warmup + step-decay schedule (ref adjust_learning_rate: linear
    # warmup over the first epochs, /10 at each decay epoch). The second
    # schedule in join_schedules sees (step - warmup_steps), so the decay
    # boundaries shift into that frame — otherwise every drop would land
    # one warmup-period late.
    spe = args.steps_per_epoch
    warmup_steps = max(int(args.warmup_epochs * spe), 1)
    decay_bounds = {int(e * spe) - warmup_steps: 0.1
                    for e in args.decay_epochs
                    if int(e * spe) > warmup_steps}
    lr_sched = optax.join_schedules(
        [optax.linear_schedule(args.lr / 10, args.lr, warmup_steps),
         optax.piecewise_constant_schedule(args.lr, decay_bounds)],
        [warmup_steps])
    tx = fused_sgd(lr=lr_sched, momentum=args.momentum,
                   weight_decay=args.weight_decay)
    opt_state = tx.init(params32)  # fp32 master state (O2 master weights)

    def train_step(master, opt_state, sstate, batch_stats, x, y):
        def loss_fn(master):
            model_params = policy.cast_model(master)
            logits, mut = model.apply(
                {"params": model_params, "batch_stats": batch_stats},
                x, train=True, mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y).mean()
            return scaler.scale_loss(loss, sstate), (loss, mut["batch_stats"])

        grads, (loss, new_stats) = jax.grad(loss_fn, has_aux=True)(master)
        # DDP allreduce: with check_rep=False (jax 0.4.37's replication
        # checker rejects these out_specs, and disabling it also
        # disables the auto-psum/vma repair the old
        # sync_autodiff_gradients path relied on) EVERY grad leaf
        # arrives per-rank local — reduce them all explicitly
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, "data"), grads)
        if args.no_sync_bn:
            # non-sync BN computes per-shard running stats, but the P()
            # out_specs store ONE tree — average them at the storage
            # boundary (sync_bn already psums inside the layer, so its
            # stats are identical across ranks and skip this)
            new_stats = jax.tree_util.tree_map(
                lambda s: jax.lax.pmean(s, "data"), new_stats)
        updates, opt_state, sstate, overflow = amp.scaled_update(
            tx, scaler, grads, opt_state, master, sstate)
        master = optax.apply_updates(master, updates)
        loss = jax.lax.pmean(loss, "data")
        return master, opt_state, sstate, new_stats, loss, overflow

    def eval_step(master, batch_stats, x, y):
        logits = model.apply(
            {"params": policy.cast_model(master),
             "batch_stats": batch_stats}, x, train=False)
        c1, c5 = accuracy_counts(logits.astype(jnp.float32), y)
        return (jax.lax.psum(c1, "data"), jax.lax.psum(c5, "data"))

    stats_specs = jax.tree_util.tree_map(lambda _: P(), batch_stats)
    # check_rep=False: 0.4.37's replication checker cannot statically
    # infer these P() out_specs (the numerics are kept honest by the
    # explicit pmean above — sync_bn already psums its statistics)
    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), stats_specs, P("data"), P("data")),
        out_specs=(P(), P(), P(), stats_specs, P(), P()),
        check_rep=False,
    ))
    evalf = jax.jit(shard_map(
        eval_step, mesh=mesh,
        in_specs=(P(), stats_specs, P("data"), P("data")),
        out_specs=(P(), P()),
        check_rep=False,
    ))

    # ------------------------------------------------------ resume / ckpt
    manager = None
    if args.checkpoint_dir:
        manager = CheckpointManager(args.checkpoint_dir, max_to_keep=3)
    best_acc1 = 0.0
    start_epoch = args.start_epoch
    resume_dir = (args.checkpoint_dir if args.resume == "auto"
                  else args.resume)
    if resume_dir:
        rm = CheckpointManager(resume_dir)
        if rm.latest_step() is not None:
            template = {"params": params32, "opt_state": opt_state,
                        "sstate": sstate, "batch_stats": batch_stats,
                        "epoch": np.zeros((), np.int32),
                        "best_acc1": np.zeros((), np.float32)}
            state = rm.restore(template)
            params32, opt_state = state["params"], state["opt_state"]
            sstate, batch_stats = state["sstate"], state["batch_stats"]
            start_epoch = int(state["epoch"]) + 1
            best_acc1 = float(state["best_acc1"])
            print(f"=> resumed from '{resume_dir}' "
                  f"(epoch {int(state['epoch'])}, "
                  f"best_acc1 {best_acc1:.3f})")
        else:
            print(f"=> no checkpoint found at '{resume_dir}'")

    def validate():
        """top-1/top-5 over the val split (ref validate())."""
        n, c1, c5 = 0, 0, 0
        for rows in val_ds.loader(2, args.workers):
            x, y = val_ds.unpack(rows)
            a, b = evalf(params32, batch_stats, jnp.asarray(x),
                         jnp.asarray(y))
            c1, c5, n = c1 + int(a), c5 + int(b), n + len(y)
        print(f"val: top1 {100*c1/n:.2f}%  top5 {100*c5/n:.2f}%  ({n})")
        return 100 * c1 / n

    if args.evaluate:
        validate()
        return

    first_loss = last_loss = None
    for epoch in range(start_epoch, args.epochs):
        t0 = time.perf_counter()
        seen = 0
        # prefetching input pipeline (C++ ring when built, threads
        # otherwise) — the reference's --workers DataLoader analog
        for it, rows in enumerate(ds.loader(4, args.workers)):
            x, y = ds.unpack(rows)
            (params32, opt_state, sstate, batch_stats, loss,
             overflow) = step(params32, opt_state, sstate, batch_stats,
                              jnp.asarray(x), jnp.asarray(y))
            seen += args.batch
            if first_loss is None:
                first_loss = float(loss)
                t0 = time.perf_counter()  # exclude compile
                seen = 0
            if it % args.print_freq == 0 or it == spe - 1:
                lr_now = float(lr_sched(epoch * spe + it))
                print(f"epoch {epoch:3d} step {it:4d}  "
                      f"loss {float(loss):.4f}  lr {lr_now:.4f}  "
                      f"scale {float(sstate.loss_scale):.0f}  "
                      f"overflow {bool(overflow)}")
        dt = time.perf_counter() - t0
        if seen:
            print(f"epoch {epoch}: {seen / dt:.1f} images/s")
        last_loss = float(loss)
        acc1 = validate()
        if manager is not None:
            is_best = acc1 > best_acc1
            best_acc1 = max(acc1, best_acc1)
            manager.save(epoch, {
                "params": params32, "opt_state": opt_state,
                "sstate": sstate, "batch_stats": batch_stats,
                "epoch": np.asarray(epoch, np.int32),
                "best_acc1": np.asarray(best_acc1, np.float32)})
            print(f"=> saved epoch {epoch}"
                  + (" (new best)" if is_best else ""))

    if first_loss is not None:
        verdict = "decreased" if last_loss < first_loss else "NOT decreased"
        print(f"loss {first_loss:.4f} -> {last_loss:.4f} ({verdict})")
        # a resumed run starts near the loss floor of the tiny synthetic
        # set, so the hard decrease contract only binds from scratch
        if args.smoke and start_epoch == 0 and last_loss >= first_loss:
            raise SystemExit("smoke: loss did not decrease")


if __name__ == "__main__":
    main()
