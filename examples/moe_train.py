"""Mixture-of-Experts training over a dp x ep mesh — expert parallelism
via tiled all_to_all (apex_tpu.transformer.moe; no reference analog — the
CUDA reference predates MoE, SURVEY §1 lists 'ep' among the mesh axes).

Tokens shard over BOTH axes (ep doubles as data parallelism for the
tokens, the Megatron ep-within-dp layout); expert weights shard over 'ep'
only, the router replicates.

    python examples/moe_train.py --dp 2 --ep 4 --steps 10
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--ep", type=int, default=4)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=16, help="tokens per rank")
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--experts-per-rank", type=int, default=2)
    p.add_argument("--top-k", type=int, default=2)
    p.add_argument("--lr", type=float, default=1e-2)
    args = p.parse_args()

    n_dev = args.dp * args.ep
    from examples._common import ensure_devices, opt_partition_specs

    ensure_devices(n_dev)

    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    from apex_tpu.optimizers import fused_adam
    from apex_tpu.transformer.moe import (
        MoEConfig,
        init_moe_params,
        moe_mlp,
        moe_param_specs,
    )
    from apex_tpu.transformer.tensor_parallel.mappings import make_varying

    dp, ep = args.dp, args.ep
    mesh = Mesh(np.array(jax.devices()[:n_dev]).reshape(dp, ep),
                ("dp", "ep"))

    cfg = MoEConfig(hidden_size=args.hidden,
                    ffn_hidden_size=2 * args.hidden,
                    num_experts=args.experts_per_rank * ep,
                    top_k=args.top_k, capacity_factor=2.0)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    specs = moe_param_specs(cfg)
    tx = fused_adam(lr=args.lr)

    def pmean(t, ax):
        return jax.lax.pmean(make_varying(t, ax), ax)

    def train_step(params, opt_state, x, target):
        def loss_fn(params):
            vary = params
            for ax in ("dp", "ep"):
                vary = jax.tree_util.tree_map(
                    lambda a, ax=ax: make_varying(a, ax), vary)
            y, aux = moe_mlp(vary, x, cfg, ep_axis="ep")
            mse = jnp.mean((y - target) ** 2)
            for ax in ("dp", "ep"):
                mse = jax.lax.pmean(mse, ax)
                aux = jax.lax.pmean(aux, ax)
            return mse + aux, mse

        (loss, mse), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        # router replicated over both token-shard axes; experts ep-sharded
        grads = {"router": pmean(pmean(grads["router"], "ep"), "dp"),
                 "wi": pmean(grads["wi"], "dp"),
                 "wo": pmean(grads["wo"], "dp")}
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, opt_state, mse

    data_spec = P(("dp", "ep"), None)
    with mesh:
        opt_state = tx.init(params)
        opt_specs = opt_partition_specs(tx, params, specs)

        step = jax.jit(shard_map(
            train_step, mesh=mesh,
            in_specs=(specs, opt_specs, data_spec, data_spec),
            out_specs=(specs, opt_specs, P()),
        ))

        key = jax.random.PRNGKey(1)
        B = args.batch * n_dev
        first = loss = None
        for it in range(args.steps):
            key, sub = jax.random.split(key)
            x = jax.random.normal(sub, (B, cfg.hidden_size))
            target = jnp.sin(3.0 * x)
            t0 = time.perf_counter()
            params, opt_state, loss = step(params, opt_state, x, target)
            loss = float(loss)
            if first is None:
                first = loss
            print(f"step {it:3d}  mse {loss:.4f}  "
                  f"({(time.perf_counter() - t0) * 1e3:.0f} ms)")

    print(f"mesh dp={dp} ep={ep} experts={cfg.num_experts} "
          f"top{cfg.top_k}: mse {first:.4f} -> {loss:.4f} "
          f"({'decreased' if loss < first else 'NOT decreased'})")


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
