"""Fine-tune an imported HuggingFace checkpoint, then sample from it —
the interop loop in one script: ``transformers`` weights →
``models.convert`` → fp32 DDP fine-tuning with FusedAdam + chunked CE →
``models.generate`` KV-cache decoding.

Offline-friendly: with no checkpoint to download, a randomly initialized
tiny HF Llama stands in (``--hf-dir`` loads a local pretrained dir via
``transformers.AutoModelForCausalLM`` instead). Synthetic token data;
the loss-decrease verdict and a generation round-trip are the checks.

    python examples/hf_finetune.py --steps 20
    python examples/hf_finetune.py --hf-dir /path/to/llama --steps 100
"""

from __future__ import annotations

import argparse
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--hf-dir", default="",
                   help="local HF checkpoint dir (empty = tiny random)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=8, help="global batch")
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--vocab-chunks", type=int, default=4)
    p.add_argument("--sample-tokens", type=int, default=8)
    args = p.parse_args()

    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from examples._common import ensure_devices

    ensure_devices(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    import transformers

    from apex_tpu.models import convert, generate, llama
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.parallel import sync_autodiff_gradients

    # ---- import the checkpoint
    if args.hf_dir:
        hf = transformers.AutoModelForCausalLM.from_pretrained(args.hf_dir)
    else:
        import torch

        torch.manual_seed(0)
        hf = transformers.LlamaForCausalLM(transformers.LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=128))
    params, cfg = convert.llama_from_hf(hf, dtype=jnp.float32)
    del hf
    n = sum(x.size for x in jax.tree_util.tree_leaves(params))
    print(f"imported llama: {n/1e6:.2f}M params, vocab {cfg.vocab_size}")

    # ---- DDP fine-tuning step (replicated params, dp-sharded batch)
    mesh = Mesh(np.array(jax.devices()[:args.devices]), ("dp",))
    tx = fused_adam(lr=args.lr)
    opt_state = tx.init(params)

    def train_step(params, opt_state, tokens, targets):
        def loss_fn(p):
            return llama.loss_fn(p, (tokens, targets), cfg, tp_axis=None,
                                 cp_axis=None,
                                 vocab_chunks=args.vocab_chunks)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = sync_autodiff_gradients(grads, axis_name="dp")
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state,
                jax.lax.pmean(loss, "dp"))

    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P(), P())))

    # fixed synthetic batch (overfit -> deterministic decrease)
    tokens = jax.random.randint(jax.random.PRNGKey(1),
                                (args.batch, args.seq), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=-1)

    first = loss = None
    t0 = time.perf_counter()
    for it in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        loss = float(loss)
        if first is None:
            first, t0 = loss, time.perf_counter()
        if it % 5 == 0 or it == args.steps - 1:
            print(f"step {it:3d}  loss {loss:.4f}")
    dt = (time.perf_counter() - t0) / max(args.steps - 1, 1)
    print(f"{dt*1e3:.0f} ms/step")

    # ---- sample from the fine-tuned weights
    prompt = tokens[:1, :4]
    out = generate.greedy_generate(params, prompt, cfg,
                                   args.sample_tokens)
    print(f"prompt {np.asarray(prompt[0]).tolist()} -> "
          f"{np.asarray(out[0, 4:]).tolist()}")

    verdict = "decreased" if loss < first else "NOT decreased"
    print(f"hf-finetune: loss {first:.4f} -> {loss:.4f} ({verdict})")
    if loss >= first:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
