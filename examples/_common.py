"""Shared example plumbing: device-mesh forcing + synthetic data.

Examples default to whatever devices exist; ``ensure_devices(n)`` forces an
``n``-device virtual CPU platform when fewer real chips are available (the
container's sitecustomize imports jax before env vars apply, so this goes
through jax.config — same dance as tests/conftest.py).
"""

from __future__ import annotations

import os

import jax


def ensure_devices(n: int) -> None:
    # Probing jax.devices() first would initialize (and possibly hang on)
    # the default accelerator backend, so the examples force the virtual CPU
    # platform up front. Set APEX_TPU_EXAMPLES_REAL=1 to run on whatever
    # real devices exist instead.
    if os.environ.get("APEX_TPU_EXAMPLES_REAL") == "1":
        assert len(jax.devices()) >= n, (
            f"need {n} devices, have {len(jax.devices())}")
        return
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    for key, val in (("jax_platforms", "cpu"), ("jax_num_cpu_devices", n)):
        try:
            jax.config.update(key, val)
        except (AttributeError, ValueError):
            # this jax predates the option (0.4.37 has no
            # jax_num_cpu_devices); XLA_FLAGS above covers it
            pass
    if len(jax.devices()) < n or jax.devices()[0].platform != "cpu":
        from jax.extend import backend as _backend

        _backend.clear_backends()
    assert len(jax.devices()) >= n, (
        f"need {n} devices, have {len(jax.devices())}")


def synthetic_images(key, batch: int, size: int, classes: int):
    """One synthetic (images, labels) batch — stands in for the imagenet
    loader (ref examples/imagenet/main_amp.py uses real ImageFolder; the
    example trains on fixed random data so it runs anywhere)."""
    import jax.numpy as jnp

    kx, ky = jax.random.split(key)
    x = jax.random.normal(kx, (batch, size, size, 3), jnp.float32)
    y = jax.random.randint(ky, (batch,), 0, classes)
    return x, y


def opt_partition_specs(tx, params, param_specs):
    """Re-export of :func:`apex_tpu.optimizers.opt_partition_specs` (the
    examples imported it from here before it was promoted to the package)."""
    from apex_tpu.optimizers import opt_partition_specs as f

    return f(tx, params, param_specs)


def resume_exhausted(start_it, total_steps) -> bool:
    """True (with a message) when a resumed step index is already past
    the requested step count — the train loop would run zero iterations."""
    if start_it is not None and start_it >= total_steps:
        print(f"nothing to do: resumed step + 1 ({start_it}) >= "
              f"--steps {total_steps}")
        return True
    return False
