"""Minimal data-parallel training — the TPU analog of
ref examples/simple/distributed/distributed_data_parallel.py.

The reference launches one process per GPU (`torch.distributed.launch`),
wraps a 10-step linear model in apex DDP, and checks grads are synced. On
TPU the devices live in one process: the same model runs under ``shard_map``
over a 'data' mesh axis, and DDP is an explicit ``pmean`` of the per-rank
gradients inside the jitted step. The script verifies the synced gradient
equals the gradient of the global batch computed on one device — the
invariant the reference's multi-process test asserts.

Numerics note (jax 0.4.37 at HEAD): the container's shard_map replication
checker rejects ``out_specs=P()`` it cannot statically infer, and with
``check_rep=False`` the transpose no longer auto-psums grads of
replicated params — they arrive per-rank LOCAL. The step therefore does
the DDP reduction explicitly (``lax.pmean`` over 'data'), which is also
what makes it checkable: the step is a registered
``apex_tpu.analysis`` spmd-checks target (``spmd_simple_distributed``),
so dropping the pmean fails tier-1 as a ``rank-divergent-update``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def local_loss(w, x, y):
    return jnp.mean((x @ w - y) ** 2)


def make_train_step(tx):
    """The shard_map body (module-level so the analysis target can
    trace exactly what the script runs): explicit psum-mean DDP over
    'data', fused-adam update, replicated outputs."""

    def train_step(w, opt_state, x, y):
        # w is replicated (in_specs P()); with check_rep=False the
        # shard_map transpose does NOT auto-psum its grads, so each
        # rank holds the grad of its local shard — reduce explicitly.
        # pmean of per-shard mean-grads == the global-batch mean grad
        # (equal shard sizes), apex DDP's gradient_average=True.
        grads = jax.grad(local_loss)(w, x, y)
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, "data"), grads)
        updates, opt_state = tx.update(grads, opt_state, w)
        return w + updates, opt_state, jax.lax.pmean(
            local_loss(w, x, y), "data"), grads

    return train_step


def main():
    from examples._common import ensure_devices

    ensure_devices(8)

    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    from apex_tpu.optimizers import fused_adam

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))

    w = jnp.zeros((16, 1))
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 16))
    y = x @ jnp.full((16, 1), 0.5) + 0.1

    tx = fused_adam(lr=1e-2)
    opt_state = tx.init(w)
    train_step = make_train_step(tx)

    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P(), P()),
        check_rep=False,
    ))

    # invariant: synced grad == single-device grad of the global batch
    _, _, _, synced = step(w, opt_state, x, y)
    full = jax.grad(local_loss)(w, x, y)
    np.testing.assert_allclose(np.asarray(synced), np.asarray(full),
                               rtol=1e-5, atol=1e-6)
    print("DDP grad == global-batch grad: OK")

    for it in range(100):
        w, opt_state, loss, _ = step(w, opt_state, x, y)
    print(f"final loss {float(loss):.6f} (started ~{0.1 ** 2 + 0.25:.2f})")
    assert float(loss) < 0.01
    print("converged: OK")


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
