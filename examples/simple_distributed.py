"""Minimal data-parallel training — the TPU analog of
ref examples/simple/distributed/distributed_data_parallel.py.

The reference launches one process per GPU (`torch.distributed.launch`),
wraps a 10-step linear model in apex DDP, and checks grads are synced. On
TPU the devices live in one process: the same model runs under ``shard_map``
over a 'data' mesh axis, and DDP is a ``pmean`` of the gradients inside the
jitted step. The script verifies the synced gradient equals the gradient of
the global batch computed on one device — the invariant the reference's
multi-process test asserts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from examples._common import ensure_devices

    ensure_devices(8)

    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    from apex_tpu.optimizers import fused_adam
    from apex_tpu.parallel import average_reduced

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))

    w = jnp.zeros((16, 1))
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 16))
    y = x @ jnp.full((16, 1), 0.5) + 0.1

    def local_loss(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    tx = fused_adam(lr=1e-2)
    opt_state = tx.init(w)

    def train_step(w, opt_state, x, y):
        # w is replicated (in_specs P()), so jax's shard_map transpose
        # already psums the local grads over 'data' — the DDP allreduce
        # itself. average_reduced turns the sum into the global-batch mean
        # (apex DDP's gradient_average=True).
        grads = jax.grad(local_loss)(w, x, y)
        grads = average_reduced(grads, axis_name="data")
        updates, opt_state = tx.update(grads, opt_state, w)
        return w + updates, opt_state, jax.lax.pmean(
            local_loss(w, x, y), "data"), grads

    step = jax.jit(shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P(), P()),
    ))

    # invariant: synced grad == single-device grad of the global batch
    _, _, _, synced = step(w, opt_state, x, y)
    full = jax.grad(local_loss)(w, x, y)
    np.testing.assert_allclose(np.asarray(synced), np.asarray(full),
                               rtol=1e-5, atol=1e-6)
    print("DDP grad == global-batch grad: OK")

    for it in range(100):
        w, opt_state, loss, _ = step(w, opt_state, x, y)
    print(f"final loss {float(loss):.6f} (started ~{0.1 ** 2 + 0.25:.2f})")
    assert float(loss) < 0.01
    print("converged: OK")


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
