"""GPT-2 tensor-parallel training — the BASELINE.json "GPT-2 345M
apex.transformer tensor-parallel + fused softmax" config (ref
apex/transformer/tensor_parallel/layers.py + csrc/megatron softmax
kernels; here the causal fused softmax is the Pallas kernel inside the
model and the whole step is one jit over a dp x tp mesh).

    python examples/gpt2_train.py --dp 2 --tp 4 --steps 10
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--tp", type=int, default=4)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=4, help="per-dp-rank batch")
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--checkpoint-dir", default="",
                   help="save train state here every --save-every steps")
    p.add_argument("--save-every", type=int, default=5)
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest step in --checkpoint-dir")
    args = p.parse_args()

    n_dev = args.dp * args.tp
    from examples._common import (
        ensure_devices, opt_partition_specs, resume_exhausted)

    ensure_devices(n_dev)

    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    from apex_tpu.models import gpt2
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.transformer.tensor_parallel.mappings import _to_varying

    dp, tp = args.dp, args.tp
    mesh = Mesh(np.array(jax.devices()[:n_dev]).reshape(dp, tp),
                ("dp", "tp"))

    cfg = gpt2.tiny(num_layers=args.layers, num_heads=2 * tp,
                    hidden_size=32 * tp, vocab_size=128 * tp,
                    max_seq_len=args.seq)
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    specs = gpt2.param_specs(cfg)
    tx = fused_adam(lr=args.lr)

    B, S = args.batch, args.seq

    def pmean(t, ax):
        return jax.lax.pmean(_to_varying(t, ax), ax)

    def train_step(params, opt_state, tokens, targets):
        def loss_fn(params):
            vary = params
            for ax in ("dp", "tp"):
                vary = jax.tree_util.tree_map(
                    lambda a, ax=ax: _to_varying(a, ax), vary)
            return gpt2.loss_fn(vary, (tokens, targets), cfg, tp_axis="tp")

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree_util.tree_map(lambda g: pmean(g, "dp"), grads)
        grads = jax.tree_util.tree_map(
            lambda g, s: g if "tp" in s else pmean(g, "tp"), grads, specs)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        loss = jax.lax.pmean(jax.lax.pmean(loss, "dp"), "tp")
        return params, opt_state, loss

    data_spec = P("dp", None)
    with mesh:
        opt_state = tx.init(params)
        opt_specs = opt_partition_specs(tx, params, specs)

        step = jax.jit(shard_map(
            train_step, mesh=mesh,
            in_specs=(specs, opt_specs, data_spec, data_spec),
            out_specs=(specs, opt_specs, P()),
        ))

        manager = start_it = None
        if args.checkpoint_dir:
            from apex_tpu.checkpoint import CheckpointManager

            manager = CheckpointManager(args.checkpoint_dir, max_to_keep=2)
            if args.resume and manager.latest_step() is not None:
                template = {"params": params, "opt": opt_state,
                            "it": np.zeros((), np.int32)}
                st = manager.restore(template)
                params, opt_state = st["params"], st["opt"]
                start_it = int(st["it"]) + 1
                print(f"=> resumed from step {int(st['it'])}")
                if resume_exhausted(start_it, args.steps):
                    return

        key = jax.random.PRNGKey(1)
        first = loss = None
        for it in range(start_it or 0, args.steps):
            key, sub = jax.random.split(key)
            tokens = jax.random.randint(sub, (B * dp, S), 0, cfg.vocab_size)
            targets = jnp.roll(tokens, -1, axis=-1)
            t0 = time.perf_counter()
            params, opt_state, loss = step(params, opt_state, tokens,
                                           targets)
            loss = float(loss)
            if first is None:
                first = loss
            print(f"step {it:3d}  loss {loss:.4f}  "
                  f"({(time.perf_counter() - t0) * 1e3:.0f} ms)")
            if manager is not None and (it % args.save_every == 0
                                        or it == args.steps - 1):
                manager.save(it, {"params": params, "opt": opt_state,
                                  "it": np.asarray(it, np.int32)})

    print(f"mesh dp={dp} tp={tp}: loss {first:.4f} -> {loss:.4f} "
          f"({'decreased' if loss < first else 'NOT decreased'})")


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
