"""BERT MLM pretraining with FusedLAMB + FusedLayerNorm over a dp mesh —
the BASELINE.json "BERT-base FusedLAMB + FusedLayerNorm" config (ref
apex/optimizers/fused_lamb.py + csrc/multi_tensor_lamb.cu powering the
NVIDIA BERT recipe; the TPU analog fuses the whole LAMB step into one jit).

Data-parallel like the reference recipe: LAMB's layerwise trust ratios and
global grad-norm clip are norms over FULL parameter tensors, so the
optimizer runs on replicated params with dp-mean'd grads (sharding params
across tp would silently localize those norms — the reference's BERT runs
LAMB under DDP for the same reason).

    python examples/bert_train.py --dp 8 --steps 10
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=8)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=4, help="per-dp-rank batch")
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--mask-prob", type=float, default=0.15)
    p.add_argument("--checkpoint-dir", default="",
                   help="save train state here every --save-every steps")
    p.add_argument("--save-every", type=int, default=5)
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest step in --checkpoint-dir")
    args = p.parse_args()

    n_dev = args.dp
    from examples._common import (
        ensure_devices, opt_partition_specs, resume_exhausted)

    ensure_devices(n_dev)

    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    from apex_tpu.models import bert
    from apex_tpu.optimizers import fused_lamb
    from apex_tpu.transformer.tensor_parallel.mappings import _to_varying

    dp = args.dp
    mesh = Mesh(np.array(jax.devices()[:n_dev]).reshape(dp), ("dp",))

    cfg = bert.tiny(num_layers=args.layers, num_heads=4, hidden_size=64,
                    vocab_size=256, max_seq_len=args.seq)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    specs = jax.tree_util.tree_map(lambda _: P(), params)
    tx = fused_lamb(lr=args.lr)  # trust-ratio update (ref fused_lamb.py)

    B, S = args.batch, args.seq
    MASK_ID = 3

    def pmean(t, ax):
        return jax.lax.pmean(_to_varying(t, ax), ax)

    def train_step(params, opt_state, tokens, targets, loss_mask):
        def loss_fn(params):
            vary = jax.tree_util.tree_map(
                lambda a: _to_varying(a, "dp"), params)
            return bert.loss_fn(vary, (tokens, targets, loss_mask), cfg,
                                tp_axis=None)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # dp-mean every grad; LAMB then sees the same full-tensor grads on
        # every rank, so its trust ratios and clip norm are exact
        grads = jax.tree_util.tree_map(lambda g: pmean(g, "dp"), grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        loss = jax.lax.pmean(loss, "dp")
        return params, opt_state, loss

    data_spec = P("dp", None)
    with mesh:
        opt_state = tx.init(params)
        opt_specs = opt_partition_specs(tx, params, specs)

        step = jax.jit(shard_map(
            train_step, mesh=mesh,
            in_specs=(specs, opt_specs, data_spec, data_spec, data_spec),
            out_specs=(specs, opt_specs, P()),
        ))

        manager = start_it = None
        if args.checkpoint_dir:
            from apex_tpu.checkpoint import CheckpointManager

            manager = CheckpointManager(args.checkpoint_dir, max_to_keep=2)
            if args.resume and manager.latest_step() is not None:
                template = {"params": params, "opt": opt_state,
                            "it": np.zeros((), np.int32)}
                st = manager.restore(template)
                params, opt_state = st["params"], st["opt"]
                start_it = int(st["it"]) + 1
                print(f"=> resumed from step {int(st['it'])}")
                if resume_exhausted(start_it, args.steps):
                    return

        key = jax.random.PRNGKey(1)
        first = loss = None
        for it in range(start_it or 0, args.steps):
            key, k1, k2 = jax.random.split(key, 3)
            clean = jax.random.randint(k1, (B * dp, S), 4, cfg.vocab_size)
            mask = jax.random.bernoulli(k2, args.mask_prob, (B * dp, S))
            tokens = jnp.where(mask, MASK_ID, clean)
            t0 = time.perf_counter()
            params, opt_state, loss = step(
                params, opt_state, tokens, clean,
                mask.astype(jnp.float32))
            loss = float(loss)
            if first is None:
                first = loss
            print(f"step {it:3d}  mlm loss {loss:.4f}  "
                  f"({(time.perf_counter() - t0) * 1e3:.0f} ms)")
            if manager is not None and (it % args.save_every == 0
                                        or it == args.steps - 1):
                manager.save(it, {"params": params, "opt": opt_state,
                                  "it": np.asarray(it, np.int32)})

    print(f"mesh dp={dp} FusedLAMB: loss {first:.4f} -> {loss:.4f} "
          f"({'decreased' if loss < first else 'NOT decreased'})")


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
