"""DCGAN with mixed precision — the TPU analog of ref examples/dcgan/
main_amp.py: amp with MULTIPLE models, optimizers, and losses.

The reference calls ``amp.initialize([netD, netG], [optD, optG],
num_losses=3)`` and scales errD_real / errD_fake / errG with separate
loss-scale ids. Functionally on TPU: one scaler state per loss, two
optimizers, bf16 compute via the O2 policy's cast, all inside two jitted
steps (one per network). Synthetic 'real' data (blurred blobs) keeps the
example self-contained.

    python examples/dcgan.py --steps 20
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import optax


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--latent", type=int, default=32)
    p.add_argument("--width", type=int, default=16)
    p.add_argument("--opt-level", default="O2")
    args = p.parse_args()

    from examples._common import ensure_devices

    ensure_devices(1)

    import apex_tpu.amp as amp
    from apex_tpu.models.dcgan import Discriminator, Generator
    from apex_tpu.optimizers import fused_adam

    netG = Generator(latent_dim=args.latent, width=args.width,
                     axis_name=None)
    netD = Discriminator(width=args.width, axis_name=None)

    z0 = jnp.zeros((2, args.latent))
    varG = netG.init(jax.random.PRNGKey(0), z0, train=False)
    varD = netD.init(jax.random.PRNGKey(1),
                     jnp.zeros((2, 32, 32, 3)), train=False)
    pG, sG = varG["params"], varG["batch_stats"]
    pD, sD = varD["params"], varD["batch_stats"]

    # amp.initialize list-of-models path (ref main_amp.py: two nets, two
    # optimizers, three scaled losses)
    (pG, pD), handle = amp.initialize([pG, pD], opt_level=args.opt_level,
                                      verbosity=0)
    policy, scaler = handle.policy, handle.scaler
    sstates = [scaler.init() for _ in range(3)]  # errD_real/errD_fake/errG

    txG, txD = fused_adam(lr=2e-4, betas=(0.5, 0.999)), fused_adam(
        lr=2e-4, betas=(0.5, 0.999))
    optG, optD = txG.init(pG), txD.init(pD)

    bce = lambda logit, target: optax.sigmoid_binary_cross_entropy(  # noqa: E731
        logit, target).mean()

    def fake_batch(pG, sG, z):
        imgs, mut = netG.apply({"params": policy.cast_model(pG),
                                "batch_stats": sG}, z, train=True,
                               mutable=["batch_stats"])
        return imgs, mut["batch_stats"]

    @jax.jit
    def d_step(pD, optD, sD, s_real, s_fake, real, fake):
        def loss_fn(pD):
            logits_r, mut = netD.apply(
                {"params": policy.cast_model(pD), "batch_stats": sD},
                real, train=True, mutable=["batch_stats"])
            errD_real = bce(logits_r, jnp.ones_like(logits_r))
            logits_f, mut = netD.apply(
                {"params": policy.cast_model(pD),
                 "batch_stats": mut["batch_stats"]},
                fake, train=True, mutable=["batch_stats"])
            errD_fake = bce(logits_f, jnp.zeros_like(logits_f))
            # separate loss scales per loss id (ref amp.scale_loss(loss_id=))
            scaled = (scaler.scale_loss(errD_real, s_real)
                      + scaler.scale_loss(errD_fake, s_fake))
            return scaled, (errD_real + errD_fake, mut["batch_stats"])

        grads, (errD, sD) = jax.grad(loss_fn, has_aux=True)(pD)
        # one shared unscale/skip using the max of the two scales is NOT
        # what apex does — each loss id advances its own automaton:
        un_r, ov_r = scaler.unscale(grads, s_real)
        del un_r
        updates, optD, s_fake, ov = amp.scaled_update(
            tx=txD, scaler=scaler, grads=grads, opt_state=optD, params=pD,
            scaler_state=s_fake)
        s_real = scaler.update(s_real, ov_r)
        pD = optax.apply_updates(pD, updates)
        return pD, optD, sD, s_real, s_fake, errD

    @jax.jit
    def g_step(pG, optG, sG, pD, sD, s_g, z):
        def loss_fn(pG):
            fake, newsG = fake_batch(pG, sG, z)
            logits = netD.apply({"params": policy.cast_model(pD),
                                 "batch_stats": sD}, fake, train=False)
            errG = bce(logits, jnp.ones_like(logits))
            return scaler.scale_loss(errG, s_g), (errG, newsG)

        grads, (errG, sG) = jax.grad(loss_fn, has_aux=True)(pG)
        updates, optG, s_g, _ = amp.scaled_update(
            tx=txG, scaler=scaler, grads=grads, opt_state=optG, params=pG,
            scaler_state=s_g)
        pG = optax.apply_updates(pG, updates)
        return pG, optG, sG, s_g, errG

    key = jax.random.PRNGKey(2)
    for it in range(args.steps):
        key, kz, kr = jax.random.split(key, 3)
        z = jax.random.normal(kz, (args.batch, args.latent))
        # synthetic "real" images: smooth random blobs in (-1, 1)
        real = jnp.tanh(jax.image.resize(
            jax.random.normal(kr, (args.batch, 4, 4, 3)),
            (args.batch, 32, 32, 3), "bilinear") * 2.0)
        fake, sG = fake_batch(pG, sG, z)
        pD, optD, sD, sstates[0], sstates[1], errD = d_step(
            pD, optD, sD, sstates[0], sstates[1], real, fake)
        pG, optG, sG, sstates[2], errG = g_step(
            pG, optG, sG, pD, sD, sstates[2], z)
        if it % 5 == 0 or it == args.steps - 1:
            print(f"step {it:3d}  errD {float(errD):.4f}  "
                  f"errG {float(errG):.4f}")

    assert all(bool(jnp.isfinite(jnp.asarray(float(v)))) for v in
               (errD, errG))
    print("dcgan amp training ran to completion: OK")


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    main()
