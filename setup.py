"""Build script (ref setup.py: CUDA extension build; here the native piece
is the C++ host runtime, built as a plain shared library and loaded via
ctypes — no Python ABI dependency).

The library is optional: if no C++ toolchain is available the package
installs anyway and `apex_tpu.runtime.host` uses its numpy fallbacks.

    pip install .            # builds csrc/host_runtime.cpp if g++ exists
    APEX_TPU_SKIP_NATIVE=1 pip install .   # pure-Python install
"""

import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py

THIS_DIR = os.path.dirname(os.path.abspath(__file__))


class BuildWithHostLib(build_py):
    """Compile the ctypes host library and ship it as package data."""

    def run(self):
        super().run()
        if os.environ.get("APEX_TPU_SKIP_NATIVE") == "1":
            return
        src = os.path.join(THIS_DIR, "csrc", "host_runtime.cpp")
        cxx = os.environ.get("CXX", "g++")
        if not (os.path.exists(src) and shutil.which(cxx)):
            print("apex_tpu: no C++ toolchain/source; using numpy fallbacks")
            return
        out_dir = os.path.join(self.build_lib, "apex_tpu", "_lib")
        os.makedirs(out_dir, exist_ok=True)
        out = os.path.join(out_dir, "libapex_tpu_host.so")
        cmd = [cxx, "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
               "-Wall", "-o", out, src]
        try:
            subprocess.run(cmd, check=True, timeout=300)
            print(f"apex_tpu: built host runtime -> {out}")
        except Exception as exc:  # noqa: BLE001 - install must not fail
            print(f"apex_tpu: host runtime build failed ({exc}); "
                  "using numpy fallbacks")


setup(cmdclass={"build_py": BuildWithHostLib})
