#!/usr/bin/env bash
# apex_tpu chaos gate: the seeded fault-injection suite
# (tests/run_resilience + the checkpoint failure paths) on the same
# 8-device virtual CPU mesh as the tier-1 run.
#
#   bash tools/chaos.sh           # tier-1 subset (-m 'not slow'): the
#                                 # deterministic headline cases —
#                                 # preempt/crash-restart bit-identical
#                                 # resume, torn-write fallback, NaN
#                                 # rollback, retry/abort ladder
#   bash tools/chaos.sh --full    # + the slow probabilistic chaos
#                                 # matrix (every fault kind, seeded
#                                 # storms, restart-driven to
#                                 # completion)
#
# Extra args are forwarded to pytest. A standalone chaos run of any
# workload: APEX_TPU_FAULT_PLAN="seed=1,preempt@7,ckpt_torn@4" wired
# through bench.py or examples/llama_train.py (docs/resilience.md).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

marker=(-m "not slow")
if [[ "${1:-}" == "--full" ]]; then
    shift
    marker=()
fi

exec python -m pytest tests/run_resilience tests/run_checkpoint -q \
    -p no:cacheprovider ${marker[@]+"${marker[@]}"} "$@"
