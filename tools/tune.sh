#!/usr/bin/env bash
# One-shot offline kernel autotune: sweep every registered Pallas kernel
# on the CURRENT backend (real corrected-sync races on TPU; the
# docs/kernel_cost_study.md roofline fallback elsewhere — deterministic,
# so this is CI-runnable), write the persistent per-device tuning cache
# (~/.cache/apex_tpu/tuning_cache.json or APEX_TPU_TUNING_CACHE) and
# print the winners. Dispatch consults the cache on the next trace; a
# race verdict flips pallas_config._KERNEL_AUTO with the cache file as
# its provenance evidence artifact (docs/tuning.md).
#
#   bash tools/tune.sh                          # tune all, write cache
#   bash tools/tune.sh --kernel flat_adam       # one kernel
#   bash tools/tune.sh --export TUNING_CACHE.json  # repo-committable copy
#   bash tools/tune.sh --no-write --json        # dry sweep report
#
# tools/relay_hunter.py runs this opportunistically on a live-TPU window
# so the next relay capture lands with tuned tiles as evidence.
set -euo pipefail
cd "$(dirname "$0")/.."

exec python -m apex_tpu.tuning "$@"
