#!/usr/bin/env python
"""Capture a jax.profiler trace of the flagship Llama train step on the
live TPU (SURVEY §5 tracing subsystem, operationalized).

Companion to tools/tpu_validate.py (correctness pre-flight) and bench.py
(numbers): this produces the xplane trace that says WHERE the step time
goes — MXU busy %, HBM stalls, collective time — for the
profile-and-iterate loop the scaling playbook prescribes.

    python tools/tpu_profile.py                 # ~5 traced steps
    python tools/tpu_profile.py --out /tmp/trace --steps 10 --batch 8

View with TensorBoard's profile plugin or xprof on the written logdir.
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/apex_tpu_trace")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--vocab-chunks", type=int, default=0,
                    help="stream the lm-head CE in N slices (0 = off)")
    ap.add_argument("--remat", default="none",
                    choices=["none", "dots", "full"],
                    help="default 'none' matches bench.py's surviving "
                         "ladder rung (no-remat B=4 fits a v5e) so the "
                         "profile explains the bench number; pass 'dots' "
                         "to compare with r5's TPU_TRACE_r05 capture")
    ap.add_argument("--force", action="store_true",
                    help="profile even on a non-TPU backend")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (implies --force); without "
                         "this, a dead TPU relay makes the first device "
                         "query hang — probe with tools/relay_hunter.py "
                         "semantics first")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        args.force = True
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"platform={dev.platform} kind={dev.device_kind}", flush=True)
    if dev.platform != "tpu" and not args.force:
        print("not a TPU backend — pass --force to trace anyway")
        return 2

    from apex_tpu.models import llama
    from apex_tpu.optimizers import fused_adam

    cfg = llama.flagship_0p9b()
    remat = {"none": False, "dots": "dots", "full": True}[args.remat]
    chunks = args.vocab_chunks or None

    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1),
                                (args.batch, cfg.max_seq_len),
                                0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=-1)
    tx = fused_adam(lr=1e-4)
    opt_state = tx.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(llama.loss_fn)(
            params, batch, cfg, tp_axis=None, cp_axis=None, remat=remat,
            vocab_chunks=chunks)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, opt_state, loss

    batch = (tokens, targets)
    # compile + warm outside the trace; host-fetch sync (timing.sync)
    # because block_until_ready is a no-op over the tunnel and the
    # printed ms/step below would otherwise be dispatch time (the r5
    # MFU=330 bug class)
    from apex_tpu.runtime import timing

    params, opt_state, loss = train_step(params, opt_state, batch)
    fetch = timing.fetch_cost(loss)  # ~79 ms/fetch through the tunnel
    print(f"warm step loss={float(loss):.4f}; tracing {args.steps} steps "
          f"to {args.out}", flush=True)

    t0 = time.perf_counter()
    with jax.profiler.trace(args.out):
        for i in range(args.steps):
            with jax.profiler.StepTraceAnnotation("train", step_num=i):
                params, opt_state, loss = train_step(params, opt_state,
                                                     batch)
        timing.sync(loss)
    dt = max(time.perf_counter() - t0 - fetch, 1e-9) / args.steps
    print(f"traced: {dt * 1e3:.1f} ms/step  -> {args.out}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
