#!/usr/bin/env python
"""Per-op attribution report from a jax.profiler capture.

Thin CLI over the library (ISSUE 7): the xplane parsing lives in
``apex_tpu.pyprof.parse``/``prof`` and the coarse per-phase rollup
(compute / comms / data-movement / attention / gather-scatter) in
:mod:`apex_tpu.observability.profiling.xplane` — this tool only formats
and writes. Turn an xplane capture (from ``tools/tpu_profile.py``,
``jax.profiler.trace`` or ``apex_tpu.pyprof.start/stop``) into per-op,
per-category and per-phase time/flops attribution, plus MFU when the
capture carries device-plane op metrics (i.e. on TPU).

    python tools/trace_report.py /tmp/apex_tpu_trace
    python tools/trace_report.py TPU_TRACE_r05 --peak-tflops 197 \
        --json report.json --top 40

Peak defaults to a v5e chip (197 bf16 TFLOP/s, 819 GB/s HBM).
``bytes_accessed`` / HBM utilization are reported only when the capture
actually measured them — a host-only capture says nothing about HBM
traffic, and the old 0.0 placeholder misled TRACE_REPORT_r05.json.
For a Perfetto-loadable view of the same capture:
``python -m apex_tpu.observability trace <logdir>``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logdir", help="trace logdir, run dir, or .xplane.pb")
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--peak-tflops", type=float, default=197.0,
                    help="chip peak for MFU (default: v5e bf16)")
    ap.add_argument("--peak-hbm-gbps", type=float, default=819.0)
    ap.add_argument("--json", default="",
                    help="also write the full report as JSON")
    args = ap.parse_args()

    from apex_tpu.observability.profiling.xplane import attribute_report
    from apex_tpu.pyprof.prof import Report

    report = Report.from_capture(args.logdir)
    if not report.ops:
        print("no HLO op events in capture", file=sys.stderr)
        return 1
    print(report.format_table(top=args.top))

    attribution = attribute_report(report)
    print(f"\n{'phase':<16} {'self ms':>10} {'share':>7}")
    for ph, rec in attribution.phases.items():
        print(f"{ph:<16} {rec['self_us'] / 1e3:>10.3f} "
              f"{rec['share'] * 100:>6.1f}%")
    eff = attribution.overlap_efficiency()
    if eff is not None:
        print(f"compute<->comms overlap efficiency: {eff:.2f}")

    has_flops = any(o.flops for o in report.ops)
    if has_flops:
        util = report.utilization(args.peak_tflops, args.peak_hbm_gbps)
        line = (f"\nbusy {util['busy_s'] * 1e3:.2f} ms   "
                f"{util['total_flops'] / 1e9:.2f} GFLOP   "
                f"MFU {util['mfu'] * 100:.1f}%")
        # hbm_util is only present when the capture MEASURED bytes — a
        # fabricated 0.0 here is exactly the r05 report bug
        if "hbm_util" in util:
            line += f"   HBM util {util['hbm_util'] * 100:.1f}%"
        print(line)
    else:
        print("\n(no per-op flops in this capture — host-only planes; "
              "MFU needs a device-plane trace, i.e. a TPU run)")

    if args.json:
        payload = report.to_dict()
        payload["attribution"] = attribution.to_dict()
        if has_flops:
            payload["utilization"] = report.utilization(
                args.peak_tflops, args.peak_hbm_gbps)
        with open(args.json, "w") as f:
            json.dump(payload, f, indent=1)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
