#!/usr/bin/env bash
# apex_tpu static-analysis gate: both apex_tpu.analysis engines over the
# canonical target set, failing on any finding not grandfathered in
# tests/run_analysis/baseline.json.
#
#   bash tools/lint.sh                 # the tier-1 gate (run by
#                                      # tests/run_analysis/test_repo_selfcheck.py)
#   bash tools/lint.sh --write-baseline tests/run_analysis/baseline.json
#
# Extra args are forwarded to `python -m apex_tpu.analysis` (which
# ignores --baseline when --write-baseline is given).
set -euo pipefail
cd "$(dirname "$0")/.."

# CPU backend + an 8-device virtual mesh, same environment the test
# suite runs under (tests/conftest.py), so the tp_collectives jaxpr
# target sees a multi-device mesh without hardware.
export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

exec python -m apex_tpu.analysis \
    --baseline tests/run_analysis/baseline.json \
    apex_tpu examples tools bench.py "$@"
