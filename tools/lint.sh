#!/usr/bin/env bash
# apex_tpu static-analysis gate: every apex_tpu.analysis engine over the
# canonical target set, failing on any finding not grandfathered in
# tests/run_analysis/baseline.json.
#
#   bash tools/lint.sh                 # the tier-1 gate (run by
#                                      # tests/run_analysis/test_repo_selfcheck.py)
#   bash tools/lint.sh --changed-only  # AST + concurrency engines over
#                                      # files changed vs the merge base
#                                      # only (LINT_BASE, default main);
#                                      # jaxpr/dataflow targets still
#                                      # run in full
#   bash tools/lint.sh --write-baseline tests/run_analysis/baseline.json
#
# Extra args are forwarded to `python -m apex_tpu.analysis` (which
# ignores --baseline when --write-baseline is given). That includes the
# ISSUE 18 ergonomics flags: `--engines ast,state` narrows the run to an
# explicit engine subset (composes with --changed-only, since the
# forwarded args reach both exec paths) and `--list-targets` prints the
# registered jaxpr/dataflow/sharding/spmd/state/memory targets with
# their owning engine. The checkpoint/state-flow engine (ISSUE 18) runs
# its four resume-path targets here like any other tracing engine and
# gets its own line in the per-engine wall-time breakdown; the
# memory-liveness engine (ISSUE 19, `--engines memory`) does the same
# with its four donated-carry targets, which the gate holds at 0
# findings.
#
# Wall-time budget (ISSUE 14 satellite): the CLI fails (exit 2, LOUD)
# when the summed engine wall time exceeds LINT_TIME_BUDGET_S (default
# 180s; <= 0 disables) — the growing engine stack must not silently rot
# tier-1 runtime. The per-engine breakdown is printed on every run.
#
# Goodput gate (ISSUE 17 satellite): after the analysis engines, the
# full run also exercises `tools/metrics_report.py --compare` against
# the pinned BENCH_BASELINE.jsonl (self-compare by default; set
# BENCH_COMPARE_CURRENT to a fresh bench dump to gate a real run).
set -euo pipefail
cd "$(dirname "$0")/.."

# CPU backend + an 8-device virtual mesh, same environment the test
# suite runs under (tests/conftest.py), so the tp_collectives jaxpr
# target sees a multi-device mesh without hardware.
export JAX_PLATFORMS=cpu
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

if [[ "${1:-}" == "--changed-only" ]]; then
    shift
    # Narrow the path-driven engines (AST + host-concurrency — both
    # consume the same explicit path list) to python files changed
    # since the merge base (working tree + index + committed-vs-base;
    # deleted files drop out via the existence filter). The jaxpr +
    # dataflow/sharding targets are NOT narrowed: they trace whole
    # entry points, so an edit anywhere in a traced module can move
    # their verdicts.
    #
    # LINT_DIFF_REPORT: path to a stored `--json` dump from the merge
    # base (generate once per base rev: `python -m apex_tpu.analysis
    # --json > base.json`). When set, the gate fails only on findings
    # NEW relative to that run — pre-existing base findings and their
    # churn never block a branch, which is what keeps --changed-only
    # usable as the fast CI gate.
    diff_args=()
    if [[ -n "${LINT_DIFF_REPORT:-}" ]]; then
        if [[ ! -f "${LINT_DIFF_REPORT}" ]]; then
            echo "LINT_DIFF_REPORT=${LINT_DIFF_REPORT} does not exist" >&2
            exit 2
        fi
        diff_args+=(--diff "${LINT_DIFF_REPORT}")
    fi
    base="$(git merge-base HEAD "${LINT_BASE:-main}" 2>/dev/null || true)"
    changed="$(
        { git diff --name-only "${base:-HEAD}" -- 2>/dev/null;
          git diff --name-only --cached 2>/dev/null;
          git diff --name-only 2>/dev/null; } \
        | sort -u \
        | grep -E '^(apex_tpu|examples|tools)/.*\.py$|^bench\.py$' || true)"
    ast_paths=()
    while IFS= read -r f; do
        [[ -n "$f" && -e "$f" ]] && ast_paths+=("$f")
    done <<< "$changed"
    if [[ ${#ast_paths[@]} -eq 0 ]]; then
        # nothing changed under the linted paths: skip both path-driven
        # engines entirely (an empty explicit path list would be
        # rejected as a typo by the CLI's loud-failure rule)
        exec python -m apex_tpu.analysis \
            --baseline tests/run_analysis/baseline.json \
            --no-ast --no-concurrency \
            ${diff_args[@]+"${diff_args[@]}"} "$@"
    fi
    exec python -m apex_tpu.analysis \
        --baseline tests/run_analysis/baseline.json \
        ${diff_args[@]+"${diff_args[@]}"} "${ast_paths[@]}" "$@"
fi

rc=0
python -m apex_tpu.analysis \
    --baseline tests/run_analysis/baseline.json \
    apex_tpu examples tools bench.py "$@" || rc=$?

# Goodput regression gate (ISSUE 17 satellite): compare a bench metrics
# dump against the pinned BENCH_BASELINE.jsonl. By default the baseline
# is compared against itself — a deterministic arming check that proves
# the gate parses the pinned dump and the goodput/* family is present
# (a broken baseline or renamed gauge fails loudly here, not silently
# in CI). Point BENCH_COMPARE_CURRENT at a fresh `python bench.py`
# dump to gate a real run's goodput ratio against the baseline.
if [[ -f BENCH_BASELINE.jsonl ]]; then
    current="${BENCH_COMPARE_CURRENT:-BENCH_BASELINE.jsonl}"
    if [[ ! -f "$current" ]]; then
        echo "BENCH_COMPARE_CURRENT=$current does not exist" >&2
        exit 2
    fi
    python tools/metrics_report.py "$current" \
        --compare BENCH_BASELINE.jsonl || rc=$?
else
    echo "WARNING: BENCH_BASELINE.jsonl missing - goodput gate skipped" >&2
fi

exit "$rc"
