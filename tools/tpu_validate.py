"""On-hardware validation of the compiled (Mosaic) Pallas kernel paths.

CI only ever exercises the kernels through the Pallas interpreter on the
CPU mesh (tests/conftest.py forces JAX_PLATFORMS=cpu), so compiled-mode
lowering — VMEM fit, sub-tile scalar blocks, uint32 dropout-mask ops —
is unproven until something runs on a real chip. This script is that
something: each check runs the compiled kernel (pallas_config 'auto' on
TPU) and compares against the jnp fallback ('off') at bench-like shapes.

Run on a live TPU (the axon tunnel must be up):

    python tools/tpu_validate.py            # all checks
    python tools/tpu_validate.py --quick    # small shapes only

Prints one PASS/FAIL line per check and exits nonzero on any failure.
Keep it fast (~a minute of compiles): it is the pre-flight for bench.py.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

# host-fetch sync: block_until_ready is a no-op over the axon tunnel, so
# the per-check wall times printed by check() would otherwise measure
# dispatch only (and a compiled-kernel failure could surface later, at
# the comparison fetch, attributed to the wrong check)
from apex_tpu.runtime.timing import sync as device_sync

RESULTS = []


def check(name):
    def deco(fn):
        def run(*a, **kw):
            t0 = time.perf_counter()
            try:
                fn(*a, **kw)
                RESULTS.append((name, True, ""))
                print(f"PASS {name} ({time.perf_counter() - t0:.1f}s)",
                      flush=True)
            except Exception as e:  # noqa: BLE001
                RESULTS.append((name, False, repr(e)[:300]))
                print(f"FAIL {name}: {repr(e)[:300]}", flush=True)
        return run
    return deco


def _close(a, b, rtol=2e-2, atol=2e-2, name=""):
    # bf16 compiled vs fp32-ish jnp fallback: loose but real tolerance
    np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32),
        rtol=rtol, atol=atol, err_msg=name)


def _close_flash_bwd(a, b, tol=5e-2, max_abs=0.5, frac=5e-4, name=""):
    """Flash bwd vs autodiff-of-fallback, delta-cancellation aware.

    The kernel uses the standard flash convention delta = sum(do * o) with
    o saved in bf16; autodiff of the materialized-softmax fallback cancels
    p*(dp - sum(p*dp)) EXACTLY for near-degenerate rows (causal row 0 sees
    one key -> softmax == [1]). The kernel's residual there is bounded by
    |do|*|o|*bf16_eps*sqrt(D) (~0.2 at bench shapes, x1/(1-p) under
    dropout) — measured on a v5e 2026-07-31: violations cluster at s==0
    across all (b, h), fwd outputs bit-identical. Same property as the
    CUDA flash kernels (half-precision saved o). So: elementwise tol for
    ~all elements, a bounded violating fraction, and a hard abs cap.
    """
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    d = np.abs(a - b)
    lim = tol + tol * np.abs(b)
    n_viol = int((d > lim).sum())
    if n_viol > frac * d.size or float(d.max()) > max_abs:
        raise AssertionError(
            f"{name}: {n_viol}/{d.size} elements beyond tol "
            f"(allowed {int(frac * d.size)}), max abs {float(d.max()):.4f} "
            f"(cap {max_abs})")


@check("flash_fwd_causal")
def flash_fwd(B, S, H, D):
    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
               for kk in ks)
    with pallas_config.force("on"):
        got = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True))(q, k, v)
        device_sync(got)
    with pallas_config.force("off"):
        want = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True))(q, k, v)
    _close(got, want, name="flash fwd")


@check("flash_bwd_causal_gqa")
def flash_bwd(B, S, H, D):
    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, H // 2, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, H // 2, D), jnp.bfloat16)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    with pallas_config.force("on"):
        got = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        device_sync(got)
    with pallas_config.force("off"):
        want = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for n, a, b in zip("qkv", got, want):
        _close_flash_bwd(a, b, name=f"flash d{n}")


@check("flash_varlen")
def flash_varlen(B, S, H, D):
    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
               for kk in ks)
    lens = jnp.asarray([S] + [max(1, S // (i + 2)) for i in range(B - 1)],
                       jnp.int32)
    with pallas_config.force("on"):
        got = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, kv_lens=lens))(q, k, v)
        device_sync(got)
    with pallas_config.force("off"):
        want = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, kv_lens=lens))(q, k, v)
    _close(got, want, name="flash varlen")


@check("flash_dropout_fwd_bwd")
def flash_dropout(B, S, H, D):
    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
               for kk in ks)
    key = jax.random.PRNGKey(7)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, dropout_p=0.25,
                            dropout_key=key)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    # same counter-based mask on both paths -> grads must agree
    with pallas_config.force("on"):
        got = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        device_sync(got)
    with pallas_config.force("off"):
        want = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for n, a, b in zip("qkv", got, want):
        _close_flash_bwd(a, b, name=f"dropout d{n}")


@check("layer_norm_fwd_bwd")
def layer_norm(rows, hidden):
    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.layer_norm import layer_norm as ln

    x = jax.random.normal(jax.random.PRNGKey(4), (rows, hidden),
                          jnp.bfloat16)
    w = jnp.ones((hidden,), jnp.float32)
    b = jnp.zeros((hidden,), jnp.float32)

    def loss(x, w, b):
        return jnp.sum(ln(x, w, b, (hidden,)).astype(jnp.float32) ** 2)

    with pallas_config.force("on"):
        got = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(x, w, b)
        device_sync(got)
    with pallas_config.force("off"):
        want = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(x, w, b)
    # dx: elementwise. dw/db: sums over `rows` of bf16-quantized grads —
    # the two paths round y to different bf16 ulps, and sqrt(rows)-scaled
    # quantization noise survives the reduction (kernel vs closed form on
    # IDENTICAL tensors agrees to 1e-4; measured v5e 2026-07-31).
    _close(got[0], want[0], rtol=5e-2, atol=5e-1, name="ln dx")
    noise = 4.0 * np.sqrt(rows) * 0.0078
    for n, a, b2 in zip(["dw", "db"], got[1:], want[1:]):
        _close(a, b2, rtol=5e-2, atol=float(noise), name=f"ln {n}")


@check("rms_norm_fwd")
def rms_norm(rows, hidden):
    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.layer_norm import rms_norm as rms

    x = jax.random.normal(jax.random.PRNGKey(5), (rows, hidden),
                          jnp.bfloat16)
    w = jnp.ones((hidden,), jnp.float32)
    with pallas_config.force("on"):
        got = jax.jit(lambda x: rms(x, w, (hidden,)))(x)
        device_sync(got)
    with pallas_config.force("off"):
        want = jax.jit(lambda x: rms(x, w, (hidden,)))(x)
    _close(got, want, name="rms")


@check("causal_softmax")
def causal_softmax(bh, S):
    from apex_tpu.ops import pallas_config
    from apex_tpu.transformer.functional.fused_softmax import (
        scaled_upper_triang_masked_softmax as causal_sm,
    )

    x = jax.random.normal(jax.random.PRNGKey(6), (bh, S, S), jnp.bfloat16)
    with pallas_config.force("on"):
        got = jax.jit(lambda x: causal_sm(x, None, 1.0))(x)
        device_sync(got)
    with pallas_config.force("off"):
        want = jax.jit(lambda x: causal_sm(x, None, 1.0))(x)
    _close(got, want, name="causal softmax")


@check("masked_softmax")
def masked_softmax(bh, S):
    from apex_tpu.ops import pallas_config
    from apex_tpu.transformer.functional.fused_softmax import (
        scaled_masked_softmax,
    )

    x = jax.random.normal(jax.random.PRNGKey(9), (4, bh // 4, S, S),
                          jnp.bfloat16)
    mask = (jax.random.uniform(jax.random.PRNGKey(10), (4, 1, S, S))
            > 0.8)
    with pallas_config.force("on"):
        got = jax.jit(lambda x: scaled_masked_softmax(x, mask, 0.5))(x)
        device_sync(got)
    with pallas_config.force("off"):
        want = jax.jit(lambda x: scaled_masked_softmax(x, mask, 0.5))(x)
    _close(got, want, name="masked softmax")


@check("flat_adam_kernel")
def flat_adam(n_params):
    """The Pallas flat-buffer Adam (non-default since r4 — the XLA chain
    won the cost study) must still execute correctly when forced on:
    scalar (1,4) block + slab padding are Mosaic-sensitive."""
    from apex_tpu.ops import pallas_config
    from apex_tpu.optimizers import fused_adam

    params = {"a": jax.random.normal(jax.random.PRNGKey(11), (n_params,)),
              "b": jax.random.normal(jax.random.PRNGKey(12), (137,))}
    grads = jax.tree_util.tree_map(lambda p: p * 1e-2, params)

    def one_step(use_kernel):
        tx = fused_adam(lr=1e-3, weight_decay=0.01, flat=True,
                        use_kernel=use_kernel)
        state = tx.init(params)
        updates, _ = jax.jit(tx.update)(grads, state, params)
        device_sync(updates)
        return updates

    with pallas_config.force("on"):
        got = one_step(True)
    want = one_step(False)
    for k in params:
        _close(got[k], want[k], rtol=1e-5, atol=1e-6, name=f"adam {k}")


@check("odd_rows_layer_norm")
def odd_rows(hidden):
    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.layer_norm import layer_norm as ln

    x = jax.random.normal(jax.random.PRNGKey(8), (13, hidden), jnp.bfloat16)
    w = jnp.ones((hidden,), jnp.float32)
    b = jnp.zeros((hidden,), jnp.float32)
    with pallas_config.force("on"):
        got = jax.jit(lambda x: ln(x, w, b, (hidden,)))(x)
        device_sync(got)
    with pallas_config.force("off"):
        want = jax.jit(lambda x: ln(x, w, b, (hidden,)))(x)
    _close(got, want, name="odd rows")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true")
    p.add_argument("--force", action="store_true",
                   help="run even on a non-TPU backend (compiled Pallas "
                        "off-TPU is unsupported/slow; for CI debugging)")
    args = p.parse_args()

    dev = jax.devices()[0]
    print(f"platform={dev.platform} kind={dev.device_kind}", flush=True)
    if dev.platform != "tpu" and not args.force:
        print("not a TPU backend — compiled Mosaic kernels cannot be "
              "validated here (tests cover interpret mode); pass --force "
              "to try anyway", flush=True)
        return 2

    if args.quick:
        B, S, H, D = 2, 512, 4, 128
        rows, hidden = 1024, 1024
        bh, sm_s = 8, 512
    else:
        B, S, H, D = 4, 2048, 16, 128
        rows, hidden = 8192, 4096
        bh, sm_s = 64, 1024

    flash_fwd(B, S, H, D)
    flash_bwd(B, S, H, D)
    flash_varlen(B, S, H, D)
    flash_dropout(B, S, H, D)
    layer_norm(rows, hidden)
    rms_norm(rows, hidden)
    causal_softmax(bh, sm_s)
    masked_softmax(bh, sm_s // 2)
    flat_adam(4096 if args.quick else 1_000_000)
    odd_rows(hidden)

    fails = [r for r in RESULTS if not r[1]]
    print(f"{len(RESULTS) - len(fails)}/{len(RESULTS)} checks passed",
          flush=True)
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
