#!/usr/bin/env python
"""Summarize apex_tpu metrics JSONL dumps.

Thin wrapper over ``python -m apex_tpu.observability report`` so the
tools/ directory carries the complete telemetry workflow next to
tpu_profile.py / trace_report.py:

    python tools/metrics_report.py BENCH_METRICS.jsonl
    python tools/metrics_report.py run1.jsonl run2.jsonl --json
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.observability.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.argv.insert(1, "report")
    sys.exit(main(sys.argv[1:]))
