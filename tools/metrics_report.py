#!/usr/bin/env python
"""Summarize apex_tpu metrics JSONL dumps and analysis JSON reports.

Thin wrapper over ``python -m apex_tpu.observability report`` so the
tools/ directory carries the complete telemetry workflow next to
tpu_profile.py / trace_report.py:

    python tools/metrics_report.py BENCH_METRICS.jsonl
    python tools/metrics_report.py run1.jsonl run2.jsonl --json
    python tools/metrics_report.py NEW.jsonl --compare BASE.jsonl

``--compare BASE.jsonl`` is the observability analog of the analyzer's
``--diff`` gate (ISSUE 7): diff this dump against a stored base and
exit non-zero when any ``*/step_time_ms`` p50 regresses past
``--compare-threshold`` (default 10%) or any kernel's
``tuning/race_won_*`` verdict flips toward the XLA fallback — runnable
in CI against a committed ``BENCH_METRICS.jsonl``.

It also ingests ``python -m apex_tpu.analysis --json`` dumps (detected
by their ``schema_version`` + ``kind`` header), printing a per-check
finding summary — so one command reads every machine report the repo
emits:

    python -m apex_tpu.analysis --json > lint.json
    python tools/metrics_report.py lint.json BENCH_METRICS.jsonl

Metrics JSONL dumps carrying the ``analysis/sharding_*`` family (bench
runs since ISSUE 4) additionally get a per-target table of estimated
comms bytes/step and peak live HBM; the ``analysis/plan_*`` family
(ISSUE 8) renders the auto-shard planner's ranked candidate table and
its predicted-vs-measured calibration ratio, and ``--compare`` gates a
chosen-plan flip between runs as a regression. The ``numerics/*``
family (ISSUE 9) gets a per-source health table, and ``--compare``
additionally gates two numerics regressions: a finite→non-finite flip
of any ``numerics/finite`` gauge (binary — a run that started
producing NaNs is broken no matter how fast it got) and a >10x jump
of a ``numerics/grad_norm`` p50 (fixed factor, independent of
``--compare-threshold``). The ``fleet/*`` family (ISSUE 12 — either a
live rank's dump or the merged view ``python -m
apex_tpu.observability fleet --emit-metrics`` writes) gets the
cross-rank table: per-metric step-time skew with per-rank p50s,
straggler/desync counts, and the grad-sync barrier-wait timers;
``--compare`` additionally gates a ``fleet/step_time_skew`` gauge
growing by more than ``--compare-threshold`` skew points — one rank
falling behind the fleet is a regression regardless of absolute step
time. The ``memory/*`` family (ISSUE 15) gets the live-HBM table
(per-source live/watermark bytes, snapshot cost + derived cadence,
the per-target measured-vs-modeled HBM calibration ratios, the
largest compiled executables), and ``--compare`` additionally gates
two memory regressions: a ``memory/watermark_bytes`` gauge growing
past ``--compare-threshold`` (the same workload keeps more bytes
alive — the next OOM on a smaller chip), and a
``memory/hbm_calibration_ratio{target=}`` gauge drifting past
``--compare-threshold`` in either direction (the sharding cost model
and XLA's allocator started disagreeing — every planner pruning
decision inherits the error). The
``analysis/concurrency_findings{check=}`` family (ISSUE 16 — the
host-concurrency engine's per-check race/signal/callback verdict)
gets a per-check table, and ``--compare`` gates any check counter
growing above its base value or a new check id going nonzero —
binary, no threshold: one new confirmed race in the host runtime is
a regression regardless of speed. The
``analysis/state_findings{check=}`` family (ISSUE 18 — the
checkpoint/state-flow engine's resume-compatibility verdict,
zero-filled so every check id is explicit every run) gets the same
treatment: a per-check table plus per-target carried/saved leaf
gauges, and a binary ``--compare`` gate — one new unsaved-state /
schema-drift / illegal-reshard / donation finding is a regression
regardless of speed. The ``analysis/memory_findings{check=}`` family
(ISSUE 19 — the memory-liveness engine's verdict, zero-filled the
same way) gets the identical treatment: a per-check table plus
per-target modeled-peak gauges, and a binary ``--compare`` gate —
one new missed-donation / peak-spike / held-upcast finding is a
regression regardless of speed. The ``goodput/*`` family (ISSUE 17
— published by the run-ledger accounting, ``python -m
apex_tpu.observability goodput``) gets the goodput table (ratio +
fleet min, lost seconds by cause, badput top-3, per-rank ratios),
and ``--compare`` gates a ``goodput/ratio`` or ``goodput/
fleet_ratio`` gauge dropping by more than ``--compare-threshold``
ratio points — the same workload spending more of its wall-clock on
badput causes is a regression regardless of absolute speed. Unknown
``schema_version`` values in analysis reports fail loudly rather
than mis-summarizing.
"""

from __future__ import annotations

import collections
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.observability.cli import main  # noqa: E402
from apex_tpu.observability.registry import read_jsonl  # noqa: E402

# analysis --json schema versions this reader understands
KNOWN_ANALYSIS_SCHEMAS = (1,)


def _read_records(path):
    """Metrics JSONL via the registry's tolerant reader (its
    parse-error records pass through harmlessly — every consumer here
    keys on name/type); None when the file itself is unreadable."""
    try:
        return read_jsonl(path)
    except OSError:
        return None


def load_analysis_report(path):
    """Parse ``path`` as an apex_tpu.analysis --json dump; returns the
    payload dict or None when the file is something else (e.g. a
    metrics JSONL). Unknown schema versions fail loudly rather than
    mis-summarizing."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or "schema_version" not in data:
        return None
    if data.get("kind") != "apex_tpu.analysis":
        return None
    version = data["schema_version"]
    if version not in KNOWN_ANALYSIS_SCHEMAS:
        raise SystemExit(
            f"{path}: analysis schema_version {version} is newer than "
            f"this reader (knows {list(KNOWN_ANALYSIS_SCHEMAS)}) — "
            f"update tools/metrics_report.py")
    return data


def _fmt_bytes(n):
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    if n >= 1 << 30:
        return f"{n / (1 << 30):.2f} GiB"
    if n >= 1 << 20:
        return f"{n / (1 << 20):.2f} MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f} KiB"
    return f"{int(n)} B"


def render_sharding_family(path):
    """Per-target table of the ``analysis/sharding_*`` gauge/counter
    family from a metrics JSONL dump (None when the file carries none).
    Lines that are not JSON are skipped (truncated dumps), matching the
    tolerant observability reader."""
    targets = {}  # name -> {"comms_bytes": .., "peak_hbm_bytes": ..}
    checks = {}
    total = None
    records = _read_records(path)
    if records is None:
        return None
    for rec in records:
        name = rec.get("name", "")
        if not isinstance(name, str) or \
                not name.startswith("analysis/sharding_"):
            continue
        labels = rec.get("labels", {}) or {}
        if name == "analysis/sharding_findings_total":
            total = rec.get("value")
        elif name == "analysis/sharding_findings":
            checks[labels.get("check", "?")] = rec.get("value")
        elif name in ("analysis/sharding_comms_bytes",
                      "analysis/sharding_peak_hbm_bytes"):
            key = name.rsplit("_bytes", 1)[0].split("sharding_")[-1]
            targets.setdefault(labels.get("target", "?"), {})[
                key + "_bytes"] = rec.get("value")
    if not targets and total is None and not checks:
        return None
    return {"targets": targets, "checks": checks,
            "findings_total": total}


def summarize_sharding(path, fam):
    print(f"{path}: analysis/sharding_* family")
    if fam["findings_total"] is not None:
        print(f"  findings: {fam['findings_total']}")
    for check, n in sorted(fam["checks"].items()):
        print(f"    {check:24s} {n}")
    if fam["targets"]:
        width = max(len(t) for t in fam["targets"])
        print(f"  {'target':{width}s}  {'comms/step':>12s}  "
              f"{'peak HBM':>12s}")
        for t, vals in sorted(fam["targets"].items()):
            print(f"  {t:{width}s}  "
                  f"{_fmt_bytes(vals.get('comms_bytes', 0)):>12s}  "
                  f"{_fmt_bytes(vals.get('peak_hbm_bytes', 0)):>12s}")


def render_concurrency_family(path):
    """Per-check table of the ``analysis/concurrency_findings{check=}``
    counter family (ISSUE 16 — the host-concurrency engine's verdict a
    bench run ships with) from a metrics JSONL dump; None when the file
    carries none. Later records win, matching the registry's cumulative
    counter dumps."""
    checks = {}
    total = None
    records = _read_records(path)
    if records is None:
        return None
    for rec in records:
        name = rec.get("name", "")
        if not isinstance(name, str):
            continue
        labels = rec.get("labels", {}) or {}
        if name == "analysis/concurrency_findings_total":
            total = rec.get("value")
        elif name == "analysis/concurrency_findings":
            checks[labels.get("check", "?")] = rec.get("value")
    if total is None and not checks:
        return None
    return {"checks": checks, "findings_total": total}


def summarize_concurrency(path, fam):
    print(f"{path}: analysis/concurrency_* family")
    if fam["findings_total"] is not None:
        print(f"  findings: {int(fam['findings_total'])}")
    for check, n in sorted(fam["checks"].items()):
        print(f"    {check:24s} {n}")


def _concurrency_check_counts(records):
    """{check id: count} from ``analysis/concurrency_findings``
    counters; later records win (cumulative counter dumps)."""
    counts = {}
    for rec in records:
        if rec.get("name") != "analysis/concurrency_findings":
            continue
        labels = rec.get("labels", {}) or {}
        try:
            counts[labels.get("check", "?")] = float(rec.get("value"))
        except (TypeError, ValueError):
            continue
    return counts


def render_state_family(path):
    """Per-check table of the ``analysis/state_findings{check=}``
    counter family (ISSUE 18 — the checkpoint/state-flow engine's
    resume-compatibility verdict a bench run ships with) from a metrics
    JSONL dump; None when the file carries none. The family is
    zero-filled by the engine (every check id present every run), so a
    missing family means the engine never ran, not that it was clean.
    Later records win, matching the registry's cumulative counter
    dumps."""
    checks = {}
    total = None
    targets: dict = {}
    records = _read_records(path)
    if records is None:
        return None
    for rec in records:
        name = rec.get("name", "")
        if not isinstance(name, str):
            continue
        labels = rec.get("labels", {}) or {}
        if name == "analysis/state_findings_total":
            total = rec.get("value")
        elif name == "analysis/state_findings":
            checks[labels.get("check", "?")] = rec.get("value")
        elif name == "analysis/state_carried_leaves":
            targets.setdefault(labels.get("target", "?"), {})[
                "carried"] = rec.get("value")
        elif name == "analysis/state_saved_leaves":
            targets.setdefault(labels.get("target", "?"), {})[
                "saved"] = rec.get("value")
    if total is None and not checks:
        return None
    return {"checks": checks, "findings_total": total,
            "targets": targets}


def summarize_state(path, fam):
    print(f"{path}: analysis/state_* family")
    if fam["findings_total"] is not None:
        print(f"  findings: {int(fam['findings_total'])}")
    for check, n in sorted(fam["checks"].items()):
        print(f"    {check:26s} {n}")
    for tgt, row in sorted(fam.get("targets", {}).items()):
        carried = row.get("carried")
        saved = row.get("saved")
        print(f"    {tgt:32s} carried {carried}  saved {saved}")


def _state_check_counts(records):
    """{check id: count} from ``analysis/state_findings`` counters;
    later records win (cumulative counter dumps)."""
    counts = {}
    for rec in records:
        if rec.get("name") != "analysis/state_findings":
            continue
        labels = rec.get("labels", {}) or {}
        try:
            counts[labels.get("check", "?")] = float(rec.get("value"))
        except (TypeError, ValueError):
            continue
    return counts


def render_memory_findings_family(path):
    """Per-check table of the ``analysis/memory_findings{check=}``
    counter family (ISSUE 19 — the memory-liveness engine's verdict a
    bench run ships with) from a metrics JSONL dump; None when the file
    carries none. Distinct from :func:`render_memory_family`, which
    reads the live ``memory/*`` HBM gauges — this family is the static
    engine's zero-filled finding counters plus the per-target modeled
    peaks the calibration priors correct. Later records win, matching
    the registry's cumulative counter dumps."""
    checks = {}
    total = None
    targets: dict = {}
    records = _read_records(path)
    if records is None:
        return None
    for rec in records:
        name = rec.get("name", "")
        if not isinstance(name, str):
            continue
        labels = rec.get("labels", {}) or {}
        if name == "analysis/memory_findings_total":
            total = rec.get("value")
        elif name == "analysis/memory_findings":
            checks[labels.get("check", "?")] = rec.get("value")
        elif name == "analysis/memory_peak_hbm_bytes":
            targets.setdefault(labels.get("target", "?"), {})[
                "peak"] = rec.get("value")
    if total is None and not checks:
        return None
    return {"checks": checks, "findings_total": total,
            "targets": targets}


def summarize_memory_findings(path, fam):
    print(f"{path}: analysis/memory_* family")
    if fam["findings_total"] is not None:
        print(f"  findings: {int(fam['findings_total'])}")
    for check, n in sorted(fam["checks"].items()):
        print(f"    {check:26s} {n}")
    for tgt, row in sorted(fam.get("targets", {}).items()):
        peak = row.get("peak")
        if peak is not None:
            print(f"    {tgt:32s} modeled peak {int(peak)} B")


def _memory_finding_counts(records):
    """{check id: count} from ``analysis/memory_findings`` counters;
    later records win (cumulative counter dumps)."""
    counts = {}
    for rec in records:
        if rec.get("name") != "analysis/memory_findings":
            continue
        labels = rec.get("labels", {}) or {}
        try:
            counts[labels.get("check", "?")] = float(rec.get("value"))
        except (TypeError, ValueError):
            continue
    return counts


def render_tuning_family(path):
    """The ``tuning/*`` family from a metrics JSONL dump (None when the
    file carries none): per-kernel cache hit/miss and race-winner
    counters plus the best-candidate vs XLA-fallback gauges the
    autotuner emitted (apex_tpu.tuning / bench.py ISSUE 6)."""
    kernels: dict = {}
    records = _read_records(path)
    if records is None:
        return None
    for rec in records:
        name = rec.get("name", "")
        if not isinstance(name, str) or not name.startswith("tuning/"):
            continue
        labels = rec.get("labels", {}) or {}
        kernel = labels.get("kernel", "?")
        row = kernels.setdefault(kernel, {})
        key = name[len("tuning/"):]
        if key in ("cache_hit", "cache_miss", "race_won_pallas",
                   "race_won_xla", "candidate_error"):
            row[key] = row.get(key, 0) + (rec.get("value") or 0)
        elif key in ("best_pallas_ms", "xla_ms"):
            row[key] = rec.get("value")
            if "bucket" in labels:
                row["bucket"] = labels["bucket"]
    return {"kernels": kernels} if kernels else None


def summarize_tuning(path, fam):
    print(f"{path}: tuning/* family")
    width = max(len(k) for k in fam["kernels"])
    print(f"  {'kernel':{width}s}  {'hit':>5s}  {'miss':>5s}  "
          f"{'race':>9s}  {'pallas ms':>10s}  {'xla ms':>10s}")
    for kernel, row in sorted(fam["kernels"].items()):
        np_, nx = row.get("race_won_pallas", 0), row.get("race_won_xla", 0)
        # mixed outcomes (several buckets / accumulated runs) must not
        # read as a clean pallas win — dispatch ANDs its verdicts
        race = ("-" if not (np_ or nx)
                else "pallas" if not nx
                else "xla" if not np_
                else f"p:{np_}/x:{nx}")
        def ms(key):
            v = row.get(key)
            return f"{v:.3f}" if isinstance(v, (int, float)) else "-"
        line = (f"  {kernel:{width}s}  {row.get('cache_hit', 0):>5d}  "
                f"{row.get('cache_miss', 0):>5d}  {race:>9s}  "
                f"{ms('best_pallas_ms'):>10s}  {ms('xla_ms'):>10s}")
        if row.get("candidate_error"):
            line += f"  ({row['candidate_error']} candidate error(s))"
        print(line)


def render_plan_family(path):
    """The ``analysis/plan_*`` gauge family from a metrics JSONL dump
    (None when the file carries none): the auto-shard planner's ranked
    candidate table (modeled step time, comms bytes, peak HBM, chosen
    flag) plus the predicted-vs-measured calibration ratio bench.py
    emits after a planned step runs (ISSUE 8)."""
    models: dict = {}
    ratios: dict = {}
    records = _read_records(path)
    if records is None:
        return None
    for rec in records:
        name = rec.get("name", "")
        if not isinstance(name, str) or \
                not name.startswith("analysis/plan_"):
            continue
        labels = rec.get("labels", {}) or {}
        model = labels.get("model", "?")
        if name == "analysis/plan_time_ratio":
            ratios[model] = rec.get("value")
            continue
        cand = labels.get("candidate")
        if cand is None:
            continue
        key = name[len("analysis/plan_"):]
        models.setdefault(model, {}).setdefault(cand, {})[key] = \
            rec.get("value")
    if not models and not ratios:
        return None
    return {"models": models, "ratios": ratios}


def summarize_plan(path, fam):
    print(f"{path}: analysis/plan_* family")
    for model, cands in sorted(fam["models"].items()):
        width = max(len(c) for c in cands)
        print(f"  {model}: {'candidate':{width}s}  {'modeled':>11s}  "
              f"{'comms/step':>12s}  {'peak HBM':>12s}  chosen")
        ranked = sorted(
            cands.items(),
            key=lambda kv: (kv[1].get("modeled_step_ms") or 0, kv[0]))
        for cand, row in ranked:
            ms = row.get("modeled_step_ms")
            ms_s = f"{ms:.3f} ms" if isinstance(ms, (int, float)) else "-"
            comms = row.get("comms_bytes")
            comms_s = _fmt_bytes(comms) \
                if isinstance(comms, (int, float)) else "-"
            hbm = row.get("peak_hbm_bytes")
            hbm_s = _fmt_bytes(hbm) \
                if isinstance(hbm, (int, float)) else "-"
            mark = "*" if row.get("chosen") else ""
            print(f"  {'':{len(model)}s}  {cand:{width}s}  {ms_s:>11s}  "
                  f"{comms_s:>12s}  {hbm_s:>12s}  {mark}")
    for model, ratio in sorted(fam["ratios"].items()):
        print(f"  {model}: modeled/measured step-time ratio {ratio}")


def _plan_choices(records):
    """{model: chosen candidate} from analysis/plan_chosen gauges."""
    chosen = {}
    for rec in records:
        if rec.get("name") != "analysis/plan_chosen":
            continue
        if not rec.get("value"):
            continue
        labels = rec.get("labels", {}) or {}
        chosen[labels.get("model", "?")] = labels.get("candidate", "?")
    return chosen


def render_resilience_family(path):
    """The ``resilience/*`` counter family from a metrics JSONL dump
    (None when the file carries none): retries, give-ups, preemptions,
    rollbacks, resumes, injected faults — the chaos-run scoreboard
    emitted by apex_tpu.resilience / bench.py's APEX_TPU_FAULT_PLAN."""
    counters = {}
    events = 0
    records = _read_records(path)
    if records is None:
        return None
    for rec in records:
        name = rec.get("name", "")
        if not isinstance(name, str) or \
                not name.startswith("resilience/"):
            if rec.get("type") == "event" and isinstance(name, str) and \
                    name in ("preemption", "rollback", "resumed",
                             "train_aborted", "chaos_probe",
                             "checkpoint_failed", "resilience_give_up"):
                events += 1
            continue
        if rec.get("type") != "counter":
            continue
        labels = rec.get("labels", {}) or {}
        key = name[len("resilience/"):]
        if labels:
            key += "{" + ",".join(f"{k}={v}" for k, v in
                                  sorted(labels.items())) + "}"
        counters[key] = rec.get("value")
    if not counters and not events:
        return None
    return {"counters": counters, "events": events}


def summarize_resilience(path, fam):
    print(f"{path}: resilience/* family")
    width = max(len(k) for k in fam["counters"]) if fam["counters"] else 0
    for key, value in sorted(fam["counters"].items()):
        print(f"  {key:{width}s}  {value}")
    if fam["events"]:
        print(f"  ({fam['events']} resilience event(s) — see the "
              f"generic summary below)")


def render_numerics_family(path):
    """The ``numerics/*`` family from a metrics JSONL dump (None when
    the file carries none): per-source finite flag, amax ceiling,
    stats-pass cost/cadence, detector counters (ISSUE 9)."""
    sources: dict = {}
    events = 0
    records = _read_records(path)
    if records is None:
        return None
    for rec in records:
        name = rec.get("name", "")
        if not isinstance(name, str):
            continue
        if rec.get("type") == "event" and name.startswith("numerics"):
            events += 1
            continue
        if not name.startswith("numerics/"):
            continue
        labels = rec.get("labels", {}) or {}
        source = labels.get("source", "?")
        row = sources.setdefault(source, {})
        key = name[len("numerics/"):]
        if rec.get("type") == "counter":
            row[key] = row.get(key, 0) + (rec.get("value") or 0)
        elif rec.get("type") == "gauge":
            row[key] = rec.get("value")
        elif rec.get("type") in ("histogram", "timer") and \
                isinstance(rec.get("p50"), (int, float)):
            row[key + "_p50"] = rec["p50"]
    if not sources and not events:
        return None
    return {"sources": sources, "events": events}


def summarize_numerics(path, fam):
    print(f"{path}: numerics/* family")
    width = max((len(s) for s in fam["sources"]), default=6)
    print(f"  {'source':{width}s}  {'finite':>6s}  {'amax max':>12s}  "
          f"{'stats ms':>9s}  {'interval':>8s}  detectors")
    for source, row in sorted(fam["sources"].items()):
        finite = row.get("finite")
        finite_s = ("-" if finite is None
                    else "yes" if finite else "NO")
        amax = row.get("amax_max")
        amax_s = f"{amax:.4g}" if isinstance(amax, (int, float)) else "-"
        if isinstance(row.get("stats_pass_ms"), (int, float)):
            ms_s = f"{row['stats_pass_ms']:.3f}"
        elif isinstance(row.get("stats_pass_p50"), (int, float)):
            ms_s = f"{row['stats_pass_p50'] * 1e3:.3f}"  # timer: s
        else:
            ms_s = "-"
        interval = row.get("stats_interval")
        int_s = str(int(interval)) if isinstance(interval,
                                                 (int, float)) else "-"
        fired = {k: v for k, v in row.items()
                 if k.endswith(("_spikes", "_plateaus", "_streaks",
                                "nonfinite_signals")) and v}
        fired_s = ", ".join(f"{k}:{v}" for k, v in sorted(
            fired.items())) or "-"
        print(f"  {source:{width}s}  {finite_s:>6s}  {amax_s:>12s}  "
              f"{ms_s:>9s}  {int_s:>8s}  {fired_s}")
    if fam["events"]:
        print(f"  ({fam['events']} numerics event(s) — see the "
              f"generic summary below)")


def render_memory_family(path):
    """The ``memory/*`` family from a metrics JSONL dump (None when the
    file carries none): per-source live bytes / watermark / snapshot
    cost + cadence, the per-target HBM calibration ratios, and the
    per-fn compiled totals (ISSUE 15)."""
    sources: dict = {}
    calibration: dict = {}
    compiled: dict = {}
    events = 0
    records = _read_records(path)
    if records is None:
        return None
    for rec in records:
        name = rec.get("name", "")
        if not isinstance(name, str):
            continue
        if rec.get("type") == "event" and (
                name.startswith("memory") or name.startswith("memrec")):
            events += 1
            continue
        if not name.startswith("memory/"):
            continue
        labels = rec.get("labels", {}) or {}
        key = name[len("memory/"):]
        if key.startswith("hbm_") and "target" in labels:
            row = calibration.setdefault(labels["target"], {})
            row[key] = rec.get("value")
            continue
        if key.startswith("compiled_") and "fn" in labels:
            row = compiled.setdefault(labels["fn"], {})
            if rec.get("type") == "counter":
                row[key] = row.get(key, 0) + (rec.get("value") or 0)
            else:
                row[key] = rec.get("value")
            continue
        source = labels.get("source", "?")
        row = sources.setdefault(source, {})
        if rec.get("type") == "counter":
            row[key] = row.get(key, 0) + (rec.get("value") or 0)
        elif rec.get("type") == "gauge":
            row[key] = rec.get("value")
        elif rec.get("type") in ("histogram", "timer") and \
                isinstance(rec.get("p50"), (int, float)):
            row[key + "_p50"] = rec["p50"]
    if not sources and not calibration and not compiled and not events:
        return None
    return {"sources": sources, "calibration": calibration,
            "compiled": compiled, "events": events}


def summarize_memory(path, fam):
    print(f"{path}: memory/* family")
    if fam["sources"]:
        width = max(len(s) for s in fam["sources"])
        print(f"  {'source':{width}s}  {'live':>10s}  {'watermark':>10s}"
              f"  {'snap ms':>8s}  {'interval':>8s}")
        for source, row in sorted(fam["sources"].items()):
            def b(key):
                v = row.get(key)
                return _fmt_bytes(int(v)) if isinstance(
                    v, (int, float)) else "-"
            if isinstance(row.get("snapshot_ms"), (int, float)):
                ms_s = f"{row['snapshot_ms']:.3f}"
            elif isinstance(row.get("snapshot_pass_p50"), (int, float)):
                ms_s = f"{row['snapshot_pass_p50'] * 1e3:.3f}"
            else:
                ms_s = "-"
            interval = row.get("snapshot_interval")
            int_s = str(int(interval)) if isinstance(
                interval, (int, float)) else "-"
            print(f"  {source:{width}s}  {b('live_bytes'):>10s}  "
                  f"{b('watermark_bytes'):>10s}  {ms_s:>8s}  "
                  f"{int_s:>8s}")
    if fam["calibration"]:
        print("  HBM calibration (measured XLA / modeled estimator):")
        for target, row in sorted(fam["calibration"].items()):
            ratio = row.get("hbm_calibration_ratio")
            ratio_s = f"{ratio:.3f}x" if isinstance(
                ratio, (int, float)) else "-"
            modeled = row.get("hbm_modeled_bytes")
            measured = row.get("hbm_measured_bytes")
            mm = ""
            if isinstance(modeled, (int, float)) and isinstance(
                    measured, (int, float)):
                mm = (f"  (modeled {_fmt_bytes(int(modeled))} vs "
                      f"measured {_fmt_bytes(int(measured))})")
            print(f"    {target:36s} {ratio_s:>8s}{mm}")
    if fam["compiled"]:
        biggest = sorted(fam["compiled"].items(),
                         key=lambda kv: -(kv[1].get(
                             "compiled_total_bytes") or 0))[:5]
        print("  largest compiled executables:")
        for fn, row in biggest:
            total = row.get("compiled_total_bytes")
            total_s = _fmt_bytes(int(total)) if isinstance(
                total, (int, float)) else "-"
            print(f"    {fn:36s} {total_s:>10s}")
    if fam["events"]:
        print(f"  ({fam['events']} memory event(s) — see the generic "
              f"summary below)")


def _memory_watermark_gauges(records):
    """{labels-qualified name: value} for memory/watermark_bytes
    gauges."""
    out = {}
    for rec in records:
        if rec.get("type") != "gauge" or \
                rec.get("name") != "memory/watermark_bytes" or \
                not isinstance(rec.get("value"), (int, float)):
            continue
        labels = rec.get("labels", {}) or {}
        key = "memory/watermark_bytes" + (
            "{" + ",".join(f"{k}={v}" for k, v in
                           sorted(labels.items())) + "}"
            if labels else "")
        out[key] = float(rec["value"])
    return out


def _calibration_ratio_gauges(records):
    """{labels-qualified name: value} for the per-target
    memory/hbm_calibration_ratio gauges."""
    out = {}
    for rec in records:
        if rec.get("type") != "gauge" or \
                rec.get("name") != "memory/hbm_calibration_ratio" or \
                not isinstance(rec.get("value"), (int, float)):
            continue
        labels = rec.get("labels", {}) or {}
        key = "memory/hbm_calibration_ratio" + (
            "{" + ",".join(f"{k}={v}" for k, v in
                           sorted(labels.items())) + "}"
            if labels else "")
        out[key] = float(rec["value"])
    return out


def _numerics_finite_gauges(records):
    """{labels-qualified name: value} for numerics/finite gauges."""
    out = {}
    for rec in records:
        if rec.get("type") != "gauge" or \
                rec.get("name") != "numerics/finite":
            continue
        labels = rec.get("labels", {}) or {}
        key = "numerics/finite" + (
            "{" + ",".join(f"{k}={v}" for k, v in
                           sorted(labels.items())) + "}"
            if labels else "")
        out[key] = rec.get("value")
    return out


def _grad_norm_p50s(records):
    """{labels-qualified name: p50} for numerics/grad_norm
    histograms."""
    out = {}
    for rec in records:
        if rec.get("type") not in ("histogram", "timer") or \
                rec.get("name") != "numerics/grad_norm" or \
                not isinstance(rec.get("p50"), (int, float)):
            continue
        labels = rec.get("labels", {}) or {}
        key = "numerics/grad_norm" + (
            "{" + ",".join(f"{k}={v}" for k, v in
                           sorted(labels.items())) + "}"
            if labels else "")
        out[key] = float(rec["p50"])
    return out


# a >10x grad-norm p50 jump is gated as a regression regardless of
# --compare-threshold: that knob tunes step-TIME tolerance; an
# order-of-magnitude gradient blow-up is a numerics event, not noise.
GRAD_NORM_JUMP_FACTOR = 10.0


def _ddp_gauges(records):
    """{labels-qualified name: value} for the ``ddp/*`` gauge family
    (ISSUE 11: comms_bytes per sync mode, overlap_efficiency,
    allreduce bandwidth)."""
    out = {}
    for rec in records:
        name = rec.get("name", "")
        if rec.get("type") != "gauge" or not isinstance(name, str) \
                or not name.startswith("ddp/") \
                or not isinstance(rec.get("value"), (int, float)):
            continue
        labels = rec.get("labels", {}) or {}
        key = name + (
            "{" + ",".join(f"{k}={v}" for k, v in
                           sorted(labels.items())) + "}"
            if labels else "")
        out[key] = float(rec["value"])
    return out


def render_ddp_family(path):
    """rows for the ddp/* table, or None when the dump has none."""
    records = _read_records(path)
    if not records:
        return None
    fam = _ddp_gauges(records)
    if not fam:
        return None
    return [{"metric": k, "value": v} for k, v in sorted(fam.items())]


def summarize_ddp(path, fam):
    print(f"{path}: DDP comms (ddp/* gauges)")
    for row in fam:
        v = row["value"]
        if "comms_bytes" in row["metric"]:
            print(f"  {row['metric']:44s} {_fmt_bytes(int(v)):>10s}")
        else:
            print(f"  {row['metric']:44s} {v:>10.3f}")


def render_fp8_family(path):
    """The ``amp/fp8_*`` gauge family from a metrics JSONL dump (None
    when the file carries none): the fp8-vs-bf16 matmul race numbers
    bench.py records (ISSUE 13) plus any fp8_race events."""
    gauges: dict = {}
    events = 0
    records = _read_records(path)
    if records is None:
        return None
    for rec in records:
        name = rec.get("name", "")
        if not isinstance(name, str):
            continue
        if rec.get("type") == "event" and name == "fp8_race":
            events += 1
            continue
        if rec.get("type") == "gauge" and name.startswith("amp/fp8_"):
            gauges[name[len("amp/"):]] = rec.get("value")
    if not gauges and not events:
        return None
    return {"gauges": gauges, "events": events}


def summarize_fp8(path, fam):
    print(f"{path}: amp/fp8_* family (fp8-vs-bf16 race)")
    for key in ("fp8_matmul_ms", "fp8_bf16_matmul_ms", "fp8_speedup",
                "fp8_quantize_ms", "fp8_max_rel_err"):
        if key in fam["gauges"]:
            v = fam["gauges"][key]
            v_s = f"{v:.4g}" if isinstance(v, (int, float)) else str(v)
            print(f"  {key:22s} {v_s}")
    for key, v in sorted(fam["gauges"].items()):
        if key not in ("fp8_matmul_ms", "fp8_bf16_matmul_ms",
                       "fp8_speedup", "fp8_quantize_ms",
                       "fp8_max_rel_err"):
            print(f"  {key:22s} {v}")


def _fp8_speedup_gauges(records):
    """{labels-qualified name: value} for amp/fp8_speedup gauges."""
    out = {}
    for rec in records:
        if rec.get("type") != "gauge" or \
                rec.get("name") != "amp/fp8_speedup" or \
                not isinstance(rec.get("value"), (int, float)):
            continue
        labels = rec.get("labels", {}) or {}
        key = "amp/fp8_speedup" + (
            "{" + ",".join(f"{k}={v}" for k, v in
                           sorted(labels.items())) + "}"
            if labels else "")
        out[key] = float(rec["value"])
    return out


def render_fleet_family(path):
    """The ``fleet/*`` family from a metrics JSONL dump (None when the
    file carries none): cross-rank step-time skew per metric with the
    per-rank p50 row, straggler/desync counters, and the grad-sync
    wait timers the barrier probe records (ISSUE 12). Feed it either a
    live rank's dump or the merged view ``python -m
    apex_tpu.observability fleet --emit-metrics`` writes."""
    skew: dict = {}
    p50s: dict = {}
    stragglers: dict = {}
    waits: dict = {}
    desyncs = None
    ranks = None
    events = 0
    records = _read_records(path)
    if records is None:
        return None
    for rec in records:
        name = rec.get("name", "")
        if not isinstance(name, str):
            continue
        if rec.get("type") == "event" and name.startswith("fleet/"):
            events += 1
            continue
        if not name.startswith("fleet/"):
            continue
        labels = rec.get("labels", {}) or {}
        if name == "fleet/ranks":
            ranks = rec.get("value")
        elif name == "fleet/step_time_skew":
            skew[labels.get("metric", "?")] = rec.get("value")
        elif name == "fleet/step_time_p50_ms":
            p50s.setdefault(labels.get("metric", "?"), {})[
                labels.get("rank", "?")] = rec.get("value")
        elif name == "fleet/stragglers":
            stragglers[labels.get("rank", "?")] = \
                stragglers.get(labels.get("rank", "?"), 0) + \
                (rec.get("value") or 0)
        elif name == "fleet/desync_events" or name == "fleet/desyncs":
            desyncs = (desyncs or 0) + (rec.get("value") or 0)
        elif name == "fleet/grad_sync_wait_s" and \
                rec.get("type") in ("histogram", "timer"):
            # string key (not a tuple): the family dict round-trips
            # through --json
            key = f"{labels.get('site', '?')}|{labels.get('rank', '?')}"
            waits[key] = {"count": rec.get("count"),
                          "p50": rec.get("p50"),
                          "max": rec.get("max")}
    if not (skew or stragglers or waits or events
            or desyncs is not None or ranks is not None):
        return None
    return {"ranks": ranks, "skew": skew, "p50s": p50s,
            "stragglers": stragglers, "waits": waits,
            "desyncs": desyncs, "events": events}


def summarize_fleet(path, fam):
    print(f"{path}: fleet/* family"
          + (f" ({fam['ranks']} rank(s))"
             if fam["ranks"] is not None else ""))
    for metric, skew in sorted(fam["skew"].items()):
        skew_s = f"{skew:+.1%}" if isinstance(skew,
                                              (int, float)) else "-"
        row = fam["p50s"].get(metric, {})
        per_rank = "  ".join(
            f"r{rank}:{v:.3f}" for rank, v in sorted(row.items())
            if isinstance(v, (int, float)))
        print(f"  {metric}: skew {skew_s}"
              + (f"  p50(ms) {per_rank}" if per_rank else ""))
    if fam["stragglers"]:
        counts = "  ".join(f"rank {r}: {n}" for r, n in
                           sorted(fam["stragglers"].items()))
        print(f"  stragglers: {counts}")
    if fam["desyncs"]:
        print(f"  desync events: {fam['desyncs']}")
    for key, row in sorted(fam["waits"].items()):
        site, _, rank = key.rpartition("|")
        p50 = row.get("p50")
        p50_s = f"{p50 * 1e3:.3f} ms" if isinstance(
            p50, (int, float)) else "-"
        print(f"  wait {site} rank {rank}: n={row.get('count')} "
              f"p50 {p50_s}")
    if fam["events"]:
        print(f"  ({fam['events']} fleet event(s) — see the generic "
              f"summary below)")


def render_goodput_family(path):
    """The ``goodput/*`` family from a metrics JSONL dump (None when
    the file carries none): the goodput ratio + fleet min the run-
    ledger accounting published, lost seconds by cause, the badput
    top-3 and per-rank ratios (ISSUE 17)."""
    records = _read_records(path)
    if records is None:
        return None
    ratio = fleet = wall = productive = replayed = None
    lost: dict = {}
    badput: dict = {}
    rank_ratio: dict = {}
    for rec in records:
        if rec.get("type") != "gauge" or \
                not isinstance(rec.get("name"), str) or \
                not rec["name"].startswith("goodput/"):
            continue
        name = rec["name"]
        labels = rec.get("labels", {}) or {}
        value = rec.get("value")
        if name == "goodput/ratio":
            ratio = value
        elif name == "goodput/fleet_ratio":
            fleet = value
        elif name == "goodput/wall_s":
            wall = value
        elif name == "goodput/productive_s":
            productive = value
        elif name == "goodput/steps_replayed":
            replayed = value
        elif name == "goodput/lost_s":
            lost[labels.get("cause", "?")] = value
        elif name == "goodput/badput_rank":
            badput[labels.get("cause", "?")] = value
        elif name == "goodput/rank_ratio":
            rank_ratio[labels.get("rank", "?")] = value
    if ratio is None and not lost:
        return None
    return {"ratio": ratio, "fleet_ratio": fleet, "wall_s": wall,
            "productive_s": productive, "steps_replayed": replayed,
            "lost_s": lost, "badput_rank": badput,
            "rank_ratio": rank_ratio}


def summarize_goodput(path, fam):
    print(f"{path}: goodput/* family")
    ratio = fam["ratio"]
    ratio_s = f"{ratio:.4f}" if isinstance(ratio, (int, float)) else "-"
    fleet = fam["fleet_ratio"]
    fleet_s = f"{fleet:.4f}" if isinstance(fleet, (int, float)) else "-"
    print(f"  goodput ratio {ratio_s}  (fleet min {fleet_s})")
    if isinstance(fam["wall_s"], (int, float)):
        prod = fam["productive_s"] or 0.0
        print(f"  wall {fam['wall_s']:.3f} s, productive {prod:.3f} s")
    if fam["steps_replayed"]:
        print(f"  replayed steps: {fam['steps_replayed']:.0f}")
    for cause, seconds in sorted(fam["lost_s"].items(),
                                 key=lambda cs: -(cs[1] or 0)):
        if not seconds:
            continue
        marker = "  <- badput top-3" if cause in fam["badput_rank"] \
            else ""
        print(f"    lost {cause:<16} {seconds:.3f} s{marker}")
    if not any(fam["lost_s"].values()):
        print("    no lost seconds attributed")
    for rank, rr in sorted(fam["rank_ratio"].items()):
        rr_s = f"{rr:.4f}" if isinstance(rr, (int, float)) else "-"
        print(f"  rank {rank}: ratio {rr_s}")


def render_serving_family(path):
    """The ``serving/*`` family from a metrics JSONL dump (None when
    the file carries none): request/token counters, the closed-loop
    summary gauges (latency + ttft percentiles, tokens/s, mean
    occupancy), and the live occupancy / page-utilization gauges the
    engine publishes every step (ISSUE 20)."""
    records = _read_records(path)
    if records is None:
        return None
    counters: dict = {}
    gauges: dict = {}
    hists: dict = {}
    for rec in records:
        name = rec.get("name")
        if not isinstance(name, str) or not name.startswith("serving/"):
            continue
        key = name[len("serving/"):]
        if rec.get("type") == "counter" and \
                isinstance(rec.get("value"), (int, float)):
            counters[key] = counters.get(key, 0) + rec["value"]
        elif rec.get("type") == "gauge" and \
                isinstance(rec.get("value"), (int, float)):
            gauges[key] = rec["value"]
        elif rec.get("type") in ("histogram", "timer") and \
                isinstance(rec.get("p50"), (int, float)):
            hists[key] = {q: rec.get(q) for q in
                          ("count", "p50", "p90", "p99", "max")}
    if not counters and not gauges and not hists:
        return None
    return {"counters": counters, "gauges": gauges,
            "histograms": hists}


def summarize_serving(path, fam):
    print(f"{path}: serving/* family")
    c, g = fam["counters"], fam["gauges"]

    def cv(key):
        v = c.get(key)
        return f"{v:.0f}" if isinstance(v, (int, float)) else "-"

    print(f"  requests submitted {cv('requests_submitted')}  "
          f"admitted {cv('requests_admitted')}  "
          f"completed {cv('requests_completed')}  "
          f"preempted {cv('requests_preempted')}")
    if "tokens_generated" in c:
        print(f"  tokens generated {cv('tokens_generated')}")
    for key, label in (("tokens_per_s", "tokens/s"),
                       ("mean_occupancy", "mean occupancy"),
                       ("batch_occupancy", "batch occupancy"),
                       ("page_utilization", "page utilization")):
        if isinstance(g.get(key), (int, float)):
            print(f"  {label:<18} {g[key]:.4g}")
    for pair in (("latency_p50_ms", "latency_p99_ms"),
                 ("ttft_p50_ms", "ttft_p99_ms")):
        if any(isinstance(g.get(k), (int, float)) for k in pair):
            name = pair[0].split("_p50")[0]
            p50 = g.get(pair[0])
            p99 = g.get(pair[1])
            p50s = f"{p50:.3f}" if isinstance(p50, (int, float)) else "-"
            p99s = f"{p99:.3f}" if isinstance(p99, (int, float)) else "-"
            print(f"  {name:<8} p50 {p50s} ms  p99 {p99s} ms")
    for key, h in sorted(fam["histograms"].items()):
        cnt = h.get("count")
        cnt_s = f"{cnt:.0f}" if isinstance(cnt, (int, float)) else "-"
        p50 = h.get("p50")
        p99 = h.get("p99")
        p50s = f"{p50:.3f}" if isinstance(p50, (int, float)) else "-"
        p99s = f"{p99:.3f}" if isinstance(p99, (int, float)) else "-"
        print(f"    hist {key:<22} n={cnt_s} p50 {p50s} p99 {p99s}")


def _serving_gauges(records):
    """{name: value} for the unlabeled serving summary gauges the
    closed-loop bench publishes — the two the --compare gate watches
    (p99 latency, tokens/s) plus the rest for info lines."""
    out = {}
    watched = ("serving/latency_p99_ms", "serving/tokens_per_s",
               "serving/latency_p50_ms", "serving/ttft_p99_ms",
               "serving/mean_occupancy")
    for rec in records:
        if rec.get("type") == "gauge" and rec.get("name") in watched \
                and not (rec.get("labels") or {}) \
                and isinstance(rec.get("value"), (int, float)):
            out[rec["name"]] = float(rec["value"])
    return out


def _goodput_ratio_gauges(records):
    """{name: value} for the unlabeled goodput ratio gauges the
    accounting publishes (ratio + fleet min)."""
    out = {}
    for rec in records:
        if rec.get("type") == "gauge" and rec.get("name") in (
                "goodput/ratio", "goodput/fleet_ratio") \
                and not (rec.get("labels") or {}) \
                and isinstance(rec.get("value"), (int, float)):
            out[rec["name"]] = float(rec["value"])
    return out


def _fleet_skew_gauges(records):
    """{labels-qualified name: value} for fleet/step_time_skew
    gauges."""
    out = {}
    for rec in records:
        if rec.get("type") != "gauge" or \
                rec.get("name") != "fleet/step_time_skew" or \
                not isinstance(rec.get("value"), (int, float)):
            continue
        labels = rec.get("labels", {}) or {}
        key = "fleet/step_time_skew" + (
            "{" + ",".join(f"{k}={v}" for k, v in
                           sorted(labels.items())) + "}"
            if labels else "")
        out[key] = float(rec["value"])
    return out


def _step_time_p50s(records):
    """{metric name: p50} for every */step_time_ms histogram/timer
    record that carries a sampled p50."""
    out = {}
    for rec in records:
        name = rec.get("name", "")
        if isinstance(name, str) and name.endswith("/step_time_ms") \
                and rec.get("type") in ("histogram", "timer") \
                and isinstance(rec.get("p50"), (int, float)):
            out[name] = float(rec["p50"])
    return out


def _race_wins(records):
    """{kernel: {"pallas": n, "xla": n}} from tuning/race_won_*
    counters."""
    wins = {}
    for rec in records:
        name = rec.get("name", "")
        if rec.get("type") != "counter" or not isinstance(name, str) \
                or not name.startswith("tuning/race_won_"):
            continue
        side = name[len("tuning/race_won_"):]
        if side not in ("pallas", "xla"):
            continue
        kernel = (rec.get("labels") or {}).get("kernel", "?")
        row = wins.setdefault(kernel, {"pallas": 0, "xla": 0})
        row[side] += rec.get("value") or 0
    return wins


def compare_metrics(current_path, base_path, threshold=0.10):
    """Regression diff of two metrics dumps; returns a list of
    regression strings (empty = gate passes).

    - step-time p50: any ``*/step_time_ms`` histogram present in BOTH
      dumps whose p50 grew more than ``threshold`` (fractional);
    - tuning race verdicts: any kernel whose majority winner flipped
      pallas -> xla, or a previously clean-pallas kernel (zero xla
      wins) picking up any xla win — binary, no threshold; a noisy
      share wobble that flips no verdict passes.
    - numerics finite flip (ISSUE 9): any ``numerics/finite`` gauge
      truthy in base and 0 in current — binary;
    - grad-norm blow-up (ISSUE 9): any ``numerics/grad_norm`` p50 more
      than :data:`GRAD_NORM_JUMP_FACTOR` x its base — fixed factor,
      independent of ``threshold``;
    - DDP comms (ISSUE 11): a ``ddp/comms_bytes`` gauge growing past
      ``threshold`` (the sync layout moves more bytes), or
      ``ddp/overlap_efficiency`` dropping past ``threshold`` (the
      bucket schedule stopped overlapping);
    - memory (ISSUE 15): a ``memory/watermark_bytes`` gauge growing
      past ``threshold`` (the live set grew), or a
      ``memory/hbm_calibration_ratio`` gauge drifting past
      ``threshold`` in either direction (the HBM cost model stopped
      tracking XLA);
    - host concurrency (ISSUE 16): any
      ``analysis/concurrency_findings{check=}`` counter growing above
      its base value, or a check id absent/zero in base going nonzero
      — binary, no threshold;
    - serving (ISSUE 20): the ``serving/latency_p99_ms`` gauge growing
      past ``threshold`` (request tail latency on the seeded trace),
      or ``serving/tokens_per_s`` dropping past ``threshold``
      (continuous-batching throughput) — the loadgen trace is
      deterministic per seed, so the workload cannot explain either
      move.

    Metrics present in only one dump are reported as info, never
    failed on: a shorter run is not a regression.
    """
    cur = _read_records(current_path) or []
    base = _read_records(base_path) or []
    regressions, infos = [], []

    cur_p50, base_p50 = _step_time_p50s(cur), _step_time_p50s(base)
    for name in sorted(base_p50):
        if name not in cur_p50:
            infos.append(f"{name}: only in base (p50 {base_p50[name]:.3f})")
            continue
        b, c = base_p50[name], cur_p50[name]
        if b > 0 and c > b * (1.0 + threshold):
            regressions.append(
                f"{name}: p50 {b:.3f} -> {c:.3f} ms "
                f"(+{(c / b - 1) * 100:.1f}% > {threshold * 100:.0f}%)")
        else:
            infos.append(f"{name}: p50 {b:.3f} -> {c:.3f} ms ok")
    for name in sorted(set(cur_p50) - set(base_p50)):
        infos.append(f"{name}: new (p50 {cur_p50[name]:.3f})")

    cur_plan, base_plan = _plan_choices(cur), _plan_choices(base)
    for model in sorted(base_plan):
        if model not in cur_plan:
            infos.append(f"plan {model}: only in base "
                         f"({base_plan[model]})")
            continue
        if cur_plan[model] != base_plan[model]:
            # a plan flip is binary and gated like a race-verdict flip:
            # the chosen layout changing between runs means either the
            # cost model moved or the machine did — both need eyes
            regressions.append(
                f"plan {model}: chosen candidate flipped "
                f"{base_plan[model]} -> {cur_plan[model]}")
        else:
            infos.append(f"plan {model}: {cur_plan[model]} ok")

    cur_fin, base_fin = _numerics_finite_gauges(cur), \
        _numerics_finite_gauges(base)
    for name in sorted(base_fin):
        if name not in cur_fin:
            infos.append(f"{name}: only in base")
            continue
        if base_fin[name] and not cur_fin[name]:
            regressions.append(
                f"{name}: finite -> NON-FINITE (a run that started "
                f"producing NaN/Inf is broken regardless of speed)")
        else:
            infos.append(f"{name}: {base_fin[name]} -> "
                         f"{cur_fin[name]} ok")

    cur_gn, base_gn = _grad_norm_p50s(cur), _grad_norm_p50s(base)
    for name in sorted(base_gn):
        if name not in cur_gn:
            infos.append(f"{name}: only in base "
                         f"(p50 {base_gn[name]:.4g})")
            continue
        b, c = base_gn[name], cur_gn[name]
        if b > 0 and c > b * GRAD_NORM_JUMP_FACTOR:
            regressions.append(
                f"{name}: p50 {b:.4g} -> {c:.4g} "
                f"(>{GRAD_NORM_JUMP_FACTOR:.0f}x jump)")
        else:
            infos.append(f"{name}: p50 {b:.4g} -> {c:.4g} ok")

    cur_ddp, base_ddp = _ddp_gauges(cur), _ddp_gauges(base)
    for name in sorted(base_ddp):
        if name not in cur_ddp:
            infos.append(f"{name}: only in base ({base_ddp[name]:.4g})")
            continue
        b, c = base_ddp[name], cur_ddp[name]
        if name.startswith("ddp/comms_bytes") and b > 0 \
                and c > b * (1.0 + threshold):
            # the gradient-sync layout started moving more bytes per
            # step — a schedule/packing regression regardless of the
            # wall clock on this machine
            regressions.append(
                f"{name}: {b:.0f} -> {c:.0f} B "
                f"(+{(c / b - 1) * 100:.1f}% > {threshold * 100:.0f}%)")
        elif name == "ddp/overlap_efficiency" and b > 0 \
                and c < b * (1.0 - threshold):
            regressions.append(
                f"{name}: {b:.3f} -> {c:.3f} (the bucket schedule "
                f"stopped hiding comms under backward compute)")
        else:
            infos.append(f"{name}: {b:.4g} -> {c:.4g} ok")

    cur_skew, base_skew = _fleet_skew_gauges(cur), \
        _fleet_skew_gauges(base)
    for name in sorted(base_skew):
        if name not in cur_skew:
            infos.append(f"{name}: only in base "
                         f"({base_skew[name]:+.1%})")
            continue
        b, c = base_skew[name], cur_skew[name]
        # the skew gauge is already a relative spread (slowest rank's
        # p50 over the fleet median − 1), so the gate is an absolute
        # delta in skew points: one rank drifting from +5% to +40%
        # behind the fleet is a straggler regression no matter what
        # the wall clock did
        if c > b + threshold:
            regressions.append(
                f"{name}: rank skew {b:+.1%} -> {c:+.1%} "
                f"(grew past +{threshold * 100:.0f} points — one rank "
                f"is falling behind the fleet)")
        else:
            infos.append(f"{name}: skew {b:+.1%} -> {c:+.1%} ok")

    cur_gp, base_gp = _goodput_ratio_gauges(cur), \
        _goodput_ratio_gauges(base)
    for name in sorted(base_gp):
        if name not in cur_gp:
            infos.append(f"{name}: only in base ({base_gp[name]:.4f})")
            continue
        b, c = base_gp[name], cur_gp[name]
        # the goodput ratio is already a fraction of wall-clock, so the
        # gate is an absolute delta in ratio points (like the fleet-
        # skew gate): the same workload spending threshold more of its
        # wall on non-productive causes is a regression regardless of
        # absolute speed (ISSUE 17)
        if c < b - threshold:
            regressions.append(
                f"{name}: goodput {b:.4f} -> {c:.4f} "
                f"(dropped past {threshold * 100:.0f} points — the run "
                f"spends more wall-clock on badput causes)")
        else:
            infos.append(f"{name}: goodput {b:.4f} -> {c:.4f} ok")

    cur_srv, base_srv = _serving_gauges(cur), _serving_gauges(base)
    for name in sorted(base_srv):
        if name not in cur_srv:
            infos.append(f"{name}: only in base ({base_srv[name]:.4g})")
            continue
        b, c = base_srv[name], cur_srv[name]
        # the serving gates mirror the paper's inference-SLO framing
        # (ISSUE 20): tail latency growing or throughput dropping past
        # threshold on the SAME seeded trace means the scheduler or
        # cache path regressed — the trace is deterministic, so the
        # workload cannot explain the move
        if name == "serving/latency_p99_ms" and b > 0 \
                and c > b * (1.0 + threshold):
            regressions.append(
                f"{name}: p99 {b:.3f} -> {c:.3f} ms "
                f"(+{(c / b - 1) * 100:.1f}% > {threshold * 100:.0f}% "
                f"— request tail latency grew on the same trace)")
        elif name == "serving/tokens_per_s" and b > 0 \
                and c < b * (1.0 - threshold):
            regressions.append(
                f"{name}: {b:.2f} -> {c:.2f} tok/s "
                f"(-{(1 - c / b) * 100:.1f}% > {threshold * 100:.0f}% "
                f"— continuous-batching throughput dropped)")
        else:
            infos.append(f"{name}: {b:.4g} -> {c:.4g} ok")
    for name in sorted(set(cur_srv) - set(base_srv)):
        infos.append(f"{name}: new ({cur_srv[name]:.4g})")

    cur_fp8, base_fp8 = _fp8_speedup_gauges(cur), \
        _fp8_speedup_gauges(base)
    for name in sorted(base_fp8):
        if name not in cur_fp8:
            infos.append(f"{name}: only in base "
                         f"({base_fp8[name]:.3f}x)")
            continue
        b, c = base_fp8[name], cur_fp8[name]
        # the fp8-vs-bf16 speedup RATIO is the gated quantity (ISSUE
        # 13): wall clocks move with the machine, but fp8 getting
        # relatively slower than bf16 means the epilogue/quantize path
        # regressed regardless of absolute speed
        if b > 0 and c < b * (1.0 - threshold):
            regressions.append(
                f"{name}: fp8-vs-bf16 speedup {b:.3f}x -> {c:.3f}x "
                f"(-{(1 - c / b) * 100:.1f}% > {threshold * 100:.0f}%)")
        else:
            infos.append(f"{name}: speedup {b:.3f}x -> {c:.3f}x ok")

    cur_wm, base_wm = _memory_watermark_gauges(cur), \
        _memory_watermark_gauges(base)
    for name in sorted(base_wm):
        if name not in cur_wm:
            infos.append(f"{name}: only in base "
                         f"({base_wm[name]:.0f} B)")
            continue
        b, c = base_wm[name], cur_wm[name]
        # the live-set high-watermark growing past threshold means the
        # same workload now keeps more bytes alive — an HBM regression
        # that on a smaller chip IS the next OOM, regardless of speed
        if b > 0 and c > b * (1.0 + threshold):
            regressions.append(
                f"{name}: watermark {b:.0f} -> {c:.0f} B "
                f"(+{(c / b - 1) * 100:.1f}% > {threshold * 100:.0f}% "
                f"— the live set grew)")
        else:
            infos.append(f"{name}: {b:.0f} -> {c:.0f} B ok")

    cur_cal, base_cal = _calibration_ratio_gauges(cur), \
        _calibration_ratio_gauges(base)
    for name in sorted(base_cal):
        if name not in cur_cal:
            infos.append(f"{name}: only in base "
                         f"({base_cal[name]:.3f}x)")
            continue
        b, c = base_cal[name], cur_cal[name]
        # the measured/modeled HBM ratio is not expected to be 1.0 but
        # IS expected to be stable: drift in EITHER direction past
        # threshold means the cost model and XLA's buffer assignment
        # started disagreeing in a new way — every planner pruning
        # decision inherits that error (ISSUE 15)
        if b > 0 and abs(c - b) > b * threshold:
            regressions.append(
                f"{name}: calibration ratio {b:.3f}x -> {c:.3f}x "
                f"(drifted {abs(c / b - 1) * 100:.1f}% > "
                f"{threshold * 100:.0f}% — the HBM cost model no "
                f"longer tracks what XLA allocates)")
        else:
            infos.append(f"{name}: ratio {b:.3f}x -> {c:.3f}x ok")

    cur_conc, base_conc = _concurrency_check_counts(cur), \
        _concurrency_check_counts(base)
    if cur_conc or base_conc:
        for check in sorted(set(cur_conc) | set(base_conc)):
            b = base_conc.get(check, 0.0)
            c = cur_conc.get(check)
            if c is None:
                infos.append(f"concurrency {check}: only in base "
                             f"({b:.0f})")
                continue
            # binary, no threshold: one new confirmed race / signal /
            # callback hazard in the host runtime is a regression
            # regardless of what the wall clock did (ISSUE 16)
            if c > b:
                regressions.append(
                    f"concurrency {check}: findings {b:.0f} -> {c:.0f} "
                    f"(new host-concurrency hazard — see "
                    f"docs/analysis.md#host-concurrency-checks)")
            else:
                infos.append(f"concurrency {check}: {b:.0f} -> "
                             f"{c:.0f} ok")

    cur_state, base_state = _state_check_counts(cur), \
        _state_check_counts(base)
    if cur_state or base_state:
        for check in sorted(set(cur_state) | set(base_state)):
            b = base_state.get(check, 0.0)
            c = cur_state.get(check)
            if c is None:
                infos.append(f"state {check}: only in base ({b:.0f})")
                continue
            # binary, no threshold: one new resume-compatibility hole
            # (state loss, schema drift, illegal reshard, donation
            # hazard) is a regression regardless of what the wall
            # clock did (ISSUE 18). The engine zero-fills the family,
            # so c and b are explicit 0s on clean runs — a check id
            # going nonzero always trips here.
            if c > b:
                regressions.append(
                    f"state {check}: findings {b:.0f} -> {c:.0f} "
                    f"(new checkpoint/state-flow hazard — see "
                    f"docs/analysis.md#state-flow-checks)")
            else:
                infos.append(f"state {check}: {b:.0f} -> {c:.0f} ok")

    cur_mem, base_mem = _memory_finding_counts(cur), \
        _memory_finding_counts(base)
    if cur_mem or base_mem:
        for check in sorted(set(cur_mem) | set(base_mem)):
            b = base_mem.get(check, 0.0)
            c = cur_mem.get(check)
            if c is None:
                infos.append(f"memory {check}: only in base ({b:.0f})")
                continue
            # binary, no threshold: one new liveness hazard (dropped
            # donation, peak spike, held upcast) is a regression
            # regardless of what the wall clock did (ISSUE 19). The
            # engine zero-fills the family, so c and b are explicit 0s
            # on clean runs — a check id going nonzero always trips.
            if c > b:
                regressions.append(
                    f"memory {check}: findings {b:.0f} -> {c:.0f} "
                    f"(new memory-liveness hazard — see "
                    f"docs/analysis.md#memory-liveness-checks)")
            else:
                infos.append(f"memory {check}: {b:.0f} -> {c:.0f} ok")

    cur_race, base_race = _race_wins(cur), _race_wins(base)
    for kernel in sorted(base_race):
        if kernel not in cur_race:
            infos.append(f"tuning race {kernel}: only in base")
            continue
        b, c = base_race[kernel], cur_race[kernel]
        b_tot, c_tot = b["pallas"] + b["xla"], c["pallas"] + c["xla"]
        if not b_tot or not c_tot:
            continue
        b_share = b["pallas"] / b_tot
        c_share = c["pallas"] / c_tot
        # binary flip detection, not share arithmetic: racing is noisy
        # (one extra xla sample moves the share without any kernel
        # actually flipping to the fallback)
        majority_flip = b_share >= 0.5 and c_share < 0.5
        dirtied = b["xla"] == 0 and c["xla"] > 0
        if majority_flip or dirtied:
            regressions.append(
                f"tuning race {kernel}: pallas share "
                f"{b_share:.2f} -> {c_share:.2f} "
                f"(p:{c['pallas']}/x:{c['xla']} vs base "
                f"p:{b['pallas']}/x:{b['xla']})")
        else:
            infos.append(f"tuning race {kernel}: "
                         f"p:{c['pallas']}/x:{c['xla']} ok")
    return regressions, infos


def run_compare(argv):
    """Handle ``CURRENT.jsonl --compare BASE.jsonl``; returns the
    process exit code (0 pass, 1 regression, 2 usage)."""
    args = list(argv)
    json_mode = "--json" in args
    if json_mode:
        args.remove("--json")
    threshold = 0.10
    if "--compare-threshold" in args:
        i = args.index("--compare-threshold")
        try:
            threshold = float(args[i + 1])
        except (IndexError, ValueError):
            print("--compare-threshold needs a float", file=sys.stderr)
            return 2
        del args[i:i + 2]
    i = args.index("--compare")
    try:
        base = args[i + 1]
    except IndexError:
        print("--compare needs a BASE.jsonl path", file=sys.stderr)
        return 2
    del args[i:i + 2]
    files = [a for a in args if not a.startswith("-")]
    if len(files) != 1:
        print("--compare takes exactly one current dump, got "
              f"{files or 'none'}", file=sys.stderr)
        return 2
    for path in (files[0], base):
        if not os.path.isfile(path):
            print(f"cannot read {path}", file=sys.stderr)
            return 2
    regressions, infos = compare_metrics(files[0], base, threshold)
    if json_mode:
        print(json.dumps({"current": files[0], "base": base,
                          "threshold": threshold,
                          "regressions": regressions, "info": infos}))
    else:
        print(f"{files[0]} vs base {base} "
              f"(threshold {threshold * 100:.0f}%)")
        for line in infos:
            print(f"  {line}")
        for line in regressions:
            print(f"  REGRESSION {line}")
        print(f"{len(regressions)} regression(s)")
    return 1 if regressions else 0


def summarize_analysis(path, data):
    findings = data.get("findings", [])
    by_check = collections.Counter(f.get("check", "?") for f in findings)
    print(f"{path}: apex_tpu.analysis report "
          f"(schema v{data['schema_version']})")
    print(f"  findings: {len(findings)} new, "
          f"{data.get('grandfathered', 0)} grandfathered")
    for check, n in sorted(by_check.items()):
        print(f"    {check:24s} {n}")
    errors = data.get("target_errors", {})
    for name, err in sorted(errors.items()):
        print(f"  TARGET ERROR {name}: {err}")


if __name__ == "__main__":
    args = sys.argv[1:]
    if "--compare" in args:
        sys.exit(run_compare(args))
    json_mode = "--json" in args
    passthrough = []
    handled_any = False
    for arg in args:
        data = load_analysis_report(arg) if os.path.isfile(arg) else None
        if data is not None:
            if json_mode:
                # machine-readable passthrough: the payload already IS
                # the machine format (schema_version and all)
                print(json.dumps({"path": arg, **data}))
            else:
                summarize_analysis(arg, data)
            handled_any = True
        else:
            # a metrics JSONL carrying the sharding or resilience
            # families gets its dedicated table(s) in addition to the
            # generic observability summary below
            fam = render_sharding_family(arg) if os.path.isfile(arg) \
                else None
            if fam is not None:
                if json_mode:
                    print(json.dumps({"path": arg,
                                      "sharding_family": fam}))
                else:
                    summarize_sharding(arg, fam)
            conc = render_concurrency_family(arg) \
                if os.path.isfile(arg) else None
            if conc is not None:
                if json_mode:
                    print(json.dumps({"path": arg,
                                      "concurrency_family": conc}))
                else:
                    summarize_concurrency(arg, conc)
            st = render_state_family(arg) \
                if os.path.isfile(arg) else None
            if st is not None:
                if json_mode:
                    print(json.dumps({"path": arg,
                                      "state_family": st}))
                else:
                    summarize_state(arg, st)
            memf = render_memory_findings_family(arg) \
                if os.path.isfile(arg) else None
            if memf is not None:
                if json_mode:
                    print(json.dumps({"path": arg,
                                      "memory_findings_family": memf}))
                else:
                    summarize_memory_findings(arg, memf)
            pl = render_plan_family(arg) if os.path.isfile(arg) \
                else None
            if pl is not None:
                if json_mode:
                    print(json.dumps({"path": arg, "plan_family": pl}))
                else:
                    summarize_plan(arg, pl)
            res = render_resilience_family(arg) if os.path.isfile(arg) \
                else None
            if res is not None:
                if json_mode:
                    print(json.dumps({"path": arg,
                                      "resilience_family": res}))
                else:
                    summarize_resilience(arg, res)
            tun = render_tuning_family(arg) if os.path.isfile(arg) \
                else None
            if tun is not None:
                if json_mode:
                    print(json.dumps({"path": arg,
                                      "tuning_family": tun}))
                else:
                    summarize_tuning(arg, tun)
            num = render_numerics_family(arg) if os.path.isfile(arg) \
                else None
            if num is not None:
                if json_mode:
                    print(json.dumps({"path": arg,
                                      "numerics_family": num}))
                else:
                    summarize_numerics(arg, num)
            mem = render_memory_family(arg) if os.path.isfile(arg) \
                else None
            if mem is not None:
                if json_mode:
                    print(json.dumps({"path": arg,
                                      "memory_family": mem}))
                else:
                    summarize_memory(arg, mem)
            ddp = render_ddp_family(arg) if os.path.isfile(arg) \
                else None
            if ddp is not None:
                if json_mode:
                    print(json.dumps({"path": arg, "ddp_family": ddp}))
                else:
                    summarize_ddp(arg, ddp)
            f8 = render_fp8_family(arg) if os.path.isfile(arg) \
                else None
            if f8 is not None:
                if json_mode:
                    print(json.dumps({"path": arg, "fp8_family": f8}))
                else:
                    summarize_fp8(arg, f8)
            flt = render_fleet_family(arg) if os.path.isfile(arg) \
                else None
            if flt is not None:
                if json_mode:
                    print(json.dumps({"path": arg,
                                      "fleet_family": flt}))
                else:
                    summarize_fleet(arg, flt)
            gp = render_goodput_family(arg) if os.path.isfile(arg) \
                else None
            if gp is not None:
                if json_mode:
                    print(json.dumps({"path": arg,
                                      "goodput_family": gp}))
                else:
                    summarize_goodput(arg, gp)
            srv = render_serving_family(arg) if os.path.isfile(arg) \
                else None
            if srv is not None:
                if json_mode:
                    print(json.dumps({"path": arg,
                                      "serving_family": srv}))
                else:
                    summarize_serving(arg, srv)
            passthrough.append(arg)
    remaining_files = [a for a in passthrough if os.path.isfile(a)]
    if handled_any and not remaining_files:
        # flags were honored above; nothing left for the JSONL reader
        sys.exit(0)
    sys.exit(main(["report"] + passthrough))
