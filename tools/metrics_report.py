#!/usr/bin/env python
"""Summarize apex_tpu metrics JSONL dumps and analysis JSON reports.

Thin wrapper over ``python -m apex_tpu.observability report`` so the
tools/ directory carries the complete telemetry workflow next to
tpu_profile.py / trace_report.py:

    python tools/metrics_report.py BENCH_METRICS.jsonl
    python tools/metrics_report.py run1.jsonl run2.jsonl --json

It also ingests ``python -m apex_tpu.analysis --json`` dumps (detected
by their ``schema_version`` + ``kind`` header), printing a per-check
finding summary — so one command reads every machine report the repo
emits:

    python -m apex_tpu.analysis --json > lint.json
    python tools/metrics_report.py lint.json BENCH_METRICS.jsonl
"""

from __future__ import annotations

import collections
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from apex_tpu.observability.cli import main  # noqa: E402

# analysis --json schema versions this reader understands
KNOWN_ANALYSIS_SCHEMAS = (1,)


def load_analysis_report(path):
    """Parse ``path`` as an apex_tpu.analysis --json dump; returns the
    payload dict or None when the file is something else (e.g. a
    metrics JSONL). Unknown schema versions fail loudly rather than
    mis-summarizing."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or "schema_version" not in data:
        return None
    if data.get("kind") != "apex_tpu.analysis":
        return None
    version = data["schema_version"]
    if version not in KNOWN_ANALYSIS_SCHEMAS:
        raise SystemExit(
            f"{path}: analysis schema_version {version} is newer than "
            f"this reader (knows {list(KNOWN_ANALYSIS_SCHEMAS)}) — "
            f"update tools/metrics_report.py")
    return data


def summarize_analysis(path, data):
    findings = data.get("findings", [])
    by_check = collections.Counter(f.get("check", "?") for f in findings)
    print(f"{path}: apex_tpu.analysis report "
          f"(schema v{data['schema_version']})")
    print(f"  findings: {len(findings)} new, "
          f"{data.get('grandfathered', 0)} grandfathered")
    for check, n in sorted(by_check.items()):
        print(f"    {check:24s} {n}")
    errors = data.get("target_errors", {})
    for name, err in sorted(errors.items()):
        print(f"  TARGET ERROR {name}: {err}")


if __name__ == "__main__":
    args = sys.argv[1:]
    json_mode = "--json" in args
    passthrough = []
    handled_any = False
    for arg in args:
        data = load_analysis_report(arg) if os.path.isfile(arg) else None
        if data is not None:
            if json_mode:
                # machine-readable passthrough: the payload already IS
                # the machine format (schema_version and all)
                print(json.dumps({"path": arg, **data}))
            else:
                summarize_analysis(arg, data)
            handled_any = True
        else:
            passthrough.append(arg)
    remaining_files = [a for a in passthrough if os.path.isfile(a)]
    if handled_any and not remaining_files:
        # flags were honored above; nothing left for the JSONL reader
        sys.exit(0)
    sys.exit(main(["report"] + passthrough))
