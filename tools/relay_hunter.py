#!/usr/bin/env python
"""Round-long axon relay hunter (VERDICT r3 next-step #1).

Rounds 1-3 treated the TPU benchmark as a one-shot at round end and lost
every time to relay outages. This script turns it into a standing hunt:
poll the axon local relay (127.0.0.1:8083, the stateless port that
``jax.devices()`` dials) for the whole round and, the moment it answers,
run the on-hardware pre-flight (``tools/tpu_validate.py``) followed by
``bench.py``, persisting every artifact incrementally so a later hang
loses nothing:

  RELAY_PROBES.log        one JSON line per probe (proof of the hunt)
  TPU_VALIDATE_r04.log    validate stdout/stderr, appended per attempt
  BENCH_TPU_attempts.log  full bench stdout/stderr per attempt
  BENCH_r04_live.json     last parsed bench JSON with platform=tpu

Any ``platform=tpu`` bench JSON is persisted to BENCH_r04_live.json the
moment it lands, but only a CLEAN run (tpu_validate rc=0 AND bench rc=0)
exits 0 and ends the hunt — a partial result is kept while hunting for a
clean window. Exit 1 at the deadline with the probe log as evidence of
the hunt. Timed-out children
get SIGTERM and a long grace period — a SIGKILLed TPU client has been
observed (memory note 2026-07-30) to wedge the tunnel lease server-side
for >1h, so SIGKILL is a logged last resort only.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBE_LOG = os.path.join(REPO, "RELAY_PROBES.log")
VALIDATE_LOG = os.path.join(REPO, "TPU_VALIDATE_r05.log")
BENCH_LOG = os.path.join(REPO, "BENCH_TPU_attempts.log")
LIVE_JSON = os.path.join(REPO, "BENCH_r05_live.json")


def log_probe(**kw):
    kw["t"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(PROBE_LOG, "a") as f:
        f.write(json.dumps(kw) + "\n")


def port_open(port=8083, timeout=3.0) -> bool:
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=timeout):
            return True
    except OSError:
        return False


def run_child(cmd, timeout, log_path, header):
    """Run cmd with stdout/stderr redirected to a scratch file (so an
    abandoned child can never block on a full pipe); SIGTERM on timeout
    with a 300s grace. NEVER SIGKILL: a SIGKILLed TPU client has been
    observed to wedge the tunnel lease server-side for >1h, defeating the
    whole hunt — a child that ignores SIGTERM is logged and abandoned
    (rc=None), and the next devices-probe naturally waits out the lease.
    Returns (rc_or_None, output_text)."""
    # unique scratch per invocation: an abandoned child keeps its fd (and
    # write offset) on the old inode, so reusing one path would bleed a
    # zombie's output — including its bench JSON — into a later attempt
    run_child.n = getattr(run_child, "n", 0) + 1
    out_path = f"{log_path}.cur{run_child.n}"
    with open(log_path, "a") as log, open(out_path, "w") as out:
        log.write(f"\n===== {header} {time.strftime('%H:%M:%S')} =====\n")
        log.flush()
        proc = subprocess.Popen(
            cmd, cwd=REPO, stdout=out, stderr=subprocess.STDOUT,
            # own process group so signals reach grandchildren (bench.py
            # spawns a worker subprocess)
            preexec_fn=os.setsid)
        rc = None
        try:
            rc = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            log.write(f"--- timeout {timeout}s: SIGTERM ---\n")
            log.flush()
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
            try:
                rc = proc.wait(timeout=300)
            except subprocess.TimeoutExpired:
                log.write(f"--- SIGTERM ignored for 300s: abandoning "
                          f"pid {proc.pid} UNKILLED (SIGKILL wedges the "
                          f"tunnel lease) ---\n")
        with open(out_path, errors="replace") as f:
            text = f.read()
        log.write(text[-200000:])
        log.write(f"\n--- rc={rc} ---\n")
    if rc is not None:
        # the scratch file only needs to outlive an ABANDONED child (which
        # keeps writing to its inode); a finished child's output is already
        # captured in the log
        try:
            os.unlink(out_path)
        except OSError:
            pass
    return rc, text


def last_bench_json(text):
    for line in reversed((text or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            return parsed
    return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hours", type=float, default=10.5)
    ap.add_argument("--interval", type=float, default=60.0)
    args = ap.parse_args()
    deadline = time.time() + args.hours * 3600
    log_probe(event="hunter_start", hours=args.hours, pid=os.getpid())

    DEVICES_PROBE = [
        sys.executable, "-c",
        "import jax; d=jax.devices(); print(d); "
        "assert d[0].platform=='tpu', d"]

    n, last_attempt, last_direct = 0, 0.0, 0.0
    while time.time() < deadline:
        n += 1
        up = port_open()
        log_probe(event="probe", n=n, relay_up=up)
        # don't hammer a flapping relay: at most one full attempt / 10 min.
        # Checked BEFORE the probes so a scarce direct-init success is
        # never burned against the throttle (the probe is only spent when
        # the result would be acted on).
        if time.time() - last_attempt < 600:
            time.sleep(args.interval)
            continue
        direct_ok = False
        if not up:
            # VERDICT r4 #1: the port probe only detects one outage mode
            # (relay process down). Every ~30 min try a direct backend
            # init anyway — if axon reaches the chip some other way, the
            # hunt must not miss the window.
            if time.time() - last_direct >= 1800:
                last_direct = time.time()
                rc, _ = run_child(
                    DEVICES_PROBE, timeout=240, log_path=BENCH_LOG,
                    header="direct-init-probe")
                log_probe(event="direct_init_probe", rc=rc)
                direct_ok = rc == 0
                up = direct_ok  # fall through to the full attempt below
            if not up:
                time.sleep(args.interval)
                continue
        last_attempt = time.time()

        # cheap reality check: does the backend actually initialize?
        # (skipped when the direct-init probe just proved exactly this —
        # a duplicate init is an extra chance to wedge a flaky tunnel)
        if not direct_ok:
            rc, _ = run_child(
                DEVICES_PROBE, timeout=240, log_path=BENCH_LOG,
                header="devices-probe")
            log_probe(event="devices_probe", rc=rc)
            if rc != 0:
                continue

        # pre-flight: compiled-Mosaic kernel parity (VERDICT r3 weak #2)
        rc_v, _ = run_child(
            [sys.executable, "tools/tpu_validate.py"],
            timeout=2400, log_path=VALIDATE_LOG, header="tpu_validate")
        log_probe(event="tpu_validate", rc=rc_v)

        # the benchmark itself (bench.py has its own watchdogs/fallbacks)
        rc_b, out = run_child(
            [sys.executable, "bench.py"],
            timeout=5400, log_path=BENCH_LOG, header="bench")
        parsed = last_bench_json(out)
        platform = (parsed or {}).get("platform")
        log_probe(event="bench", rc=rc_b, platform=platform)
        if parsed is not None and platform == "tpu":
            # persist ANY tpu result immediately (a later hang loses
            # nothing), but only a clean validate + clean bench ends the
            # hunt — a partial/failed run must not ship as the round's
            # number while a clean window might still come
            parsed["tpu_validate_rc"] = rc_v
            parsed["bench_rc"] = rc_b
            with open(LIVE_JSON, "w") as f:
                json.dump(parsed, f, indent=1)
            if rc_v == 0 and rc_b == 0:
                log_probe(event="SUCCESS", file=LIVE_JSON)
                # tile-sweep autotune while the chip answers (ISSUE 6):
                # winners persist in the per-device tuning cache plus a
                # repo-committable export, so tuned tiles + race
                # verdicts survive the window (failure is non-fatal)
                rc_t, _ = run_child(
                    ["bash", "tools/tune.sh", "--export",
                     os.path.join(REPO, "TUNING_CACHE.json")],
                    timeout=3600, log_path=BENCH_LOG, header="tune")
                log_probe(event="tune", rc=rc_t)
                # real-TPU memory ground truth (ISSUE 15): the live
                # bytes_limit, a live-buffer snapshot, and the
                # measured-vs-modeled HBM calibration ratios computed
                # against TPU XLA's memory_analysis — the sharding
                # cost model's first on-silicon anchor (failure is
                # non-fatal)
                rc_m, _ = run_child(
                    [sys.executable, "-m", "apex_tpu.observability",
                     "memory", "--out",
                     os.path.join(REPO, "TPU_MEMORY_r05.json")],
                    timeout=1200, log_path=BENCH_LOG, header="memory")
                log_probe(event="memory_snapshot", rc=rc_m)
                # refresh the committed HBM calibration priors from
                # the live window (ISSUE 19): on-silicon ratios
                # replace the CPU-backend ones the planner otherwise
                # prices pruning on (failure is non-fatal)
                rc_pr, _ = run_child(
                    [sys.executable, "tools/refresh_priors.py",
                     "--live"],
                    timeout=1200, log_path=BENCH_LOG,
                    header="refresh_priors")
                log_probe(event="refresh_priors", rc=rc_pr)
                # bonus evidence while the window is open: an xplane
                # trace of the flagship step (failure is non-fatal)
                rc_p, _ = run_child(
                    [sys.executable, "tools/tpu_profile.py",
                     "--out", os.path.join(REPO, "TPU_TRACE_r05")],
                    timeout=1200, log_path=BENCH_LOG, header="tpu_profile")
                log_probe(event="profile", rc=rc_p)
                if rc_p == 0:
                    # per-op attribution from the fresh capture (host-side
                    # analysis; does not touch the chip)
                    rc_r, _ = run_child(
                        [sys.executable, "tools/trace_report.py",
                         os.path.join(REPO, "TPU_TRACE_r05"), "--json",
                         os.path.join(REPO, "TRACE_REPORT_r05.json")],
                        timeout=600, log_path=BENCH_LOG,
                        header="trace_report")
                    log_probe(event="trace_report", rc=rc_r)
                    # Perfetto-loadable export of the same capture
                    # (ISSUE 7): the round's trace evidence opens at
                    # ui.perfetto.dev without TensorBoard (host-side
                    # analysis; does not touch the chip)
                    rc_pf, _ = run_child(
                        [sys.executable, "-m",
                         "apex_tpu.observability", "trace",
                         os.path.join(REPO, "TPU_TRACE_r05"), "--out",
                         os.path.join(REPO,
                                      "TPU_TRACE_r05.perfetto.json")],
                        timeout=600, log_path=BENCH_LOG,
                        header="perfetto_export")
                    log_probe(event="perfetto_export", rc=rc_pf)
                return 0
            log_probe(event="partial_tpu_result", validate_rc=rc_v,
                      bench_rc=rc_b)
            last_attempt = time.time() + 1200  # ease off the chip
        # relay answered but bench fell back / failed — keep hunting

    log_probe(event="deadline", probes=n)
    return 1


if __name__ == "__main__":
    sys.exit(main())
