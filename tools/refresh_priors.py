#!/usr/bin/env python
"""Regenerate analysis/hbm_priors.json from the newest calibration
capture (ISSUE 19 satellite).

The committed priors file is the calibration loop's (PR 14) memory —
per-target measured/modeled HBM ratios the estimator and the planner
price on. This one-shot refreshes it from, in order of preference:

  1. ``--from DUMP.jsonl``   explicit bench metrics dump (reads the
     ``memory_calibration`` event lines);
  2. the newest ``BENCH_*_live.json`` / ``BENCH_BASELINE.jsonl`` in
     the repo root that carries calibration events;
  3. ``--live``              a fresh ``calibrate_targets()`` run on
     the current backend (what tools/relay_hunter.py invokes on a
     clean live TPU window, replacing CPU ratios with on-silicon
     ones).

Output is deterministic (sorted keys, fixed rounding, no clocks), so
an unchanged capture regenerates a byte-identical file and the diff in
review is exactly the ratio drift. The result is validated through
``memory_checks.load_hbm_priors`` before it lands — this tool can
never commit a file the loader would refuse.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # runnable as `python tools/refresh_priors.py`
    sys.path.insert(0, REPO)
PRIORS_PATH = os.path.join(REPO, "apex_tpu", "analysis",
                           "hbm_priors.json")


def rows_from_events(events) -> dict:
    """{target: row} from memory_calibration event payloads (the last
    event per target wins — newest capture)."""
    rows = {}
    for ev in events:
        target = ev.get("target")
        ratio = ev.get("ratio")
        if not target or not isinstance(ratio, (int, float)):
            continue
        rows[str(target)] = {
            "ratio": round(float(ratio), 4),
            "modeled_bytes": int(ev.get("modeled_bytes", 0)),
            "measured_bytes": int(ev.get("measured_bytes", 0)),
        }
    return rows


def events_from_jsonl(path):
    """memory_calibration events from a bench metrics dump (either the
    per-line record format of BENCH_BASELINE.jsonl or a single bench
    JSON object with an events list)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict):
                continue
            if rec.get("event") == "memory_calibration":
                events.append(rec)
            for ev in rec.get("events", ()) or ():
                if isinstance(ev, dict) and \
                        ev.get("event") == "memory_calibration":
                    events.append(ev)
    return events


def newest_capture() -> str | None:
    cands = sorted(
        glob.glob(os.path.join(REPO, "BENCH_*_live.json"))
        + glob.glob(os.path.join(REPO, "BENCH_BASELINE.jsonl")),
        key=lambda p: os.path.getmtime(p), reverse=True)
    for path in cands:
        if events_from_jsonl(path):
            return path
    return None


def rows_from_live() -> tuple[dict, str]:
    from apex_tpu.observability.memory.calibrate import calibrate_targets
    from apex_tpu.observability.registry import MetricRegistry

    results = calibrate_targets(registry=MetricRegistry())
    rows = {}
    for name, row in sorted(results.items()):
        if "ratio" not in row:
            print(f"refresh_priors: {name} skipped: {row.get('error')}",
                  file=sys.stderr)
            continue
        rows[name] = {
            "ratio": round(float(row["ratio"]), 4),
            "modeled_bytes": int(row["modeled_bytes"]),
            "measured_bytes": int(row["measured_bytes"]),
        }
    import jax

    backend = jax.default_backend()
    return rows, backend


def build_document(rows: dict, backend: str, source: str) -> dict:
    ratios = [r["ratio"] for r in rows.values()]
    return {
        "_comment": (
            "Calibrated HBM correction priors (ISSUE 19): per-target "
            "measured/modeled ratios distilled from the bench "
            "memory_calibration captures (apex_tpu.observability."
            "memory.calibrate). Consumed by estimate_hbm_and_comms("
            "priors=...) and apex_tpu.analysis.planner pruning; "
            "validated loudly by memory_checks.load_hbm_priors. "
            "Regenerate with: python tools/refresh_priors.py (run "
            "opportunistically by tools/relay_hunter.py on clean live "
            "TPU windows, which replaces these CPU-backend ratios "
            "with on-silicon ones)."),
        "schema_version": 1,
        "backend": backend,
        "source": source,
        "default_ratio": round(statistics.median(ratios), 4),
        "priors": {k: rows[k] for k in sorted(rows)},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="regenerate analysis/hbm_priors.json from the "
                    "newest calibration capture")
    ap.add_argument("--from", dest="dump", default=None,
                    help="bench metrics dump to read "
                         "memory_calibration events from")
    ap.add_argument("--live", action="store_true",
                    help="run calibrate_targets() fresh instead of "
                         "reading a capture")
    ap.add_argument("--out", default=PRIORS_PATH,
                    help=f"output path (default {PRIORS_PATH})")
    args = ap.parse_args(argv)

    if args.live:
        rows, backend = rows_from_live()
        source = "calibrate_targets() live run"
    else:
        dump = args.dump or newest_capture()
        if dump is None:
            print("refresh_priors: no capture with memory_calibration "
                  "events found (and --live not given) — nothing to "
                  "refresh", file=sys.stderr)
            return 1
        rows = rows_from_events(events_from_jsonl(dump))
        backend = "cpu"
        for suffix in ("_live.json",):
            if dump.endswith(suffix):
                backend = "tpu"  # live captures only land on-silicon
        source = f"memory_calibration events from " \
                 f"{os.path.relpath(dump, REPO)}"
    if not rows:
        print("refresh_priors: capture carried no usable calibration "
              "rows", file=sys.stderr)
        return 1

    doc = build_document(rows, backend, source)
    text = json.dumps(doc, indent=2, sort_keys=False) + "\n"

    # the loader is the schema authority: never write a file it
    # would refuse
    import tempfile

    from apex_tpu.analysis.memory_checks import load_hbm_priors

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as tmp:
        tmp.write(text)
    try:
        load_hbm_priors(tmp.name)
    finally:
        os.unlink(tmp.name)

    with open(args.out, "w") as f:
        f.write(text)
    print(f"refresh_priors: wrote {len(rows)} prior(s) "
          f"(default_ratio {doc['default_ratio']}) to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
