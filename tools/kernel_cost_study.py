#!/usr/bin/env python
"""Offline Pallas-vs-XLA kernel cost study (VERDICT r3 next-step #2,
fallback clause: no chip required).

Methodology — for each fused kernel at its bench.py shapes:

- **flops**: taken from XLA's HLO cost analysis of the *fallback* path,
  lowered AOT for TPU (``jit(f).trace(x).lower(lowering_platforms=
  ('tpu',)).cost_analysis()``). Flops are fusion-invariant, so they
  apply to both paths. (The Pallas path lowers to an opaque custom_call
  the analysis cannot see — hence the fallback as the flops source.)
- **HBM bytes, analytic**: both paths modeled as pass structures over
  the operands. XLA's HLO 'bytes accessed' is a no-fusion upper bound
  (every op's operands summed), so the XLA number here is the
  *post-fusion* analytic estimate — XLA reliably fuses elementwise
  chains into their producing/consuming reductions but must
  materialize matmul operands and reduction results between fusions.
- **roofline**: t = max(flops / peak_flops, bytes / hbm_bw) per chip
  generation; predicted speedup = t_xla / t_pallas.

The predictions justify each kernel's dispatch default until
``bench.py``'s on-chip ``bench_kernels`` race replaces them with
measurements (the study's decision table lives in
docs/kernel_cost_study.md).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from apex_tpu.ops import pallas_config  # noqa: E402

# v5e; override with --peak/--bw for other generations
PEAK_FLOPS = 197e12  # bf16
HBM_BW = 819e9       # bytes/s

B, S, H, D = 4, 2048, 16, 128
ROWS, HIDDEN = 8192, 4096
BH, SM_S = 64, 1024
BF2, FP4 = 2, 4


def xla_flops(fn, *args):
    with pallas_config.force("off"):
        low = jax.jit(fn).trace(*args).lower(lowering_platforms=("tpu",))
    ca = low.cost_analysis()
    return float(ca.get("flops", 0.0))


def measured_xla_bytes(fn, *args):
    """Post-fusion 'bytes accessed' of the COMPILED fallback (r4 verdict:
    replace the assumed XLA-side HBM bytes with a measured HLO stat).

    The module is compiled by the CPU backend, whose fusion pipeline is
    the available proxy for TPU's (no chip needed); inputs must be fp32 —
    CPU upcasts bf16 compute, which would inflate the count. The returned
    figure is the optimized module's HloCostAnalysis traffic, i.e. it
    reflects the fusion decisions XLA actually made, not a pass-structure
    guess."""
    with pallas_config.force("off"):
        ca = jax.jit(fn).lower(*args).compile().cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    return float(ca.get("bytes accessed", 0.0))


def roofline(flops, bytes_):
    return max(flops / PEAK_FLOPS, bytes_ / HBM_BW)


def study():
    from apex_tpu.ops.flash_attention import flash_attention
    from apex_tpu.ops.layer_norm import layer_norm, rms_norm
    from apex_tpu.transformer.functional.fused_softmax import (
        scaled_upper_triang_masked_softmax,
    )

    rows = []

    def add(name, flops, pallas_bytes, xla_bytes, note, meas_bytes=None):
        tp, tx = roofline(flops, pallas_bytes), roofline(flops, xla_bytes)
        row = {
            "kernel": name,
            "flops_g": round(flops / 1e9, 2),
            "pallas_mb": round(pallas_bytes / 2**20, 1),
            "xla_mb": round(xla_bytes / 2**20, 1),
            "pallas_roofline_us": round(tp * 1e6, 1),
            "xla_roofline_us": round(tx * 1e6, 1),
            "predicted_speedup": round(tx / tp, 2),
            "bound": "flops" if flops / PEAK_FLOPS > pallas_bytes / HBM_BW
                     else "memory",
            "note": note,
        }
        if meas_bytes is not None:
            tm = roofline(flops, meas_bytes)
            row["xla_meas_mb"] = round(meas_bytes / 2**20, 1)
            row["predicted_speedup_measured"] = round(tm / tp, 2)
        rows.append(row)

    # ---- layer norm fwd: x bf16 [ROWS, HIDDEN], w/b fp32
    x = jnp.ones((ROWS, HIDDEN), jnp.bfloat16)
    xf = jnp.ones((ROWS, HIDDEN), jnp.float32)  # f32 twin for measurement
    w = jnp.ones((HIDDEN,), jnp.float32)
    b = jnp.zeros((HIDDEN,), jnp.float32)
    xb = ROWS * HIDDEN * BF2
    f = xla_flops(lambda x: layer_norm(x, w, b, (HIDDEN,)), x)
    # measured post-fusion traffic: f32 twin (CPU would upcast bf16),
    # halved to bf16-equivalent — the fusion STRUCTURE is dtype-free
    m = measured_xla_bytes(lambda x: layer_norm(x, w, b, (HIDDEN,)), xf) / 2
    add("layer_norm_fwd", f,
        pallas_bytes=2 * xb,           # one pass: read x, write y
        xla_bytes=3 * xb,              # stat reduction pass + normalize pass
        meas_bytes=m,
        note="fused Welford single pass vs reduce-then-normalize")

    # ---- layer norm fwd+bwd
    f = xla_flops(jax.grad(lambda x: jnp.sum(
        layer_norm(x, w, b, (HIDDEN,)).astype(jnp.float32))), x)
    m = measured_xla_bytes(
        jax.grad(lambda x: jnp.sum(layer_norm(x, w, b, (HIDDEN,)))), xf) / 2
    add("layer_norm_fwd_bwd", f,
        # fwd (2 passes incl. stat save) + bwd kernel: read x, dy, write
        # dx + dw/db partials in one pass
        pallas_bytes=5 * xb,
        # fwd 3 + bwd: two reduction couplings (dy·xhat terms) force
        # re-reads of x and dy before the dx pass: ~5 passes
        xla_bytes=8 * xb,
        meas_bytes=m,
        note="bwd needs x, dy twice in XLA (reduction + dx) vs once")

    # ---- rms norm fwd
    f = xla_flops(lambda x: rms_norm(x, w, (HIDDEN,)), x)
    m = measured_xla_bytes(lambda x: rms_norm(x, w, (HIDDEN,)), xf) / 2
    add("rms_norm_fwd", f, pallas_bytes=2 * xb, xla_bytes=3 * xb,
        meas_bytes=m,
        note="same structure as LN, one stat instead of two")

    # ---- flash attention fwd (causal)
    q = jnp.ones((B, S, H, D), jnp.bfloat16)
    qf = jnp.ones((B, S, H, D), jnp.float32)
    f = xla_flops(lambda q, k, v: flash_attention(q, k, v, causal=True),
                  q, q, q)
    m = measured_xla_bytes(
        lambda q, k, v: flash_attention(q, k, v, causal=True),
        qf, qf, qf) / 2
    qkv = B * S * H * D * BF2           # one of q/k/v/o
    scores = B * H * S * S * BF2        # the S^2 materialization
    bq, _ = pallas_config.flash_blocks("fwd", S, S, D)
    reread = S // bq                    # k/v stream once per q block
    add("flash_fwd_causal", f,
        pallas_bytes=2 * qkv + 2 * reread * qkv,   # q+o once, k+v rereads
        # scores written (QK^T), read+written (softmax), read (PV):
        # 4 passes over the S^2 buffer + q/k/v/o — causality halves it
        xla_bytes=(4 * scores) // 2 + 4 * qkv,
        meas_bytes=m,
        note=f"S^2 materialization vs streamed tiles (k/v reread x{reread})")

    # ---- flash attention fwd+bwd
    def floss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32))

    f = xla_flops(jax.grad(floss, argnums=(0, 1, 2)), q, q, q)
    m = measured_xla_bytes(
        jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True)), argnums=(0, 1, 2)), qf, qf, qf) / 2
    bqb, _ = pallas_config.flash_blocks("bwd", S, S, D)
    reread_b = S // bqb
    add("flash_fwd_bwd_causal", f,
        # fwd + recompute-based bwd: dq/dk/dv accumulated over tile
        # streams; ~3x the fwd traffic at bwd tile rereads
        pallas_bytes=(2 * qkv + 2 * reread * qkv)
        + (4 * qkv + 3 * reread_b * qkv),
        # XLA bwd re-materializes scores AND probs grads: ~8 S^2 passes
        xla_bytes=(8 * scores) // 2 + 8 * qkv,
        meas_bytes=m,
        note="bwd recompute streams tiles vs dS/dP materialization")

    # ---- causal fused softmax [BH, SM_S, SM_S] bf16
    xs = jnp.ones((BH, SM_S, SM_S), jnp.bfloat16)
    xsf = jnp.ones((BH, SM_S, SM_S), jnp.float32)
    f = xla_flops(lambda x: scaled_upper_triang_masked_softmax(x, None, 1.0),
                  xs)
    m = measured_xla_bytes(
        lambda x: scaled_upper_triang_masked_softmax(x, None, 1.0), xsf) / 2
    sb = BH * SM_S * SM_S * BF2
    add("causal_softmax", f,
        pallas_bytes=3 * sb,   # two-pass (max+sum, then normalize) + write
        xla_bytes=4 * sb,      # mask+max, exp+sum, normalize as 3 fusions
        meas_bytes=m,
        note="two-pass k-blocked vs three XLA reduction fusions")

    # ---- flat-buffer fused adam (~350M params): g,p fp32 packed + m,v
    from apex_tpu.optimizers import fused_adam

    n = 350e6
    n_meas = 8 * 2**20  # fp32-native: measure small, scale linearly
    txm = fused_adam(lr=1e-3, flat=True)
    pm = {"w": jnp.ones((n_meas,), jnp.float32)}
    stm = txm.init(pm)
    gm = {"w": jnp.ones((n_meas,), jnp.float32)}
    m = measured_xla_bytes(
        lambda g, st, p: txm.update(g, st, p), gm, stm, pm)
    m = m * (n / n_meas)
    adam_bytes = n * (4 * FP4 + 3 * FP4)  # read g,p,m,v; write d,m,v
    add("flat_adam", 13 * n,
        pallas_bytes=adam_bytes, xla_bytes=adam_bytes,
        meas_bytes=m,
        note="pure elementwise chain: XLA fusion already traffic-optimal "
             "-> tie at best; r3 CPU race lost -> default XLA")

    return rows


def main():
    rows = study()
    print(json.dumps(rows, indent=1))
    print()
    hdr = ("kernel", "flops_g", "pallas_mb", "xla_mb",
           "pallas_roofline_us", "xla_roofline_us", "predicted_speedup",
           "bound")
    print(" | ".join(hdr))
    for r in rows:
        print(" | ".join(str(r[k]) for k in hdr))


if __name__ == "__main__":
    main()
