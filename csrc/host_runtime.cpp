// apex_tpu C++ host runtime (TPU re-design of the reference's host-side
// native layer: csrc/flatten_unflatten.cpp + apex/parallel/distributed.py
// bucket logic). The TPU compute path is XLA/Pallas; this library owns the
// host work that sits AROUND the device: gradient-bucket planning, flat
// buffer packing for host-side checkpoint/comm staging, and a threaded
// prefetch ring for input pipelines.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <numeric>
#include <thread>
#include <vector>
#include <functional>

extern "C" {

// ---------------------------------------------------------------- buckets
//
// Greedy size-capped bucketing in reverse registration order — gradients
// become ready roughly last-parameter-first during backprop, so DDP fills
// buckets in reverse (ref apex/parallel/distributed.py bucket assignment).
// sizes: bytes per tensor. out_bucket: bucket id per tensor.
// Returns the number of buckets.
int64_t apex_plan_buckets(const int64_t* sizes, int64_t n,
                          int64_t bucket_bytes, int64_t* out_bucket) {
  if (n <= 0) return 0;
  int64_t bucket = 0;
  int64_t used = 0;
  for (int64_t i = n - 1; i >= 0; --i) {
    if (used > 0 && used + sizes[i] > bucket_bytes) {
      ++bucket;
      used = 0;
    }
    out_bucket[i] = bucket;
    used += sizes[i];
  }
  return bucket + 1;
}

// Offsets of each tensor inside its flat bucket buffer.
// out_offset[i] = byte offset of tensor i within bucket out_bucket[i].
void apex_bucket_offsets(const int64_t* sizes, const int64_t* bucket_ids,
                         int64_t n, int64_t n_buckets, int64_t* out_offset,
                         int64_t* out_bucket_size) {
  std::vector<int64_t> used(n_buckets, 0);
  // offsets follow ascending index order within a bucket
  for (int64_t i = 0; i < n; ++i) {
    out_offset[i] = used[bucket_ids[i]];
    used[bucket_ids[i]] += sizes[i];
  }
  for (int64_t b = 0; b < n_buckets; ++b) out_bucket_size[b] = used[b];
}

// ------------------------------------------------------------ flat pack/
// unpack (ref csrc/flatten_unflatten.cpp, which defers to torch's
// flatten_dense_tensors). Multithreaded memcpy gather/scatter.

struct CopyJob {
  const uint8_t* src;
  uint8_t* dst;
  int64_t bytes;
};

static void run_jobs(std::vector<CopyJob>& jobs, int threads) {
  if (threads <= 1 || jobs.size() <= 1) {
    for (auto& j : jobs) std::memcpy(j.dst, j.src, j.bytes);
    return;
  }
  std::atomic<size_t> next{0};
  auto worker = [&]() {
    for (;;) {
      size_t i = next.fetch_add(1);
      if (i >= jobs.size()) return;
      std::memcpy(jobs[i].dst, jobs[i].src, jobs[i].bytes);
    }
  };
  std::vector<std::thread> pool;
  int nt = std::min<int>(threads, (int)jobs.size());
  pool.reserve(nt);
  for (int t = 0; t < nt; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
}

// Gather n tensors (srcs[i], sizes[i] bytes) into flat at given offsets.
void apex_flatten(const void** srcs, const int64_t* sizes,
                  const int64_t* offsets, int64_t n, void* flat,
                  int threads) {
  std::vector<CopyJob> jobs(n);
  for (int64_t i = 0; i < n; ++i)
    jobs[i] = {(const uint8_t*)srcs[i], (uint8_t*)flat + offsets[i],
               sizes[i]};
  run_jobs(jobs, threads);
}

// Scatter flat back out to n tensors.
void apex_unflatten(const void* flat, const int64_t* sizes,
                    const int64_t* offsets, int64_t n, void** dsts,
                    int threads) {
  std::vector<CopyJob> jobs(n);
  for (int64_t i = 0; i < n; ++i)
    jobs[i] = {(const uint8_t*)flat + offsets[i], (uint8_t*)dsts[i],
               sizes[i]};
  run_jobs(jobs, threads);
}

// ------------------------------------------------------- prefetch ring
//
// Threaded producer/consumer ring of fixed-size byte buffers. Producers
// call a user callback (Python via ctypes CFUNCTYPE, or any C fn) that
// fills a buffer for batch index i; consumers pop in order. This is the
// host input pipeline the reference leaves to torch DataLoader workers.

typedef int32_t (*apex_fill_fn)(int64_t batch_idx, void* buffer,
                                int64_t buffer_bytes, void* ctx);

struct PrefetchRing {
  std::vector<std::vector<uint8_t>> slots;
  std::vector<int64_t> slot_batch;     // which batch each slot holds
  std::vector<int32_t> slot_status;    // 0 empty, 1 filling, 2 ready, -1 err
  std::deque<int64_t> fill_queue;      // batch indices to produce
  int64_t next_consume = 0;
  std::mutex mu;
  std::condition_variable cv_ready, cv_work;
  std::vector<std::thread> workers;
  bool stop = false;
  apex_fill_fn fill = nullptr;
  void* ctx = nullptr;
  int64_t buffer_bytes = 0;
};

void* apex_prefetch_create(int64_t n_slots, int64_t buffer_bytes,
                           int64_t total_batches, int n_workers,
                           apex_fill_fn fill, void* ctx) {
  auto* r = new PrefetchRing();
  r->slots.assign(n_slots, std::vector<uint8_t>(buffer_bytes));
  r->slot_batch.assign(n_slots, -1);
  r->slot_status.assign(n_slots, 0);
  for (int64_t b = 0; b < total_batches; ++b) r->fill_queue.push_back(b);
  r->fill = fill;
  r->ctx = ctx;
  r->buffer_bytes = buffer_bytes;
  auto worker = [r]() {
    for (;;) {
      int64_t batch = -1;
      int64_t slot = -1;
      {
        std::unique_lock<std::mutex> lk(r->mu);
        r->cv_work.wait(lk, [r] {
          if (r->stop) return true;
          if (r->fill_queue.empty()) return false;
          // a slot is claimable if empty AND the batch at the queue head
          // is within n_slots of the consume cursor (bounded prefetch)
          int64_t b = r->fill_queue.front();
          if (b >= r->next_consume + (int64_t)r->slots.size()) return false;
          for (size_t s = 0; s < r->slots.size(); ++s)
            if (r->slot_status[s] == 0) return true;
          return false;
        });
        if (r->stop) return;
        batch = r->fill_queue.front();
        if (batch >= r->next_consume + (int64_t)r->slots.size()) continue;
        for (size_t s = 0; s < r->slots.size(); ++s)
          if (r->slot_status[s] == 0) { slot = (int64_t)s; break; }
        if (slot < 0) continue;
        r->fill_queue.pop_front();
        r->slot_status[slot] = 1;
        r->slot_batch[slot] = batch;
      }
      int32_t rc = r->fill(batch, r->slots[slot].data(), r->buffer_bytes,
                           r->ctx);
      {
        std::lock_guard<std::mutex> lk(r->mu);
        r->slot_status[slot] = rc == 0 ? 2 : -1;
      }
      r->cv_ready.notify_all();
      r->cv_work.notify_all();
    }
  };
  for (int t = 0; t < n_workers; ++t) r->workers.emplace_back(worker);
  return r;
}

// Block until the next in-order batch is ready; copy it to out. Returns the
// batch index, or -1 on fill error, -2 if exhausted.
int64_t apex_prefetch_next(void* ring, void* out, int64_t out_bytes) {
  auto* r = (PrefetchRing*)ring;
  std::unique_lock<std::mutex> lk(r->mu);
  int64_t want = r->next_consume;
  int64_t slot = -1;
  for (;;) {
    // a ring being destroyed must unblock its consumer: destroy sets
    // stop under mu and notifies cv_ready, so a consumer parked here
    // wakes, sees stop, and reports exhaustion instead of sleeping
    // through the join forever
    if (r->stop) return -2;
    bool pending = false;
    for (size_t s = 0; s < r->slots.size(); ++s) {
      if (r->slot_batch[s] == want) {
        if (r->slot_status[s] == 2) { slot = (int64_t)s; break; }
        if (r->slot_status[s] == -1) return -1;
        pending = true;
      }
    }
    if (slot >= 0) break;
    if (!pending) {
      bool queued = false;
      for (int64_t b : r->fill_queue) if (b == want) { queued = true; break; }
      if (!queued) return -2;  // nothing will ever produce it
    }
    r->cv_ready.wait(lk);
  }
  int64_t n = std::min(out_bytes, r->buffer_bytes);
  std::memcpy(out, r->slots[slot].data(), n);
  r->slot_status[slot] = 0;
  r->slot_batch[slot] = -1;
  r->next_consume = want + 1;
  lk.unlock();
  r->cv_work.notify_all();
  return want;
}

void apex_prefetch_destroy(void* ring) {
  auto* r = (PrefetchRing*)ring;
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->stop = true;
  }
  r->cv_work.notify_all();
  r->cv_ready.notify_all();
  for (auto& t : r->workers) t.join();
  delete r;
}

}  // extern "C"
