"""apex_tpu — a TPU-native re-imagining of NVIDIA Apex.

Everything Apex offers for CUDA/PyTorch (mixed precision, fused optimizers,
fused normalization, data/tensor/pipeline parallelism) rebuilt TPU-first on
JAX/XLA/Pallas: functional transforms, ``jax.sharding.Mesh`` + ``shard_map``
for parallelism, Pallas kernels for the hot ops, and XLA collectives
(psum / all_gather / ppermute / reduce_scatter) over the ICI mesh instead of
NCCL.

Reference capability surface: /root/reference (NVIDIA Apex); see SURVEY.md §2
for the component-by-component mapping.
"""

import logging

import jax as _jax

if not hasattr(_jax.lax, "axis_size"):
    # The container's jax (0.4.37) predates jax.lax.axis_size; the tree,
    # its examples and tests call it pervasively inside shard_map bodies.
    # psum of a Python scalar is statically resolved to value*axis_size
    # (no collective is emitted), which is exactly axis_size's semantics
    # — including raising NameError outside a bound axis context.
    def _axis_size(axis_name):
        return _jax.lax.psum(1, axis_name)

    _jax.lax.axis_size = _axis_size

if not hasattr(_jax, "shard_map"):
    # jax.shard_map was promoted out of jax.experimental after 0.4.37;
    # every caller here uses keyword mesh/in_specs/out_specs, which the
    # experimental entry point accepts identically.
    from jax.experimental.shard_map import shard_map as _shard_map

    _jax.shard_map = _shard_map

if not hasattr(_jax.lax, "pvary"):
    # pvary annotates varying-over-mesh-axes types for the post-0.4.37
    # check_vma system; under pre-vma jax the value is unchanged and the
    # annotation has no checker to feed, so identity is the exact analog.
    _jax.lax.pvary = lambda x, axis_names=(): x


class RankInfoFormatter(logging.Formatter):
    """ref apex/__init__.py:28 — logging formatter injecting the current
    (tp, pp, dp, ...) rank tuple into every record; pairs with
    ``transformer.log_util.set_logging_level`` for multi-rank runs."""

    def format(self, record):
        from apex_tpu.transformer.parallel_state import get_rank_info
        try:
            record.rank_info = get_rank_info()
        except Exception:  # outside an initialized mesh
            record.rank_info = "-"
        return super().format(record)


from apex_tpu import amp
from apex_tpu import observability
from apex_tpu import optimizers
from apex_tpu import normalization
from apex_tpu import parallel
from apex_tpu import multi_tensor_apply
from apex_tpu import transformer
from apex_tpu import fp16_utils
from apex_tpu import fused_dense
from apex_tpu import mlp
from apex_tpu import models
from apex_tpu import pyprof
from apex_tpu import reparameterization
from apex_tpu import rnn

__version__ = "0.1.0"

__all__ = [
    "RankInfoFormatter",
    "amp",
    "optimizers",
    "normalization",
    "parallel",
    "multi_tensor_apply",
    "observability",
    "transformer",
    "fp16_utils",
    "fused_dense",
    "mlp",
    "models",
    "pyprof",
    "reparameterization",
    "rnn",
]
