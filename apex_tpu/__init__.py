"""apex_tpu — a TPU-native re-imagining of NVIDIA Apex.

Everything Apex offers for CUDA/PyTorch (mixed precision, fused optimizers,
fused normalization, data/tensor/pipeline parallelism) rebuilt TPU-first on
JAX/XLA/Pallas: functional transforms, ``jax.sharding.Mesh`` + ``shard_map``
for parallelism, Pallas kernels for the hot ops, and XLA collectives
(psum / all_gather / ppermute / reduce_scatter) over the ICI mesh instead of
NCCL.

Reference capability surface: /root/reference (NVIDIA Apex); see SURVEY.md §2
for the component-by-component mapping.
"""

import logging

import jax as _jax

if not hasattr(_jax.lax, "axis_size"):
    # The container's jax (0.4.37) predates jax.lax.axis_size; the tree,
    # its examples and tests call it pervasively inside shard_map bodies.
    # psum of a Python scalar is statically resolved to value*axis_size
    # (no collective is emitted), which is exactly axis_size's semantics
    # — including raising NameError outside a bound axis context.
    def _axis_size(axis_name):
        return _jax.lax.psum(1, axis_name)

    _jax.lax.axis_size = _axis_size

if not hasattr(_jax, "shard_map"):
    # jax.shard_map was promoted out of jax.experimental after 0.4.37;
    # every caller here uses keyword mesh/in_specs/out_specs, which the
    # experimental entry point accepts identically. The promotion also
    # renamed check_rep -> check_vma (the rep tracker became the vma
    # type system); translate so post-rename callers run unchanged.
    import functools as _functools

    from jax.experimental.shard_map import shard_map as _shard_map

    @_functools.wraps(_shard_map)
    def _shard_map_compat(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "pvary"):
    # pvary annotates varying-over-mesh-axes types for the post-0.4.37
    # check_vma system; under pre-vma jax the value is unchanged and the
    # annotation has no checker to feed, so identity is the exact analog.
    _jax.lax.pvary = lambda x, axis_names=(): x

if not hasattr(_jax.sharding, "set_mesh"):
    # jax.sharding.set_mesh became public after 0.4.37. Its two effects —
    # binding the abstract mesh (so bare-PartitionSpec sharding
    # constraints and get_abstract_mesh resolve) and binding the concrete
    # mesh for dispatch — map onto 0.4.37's internal set_abstract_mesh
    # plus the classic `with mesh:` thread-resources context. The
    # internal helper's sharding_in_types flip is deliberately NOT
    # replicated: 0.4.37's sharding-in-types was pre-release and changes
    # unrelated jit semantics.
    import contextlib as _contextlib

    try:
        from jax._src.mesh import set_abstract_mesh as _set_abstract_mesh
    except ImportError:  # pragma: no cover - future jax without this path
        _set_abstract_mesh = None

    @_contextlib.contextmanager
    def _set_mesh(mesh):
        if mesh is None:
            yield None
            return
        with _contextlib.ExitStack() as stack:
            abstract = getattr(mesh, "abstract_mesh", None)
            if _set_abstract_mesh is not None and abstract is not None:
                stack.enter_context(_set_abstract_mesh(abstract))
            stack.enter_context(mesh)
            yield mesh

    _jax.sharding.set_mesh = _set_mesh

if not hasattr(_jax, "typeof"):
    # jax.typeof (the public aval reader, post-0.4.37) is how vma-aware
    # code asks "which mesh axes does this value vary over". 0.4.37
    # avals carry no .vma, so callers written as
    # getattr(jax.typeof(x), "vma", frozenset()) degrade to "invariant"
    # — the right answer under pre-vma shard_map, where replicated-param
    # grads arrive already psummed. Without the shim those callers
    # (parallel.distributed.sync_autodiff_gradients and friends) die on
    # AttributeError instead.
    def _typeof(x):
        import jax.core as _core

        return _core.get_aval(x)

    _jax.typeof = _typeof

if not hasattr(_jax.sharding, "get_abstract_mesh"):
    # Public alias for the internal reader the set_mesh shim feeds; the
    # tensor-parallel activation-sharding hints consult it.
    try:
        from jax._src.mesh import get_abstract_mesh as _get_abstract_mesh
    except ImportError:  # pragma: no cover
        _get_abstract_mesh = None
    if _get_abstract_mesh is not None:
        _jax.sharding.get_abstract_mesh = _get_abstract_mesh


class RankInfoFormatter(logging.Formatter):
    """ref apex/__init__.py:28 — logging formatter injecting the current
    (tp, pp, dp, ...) rank tuple into every record; pairs with
    ``transformer.log_util.set_logging_level`` for multi-rank runs."""

    def format(self, record):
        from apex_tpu.transformer.parallel_state import get_rank_info
        try:
            record.rank_info = get_rank_info()
        except Exception:  # outside an initialized mesh
            record.rank_info = "-"
        return super().format(record)


from apex_tpu import amp
from apex_tpu import observability
from apex_tpu import optimizers
from apex_tpu import normalization
from apex_tpu import parallel
from apex_tpu import multi_tensor_apply
from apex_tpu import transformer
from apex_tpu import fp16_utils
from apex_tpu import fused_dense
from apex_tpu import mlp
from apex_tpu import models
from apex_tpu import pyprof
from apex_tpu import reparameterization
from apex_tpu import resilience
from apex_tpu import rnn

__version__ = "0.1.0"

__all__ = [
    "RankInfoFormatter",
    "amp",
    "optimizers",
    "normalization",
    "parallel",
    "multi_tensor_apply",
    "observability",
    "transformer",
    "fp16_utils",
    "fused_dense",
    "mlp",
    "models",
    "pyprof",
    "reparameterization",
    "resilience",
    "rnn",
]
