"""Trace analysis: per-op attribution report from a parsed capture.

TPU re-design of the reference's profiling-report half
(ref apex/pyprof/prof/prof.py:1 — joins parsed kernel records with
per-op analytic flops/bytes tables and prints a per-op efficiency
report). On TPU the per-op flops/bytes come from the capture itself
when a device plane is present (XLA records them per op); the report
aggregates exclusive time per op and per category, and derives
utilization against a configurable peak.

Two data paths:

- :func:`Report.from_capture` — always works (any backend): the
  apex_tpu.pyprof.parse walker, exclusive-time attribution.
- :func:`xprof_hlo_stats` — the native xprof pipeline's per-op table
  (flops rate, memory BW, roofline bound) when a device plane exists;
  ``Report`` merges these columns into its rows when available.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from apex_tpu.pyprof.parse import (
    OpRecord,
    find_xplane_paths,
    is_container,
    parse_xspace,
    short_name,
    step_times_us,
)

__all__ = ["Report", "OpSummary", "xprof_hlo_stats"]


@dataclasses.dataclass
class OpSummary:
    name: str
    category: str
    program: str
    occurrences: int
    self_us: float
    total_us: float
    share: float = 0.0           # of summed (measured) exclusive time
    # None = the capture carried no flops stat for this op (host-only
    # planes) — distinct from a measured zero, like bytes_accessed
    flops: Optional[float] = None
    # None = the capture carried no bytes stat for this op (host-only
    # planes) — distinct from a measured zero
    bytes_accessed: Optional[float] = None
    gflops_per_s: float = 0.0    # from xprof hlo_stats when merged
    bound_by: str = ""


def xprof_hlo_stats(paths) -> Optional[List[Dict]]:
    """Per-op rows from the native xprof ``hlo_stats`` converter, or
    ``None`` when unavailable/empty (host-only captures have no device
    op-metrics, e.g. the CPU mesh used in CI)."""
    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError:
        return None
    try:
        data, _ = rtd.xspace_to_tool_data(list(paths), "hlo_stats", {})
    except Exception:
        return None
    table = json.loads(data if isinstance(data, str) else data.decode())
    cols = [c["id"] for c in table.get("cols", [])]
    rows = [dict(zip(cols, [c.get("v") for c in r.get("c", [])]))
            for r in table.get("rows", [])]
    return rows or None


class Report:
    """Aggregated per-op / per-category attribution for one capture."""

    def __init__(self, ops: List[OpSummary], total_self_us: float,
                 steps_us: Optional[List[float]] = None,
                 async_ops: Optional[List[OpSummary]] = None):
        self.ops = sorted(ops, key=lambda o: -o.self_us)
        self.total_self_us = total_self_us
        # device step markers ('Steps' line): the authoritative wall time
        self.steps_us = steps_us or []
        # async-copy spans overlap compute — reported separately, never
        # added into the exclusive-time total
        self.async_ops = sorted(async_ops or [], key=lambda o: -o.self_us)
        for o in self.ops:
            o.share = o.self_us / total_self_us if total_self_us else 0.0
        wall = sum(self.steps_us)
        for o in self.async_ops:
            o.share = o.total_us / wall if wall else 0.0

    # ------------------------------------------------------------ build

    @classmethod
    def from_records(cls, records: List[OpRecord],
                     steps_us: Optional[List[float]] = None) -> "Report":
        """Attribution from a real TPU capture's device 'XLA Ops' line
        when present (async copies split out; host python plane
        excluded); otherwise — CPU CI captures with only host threadpool
        lines — every HLO-tagged record counts, as before r5."""
        device_ops = [r for r in records
                      if r.plane.startswith("/device:")
                      and r.line == "XLA Ops"]
        async_recs = [r for r in records
                      if r.plane.startswith("/device:")
                      and r.line.startswith("Async")]
        main = device_ops if device_ops else records

        def aggregate(recs):
            by_key: Dict[tuple, OpSummary] = {}
            for r in recs:
                if is_container(short_name(r.name)):
                    continue  # a while/call span is its children's time
                key = (short_name(r.name), r.program)
                s = by_key.get(key)
                if s is None:
                    s = by_key[key] = OpSummary(
                        name=key[0], category=r.category,
                        program=r.program,
                        occurrences=0, self_us=0.0, total_us=0.0)
                s.occurrences += 1
                s.self_us += r.self_ps / 1e6
                s.total_us += r.duration_ps / 1e6
                if r.flops is not None:
                    s.flops = (s.flops or 0.0) + r.flops
                if r.bytes_accessed is not None:
                    s.bytes_accessed = (s.bytes_accessed or 0.0) \
                        + r.bytes_accessed
            return list(by_key.values())

        ops = aggregate(main)
        total = sum(s.self_us for s in ops)
        return cls(ops, total, steps_us=steps_us,
                   async_ops=aggregate(async_recs))

    @classmethod
    def from_capture(cls, path: str) -> "Report":
        """Build from a logdir / run dir / .xplane.pb path, merging the
        native xprof per-op columns when the capture has a device plane."""
        paths = find_xplane_paths(path)
        report = cls.from_records(parse_xspace(paths),
                                  steps_us=step_times_us(paths))
        rows = xprof_hlo_stats(paths)
        if rows:
            report.merge_hlo_stats(rows)
        return report

    def merge_hlo_stats(self, rows: List[Dict]) -> None:
        # hlo_stats rows carry a numeric program_id while OpSummary holds
        # the module NAME, so the join key is the op name alone — merge
        # only names that are unambiguous across programs (a name reused
        # by two jitted programs would get the wrong program's rate)
        counts: Dict[str, int] = {}
        for o in self.ops:
            counts[o.name] = counts.get(o.name, 0) + 1
        by_name = {o.name: o for o in self.ops if counts[o.name] == 1}
        for row in rows:
            o = by_name.get(str(row.get("hlo_op_name", "")))
            if o is None:
                continue
            o.gflops_per_s = float(row.get("model_flop_rate") or 0.0)
            o.bound_by = str(row.get("bound_by") or "")
            if not o.flops and o.gflops_per_s:
                # rate [GFLOP/s] x self time [us] -> flops
                o.flops = o.gflops_per_s * 1e9 * (o.self_us / 1e6)

    # ---------------------------------------------------------- queries

    def by_category(self) -> Dict[str, Dict[str, float]]:
        """Per-category rollup. ``bytes_accessed`` is ``None`` when no
        op in the category carried a measured bytes stat (host-only
        captures) — never a fabricated 0.0; ``share`` divides by the
        summed *measured* self time (``total_self_us`` is exactly
        that sum), so shares stay meaningful when some planes carry no
        timing at all."""
        cats: Dict[str, Dict[str, float]] = {}
        for o in self.ops:
            c = cats.setdefault(o.category, {
                "self_us": 0.0, "occurrences": 0, "flops": None,
                "bytes_accessed": None})
            c["self_us"] += o.self_us
            c["occurrences"] += o.occurrences
            if o.flops is not None:
                c["flops"] = (c["flops"] or 0.0) + o.flops
            if o.bytes_accessed is not None:
                c["bytes_accessed"] = (c["bytes_accessed"] or 0.0) \
                    + o.bytes_accessed
        for c in cats.values():
            c["share"] = (c["self_us"] / self.total_self_us
                          if self.total_self_us else 0.0)
        return dict(sorted(cats.items(), key=lambda kv: -kv[1]["self_us"]))

    def utilization(self, peak_tflops: float,
                    peak_hbm_gbps: Optional[float] = None) -> Dict:
        """Achieved fraction of peak; only meaningful when the capture
        carried per-op flops (device plane). MFU divides by the step wall
        time ('Steps' markers) when present — busy self-time would flatter
        a step with idle gaps."""
        flops = sum(o.flops for o in self.ops if o.flops is not None)
        busy_s = self.total_self_us / 1e6
        wall_s = sum(self.steps_us) / 1e6 or busy_s
        out = {"total_flops": flops, "busy_s": busy_s, "wall_s": wall_s,
               "mfu": (flops / wall_s / (peak_tflops * 1e12))
               if wall_s else 0.0}
        if peak_hbm_gbps:
            measured = [o.bytes_accessed for o in self.ops
                        if o.bytes_accessed is not None]
            # no op carried a bytes stat => HBM utilization is
            # UNMEASURED, not zero — omit rather than mislead
            if measured:
                nbytes = sum(measured)
                out["hbm_util"] = (
                    nbytes / wall_s / (peak_hbm_gbps * 1e9)
                    if wall_s else 0.0)
        return out

    # ----------------------------------------------------------- output

    def format_table(self, top: int = 30) -> str:
        lines = [
            f"{'op':<44} {'category':<18} {'#':>5} {'self ms':>9} "
            f"{'share':>6} {'GFLOP/s':>9} {'bound':>7}",
            "-" * 103,
        ]
        for o in self.ops[:top]:
            lines.append(
                f"{o.name[:44]:<44} {o.category:<18} {o.occurrences:>5} "
                f"{o.self_us / 1e3:>9.3f} {o.share * 100:>5.1f}% "
                f"{o.gflops_per_s:>9.1f} {o.bound_by[:7]:>7}")
        lines.append("-" * 103)
        lines.append(f"{'TOTAL (exclusive)':<69} "
                     f"{self.total_self_us / 1e3:>9.3f}")
        lines.append("")
        lines.append(f"{'category':<24} {'self ms':>10} {'share':>7} "
                     f"{'#ops':>6}")
        for cat, c in self.by_category().items():
            lines.append(
                f"{cat:<24} {c['self_us'] / 1e3:>10.3f} "
                f"{c['share'] * 100:>6.1f}% {int(c['occurrences']):>6}")
        if self.steps_us:
            n = len(self.steps_us)
            lines.append("")
            lines.append(
                f"steps: {n} x {sum(self.steps_us) / n / 1e3:.2f} ms "
                f"(device wall, 'Steps' markers)")
        if self.async_ops:
            tot = sum(o.total_us for o in self.async_ops)
            lines.append(
                f"async copies (overlapped, not in totals): "
                f"{tot / 1e3:.2f} ms across "
                f"{sum(o.occurrences for o in self.async_ops)} spans; top:")
            for o in self.async_ops[:5]:
                lines.append(
                    f"  {o.name[:44]:<44} {o.total_us / 1e3:>9.3f} ms "
                    f"({o.share * 100:.0f}% of wall)")
        return "\n".join(lines)

    def to_dict(self, top: int = 0) -> Dict:
        ops = self.ops[:top] if top else self.ops
        out = {
            "total_self_us": self.total_self_us,
            "categories": self.by_category(),
            "ops": [dataclasses.asdict(o) for o in ops],
        }
        if self.steps_us:
            out["steps"] = {"n": len(self.steps_us),
                            "mean_ms": sum(self.steps_us)
                            / len(self.steps_us) / 1e3}
        if self.async_ops:
            a = self.async_ops[:top] if top else self.async_ops
            out["async_ops"] = [dataclasses.asdict(o) for o in a]
        return out
