"""Trace parsing: xplane capture → per-op records.

TPU re-design of the reference's trace-parsing half
(ref apex/pyprof/parse/parse.py:1 — reads an nvprof SQLite database and
emits one record per kernel with name/duration/correlation). The TPU
analog reads the ``jax.profiler`` xplane protobuf and emits one record
per HLO-op execution event, with exclusive (self) time computed from
event nesting — the quantity per-op attribution must sum.

Works on any backend: CPU captures carry HLO thunk events on host
threadpool lines; TPU captures carry XLA-op events on the device plane.
The protobuf schema ships with tensorflow (baked into this image); the
import is guarded so the rest of apex_tpu never depends on it.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "OpRecord", "classify", "short_name", "find_xplane_paths",
    "parse_xspace", "step_times_us",
    "CATEGORIES",
]


def _xplane_pb2():
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
        return xplane_pb2
    except ImportError as e:  # pragma: no cover - tf is baked in
        raise ImportError(
            "parsing xplane captures needs the tensorflow protobuf "
            "schema (tensorflow.tsl.profiler.protobuf.xplane_pb2); "
            "install tensorflow or analyze the capture with xprof"
        ) from e


@dataclasses.dataclass
class OpRecord:
    """One HLO-op execution event (the parse.py kernel-record analog)."""

    name: str            # HLO op name, e.g. "dot.11", "psum_invariant.7"
    program: str         # HLO module name, e.g. "jit_train_step"
    plane: str           # xplane name (device or host thread pool)
    category: str        # see CATEGORIES
    duration_ps: int     # inclusive span
    self_ps: int         # exclusive time (minus nested HLO children)
    # model flops when the plane carries them (TPU); None = unmeasured
    # (same contract as bytes_accessed — a host-only capture must not
    # fabricate a 0.0)
    flops: Optional[float] = None
    # None when the plane carried no bytes stat at all — "unmeasured"
    # must stay distinguishable from a true measured zero, or every
    # host-only capture reports a misleading bytes_accessed: 0.0
    bytes_accessed: Optional[float] = None
    line: str = ""       # xplane line ('XLA Ops', 'Async XLA Ops', ...)


# Category → regexes over HLO op names. Two name families appear in
# captures: XLA's own (all-reduce, dot, fusion...) and jax-primitive
# derived (psum, all_gather...); match both.
CATEGORIES: Tuple[Tuple[str, str], ...] = (
    ("collective",
     r"^(all-reduce|all-gather|all-to-all|reduce-scatter|"
     r"collective-permute|collective-broadcast|partition-id|replica-id|"
     r"psum|pmax|pmin|all_gather|all_to_all|reduce_scatter|ppermute|"
     r"ragged-all-to-all)"),
    ("matmul", r"^(dot|cublas|gemm|matmul|dot_general)"),
    ("convolution", r"^(conv|convolution)"),
    ("attention-kernel", r"(flash|attention)"),
    # any other Pallas/Mosaic kernel lowers to an HLO custom-call
    # (e.g. a fused-Adam or layer-norm kernel) — its own bucket, NOT
    # attention
    ("custom-kernel", r"custom-call"),
    ("rng", r"^(rng|threefry|random)"),
    ("gather-scatter", r"^(gather|scatter|dynamic-slice|dynamic-update)"),
    ("data-movement",
     r"^(copy|bitcast|transpose|slice|concatenate|pad|reshape|broadcast|"
     r"reverse|tuple|get-tuple-element|wrapped_slice|wrapped_broadcast)"),
    ("host-transfer", r"^(infeed|outfeed|send|recv|host)"),
    ("control", r"^(while|call|conditional|async|done|start)"),
    ("reduction", r"^(reduce|wrapped_reduce|sort|top-k|topk|cumsum)"),
)
_COMPILED = [(cat, re.compile(pat)) for cat, pat in CATEGORIES]

# containers whose time is their children's — excluded from self-time
# rollups entirely (their exclusive remainder is scheduler overhead)
_CONTAINER = re.compile(r"^(while|call|conditional)")


def classify(name: str) -> str:
    base = short_name(name).lower()
    for cat, pat in _COMPILED:
        if pat.search(base):
            return cat
    # everything else is an elementwise chain: XLA names them
    # "<op>_<op>_fusion" / "fusion.N" / "wrapped_<op>" / bare op names
    return "fusion-elementwise"


def short_name(name: str) -> str:
    """Normalize an event name to the bare HLO op name.

    Real TPU captures (r5) carry the full HLO text — e.g.
    ``%slice-start.73 = (...) async-start(...), calls=...`` — whose
    leading ``%`` defeated every ``^``-anchored category pattern and sent
    async copies into the elementwise bucket. Strip the sigil and keep
    the lhs identifier only."""
    base = name.strip()
    if base.startswith("%"):
        base = base[1:]
    for sep in (" = ", " "):
        cut = base.find(sep)
        if cut > 0:
            base = base[:cut]
            break
    return base


def is_container(name: str) -> bool:
    return bool(_CONTAINER.match(name.lower()))


def find_xplane_paths(path: str) -> List[str]:
    """Resolve a logdir (as passed to ``jax.profiler.trace``), a profile
    run dir, or a direct ``.xplane.pb`` file to capture paths; for a
    logdir with several runs, the newest run wins."""
    if os.path.isfile(path):
        return [path]
    direct = sorted(glob.glob(os.path.join(path, "*.xplane.pb")))
    if direct:
        return direct
    runs = sorted(glob.glob(os.path.join(path, "plugins", "profile", "*")))
    # newest run first; an interrupted capture can leave an empty run dir
    for run in reversed(runs):
        found = sorted(glob.glob(os.path.join(run, "*.xplane.pb")))
        if found:
            return found
    raise FileNotFoundError(f"no xplane capture under {path!r}")


def _stat_lookup(plane) -> Dict[int, str]:
    return {m.id: m.name for m in plane.stat_metadata.values()}


def _stat_value(stat, stat_names):
    if stat.str_value:
        return stat.str_value
    if stat.ref_value:
        return stat_names.get(stat.ref_value, "")
    for field in ("int64_value", "uint64_value", "double_value"):
        v = getattr(stat, field)
        if v:
            return v
    return 0


def _line_records(plane_name, line, ev_names, stat_names) -> List[OpRecord]:
    """Self time via interval nesting: events on one line form a forest
    (a child lies within its parent's span); exclusive = inclusive minus
    the children's inclusive sums."""
    hlo_events = []
    for ev in line.events:
        stats = {}
        for s in ev.stats:
            k = stat_names.get(s.metadata_id)
            if k in ("hlo_op", "hlo_module", "flops", "model_flops",
                     "bytes_accessed", "bytes accessed",
                     "device_offset_ps", "device_duration_ps"):
                stats[k] = _stat_value(s, stat_names)
        # Two event dialects (r5): CPU captures tag HLO events with an
        # 'hlo_op' stat and use the event's own offset/duration; real TPU
        # device planes name the event with the full HLO text and put
        # timing in device_offset_ps/device_duration_ps stats instead.
        # Name-only acceptance applies to DEVICE planes only — host
        # planes name every TraceMe span (python frames etc.), which must
        # stay excluded from HLO attribution.
        named = (ev.metadata_id in ev_names
                 and plane_name.startswith("/device:"))
        if "hlo_op" not in stats and not named:
            continue
        if "device_offset_ps" in stats or "device_duration_ps" in stats:
            # a stat present with value 0 is a real zero, not "absent"
            start = int(stats.get("device_offset_ps", 0))
            dur = int(stats.get("device_duration_ps", 0))
        else:
            start, dur = ev.offset_ps, ev.duration_ps
        hlo_events.append((start, start + dur, dur, ev, stats))
    hlo_events.sort(key=lambda t: (t[0], -t[1]))

    records = []
    stack: List[Tuple[int, int, list]] = []  # (start, end, child_ps box)
    for start, end, dur, ev, stats in hlo_events:
        while stack and start >= stack[-1][1]:
            stack.pop()
        if stack:
            stack[-1][2][0] += dur
        name = ev_names.get(ev.metadata_id) or str(stats.get("hlo_op", "?"))
        child_box = [0]
        stack.append((start, end, child_box))
        records.append((dur, stats, name, child_box))

    out = []
    for dur, stats, name, child_box in records:
        raw_flops = stats.get("model_flops", stats.get("flops"))
        flops = None if raw_flops is None else float(raw_flops or 0)
        raw_bytes = stats.get("bytes_accessed",
                              stats.get("bytes accessed"))
        nbytes = None if raw_bytes is None else float(raw_bytes or 0)
        out.append(OpRecord(
            name=name,
            program=str(stats.get("hlo_module", "")),
            plane=plane_name,
            category=classify(name),
            duration_ps=dur,
            self_ps=max(dur - child_box[0], 0),
            flops=flops,
            bytes_accessed=nbytes,
            line=line.name,
        ))
    return out


def step_times_us(paths: Iterable[str]) -> List[float]:
    """Device step durations (us) from the 'Steps' line of the device
    plane — the profiler's own step markers, the authoritative wall time
    per train step (r5: 'XLA Ops' self-time sums exceed it because async
    copies overlap compute)."""
    xplane_pb2 = _xplane_pb2()
    steps: List[float] = []
    for path in paths:
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        for plane in xs.planes:
            if not plane.name.startswith("/device:"):
                continue
            for line in plane.lines:
                if line.name == "Steps":
                    steps.extend(e.duration_ps / 1e6 for e in line.events)
    return steps


def parse_xspace(paths: Iterable[str]) -> List[OpRecord]:
    """All HLO-op execution records across the capture's planes."""
    xplane_pb2 = _xplane_pb2()
    records: List[OpRecord] = []
    for path in paths:
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        for plane in xs.planes:
            stat_names = _stat_lookup(plane)
            ev_names = {m.id: m.name for m in plane.event_metadata.values()}
            for line in plane.lines:
                records.extend(
                    _line_records(plane.name, line, ev_names, stat_names))
    return records
