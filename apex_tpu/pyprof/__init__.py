"""LEGACY shim — profiling lives in :mod:`apex_tpu.observability.profiling`.

This package keeps the reference's ``apex.pyprof`` API names (``init``,
``nvtx.range_push/pop``, ``annotate``, ``wrap``) so reference-style
instrumentation ports unchanged, and hosts the xplane parser/report
internals (:mod:`~apex_tpu.pyprof.parse`, :mod:`~apex_tpu.pyprof.prof`)
the new layer consumes. Everything user-facing delegates:

- instrumentation → :func:`apex_tpu.observability.profiling.span`
  (ring buffer + ``TraceAnnotation`` + ``named_scope``) — an
  ``annotate``/``wrap`` region now also lands in the span ring and in
  Perfetto exports, not just the live profiler timeline;
- trace analysis → :mod:`apex_tpu.observability.profiling.xplane`
  (per-phase device attribution; ``tools/trace_report.py`` is the CLI);
- stall diagnostics → the
  :class:`~apex_tpu.observability.profiling.flight_recorder.FlightRecorder`.

New code should import from ``apex_tpu.observability.profiling``
directly; see docs/profiling.md.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Optional

from apex_tpu.pyprof import parse, prof  # noqa: F401 (re-export)
from apex_tpu.pyprof.prof import Report  # noqa: F401

_enabled = False
_trace_dir: Optional[str] = None


def init(enable_trace: bool = True, trace_dir: str = "/tmp/apex_tpu_trace"):
    """ref apex/pyprof/nvtx/nvmarker.py init: start instrumentation."""
    global _enabled, _trace_dir
    _enabled = enable_trace
    _trace_dir = trace_dir


def start():
    """Begin a profiler trace (analog of cuda profiler start)."""
    if _enabled and _trace_dir:
        import jax

        jax.profiler.start_trace(_trace_dir)


def stop():
    if _enabled and _trace_dir:
        import jax

        jax.profiler.stop_trace()


class nvtx:
    """nvtx-shaped annotation API; ranges become spans on every
    timeline (ring buffer, host TraceAnnotation, HLO metadata)."""

    _stack = []

    @staticmethod
    def range_push(name: str):
        from apex_tpu.observability.profiling.spans import span

        # the push/pop pair IS the reference nvtx API — the stack
        # guarantees the close that a `with` would
        ctx = span(name)  # apex-lint: disable=unclosed-span
        ctx.__enter__()
        nvtx._stack.append(ctx)

    @staticmethod
    def range_pop():
        if nvtx._stack:
            nvtx._stack.pop().__exit__(None, None, None)


@contextlib.contextmanager
def annotate(name: str):
    from apex_tpu.observability.profiling.spans import span

    with span(name):
        yield


def wrap(fn, name: Optional[str] = None):
    """Decorate ``fn`` so every call is an annotated range (ref pyprof wraps
    torch functions module-wide; explicit opt-in here)."""
    from apex_tpu.observability.profiling.spans import span

    label = name or getattr(fn, "__name__", "fn")

    @functools.wraps(fn)
    def wrapped(*a, **kw):
        with span(label):
            return fn(*a, **kw)

    return wrapped
