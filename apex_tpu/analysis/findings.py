"""Finding model, suppression comments, and the checked-in baseline.

A ``Finding`` is one report from either engine. Its ``key`` deliberately
excludes the line number: the baseline must survive unrelated edits above
a grandfathered finding, so identity is (check, path, symbol) plus an
occurrence counter handled by the baseline diff (two findings of the same
check in the same function count as two baseline slots).

Suppression (AST engine only — jaxpr findings have no source line):

    x = float(loss)  # apex-lint: disable=host-in-jit
    # apex-lint: disable=sync-timing        <- or on the line above

``# apex-lint: disable`` with no ids suppresses every check on that line.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import os
import re

SEVERITIES = ("error", "warning")

_SUPPRESS_RE = re.compile(
    r"#\s*apex-lint:\s*disable(?:=([a-z0-9_,\- ]+))?", re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class Finding:
    check: str        # check id, e.g. "donation" or "sync-timing"
    severity: str     # "error" | "warning"
    path: str         # repo-relative source path, or "<jaxpr:target>"
    line: int         # 1-based source line; 0 when not source-mapped
    symbol: str       # enclosing function / analysis-target name
    message: str

    @property
    def key(self) -> str:
        return f"{self.check}:{self.path}:{self.symbol}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.severity}] {self.check}: {self.message}" \
               f" (in {self.symbol})"


def suppressed_checks(source_lines, lineno: int):
    """Check ids suppressed at 1-based ``lineno`` (same line, or a
    comment-ONLY line directly above — a trailing comment on the
    previous code line suppresses that line, not this one). Returns
    None for "none", or a set; the empty set means ALL."""
    ids = None
    for ln in (lineno, lineno - 1):
        if not 1 <= ln <= len(source_lines):
            continue
        text = source_lines[ln - 1]
        if ln != lineno and not text.lstrip().startswith("#"):
            continue
        m = _SUPPRESS_RE.search(text)
        if m:
            named = m.group(1)
            if not named:
                return set()   # bare disable: everything
            ids = (ids or set()) | {
                s.strip() for s in named.split(",") if s.strip()}
    return ids


def is_suppressed(finding: Finding, source_lines) -> bool:
    ids = suppressed_checks(source_lines, finding.line)
    if ids is None:
        return False
    return not ids or finding.check in ids


# ------------------------------------------------------------- baseline

def load_baseline(path) -> collections.Counter:
    """Baseline file -> Counter of grandfathered finding keys."""
    with open(path) as f:
        data = json.load(f)
    return collections.Counter(data.get("grandfathered", {}))


def save_baseline(path, findings) -> None:
    counts = collections.Counter(f.key for f in findings)
    with open(path, "w") as f:
        json.dump({
            "_comment": (
                "apex_tpu.analysis grandfathered findings. Keys are "
                "check:path:symbol; values are allowed occurrence counts. "
                "Regenerate with: python -m apex_tpu.analysis "
                "--write-baseline <this file>. Shrink it, never grow it."),
            "grandfathered": dict(sorted(counts.items())),
        }, f, indent=2, sort_keys=False)
        f.write("\n")


def new_findings(findings, baseline: collections.Counter):
    """Findings not covered by the baseline (multiplicity-aware)."""
    budget = collections.Counter(baseline)
    fresh = []
    for f in findings:
        if budget[f.key] > 0:
            budget[f.key] -= 1
        else:
            fresh.append(f)
    return fresh


# --------------------------------------------------- snippet fingerprint
#
# A Finding's key embeds its PATH, so renaming/moving a file makes every
# grandfathered finding in it look NEW to `--diff` (the base dump's keys
# all name the old path). The fingerprint is the path-free identity:
# check + symbol + the flagged source LINE's text (whitespace-stripped).
# `--diff` falls back to it when the path:symbol key misses, so a pure
# rename/move never fails the gate while a genuinely new occurrence
# (different code, or one MORE of the same snippet than the base had —
# multiplicity-aware both ways) still does. Only source-mapped findings
# (line > 0) get one: jaxpr findings live at synthetic paths that never
# rename.


def finding_fingerprint(finding: Finding, root=None, lines_cache=None):
    """Stable ``check:symbol:snippet`` hash for a source-mapped finding,
    or None when the source line cannot be read (jaxpr findings,
    deleted files). ``lines_cache``: optional per-RUN dict (path ->
    line list or None) so N findings in one file cost one read; scope
    it to a single invocation — never across runs, files get rewritten
    between them."""
    if finding.line <= 0:
        return None
    path = finding.path
    if root is not None and not os.path.isabs(path):
        path = os.path.join(root, path)
    lines = lines_cache.get(path) if lines_cache is not None else None
    if lines is None:
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except (OSError, UnicodeDecodeError):
            lines = []
        if lines_cache is not None:
            lines_cache[path] = lines
    try:
        snippet = lines[finding.line - 1].strip()
    except IndexError:
        return None
    digest = hashlib.sha1(
        f"{finding.check}:{finding.symbol}:{snippet}".encode()
    ).hexdigest()
    return digest[:16]


def new_findings_with_fingerprints(findings, baseline, base_fps,
                                   root=None):
    """:func:`new_findings`, with a second chance for findings whose
    path-keyed identity missed but whose snippet fingerprint is in the
    base run (``base_fps``: Counter of fingerprints) — the
    renamed/moved-file case."""
    budget = collections.Counter(baseline)
    fp_budget = collections.Counter(base_fps or {})
    lines_cache: dict = {}

    def fp_of(f):
        return finding_fingerprint(f, root=root,
                                   lines_cache=lines_cache) \
            if fp_budget else None

    # Two passes, NOT one: every path-keyed match must land (and
    # consume its fingerprint slot — a copy-paste duplicate may not
    # ride the renamed-file budget) BEFORE any fallback matching, or
    # the verdict depends on finding order (a duplicate whose path
    # sorts before the original would steal the fingerprint slot and
    # be silently grandfathered).
    unmatched = []
    for f in findings:
        if budget[f.key] > 0:
            budget[f.key] -= 1
            fp = fp_of(f)
            if fp is not None and fp_budget[fp] > 0:
                fp_budget[fp] -= 1
        else:
            unmatched.append(f)
    fresh = []
    for f in unmatched:
        fp = fp_of(f)
        if fp is not None and fp_budget[fp] > 0:
            fp_budget[fp] -= 1
            continue
        fresh.append(f)
    return fresh
