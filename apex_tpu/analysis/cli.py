"""``python -m apex_tpu.analysis`` — run both lint engines.

    python -m apex_tpu.analysis                       # default target set
    python -m apex_tpu.analysis apex_tpu/ops bench.py # AST over a subset
    python -m apex_tpu.analysis --no-jaxpr            # AST engine only
    python -m apex_tpu.analysis --baseline tests/run_analysis/baseline.json
    python -m apex_tpu.analysis --write-baseline tests/run_analysis/baseline.json
    python -m apex_tpu.analysis --json > base.json   # on the base rev
    python -m apex_tpu.analysis --diff base.json     # fail only on NEW
    python -m apex_tpu.analysis --allow my_target:master-weights
    python -m apex_tpu.analysis --list-checks
    python -m apex_tpu.analysis --list-targets       # registered targets + engine
    python -m apex_tpu.analysis --engines ast,state  # engine subset
    python -m apex_tpu.analysis plan --target llama  # auto-shard planner

Exit codes: 0 clean (or all findings grandfathered), 1 new findings,
2 a registered jaxpr target failed to trace.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from apex_tpu.analysis import ast_checks, findings as findings_mod, targets
from apex_tpu.analysis.concurrency_checks import CONCURRENCY_CHECKS
from apex_tpu.analysis.jaxpr_checks import JAXPR_CHECKS
from apex_tpu.analysis.memory_checks import MEMORY_CHECKS
from apex_tpu.analysis.precision_checks import PRECISION_CHECKS
from apex_tpu.analysis.sharding_checks import SHARDING_CHECKS
from apex_tpu.analysis.spmd_checks import SPMD_CHECKS
from apex_tpu.analysis.state_checks import STATE_CHECKS

DEFAULT_PATHS = ("apex_tpu", "examples", "tools", "bench.py")

# Engines the per-target wall time rolls up into (the lint summary's
# gate-latency line — the unified-interpreter speedup and any future
# regression show up here, per ISSUE 8 satellite). Also the vocabulary
# of --engines selection.
ENGINE_NAMES = ("ast", "concurrency", "jaxpr", "dataflow", "sharding",
                "spmd", "state", "memory", "serving")

# The engines that run via the registered tracing targets (everything
# in ENGINE_NAMES except the two path-driven ones).
_TRACING_ENGINES = frozenset(ENGINE_NAMES) - {"ast", "concurrency"}

# Total-wall-time budget for one gate run (ISSUE 14 satellite): the
# engine stack keeps growing, and tier-1 runs the gate every round — a
# silently-slowing gate rots the whole suite's latency. The default is
# deliberately generous (the full run is ~10s today); override with
# LINT_TIME_BUDGET_S, or set it <= 0 to disable.
DEFAULT_TIME_BUDGET_S = 180.0

# Version of the --json payload; bump when its shape changes so
# downstream readers (tools/metrics_report.py) can dispatch on it.
# Version 1 payloads MAY additionally carry a per-finding "fingerprint"
# (check+symbol+snippet hash, see findings.finding_fingerprint) — an
# additive field old readers ignore; --diff uses it to survive file
# renames/moves.
JSON_SCHEMA_VERSION = 1


def _default_paths(root):
    return [p for p in DEFAULT_PATHS if os.path.exists(
        os.path.join(root, p))]


def known_checks():
    return (set(ast_checks.AST_CHECKS) | set(CONCURRENCY_CHECKS)
            | set(JAXPR_CHECKS)
            | set(PRECISION_CHECKS) | set(SHARDING_CHECKS)
            | set(SPMD_CHECKS) | set(STATE_CHECKS)
            | set(MEMORY_CHECKS) | set(targets.TARGET_CHECKS))


def target_engine(target_name):
    """Which ENGINE_NAMES bucket a registered target's wall time and
    findings roll up into."""
    # serving first: its targets also live in the spmd/state/memory
    # family tuples (their checks are those families') but their wall
    # time gets the dedicated serving bucket
    return ("serving" if target_name in targets.SERVING_TARGETS else
            "dataflow" if target_name in targets.PRECISION_TARGETS else
            "sharding" if target_name in targets.SHARDING_TARGETS else
            "spmd" if target_name in targets.SPMD_TARGETS else
            "state" if target_name in targets.STATE_TARGETS else
            "memory" if target_name in targets.MEMORY_TARGETS else
            "jaxpr")


def parse_engines(spec):
    """--engines value -> validated frozenset of engine names; loud on
    typos and on an empty selection (either would silently run
    nothing/everything forever)."""
    if spec is None:
        return None
    if isinstance(spec, str):
        spec = [e.strip() for e in spec.split(",") if e.strip()]
    engines = frozenset(spec)
    if not engines:
        raise ValueError(
            f"--engines selected no engine; valid: {list(ENGINE_NAMES)}")
    unknown = engines - set(ENGINE_NAMES)
    if unknown:
        raise ValueError(
            f"unknown engine(s) {sorted(unknown)}; valid: "
            f"{list(ENGINE_NAMES)}")
    return engines


def load_diff_report(path):
    """A stored ``--json`` dump -> (Counter of finding keys, Counter of
    snippet fingerprints) — the --diff base. Loud on anything that is
    not an apex_tpu.analysis report of a schema this reader knows — a
    silently-ignored base would report every finding as old forever.
    Fingerprints are absent from pre-rename-fix dumps; the fallback
    then simply never matches (the old, path-keyed behavior)."""
    import collections

    with open(path) as f:
        try:
            data = json.load(f)
        except ValueError as e:
            raise ValueError(f"--diff base {path} is not JSON: {e}")
    if not isinstance(data, dict) or \
            data.get("kind") != "apex_tpu.analysis":
        raise ValueError(
            f"--diff base {path} is not an apex_tpu.analysis --json "
            f"dump (missing kind header)")
    version = data.get("schema_version")
    if version not in (JSON_SCHEMA_VERSION,):
        raise ValueError(
            f"--diff base {path} has schema_version {version}; this "
            f"reader knows [{JSON_SCHEMA_VERSION}]")
    keys = collections.Counter()
    fps = collections.Counter()
    for f in data.get("findings", ()):
        keys[f"{f.get('check')}:{f.get('path')}:{f.get('symbol')}"] += 1
        if f.get("fingerprint"):
            fps[f["fingerprint"]] += 1
    return keys, fps


def parse_allow(entries):
    """['target:check', ...] -> {target: {check, ...}}; loud on typos
    (an allow matching nothing would silently stop allowing). Only
    target-emittable check ids are accepted — an AST id here could
    never filter anything (same rule as @target(allow=...))."""
    target_checks = set(targets.TRACING_CHECKS) | set(
        targets.TARGET_CHECKS)
    allow: dict = {}
    for entry in entries or ():
        target_name, sep, check = entry.partition(":")
        if not sep or not target_name or not check:
            raise ValueError(
                f"--allow expects target:check, got {entry!r}")
        if target_name not in targets.TARGETS:
            raise ValueError(
                f"--allow names unknown target {target_name!r}; valid: "
                f"{sorted(targets.TARGETS)}")
        if check not in target_checks:
            raise ValueError(
                f"--allow names check id {check!r} that no jaxpr "
                f"target can emit; valid: {sorted(target_checks)}")
        allow.setdefault(target_name, set()).add(check)
    return allow


def run(paths=None, root=None, ast=True, jaxpr=True, concurrency=True,
        checks=None, allow=None, engine_seconds=None, engines=None):
    """Programmatic entry: returns (findings, target_errors).

    ``allow``: {target: {check ids}} per-target grandfather, merged over
    the ``@target(allow=...)`` declarations. ``engine_seconds``: an
    optional dict that receives per-engine wall time (keys
    :data:`ENGINE_NAMES`) — the gate-latency breakdown the lint summary
    prints. The concurrency engine shares the AST engine's path list,
    so ``--changed-only`` narrowing applies to both. ``engines``: an
    iterable of :data:`ENGINE_NAMES` to restrict the run to (validated
    loudly); composes with the ``--no-*`` flags (both must select an
    engine) and with ``checks`` (intersection).
    """
    engines = parse_engines(engines)
    if engines is not None:
        ast = ast and "ast" in engines
        concurrency = concurrency and "concurrency" in engines
    if checks:
        unknown = set(checks) - known_checks()
        if unknown:
            # a typo'd id silently matching nothing would report a clean
            # run forever — fail loudly instead
            raise ValueError(
                f"unknown check id(s): {sorted(unknown)}; valid: "
                f"{sorted(known_checks())}")
    root = os.path.abspath(root or os.getcwd())
    use = [os.path.join(root, p) if not os.path.isabs(p) else p
           for p in (paths or _default_paths(root))]
    if paths:
        # validate EXPLICIT paths regardless of engine selection: a
        # typo'd path yielding zero files would report a clean run
        # forever — same failure mode as a typo'd check id
        missing = [p for p in use if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(
                f"lint path(s) do not exist: {missing}")
    all_findings, errors = [], {}
    if ast:
        ast_ids = (set(checks) & set(ast_checks.AST_CHECKS)
                   if checks else None)
        if ast_ids is None or ast_ids:
            t0 = time.perf_counter()  # apex-lint: disable=raw-clock
            all_findings += ast_checks.lint_paths(use, root=root,
                                                 checks=ast_ids)
            if engine_seconds is not None:
                engine_seconds["ast"] = (
                    engine_seconds.get("ast", 0.0)
                    + time.perf_counter() - t0)  # apex-lint: disable=raw-clock
    if concurrency:
        from apex_tpu.analysis import concurrency_checks
        conc_ids = (set(checks) & set(CONCURRENCY_CHECKS)
                    if checks else None)
        if conc_ids is None or conc_ids:
            t0 = time.perf_counter()  # apex-lint: disable=raw-clock
            all_findings += concurrency_checks.lint_paths(
                use, root=root, checks=conc_ids)
            if engine_seconds is not None:
                engine_seconds["concurrency"] = (
                    engine_seconds.get("concurrency", 0.0)
                    + time.perf_counter() - t0)  # apex-lint: disable=raw-clock
    if jaxpr:
        if checks is None or set(checks) & set(targets.TRACING_CHECKS):
            names = None  # tracing targets can emit any tracing check
        else:
            # only the (cheap, non-tracing) targets whose checks were
            # asked for — skips the kernel trace suite
            names = set(checks) & set(targets.TARGET_CHECKS)
        if engines is not None:
            tracing = engines & _TRACING_ENGINES
            wanted = {t for t in targets.TARGETS
                      if target_engine(t) in tracing}
            names = wanted if names is None else set(names) & wanted
        if names is None or names:
            per_target = {} if engine_seconds is not None else None
            jf, errors = targets.run_targets(names, extra_allow=allow,
                                             timings=per_target)
            if per_target is not None:
                for target_name, seconds in per_target.items():
                    engine = target_engine(target_name)
                    engine_seconds[engine] = engine_seconds.get(
                        engine, 0.0) + seconds
            if checks:
                jf = [f for f in jf if f.check in checks]
            all_findings += jf
    return all_findings, errors


def sarif_report(findings, root=None) -> dict:
    """Findings -> a SARIF 2.1.0 ``run`` document (ISSUE 19 satellite):
    one reporting rule per known check id (stable, sorted — present
    even at 0 results so viewers can enumerate the rule set), one
    result per finding. Deterministic on purpose: no clocks, sorted
    rule table, insertion order of results follows the CLI's sorted
    finding order — re-exporting the same run yields a byte-identical
    file. Snippet fingerprints (:func:`findings.finding_fingerprint`)
    land in ``partialFingerprints`` so SARIF consumers get the same
    rename-survival the ``--diff`` gate uses; jaxpr findings (line 0,
    ``<jaxpr:target>`` paths) carry a logical location instead of a
    physical one — there is no file region to point at."""
    rule_ids = sorted(known_checks())
    rule_index = {cid: i for i, cid in enumerate(rule_ids)}
    lines_cache: dict = {}
    results = []
    for f in findings:
        result = {
            "ruleId": f.check,
            "ruleIndex": rule_index.get(f.check, -1),
            "level": f.severity if f.severity in ("error", "warning")
            else "warning",
            "message": {"text": f.message},
        }
        if f.line > 0:
            result["locations"] = [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace(os.sep, "/")},
                    "region": {"startLine": f.line},
                },
            }]
        else:
            result["locations"] = [{
                "logicalLocations": [{
                    "name": f.symbol,
                    "fullyQualifiedName": f"{f.path}:{f.symbol}",
                }],
            }]
        fp = findings_mod.finding_fingerprint(f, root=root,
                                              lines_cache=lines_cache)
        if fp:
            result["partialFingerprints"] = {
                "apexTpuFingerprint/v1": fp}
        results.append(result)
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "apex_tpu.analysis",
                "informationUri":
                    "https://github.com/apex-tpu/apex-tpu",
                "rules": [{"id": cid} for cid in rule_ids],
            }},
            "results": results,
        }],
    }


def write_sarif(path, findings, root=None):
    with open(path, "w") as f:
        f.write(json.dumps(sarif_report(findings, root=root),
                           indent=2, sort_keys=True) + "\n")


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "plan":
        # subcommand: the auto-sharding planner (ISSUE 8) rides the
        # same module entry so `python -m apex_tpu.analysis plan
        # --target llama` is the one front door to the analysis stack
        from apex_tpu.analysis import planner
        return planner.main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="apex_tpu static TPU lint (jaxpr + AST engines)")
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs for the AST engine "
                         f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--root", default=None,
                    help="repo root findings are reported relative to "
                         "(default: cwd)")
    ap.add_argument("--no-ast", dest="ast", action="store_false")
    ap.add_argument("--no-jaxpr", dest="jaxpr", action="store_false")
    ap.add_argument("--no-concurrency", dest="concurrency",
                    action="store_false",
                    help="skip the host-concurrency engine (it shares "
                         "the AST engine's path list)")
    ap.add_argument("--checks", default=None,
                    help="comma-separated check ids to run")
    ap.add_argument("--engines", default=None,
                    help=f"comma-separated engine subset to run "
                         f"(valid: {','.join(ENGINE_NAMES)}); composes "
                         f"with --checks and tools/lint.sh "
                         f"--changed-only")
    ap.add_argument("--allow", action="append", default=[],
                    metavar="TARGET:CHECK",
                    help="drop findings of CHECK from jaxpr TARGET "
                         "(repeatable) — per-target grandfather for "
                         "deliberate violations")
    ap.add_argument("--baseline", default=None,
                    help="JSON baseline of grandfathered findings; only "
                         "NEW findings fail the run")
    ap.add_argument("--diff", default=None, metavar="REPORT.json",
                    help="a stored --json dump to diff against: only "
                         "findings not in that run fail (composes with "
                         "--baseline; tools/lint.sh --changed-only "
                         "feeds it a merge-base run via "
                         "LINT_DIFF_REPORT)")
    ap.add_argument("--write-baseline", default=None, metavar="PATH",
                    help="write current findings as the baseline and exit")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--sarif", default=None, metavar="OUT.json",
                    help="also write the (post-baseline) findings as a "
                         "SARIF 2.1.0 report — one rule per check id, "
                         "snippet fingerprints as partialFingerprints; "
                         "byte-stable across identical runs")
    ap.add_argument("--list-checks", action="store_true")
    ap.add_argument("--list-targets", action="store_true",
                    help="print the registered tracing targets and the "
                         "engine each rolls up into, then exit")
    args = ap.parse_args(argv)

    if args.list_checks:
        for cid in ast_checks.AST_CHECKS:
            print(f"{cid:32s} [ast]")
        for cid in CONCURRENCY_CHECKS:
            print(f"{cid:32s} [concurrency]")
        for cid in JAXPR_CHECKS:
            print(f"{cid:32s} [jaxpr]")
        for cid in PRECISION_CHECKS:
            print(f"{cid:32s} [jaxpr/dataflow]")
        for cid in SHARDING_CHECKS:
            print(f"{cid:32s} [jaxpr/sharding]")
        for cid in SPMD_CHECKS:
            print(f"{cid:32s} [jaxpr/spmd]")
        for cid in STATE_CHECKS:
            print(f"{cid:32s} [jaxpr/state]")
        for cid in MEMORY_CHECKS:
            print(f"{cid:32s} [jaxpr/memory]")
        for cid in targets.TARGET_CHECKS:
            print(f"{cid:32s} [jaxpr]")
        return 0

    if args.list_targets:
        for name in targets.TARGETS:
            print(f"{name:36s} [{target_engine(name)}]")
        return 0

    checks = None
    if args.checks:
        checks = {c.strip() for c in args.checks.split(",") if c.strip()}

    engine_seconds: dict = {}
    try:
        allow = parse_allow(args.allow)
        # validate the diff base BEFORE the (expensive) run: a bad base
        # should fail in milliseconds, not after tracing every target
        diff_keys = diff_fps = None
        if args.diff:
            diff_keys, diff_fps = load_diff_report(args.diff)
        found, errors = run(paths=args.paths or None, root=args.root,
                            ast=args.ast, jaxpr=args.jaxpr,
                            concurrency=args.concurrency, checks=checks,
                            allow=allow, engine_seconds=engine_seconds,
                            engines=args.engines)
    except (OSError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2
    found.sort(key=lambda f: (f.path, f.line, f.check))

    for name, err in sorted(errors.items()):
        print(f"TARGET ERROR {name}: {err}", file=sys.stderr)

    if args.write_baseline:
        findings_mod.save_baseline(args.write_baseline, found)
        print(f"wrote {len(found)} grandfathered finding(s) to "
              f"{args.write_baseline}")
        return 2 if errors else 0

    fresh = found
    grandfathered = 0
    base_keys = None
    if args.baseline:
        base_keys = findings_mod.load_baseline(args.baseline)
    if diff_keys is not None:
        # per-key MAX, not sum: a finding present in both bases must
        # not double its grandfather budget (a second, genuinely new
        # occurrence of the same key has to fail the gate)
        base_keys = diff_keys if base_keys is None \
            else base_keys | diff_keys
    if base_keys is not None:
        # the diff base's snippet fingerprints give renamed/moved files
        # a second chance: same check+symbol+source line under a new
        # path is churn, not a NEW finding
        fresh = findings_mod.new_findings_with_fingerprints(
            found, base_keys, diff_fps, root=args.root)
        grandfathered = len(found) - len(fresh)

    if args.sarif:
        write_sarif(args.sarif, fresh, root=args.root)
        print(f"sarif -> {args.sarif}", file=sys.stderr)

    timing = "  ".join(
        f"{name} {engine_seconds.get(name, 0.0):.1f}s"
        for name in ENGINE_NAMES)
    total = sum(engine_seconds.values())
    over_budget = _check_time_budget(total)
    if args.json:
        lines_cache: dict = {}
        print(json.dumps({
            "schema_version": JSON_SCHEMA_VERSION,
            "kind": "apex_tpu.analysis",
            "findings": [
                dict(vars(f),
                     fingerprint=findings_mod.finding_fingerprint(
                         f, root=args.root, lines_cache=lines_cache))
                for f in fresh],
            "grandfathered": grandfathered,
            "target_errors": errors,
            "engine_seconds": {k: round(v, 3) for k, v in
                               sorted(engine_seconds.items())},
        }, indent=2))
        print(f"engine wall time: {timing}  (total {total:.1f}s)",
              file=sys.stderr)
    else:
        for f in fresh:
            print(f.render())
        tail = f" ({grandfathered} grandfathered)" \
            if base_keys is not None else ""
        print(f"{len(fresh)} finding(s){tail}", file=sys.stderr)
        print(f"engine wall time: {timing}  (total {total:.1f}s)",
              file=sys.stderr)

    if errors or over_budget:
        return 2
    return 1 if fresh else 0


def _check_time_budget(total_seconds) -> bool:
    """ISSUE 14 satellite: the gate's wall time is itself gated. True
    (and a LOUD stderr report) when the summed engine_seconds exceed
    LINT_TIME_BUDGET_S (default :data:`DEFAULT_TIME_BUDGET_S`; <= 0
    disables). A malformed override is an error, not a silent
    default — a typo'd budget would never fire again."""
    raw = os.environ.get("LINT_TIME_BUDGET_S", "")
    if raw.strip():
        try:
            budget = float(raw)
        except ValueError:
            print(f"LINT_TIME_BUDGET_S={raw!r} is not a number",
                  file=sys.stderr)
            return True
    else:
        budget = DEFAULT_TIME_BUDGET_S
    if budget <= 0 or total_seconds <= budget:
        return False
    print(f"LINT TIME BUDGET EXCEEDED: engines took "
          f"{total_seconds:.1f}s > {budget:.1f}s "
          f"(LINT_TIME_BUDGET_S) — the static gate runs inside tier-1 "
          f"every round; profile the per-engine wall-time line above "
          f"and trim the offending targets (or raise the budget "
          f"deliberately)", file=sys.stderr)
    return True
