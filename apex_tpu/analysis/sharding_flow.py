"""Forward sharding propagation over closed jaxprs — the flow engine
under the sharding checks (ISSUE 4 tentpole).

:mod:`.dataflow` answers dtype-flow questions; this module answers the
*placement* questions that decide whether a distributed step is fast or
silently all-gathers itself to death: where does every value live on
the mesh, which collectives actually move data, and how much HBM does
the live set peak at. The lattice tracks, per jaxpr ``Var``,

- ``spec``            the GSPMD-world partitioning: one entry per array
  dim, each a tuple of mesh axis names (or ``()`` for replicated);
  ``None`` means unknown (the analysis stays quiet rather than guess);
- ``pending``         mesh axes holding *unreduced partial sums* — a
  ``dot_general`` whose contracting dim was sharded produces per-shard
  partials that some later psum / sharding boundary must combine;
- ``distinct``        shard_map-world truth: the mesh axes across which
  the per-shard data can actually *differ*. ``pbroadcast``/``pvary``
  re-type a value without changing its bytes, so they do NOT add axes
  here — which is exactly how a psum of replicated data is caught as a
  dead collective;
- ``from_axis_index`` axes this (integer) value derives from
  ``lax.axis_index`` over — the signal that a dynamic_slice start is
  "my rank's chunk";
- ``psum_axes``       set while the value is (a preserve-chain of) a
  fresh ``psum`` result over those axes — the psum→slice
  reduce-scatter pattern detector's memory.

Sub-jaxprs are entered like :mod:`.dataflow` (``pjit``/``remat``/
``custom_vjp``/``scan``/``while``/``cond`` one-pass). ``shard_map`` is
the world boundary: entering strips the manual axes into ``distinct``;
leaving rebuilds the outer ``spec`` from ``out_names``. ``pallas_call``
stays opaque via in/out avals.

On top of the interpreter, :func:`estimate_hbm_and_comms` runs the
liveness walk: per-value local bytes (global aval bytes over the
product of the sharded axis sizes), last-use liveness with donation
credit (a donated input's buffer dies at its last read; a non-donated
input is caller-owned for the whole step), plus a per-collective
comms-bytes model. Clients subscribe with visitor callbacks;
:mod:`.sharding_checks` builds the five shipped analyses on top. The
engine itself never emits a Finding.
"""

from __future__ import annotations

import dataclasses
import math
import weakref

import numpy as np

from apex_tpu.analysis import interp
from apex_tpu.analysis.interp import MeshCtx

__all__ = [
    "ShardVal", "MeshCtx", "COLLECTIVE_PRIMS", "interpret_sharding",
    "ShardingLattice", "SHARDING_LATTICE",
    "shard_val_for_aval", "spec_from_partition_spec", "local_bytes",
    "collective_bytes", "estimate_hbm_and_comms", "normalize_spec",
    "Liveness", "compute_liveness", "prior_ratio_of",
]

# Call-like primitives whose bodies run in the caller's value world.
_CALL_PRIMS = interp.CALL_PRIMS

# Ops that preserve the value's identity: psum_axes / from_axis_index
# flow through (a reshaped psum result is still "the psum result").
_PRESERVE_PRIMS = frozenset({
    "broadcast_in_dim", "reshape", "squeeze", "expand_dims",
    "stop_gradient", "copy", "convert_element_type", "neg",
    "pbroadcast", "pvary",
})

# Collectives with an axis-name param and (per-device, per-byte) comms
# cost factors as a function of the axis size n. psum is a ring
# allreduce (reduce-scatter + all-gather): 2(n-1)/n. all_gather
# receives the other n-1 shards. ppermute moves the whole block once.
COLLECTIVE_PRIMS = {
    "psum": "axes", "psum2": "axes", "pmin": "axes", "pmax": "axes",
    "all_gather": "axis_name", "all_gather_invariant": "axis_name",
    "all_to_all": "axis_name", "reduce_scatter": "axis_name",
    "psum_scatter": "axis_name", "ppermute": "axis_name",
}

_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "argmax", "argmin",
})


def _axis_names_of(value):
    if value is None:
        return ()
    if isinstance(value, (tuple, list, frozenset, set)):
        out = []
        for v in value:
            out.extend(_axis_names_of(v))
        return tuple(out)
    return (str(value),)


def normalize_spec(partition_spec, ndim):
    """A PartitionSpec (or None) -> canonical per-dim tuple of
    axis-name tuples, padded to ``ndim``."""
    if partition_spec is None:
        return tuple(() for _ in range(ndim))
    entries = []
    for entry in tuple(partition_spec):
        entries.append(_axis_names_of(entry))
    while len(entries) < ndim:
        entries.append(())
    return tuple(entries[:ndim])


spec_from_partition_spec = normalize_spec


@dataclasses.dataclass(frozen=True)
class ShardVal:
    """One point of the sharding lattice (see module docstring)."""

    spec: tuple = None  # per-dim tuples of axis names; None = unknown
    pending: frozenset = frozenset()
    distinct: frozenset = frozenset()
    from_axis_index: frozenset = frozenset()
    psum_axes: frozenset = frozenset()

    def with_(self, **kw) -> "ShardVal":
        return dataclasses.replace(self, **kw)

    def axes_used(self) -> frozenset:
        if self.spec is None:
            return frozenset()
        return frozenset(a for entry in self.spec for a in entry)


def shard_val_for_aval(aval, partition_spec=None,
                       distinct=frozenset()) -> ShardVal:
    ndim = len(getattr(aval, "shape", ()) or ())
    return ShardVal(spec=normalize_spec(partition_spec, ndim),
                    distinct=frozenset(distinct))


def _aval_bytes(aval) -> int:
    shape = tuple(getattr(aval, "shape", ()) or ())
    dtype = getattr(aval, "dtype", "float32")
    try:
        itemsize = np.dtype(str(dtype)).itemsize
    except TypeError:
        # exotic dtypes numpy cannot parse by name — jax's float0
        # tangent (zero bytes) being the one AD actually produces (an
        # int-input value_and_grad trace carries it); trust the dtype's
        # own itemsize when it has one
        itemsize = getattr(dtype, "itemsize", 0) or 0
    return math.prod(shape or (1,)) * itemsize


def local_bytes(aval, val, ctx: MeshCtx) -> int:
    """Per-device bytes of ``aval`` under ``val``'s sharding (global
    bytes over the product of the sharded axis sizes; unknown specs
    count as replicated — the conservative direction for HBM)."""
    nbytes = _aval_bytes(aval)
    if val is None or val.spec is None:
        return nbytes
    denom = 1
    for entry in val.spec:
        for axis in entry:
            denom *= ctx.size(axis)
    return max(1, nbytes // max(1, denom))


def collective_bytes(prim: str, nbytes: int, axis_sizes) -> int:
    """Per-device bytes moved by collective ``prim`` over a per-shard
    operand of ``nbytes`` riding axes of the given sizes."""
    n = 1
    for s in axis_sizes:
        n *= max(1, int(s))
    if n <= 1:
        return 0
    if prim in ("psum", "psum2", "pmin", "pmax"):
        return int(2 * nbytes * (n - 1) / n)
    if prim in ("all_gather", "all_gather_invariant"):
        return nbytes * (n - 1)
    if prim in ("reduce_scatter", "psum_scatter", "all_to_all"):
        return int(nbytes * (n - 1) / n)
    if prim == "ppermute":
        return nbytes
    return nbytes


# ----------------------------------------------------------- transfer

def _merge_specs(specs):
    """Elementwise join of same-rank specs. Returns (spec, conflicts)
    where conflicts is a list of (dim, entry_a, entry_b) that disagree
    (both sharded, differently) — GSPMD has to reshard one side."""
    known = [s for s in specs if s is not None]
    if not known:
        return None, []
    rank = max(len(s) for s in known)
    out, conflicts = [], []
    for d in range(rank):
        entries = [s[d] for s in known if len(s) == rank and s[d]]
        if not entries:
            out.append(())
            continue
        first = entries[0]
        for other in entries[1:]:
            if other != first:
                conflicts.append((d, first, other))
        out.append(first)
    # one mesh axis cannot shard two dims: keep the first occurrence
    seen = set()
    cleaned = []
    for entry in out:
        kept = tuple(a for a in entry if a not in seen)
        seen.update(kept)
        cleaned.append(kept)
    return tuple(cleaned), conflicts


def _join(ins, out_aval):
    present = [v for v in ins if v is not None]
    ndim = len(getattr(out_aval, "shape", ()) or ())
    same_rank = [v.spec for v in present
                 if v.spec is not None and len(v.spec) == ndim]
    spec, _ = _merge_specs(same_rank) if same_rank else (None, [])
    if spec is None and ndim == 0:
        spec = ()
    return ShardVal(
        spec=spec,
        pending=frozenset().union(*(v.pending for v in present))
        if present else frozenset(),
        distinct=frozenset().union(*(v.distinct for v in present))
        if present else frozenset(),
        from_axis_index=frozenset().union(
            *(v.from_axis_index for v in present))
        if present else frozenset(),
    )


def _reshape_spec(spec, in_shape, out_shape):
    """Map a spec across reshape. Dims whose sizes match positionally
    from the front/back keep their entries; anything in the mixed
    middle goes unknown-replicated (the quiet, no-false-positive
    choice)."""
    if spec is None:
        return None
    out = [()] * len(out_shape)
    i = 0
    while (i < len(in_shape) and i < len(out_shape)
           and in_shape[i] == out_shape[i]):
        out[i] = spec[i]
        i += 1
    j = 0
    while (j < len(in_shape) - i and j < len(out_shape) - i
           and in_shape[-1 - j] == out_shape[-1 - j]):
        out[len(out_shape) - 1 - j] = spec[len(in_shape) - 1 - j]
        j += 1
    # an axis must not survive twice after the positional match
    seen = set()
    cleaned = []
    for entry in out:
        kept = tuple(a for a in entry if a not in seen)
        seen.update(kept)
        cleaned.append(kept)
    return tuple(cleaned)


def _dot_general_transfer(eqn, ins, out_aval):
    lhs, rhs = (ins + (None, None))[:2]
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    lhs_spec = lhs.spec if lhs is not None else None
    rhs_spec = rhs.spec if rhs is not None else None
    base = _join(ins, out_aval)
    pending = set(base.pending)
    for spec, cdims in ((lhs_spec, lc), (rhs_spec, rc)):
        if spec is None:
            continue
        for d in cdims:
            if d < len(spec):
                pending.update(spec[d])
    out_spec = None
    if lhs_spec is not None and rhs_spec is not None:
        entries = [lhs_spec[d] for d in lb]
        entries += [lhs_spec[d] for d in range(len(lhs_spec))
                    if d not in lc and d not in lb]
        entries += [rhs_spec[d] for d in range(len(rhs_spec))
                    if d not in rc and d not in rb]
        seen = set()
        cleaned = []
        for entry in entries:
            kept = tuple(a for a in entry if a not in seen)
            seen.update(kept)
            cleaned.append(kept)
        ndim = len(getattr(out_aval, "shape", ()) or ())
        while len(cleaned) < ndim:
            cleaned.append(())
        out_spec = tuple(cleaned[:ndim])
    return base.with_(spec=out_spec, pending=frozenset(pending),
                      from_axis_index=frozenset())


def _transfer(eqn, ins, out_avals, ctx: MeshCtx):
    prim = eqn.primitive.name
    src = next((v for v in ins if v is not None), None)

    if prim in _PRESERVE_PRIMS:
        outs = []
        for aval in out_avals:
            ndim = len(getattr(aval, "shape", ()) or ())
            if src is None:
                outs.append(shard_val_for_aval(aval))
                continue
            if prim == "reshape":
                spec = _reshape_spec(src.spec,
                                     tuple(eqn.invars[0].aval.shape),
                                     tuple(aval.shape))
            elif prim == "broadcast_in_dim":
                spec = [()] * ndim
                bdims = eqn.params.get("broadcast_dimensions", ())
                if src.spec is not None:
                    for sdim, odim in enumerate(bdims):
                        if sdim < len(src.spec) and odim < ndim:
                            spec[odim] = src.spec[sdim]
                spec = tuple(spec)
            elif src.spec is not None and len(src.spec) == ndim:
                spec = src.spec
            else:
                spec = normalize_spec(None, ndim)
            outs.append(src.with_(spec=spec))
        return tuple(outs)

    if prim == "transpose":
        perm = eqn.params.get("permutation", ())
        spec = None
        if src is not None and src.spec is not None:
            spec = tuple(src.spec[p] if p < len(src.spec) else ()
                         for p in perm)
        base = src if src is not None else ShardVal()
        return tuple(base.with_(spec=spec) for _ in out_avals)

    if prim == "dot_general":
        return tuple(_dot_general_transfer(eqn, tuple(ins), a)
                     for a in out_avals)

    if prim in _REDUCE_PRIMS or prim in ("reduce_window_sum",):
        dims = set(eqn.params.get("axes", ()) or ())
        base = _join(ins, out_avals[0])
        pending = set(base.pending)
        spec = None
        if src is not None and src.spec is not None:
            spec = []
            for d, entry in enumerate(src.spec):
                if d in dims:
                    pending.update(entry)
                else:
                    spec.append(entry)
            spec = tuple(spec)
        return tuple(base.with_(spec=spec, pending=frozenset(pending),
                                from_axis_index=frozenset())
                     for _ in out_avals)

    if prim == "axis_index":
        axis = str(eqn.params.get("axis_name"))
        return tuple(ShardVal(spec=normalize_spec(None, 0),
                              distinct=frozenset({axis}),
                              from_axis_index=frozenset({axis}))
                     for _ in out_avals)

    if prim in ("psum", "psum2", "pmin", "pmax"):
        axes = frozenset(_axis_names_of(eqn.params.get("axes")))
        base = _join(ins, out_avals[0])
        return tuple(base.with_(
            pending=base.pending - axes,
            distinct=base.distinct - axes,
            psum_axes=axes if prim in ("psum", "psum2") else frozenset(),
            from_axis_index=frozenset(),
        ) for _ in out_avals)

    if prim in ("all_gather", "all_gather_invariant"):
        axes = frozenset(_axis_names_of(eqn.params.get("axis_name")))
        base = _join(ins, out_avals[0])
        ndim = len(getattr(out_avals[0], "shape", ()) or ())
        return tuple(base.with_(spec=normalize_spec(None, ndim),
                                distinct=base.distinct - axes,
                                psum_axes=frozenset(),
                                from_axis_index=frozenset())
                     for _ in out_avals)

    if prim in ("psum_scatter", "reduce_scatter"):
        axes = frozenset(_axis_names_of(eqn.params.get("axis_name")))
        base = _join(ins, out_avals[0])
        return tuple(base.with_(distinct=base.distinct | axes,
                                pending=base.pending - axes,
                                psum_axes=frozenset(),
                                from_axis_index=frozenset())
                     for _ in out_avals)

    if prim in ("ppermute", "all_to_all"):
        base = _join(ins, out_avals[0])
        return tuple(base.with_(psum_axes=frozenset(),
                                from_axis_index=frozenset())
                     for _ in out_avals)

    if prim == "sharding_constraint":
        sharding = eqn.params.get("sharding")
        pspec = getattr(sharding, "spec", None)
        base = src if src is not None else ShardVal()
        outs = []
        for aval in out_avals:
            ndim = len(getattr(aval, "shape", ()) or ())
            outs.append(base.with_(spec=normalize_spec(pspec, ndim),
                                   pending=frozenset()))
        return tuple(outs)

    if prim in ("slice", "dynamic_slice", "rev", "squeeze", "gather",
                "dynamic_update_slice", "scatter", "scatter-add",
                "select_n", "pad", "concatenate", "iota"):
        base = _join(ins, out_avals[0])
        if prim == "dynamic_slice" and ins and ins[0] is not None:
            # the slice keeps the operand's provenance so a following
            # check can see "this is a chunk of a psum result"
            base = base.with_(psum_axes=ins[0].psum_axes)
        outs = []
        for aval in out_avals:
            ndim = len(getattr(aval, "shape", ()) or ())
            spec = base.spec
            if spec is not None and len(spec) != ndim:
                spec = normalize_spec(None, ndim)
            elif spec is not None and prim in ("slice", "dynamic_slice",
                                               "dynamic_update_slice"):
                in_shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
                out_shape = tuple(getattr(aval, "shape", ()) or ())
                if len(in_shape) == ndim:
                    spec = tuple(
                        entry if in_shape[d] == out_shape[d] else ()
                        for d, entry in enumerate(spec))
            outs.append(base.with_(spec=spec))
        return tuple(outs)

    if prim == "pallas_call":
        present = [v for v in ins if v is not None]
        distinct = frozenset().union(*(v.distinct for v in present)) \
            if present else frozenset()
        return tuple(shard_val_for_aval(a, distinct=distinct)
                     for a in out_avals)

    base = _join(ins, out_avals[0])
    outs = []
    for aval in out_avals:
        ndim = len(getattr(aval, "shape", ()) or ())
        spec = base.spec
        if spec is not None and len(spec) != ndim:
            spec = None if ndim else ()
        outs.append(base.with_(spec=spec))
    return tuple(outs)


# ----------------------------------------------------------- interp

_is_var = interp.is_var
_closed_jaxprs_in = interp.closed_jaxprs_in
_jaxpr_of = interp.jaxpr_of
_consts_of = interp.consts_of


def _names_to_spec(names, ndim):
    """shard_map in_names/out_names entry ({dim: (axes,)}) -> spec."""
    spec = [()] * ndim
    for dim, axes in dict(names or {}).items():
        if int(dim) < ndim:
            spec[int(dim)] = tuple(str(a) for a in axes)
    return tuple(spec)


class ShardingLattice(interp.Lattice):
    """The placement value semantics, plugged into the unified walk
    (:mod:`.interp`). Scan/while carries run the two-pass fixpoint (a
    loop-carried value picks up distinctness on iteration 1 — e.g. a
    pipeline carry init'd to zeros but fed by a ppermute — so the body
    runs once silently and the output carries join into the input
    carries before the visited pass). ``shard_map`` is the world
    boundary: entering strips the manual axes into ``distinct``;
    leaving rebuilds the outer ``spec`` from ``out_names``."""

    name = "sharding"
    warm_carry_join = True

    def for_aval(self, aval):
        return shard_val_for_aval(aval)

    def transfer(self, eqn, ins, out_avals, ctx):
        return _transfer(eqn, ins, out_avals, ctx)

    def bind_sub(self, aval, val):
        ndim = len(getattr(aval, "shape", ()) or ())
        if val is None:
            return shard_val_for_aval(aval)
        if val.spec is not None and len(val.spec) != ndim:
            return val.with_(spec=normalize_spec(None, ndim))
        return val

    def fix_out(self, aval, val, restack=False):
        ndim = len(getattr(aval, "shape", ()) or ())
        if val is None:
            return shard_val_for_aval(aval)
        if val.spec is not None and len(val.spec) != ndim:
            if restack and len(val.spec) == ndim - 1:
                # stacked scan ys grow a leading (replicated) dim
                return val.with_(spec=((),) + val.spec)
            return val.with_(spec=normalize_spec(None, ndim))
        return val

    def join_branch(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        spec, _ = _merge_specs([a.spec, b.spec]) \
            if a.spec is not None and b.spec is not None \
            and len(a.spec) == len(b.spec) else (None, [])
        return a.with_(
            spec=spec if spec is not None else a.spec,
            pending=a.pending | b.pending,
            distinct=a.distinct | b.distinct,
            from_axis_index=a.from_axis_index | b.from_axis_index,
            psum_axes=a.psum_axes & b.psum_axes,
        )

    join_carry = join_branch

    def map_scan_xs(self, val):
        # xs lose their leading (scan) dim inside the body
        if val.spec:
            return val.with_(spec=val.spec[1:])
        return val

    def shard_map_enter(self, eqn, ins, sub, ctx):
        in_names = eqn.params.get("in_names", ())
        mapped = []
        for i, var in enumerate(sub.invars):
            ndim = len(getattr(var.aval, "shape", ()) or ())
            names = in_names[i] if i < len(in_names) else {}
            consumed = frozenset(
                str(a) for axes in dict(names or {}).values()
                for a in axes)
            outer = ins[i] if i < len(ins) else None
            distinct = consumed | (outer.distinct if outer else
                                   frozenset())
            mapped.append(ShardVal(spec=normalize_spec(None, ndim),
                                   distinct=distinct))
        return mapped

    def shard_map_exit(self, eqn, inner_outs, ctx):
        out_names = eqn.params.get("out_names", ())
        outs = []
        for i, var in enumerate(eqn.outvars):
            ndim = len(getattr(var.aval, "shape", ()) or ())
            names = out_names[i] if i < len(out_names) else {}
            inner = inner_outs[i] if i < len(inner_outs) else None
            pending = inner.pending if inner else frozenset()
            outs.append(ShardVal(spec=_names_to_spec(names, ndim),
                                 pending=pending,
                                 distinct=ctx.manual_axes & (
                                     inner.distinct if inner
                                     else frozenset())))
        return outs


SHARDING_LATTICE = ShardingLattice()


def interpret_sharding(closed, in_vals, axis_sizes=None, visit=None):
    """Run the forward sharding propagation over ``closed`` (a
    ``ClosedJaxpr``).

    ``in_vals``: one :class:`ShardVal` (or None) per flat invar.
    ``axis_sizes``: the mesh axis universe (name -> size); defaults to
    the live ``parallel_state`` mesh when initialized.
    ``visit(eqn, in_vals, out_vals, mesh_ctx)`` runs for every equation
    at every depth. Returns the abstract output values.
    """
    if axis_sizes is None:
        axis_sizes = live_mesh_axis_sizes()
    (outs,) = interp.interpret_lattices(
        closed, [interp.LatticeRun(SHARDING_LATTICE, in_vals, visit)],
        axis_sizes=axis_sizes)
    return outs


def live_mesh_axis_sizes() -> dict:
    """Axis sizes of the live ``parallel_state`` mesh, {} when none."""
    try:
        from apex_tpu.transformer import parallel_state
        if parallel_state.model_parallel_is_initialized():
            return {str(k): int(v) for k, v in
                    dict(parallel_state.get_mesh().shape).items()}
    except Exception:
        pass
    return {}


# ----------------------------------------------- liveness / HBM walk

def _linearize(jaxpr, env, steps):
    """Flatten call-like primitives into one step list (var identity
    mapped into the caller world, as in jaxpr_checks._linearize);
    control flow / shard_map / pallas stay opaque single steps."""
    def canon(v):
        while v in env:
            v = env[v]
        return v

    for eqn in jaxpr.eqns:
        sub = None
        if eqn.primitive.name in _CALL_PRIMS:
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                if key in eqn.params:
                    subs = _closed_jaxprs_in(eqn.params[key])
                    if subs:
                        sub = _jaxpr_of(subs[0])
                        break
        if sub is not None and len(sub.invars) == len(eqn.invars):
            for iv, ov in zip(sub.invars, eqn.invars):
                if _is_var(ov):
                    env[iv] = canon(ov)
            _linearize(sub, env, steps)
            for inner_ov, outer_ov in zip(sub.outvars, eqn.outvars):
                if _is_var(inner_ov):
                    env[outer_ov] = canon(inner_ov)
            continue
        reads = [canon(v) if _is_var(v) else None for v in eqn.invars]
        steps.append((eqn, reads))


# Linearization depends only on the jaxpr structure, never on in_vals
# or the mesh — memoize it so the planner's inner loop (many spec
# candidates x one jaxpr) pays the flattening walk once. Weak keys: the
# cache must not keep a traced program alive after its caller drops it.
_LINEARIZE_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _linearized(jaxpr):
    try:
        hit = _LINEARIZE_CACHE.get(jaxpr)
    except TypeError:  # unhashable/unweakrefable jaxpr: just rebuild
        hit = None
    if hit is None:
        env: dict = {}
        steps: list = []
        _linearize(jaxpr, env, steps)
        hit = (env, steps)
        try:
            _LINEARIZE_CACHE[jaxpr] = hit
        except TypeError:
            pass
    return hit


@dataclasses.dataclass
class Liveness:
    """Per-value live-interval record of one linearized walk — the ONE
    truth under both :func:`estimate_hbm_and_comms` and the
    memory-liveness checks (:mod:`.memory_checks`, ISSUE 19). Every
    field is in the canonical (caller-world) var namespace of
    :func:`_linearize`; steps index into the linearized program.

    ``births[cv]``/``deaths[cv]``: the half-open live interval (a var
    is live at step ``s`` iff ``births[cv] <= s < deaths[cv]``).
    Donation credit shows up as an early death: a donated invar that is
    not returned dies at ``last_use + 1`` instead of surviving the
    whole step."""

    ctx: MeshCtx
    env: dict
    steps: list
    vals: dict
    births: dict
    deaths: dict
    first_use: dict
    last_use: dict
    producer: dict          # canonical var -> (step idx, eqn)
    out_vars: frozenset
    donated_vars: frozenset
    invar_canon: tuple      # canonical var per flat invar index
    n_steps: int
    peak_hbm_bytes: int
    peak_step: int
    comms_bytes: int
    input_bytes: int
    output_bytes: int

    def canon(self, v):
        while v in self.env:
            v = self.env[v]
        return v

    def var_bytes(self, cv) -> int:
        return local_bytes(cv.aval, self.vals.get(cv), self.ctx)

    def live_at(self, step):
        """Canonical vars live at ``step`` (birth <= step < death)."""
        return [cv for cv, b in self.births.items()
                if b <= step < self.deaths[cv]]

    def live_at_peak(self):
        """The peak-composition record: ``(cv, bytes)`` pairs live at
        the modeled peak, largest first."""
        pairs = [(cv, self.var_bytes(cv))
                 for cv in self.live_at(self.peak_step)]
        pairs.sort(key=lambda p: (-p[1], str(p[0])))
        return pairs

    def steady_bytes(self) -> int:
        """Bytes still live when the step returns (outputs plus every
        caller-owned input/const) — the post-peak watermark the
        peak-spike check compares the transient peak against."""
        return sum(self.var_bytes(cv) for cv, d in self.deaths.items()
                   if d > self.n_steps)

    def donation_credit(self):
        """Per flat invar index: True when the input's buffer was
        donated AND actually credited (it dies before the step ends)."""
        out = {}
        for i, cv in enumerate(self.invar_canon):
            out[i] = cv in self.donated_vars and \
                self.deaths.get(cv, self.n_steps + 1) <= self.n_steps
        return out


def compute_liveness(closed, in_vals, donated=frozenset(),
                     axis_sizes=None) -> Liveness:
    """The liveness walk over the linearized program: propagate
    ShardVals, account comms, and assign every canonical var its
    birth/death interval with donation credit. Both the HBM estimator
    and the memory-liveness engine consume this record, so the two can
    never disagree on what is live when.
    """
    if axis_sizes is None:
        axis_sizes = live_mesh_axis_sizes()
    ctx = MeshCtx(axis_sizes)
    jaxpr = closed.jaxpr

    env, steps = _linearized(jaxpr)

    def canon(v):
        while v in env:
            v = env[v]
        return v

    # forward-propagate ShardVals over the linearized steps so every
    # var (at any inlined depth) has a sharding for its byte estimate
    vals: dict = {}
    comms = 0
    for i, var in enumerate(jaxpr.invars):
        v = in_vals[i] if i < len(in_vals) else None
        vals[var] = v if v is not None else shard_val_for_aval(var.aval)
    for var in jaxpr.constvars:
        vals[var] = shard_val_for_aval(var.aval)

    manual = ctx.manual_axes
    for eqn, reads in steps:
        prim = eqn.primitive.name
        ins = tuple(vals.get(r) if r is not None else None
                    for r in reads)
        if prim == "shard_map":
            out_names = eqn.params.get("out_names", ())
            outs = []
            for k, ov in enumerate(eqn.outvars):
                ndim = len(getattr(ov.aval, "shape", ()) or ())
                names = out_names[k] if k < len(out_names) else {}
                outs.append(ShardVal(spec=_names_to_spec(names, ndim)))
            # collectives inside the opaque body still cost comms
            # (trip-count aware: a psum in a scanned body runs once
            # per iteration)
            comms += _control_flow_comms(eqn, ctx)
        elif prim in ("scan", "while", "cond"):
            outs = _transfer(eqn, ins,
                             tuple(v.aval for v in eqn.outvars), ctx)
            comms += _control_flow_comms(eqn, ctx)
        else:
            outs = _transfer(eqn, ins,
                             tuple(v.aval for v in eqn.outvars), ctx)
            if prim in _CALL_PRIMS:
                # a call prim _linearize could not inline (arity
                # mismatch): still sweep its body for collectives
                comms += _control_flow_comms(eqn, ctx)
            param = COLLECTIVE_PRIMS.get(prim)
            if param is not None:
                axes = _axis_names_of(eqn.params.get(param))
                # sum over ALL array operands: a tree psum moves every
                # leaf, not just the first
                nbytes = sum(
                    local_bytes(v.aval, ins[k] if k < len(ins) else
                                None, ctx)
                    for k, v in enumerate(eqn.invars) if _is_var(v))
                comms += collective_bytes(
                    prim, nbytes, [ctx.size(a) for a in axes])
            if prim == "sharding_constraint" and ins and \
                    ins[0] is not None:
                before = ins[0]
                after = outs[0]
                if before.pending:
                    # the boundary resolves partial sums: GSPMD inserts
                    # the allreduce the row-parallel pattern relies on
                    nb = local_bytes(eqn.invars[0].aval, after, ctx)
                    comms += collective_bytes(
                        "psum", nb, [ctx.size(a) for a in before.pending])
                if before.spec is not None and \
                        before.spec != after.spec:
                    gone = before.axes_used() - after.axes_used()
                    if gone:  # all-gather'd axes move the other shards
                        nb = local_bytes(eqn.invars[0].aval, before, ctx)
                        n = 1
                        for a in gone:
                            n *= ctx.size(a)
                        comms += nb * (n - 1)
        for var, val in zip(eqn.outvars, outs):
            vals[var] = val

    # liveness: birth/death step per canonical var
    first_use: dict = {}
    last_use: dict = {}
    for idx, (eqn, reads) in enumerate(steps):
        for r in reads:
            if r is not None:
                first_use.setdefault(r, idx)
                last_use[r] = idx
    out_vars = frozenset(canon(v) for v in jaxpr.outvars if _is_var(v))
    donated_vars = frozenset(canon(jaxpr.invars[i]) for i in donated
                             if i < len(jaxpr.invars))
    n_steps = len(steps)

    def var_bytes(v):
        return local_bytes(v.aval, vals.get(v), ctx)

    births: dict = {}
    deaths: dict = {}
    producer: dict = {}
    for i, var in enumerate(jaxpr.invars):
        cv = canon(var)
        births[cv] = 0
        if cv in donated_vars and cv not in out_vars:
            deaths[cv] = last_use.get(cv, 0) + 1
        else:
            deaths[cv] = n_steps + 1
    for var in jaxpr.constvars:
        cv = canon(var)
        births[cv] = 0
        deaths[cv] = n_steps + 1
    for idx, (eqn, _reads) in enumerate(steps):
        for var in eqn.outvars:
            cv = canon(var)
            if cv in births:
                continue
            births[cv] = idx
            producer[cv] = (idx, eqn)
            if cv in out_vars:
                deaths[cv] = n_steps + 1
            else:
                deaths[cv] = last_use.get(cv, idx) + 1

    events: dict = {}
    for cv, b in births.items():
        nb = var_bytes(cv)
        events[b] = events.get(b, 0) + nb
        events[deaths[cv]] = events.get(deaths[cv], 0) - nb
    peak, cur, peak_step = 0, 0, 0
    for step in sorted(events):
        cur += events[step]
        if cur > peak:
            peak, peak_step = cur, step

    input_bytes = sum(var_bytes(canon(v)) for v in jaxpr.invars)
    output_bytes = sum(var_bytes(canon(v)) for v in jaxpr.outvars
                       if _is_var(v))
    # partial sums still pending at an output: GSPMD resolves them to
    # the (replicated) out sharding with an allreduce at the boundary
    for v in jaxpr.outvars:
        if not _is_var(v):
            continue
        val = vals.get(canon(v))
        if val is not None and val.pending:
            comms += collective_bytes(
                "psum", var_bytes(canon(v)),
                [ctx.size(a) for a in val.pending])
    return Liveness(
        ctx=ctx, env=env, steps=steps, vals=vals, births=births,
        deaths=deaths, first_use=first_use, last_use=last_use,
        producer=producer, out_vars=out_vars,
        donated_vars=donated_vars,
        invar_canon=tuple(canon(v) for v in jaxpr.invars),
        n_steps=n_steps, peak_hbm_bytes=int(peak),
        peak_step=int(peak_step), comms_bytes=int(comms),
        input_bytes=int(input_bytes), output_bytes=int(output_bytes))


def prior_ratio_of(priors):
    """Normalize a prior to a positive finite float ratio. Accepts a
    bare number or a priors-file row (``{"ratio": ...}``); loud on
    anything else — a drifted priors file must never silently price
    the planner's pruning."""
    ratio = priors.get("ratio") if isinstance(priors, dict) else priors
    try:
        ratio = float(ratio)
    except (TypeError, ValueError):
        raise ValueError(
            f"HBM prior must be a number or a {{'ratio': ...}} row, "
            f"got {priors!r}")
    if not math.isfinite(ratio) or ratio <= 0:
        raise ValueError(
            f"HBM prior ratio must be positive and finite, got "
            f"{ratio!r} (from {priors!r})")
    return ratio


def estimate_hbm_and_comms(closed, in_vals, donated=frozenset(),
                           axis_sizes=None, priors=None):
    """Liveness walk over the linearized program (a thin view over
    :func:`compute_liveness` — the memory-liveness engine shares the
    same record).

    ``donated``: flat invar indices whose buffers die at their last
    read (jit donation); everything else is caller-owned for the whole
    step. ``priors``: an optional measured/modeled calibration ratio
    (a number, or an ``analysis/hbm_priors.json`` row) — when given,
    the result additionally carries ``prior_ratio`` and
    ``calibrated_peak_hbm_bytes`` (modeled peak x prior), the bytes
    calibrated consumers (planner pruning, hbm-budget) should price
    on. Returns ``{"peak_hbm_bytes", "input_bytes", "output_bytes",
    "comms_bytes", "peak_step"}`` — all per-device estimates under the
    propagated shardings.
    """
    live = compute_liveness(closed, in_vals, donated=donated,
                            axis_sizes=axis_sizes)
    out = {
        "peak_hbm_bytes": live.peak_hbm_bytes,
        "input_bytes": live.input_bytes,
        "output_bytes": live.output_bytes,
        "comms_bytes": live.comms_bytes,
        "peak_step": live.peak_step,
    }
    if priors is not None:
        ratio = prior_ratio_of(priors)
        out["prior_ratio"] = ratio
        out["calibrated_peak_hbm_bytes"] = int(
            round(live.peak_hbm_bytes * ratio))
    return out


def _jaxpr_comms(jaxpr, ctx: MeshCtx, mult: int) -> int:
    """Per-device comms bytes of the collectives in ``jaxpr``, each
    weighted by ``mult`` executions."""
    total = 0
    for eqn in jaxpr.eqns:
        param = COLLECTIVE_PRIMS.get(eqn.primitive.name)
        if param is not None:
            axes = _axis_names_of(eqn.params.get(param))
            nbytes = sum(_aval_bytes(v.aval)
                         for v in eqn.invars if _is_var(v))
            total += mult * collective_bytes(
                eqn.primitive.name, nbytes,
                [ctx.size(a) for a in axes])
        else:
            total += _control_flow_comms(eqn, ctx, mult)
    return total


def _control_flow_comms(eqn, ctx: MeshCtx, mult: int = 1) -> int:
    """Comms bytes from collectives nested anywhere inside ``eqn``.
    Scan bodies are weighted by their trip count, cond counts its most
    expensive branch (not the sum), while-loop bodies count one
    iteration (an unknowable trip count — a documented floor)."""
    prim = eqn.primitive.name
    params = eqn.params

    if prim == "shard_map":
        shape = getattr(params.get("mesh"), "shape", None)
        sizes = {str(k): int(v) for k, v in dict(shape).items()} \
            if shape else {}
        inner = ctx.child(sizes, sizes.keys())
        return sum(_jaxpr_comms(_jaxpr_of(s), inner, mult)
                   for s in _closed_jaxprs_in(params.get("jaxpr", ())))

    if prim == "scan":
        length = params.get("length") or 1
        return sum(
            _jaxpr_comms(_jaxpr_of(s), ctx, mult * int(length))
            for s in _closed_jaxprs_in(params.get("jaxpr", ())))

    if prim == "cond":
        branches = _closed_jaxprs_in(params.get("branches", ()))
        return max((_jaxpr_comms(_jaxpr_of(b), ctx, mult)
                    for b in branches), default=0)

    total = 0
    for value in params.values():
        for sub in _closed_jaxprs_in(value):
            total += _jaxpr_comms(_jaxpr_of(sub), ctx, mult)
    return total
