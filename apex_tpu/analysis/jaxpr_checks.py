"""Engine 1: jaxpr-level TPU lint.

Traces a function with abstract avals (``jax.make_jaxpr`` — no backend,
no compile; runs fine under ``JAX_PLATFORMS=cpu``) and walks the closed
jaxpr for the bug classes round 5's VERDICT showed slip past review:

- ``donation``        donated input aliased into an output by a
                      ``pallas_call`` and read again afterwards (in-place
                      clobber / defeated donation), or donated with no
                      aval-matching output (wasted donation).
- ``recompile``       retrace-per-step hazards: weak-typed Python-scalar
                      arguments and large closed-over concrete arrays
                      baked into the trace.
- ``collective-axis`` ``psum``/``ppermute``/``all_gather``/... axis names
                      checked against the live mesh axes (default: the
                      ``transformer.parallel_state`` mesh), plus
                      ``ppermute`` permutation validation — the
                      mismatches that deadlock multichip runs.
- ``pallas-block``    every ``pl.pallas_call`` BlockSpec checked for
                      (sublane, 128) tiling alignment by dtype and a
                      double-buffered VMEM residency estimate against
                      ``ops.pallas_config.device_vmem_bytes()``.

Entry point: :func:`analyze_fn`.
"""

from __future__ import annotations

import math

import numpy as np

from apex_tpu.analysis.findings import Finding

JAXPR_CHECKS = ("donation", "recompile", "collective-axis", "pallas-block")

# Call-like primitives inlined for the donation liveness walk: their
# bodies execute in the caller's buffer world, so reads inside them are
# reads of the caller's (possibly donated) buffers.
_INLINE_PRIMS = {"pjit", "closed_call", "core_call", "custom_jvp_call",
                 "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                 "checkpoint"}

# Collective primitives and the param carrying their axis name(s).
_COLLECTIVE_AXIS_PARAMS = {
    "psum": "axes", "psum2": "axes", "pmin": "axes", "pmax": "axes",
    "ppermute": "axis_name", "pbroadcast": "axes",
    "all_gather": "axis_name", "all_gather_invariant": "axis_name",
    "all_to_all": "axis_name", "reduce_scatter": "axis_name",
    "psum_scatter": "axis_name", "axis_index": "axis_name",
}

# Sublane multiple (second-minor tile dim) by dtype itemsize; the lane
# (minor) dim is always 128 (pallas_guide.md tiling table).
_SUBLANE_BY_ITEMSIZE = {8: 8, 4: 8, 2: 16, 1: 32}
_LANE = 128


def _closed_jaxprs_in(value):
    """Jaxpr-like objects inside an eqn param value."""
    import jax.core as core
    out = []
    if isinstance(value, core.ClosedJaxpr):
        out.append(value.jaxpr)
    elif isinstance(value, core.Jaxpr):
        out.append(value)
    elif isinstance(value, (tuple, list)):
        for v in value:
            out.extend(_closed_jaxprs_in(v))
    return out


def _canon(env, v):
    while v in env:
        v = env[v]
    return v


def _is_var(v):
    import jax.core as core
    return isinstance(v, core.Var)


def _linearize(jaxpr, env, steps):
    """Flatten call-like primitives into one eqn sequence, mapping inner
    vars onto their caller operands so a read inside a pjit body counts
    as a read of the caller's (donated) buffer."""
    for eqn in jaxpr.eqns:
        sub = None
        if eqn.primitive.name in _INLINE_PRIMS:
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                if key in eqn.params:
                    subs = _closed_jaxprs_in(eqn.params[key])
                    if subs:
                        sub = subs[0]
                        break
        if sub is not None and len(sub.invars) == len(eqn.invars):
            for iv, ov in zip(sub.invars, eqn.invars):
                if _is_var(ov):
                    env[iv] = _canon(env, ov)
            _linearize(sub, env, steps)
            for inner_ov, outer_ov in zip(sub.outvars, eqn.outvars):
                if _is_var(inner_ov):
                    env[outer_ov] = _canon(env, inner_ov)
            continue
        # keep Literal slots as None so positional lookups (pallas_call
        # input_output_aliases operand indices) stay aligned
        reads = [_canon(env, v) if _is_var(v) else None
                 for v in eqn.invars]
        steps.append((eqn, reads))


def _walk_all(jaxpr, axis_sizes, out):
    """Yield (eqn, axis_sizes-at-that-depth) for every eqn at any depth,
    tracking axis sizes bound by enclosing shard_map meshes."""
    for eqn in jaxpr.eqns:
        out.append((eqn, axis_sizes))
        inner_sizes = axis_sizes
        if eqn.primitive.name == "shard_map":
            mesh = eqn.params.get("mesh")
            shape = getattr(mesh, "shape", None)
            if shape:
                inner_sizes = dict(axis_sizes)
                inner_sizes.update({str(k): int(v)
                                    for k, v in dict(shape).items()})
        for value in eqn.params.values():
            for sub in _closed_jaxprs_in(value):
                _walk_all(sub, inner_sizes, out)


# ----------------------------------------------------------- the checks

def _donated_invar_indices(example_args, donate_argnums):
    """Map top-level donate_argnums onto flat invar index ranges."""
    import jax
    donate = {donate_argnums} if isinstance(donate_argnums, int) \
        else set(donate_argnums)
    idx, out = 0, {}
    for argnum, arg in enumerate(example_args):
        n = len(jax.tree_util.tree_leaves(arg))
        if argnum in donate:
            for k in range(n):
                out[idx + k] = (argnum, k)
        idx += n
    return out


def check_donation(closed, donated, name, path):
    """donated: {flat invar index: (argnum, leaf)} from the caller."""
    findings = []
    jaxpr = closed.jaxpr
    env: dict = {}
    steps: list = []
    _linearize(jaxpr, env, steps)
    out_avals = [(tuple(v.aval.shape), str(v.aval.dtype))
                 for v in jaxpr.outvars if _is_var(v)]
    outvars = {_canon(env, v) for v in jaxpr.outvars if _is_var(v)}

    for flat_idx, (argnum, leaf) in sorted(donated.items()):
        if flat_idx >= len(jaxpr.invars):
            continue
        var = jaxpr.invars[flat_idx]
        sig = (tuple(var.aval.shape), str(var.aval.dtype))
        where = f"arg {argnum} leaf {leaf} {sig[1]}{list(sig[0])}"

        if var not in outvars and sig not in out_avals:
            findings.append(Finding(
                "donation", "warning", path, 0, name,
                f"donated {where} matches no output shape/dtype: XLA "
                f"cannot reuse the buffer, so donation is wasted and the "
                f"caller still loses the array"))
            continue

        alias_step = None
        for i, (eqn, reads) in enumerate(steps):
            if eqn.primitive.name != "pallas_call" or var not in reads:
                continue
            gm = eqn.params.get("grid_mapping")
            n_index = getattr(gm, "num_index_operands", 0)
            for in_idx, _out_idx in eqn.params.get(
                    "input_output_aliases", ()):
                pos = n_index + in_idx
                if pos < len(reads) and reads[pos] is var:
                    alias_step = i
                    break
            if alias_step is not None:
                break
        if alias_step is None:
            continue
        kernel = str(eqn.params.get("name_and_src_info", "pallas kernel"))
        read_after = None
        for j in range(alias_step + 1, len(steps)):
            later_eqn, later_reads = steps[j]
            if var in later_reads:
                read_after = f"'{later_eqn.primitive.name}'"
                break
        if read_after is None and var in outvars:
            # the pre-alias value is returned directly: same clobber,
            # just read by the caller instead of a later eqn
            read_after = "the caller (it is returned as an output)"
        if read_after is not None:
            findings.append(Finding(
                "donation", "error", path, 0, name,
                f"donated {where} is aliased into an output by "
                f"pallas_call [{kernel}] and read again by "
                f"{read_after} afterwards — the kernel's in-place "
                f"write clobbers the later read (or forces a "
                f"defensive copy that defeats donation)"))
    return findings


_CONST_CAPTURE_MIN_ELEMS = 256


def check_recompile(closed, name, path, example_args=()):
    findings = []
    jaxpr = closed.jaxpr

    import jax
    arg_of_invar = {}
    idx = 0
    for argnum, arg in enumerate(example_args):
        for _ in jax.tree_util.tree_leaves(arg):
            arg_of_invar[idx] = argnum
            idx += 1

    for i, var in enumerate(jaxpr.invars):
        aval = var.aval
        if getattr(aval, "weak_type", False) and \
                getattr(aval, "ndim", None) == 0:
            argnum = arg_of_invar.get(i, i)
            findings.append(Finding(
                "recompile", "warning", path, 0, name,
                f"argument {argnum} is a weak-typed Python scalar "
                f"({aval.dtype}): weak promotion can flip downstream "
                f"dtypes between call sites, and a scalar hyperparameter "
                f"fed this way is one refactor away from a per-value "
                f"retrace — pass jnp.asarray(x, dtype) instead"))

    for const in closed.consts:
        size = int(np.size(const))
        if size >= _CONST_CAPTURE_MIN_ELEMS:
            shape = tuple(np.shape(const))
            dtype = getattr(const, "dtype", type(const).__name__)
            findings.append(Finding(
                "recompile", "warning", path, 0, name,
                f"trace closes over a concrete {dtype}{list(shape)} "
                f"array ({size} elements) baked in as a constant: every "
                f"retrace re-stages it, it bloats the executable, and it "
                f"can neither be donated nor resharded — thread it "
                f"through as an argument"))
    return findings


def _axis_names(value):
    if value is None:
        return []
    if isinstance(value, (tuple, list, frozenset, set)):
        out = []
        for v in value:
            out.extend(_axis_names(v))
        return out
    return [str(value)]


def check_collectives(closed, name, path, mesh_axes=None):
    """``mesh_axes``: the axis universe collectives must live in — a
    dict name->size, an iterable of names, or a Mesh. Default: the live
    ``parallel_state`` mesh when one is initialized, else the axes bound
    by enclosing shard_maps in the trace itself."""
    declared_sizes = {}
    declared = None
    if mesh_axes is None:
        try:
            from apex_tpu.transformer import parallel_state
            if parallel_state.model_parallel_is_initialized():
                mesh_axes = parallel_state.get_mesh()
        except Exception:
            mesh_axes = None
    if mesh_axes is not None:
        shape = getattr(mesh_axes, "shape", None)
        if isinstance(mesh_axes, dict):
            declared_sizes = {str(k): int(v) for k, v in mesh_axes.items()}
            declared = set(declared_sizes)
        elif shape:
            declared_sizes = {str(k): int(v) for k, v in dict(shape).items()}
            declared = set(declared_sizes)
        else:
            declared = {str(a) for a in mesh_axes}

    findings = []
    eqns: list = []
    _walk_all(closed.jaxpr, {}, eqns)
    for eqn, bound_sizes in eqns:
        prim = eqn.primitive.name
        param = _COLLECTIVE_AXIS_PARAMS.get(prim)
        if param is None:
            continue
        axes = _axis_names(eqn.params.get(param))
        valid = declared if declared is not None else set(bound_sizes)
        for ax in axes:
            if valid and ax not in valid:
                findings.append(Finding(
                    "collective-axis", "error", path, 0, name,
                    f"'{prim}' rides axis '{ax}' which is not in the "
                    f"live mesh axes {sorted(valid)} — on a multichip "
                    f"run this deadlocks (some chips enter the "
                    f"collective, the rest never will)"))
        if prim == "ppermute":
            perm = eqn.params.get("perm") or ()
            ax = axes[0] if axes else None
            size = bound_sizes.get(ax) or declared_sizes.get(ax)
            srcs = [s for s, _ in perm]
            dsts = [d for _, d in perm]
            if size is not None:
                bad = [p for p in perm
                       if not (0 <= p[0] < size and 0 <= p[1] < size)]
                if bad:
                    findings.append(Finding(
                        "collective-axis", "error", path, 0, name,
                        f"ppermute over axis '{ax}' (size {size}) names "
                        f"out-of-range ranks {bad[:4]} — the transfer "
                        f"never completes"))
            if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
                findings.append(Finding(
                    "collective-axis", "error", path, 0, name,
                    f"ppermute permutation over axis '{ax}' repeats a "
                    f"source or destination rank: {list(perm)[:6]} — "
                    f"ppermute requires a partial permutation (each rank "
                    f"sends/receives at most once)"))
    return findings


def check_pallas_blocks(closed, name, path, vmem_bytes=None):
    from apex_tpu.ops import pallas_config

    if vmem_bytes is None:
        vmem_bytes = pallas_config.device_vmem_bytes()
    findings = []
    eqns: list = []
    _walk_all(closed.jaxpr, {}, eqns)
    for eqn, _ in eqns:
        if eqn.primitive.name != "pallas_call":
            continue
        gm = eqn.params.get("grid_mapping")
        if gm is None:
            continue
        kernel = str(eqn.params.get("name_and_src_info", "pallas kernel"))
        resident = 0
        for bm in gm.block_mappings:
            sd = bm.array_shape_dtype
            dtype = np.dtype(sd.dtype)
            block = tuple(bm.block_shape)
            idims = [d for d in block if isinstance(d, int)]
            resident += math.prod(idims or [1]) * dtype.itemsize
            if len(idims) < 2:
                continue  # scalar/1D blocks: no (sublane, lane) tiling
            minor, second = idims[-1], idims[-2]
            a_shape = tuple(sd.shape)
            a_minor = a_shape[-1] if a_shape else minor
            a_second = a_shape[-2] if len(a_shape) >= 2 else second
            sublane = _SUBLANE_BY_ITEMSIZE.get(dtype.itemsize, 8)
            if minor % _LANE and minor != a_minor:
                findings.append(Finding(
                    "pallas-block", "warning", path, 0, name,
                    f"[{kernel}] {bm.origin}: block minor dim {minor} is "
                    f"neither a multiple of the {_LANE}-lane width nor "
                    f"the full array dim ({a_minor}) — Mosaic pads every "
                    f"block, wasting VMEM and bandwidth"))
            if second % sublane and second != a_second:
                findings.append(Finding(
                    "pallas-block", "warning", path, 0, name,
                    f"[{kernel}] {bm.origin}: block sublane dim {second} "
                    f"is neither a multiple of {sublane} (dtype "
                    f"{dtype.name}) nor the full array dim ({a_second}) "
                    f"— Mosaic pads every block"))
        est = 2 * resident  # double-buffered pipeline
        if est > vmem_bytes:
            findings.append(Finding(
                "pallas-block", "error", path, 0, name,
                f"[{kernel}] estimated VMEM residency "
                f"{est / 2**20:.1f} MiB (double-buffered block set) "
                f"exceeds the ~{vmem_bytes / 2**20:.0f} MiB per-core "
                f"budget — the kernel will fail to compile or thrash "
                f"HBM; shrink the BlockSpecs"))
    return findings


# -------------------------------------------------------------- entry

def analyze_fn(fn, *example_args, donate_argnums=(), mesh_axes=None,
               name=None, checks=None, vmem_bytes=None):
    """Trace ``fn`` with ``example_args`` and run the jaxpr checks.

    ``donate_argnums`` mirrors ``jax.jit``'s (top-level positional args).
    ``checks`` restricts to a subset of :data:`JAXPR_CHECKS`. Returns a
    list of :class:`Finding`.
    """
    import jax

    name = name or getattr(fn, "__name__", "fn")
    path = f"<jaxpr:{name}>"
    run = set(checks or JAXPR_CHECKS)
    unknown = run - set(JAXPR_CHECKS)
    if unknown:
        raise ValueError(f"unknown jaxpr check(s) {sorted(unknown)}; "
                         f"valid: {list(JAXPR_CHECKS)}")

    closed = jax.make_jaxpr(fn)(*example_args)

    findings = []
    if "donation" in run:
        donated = _donated_invar_indices(example_args, donate_argnums)
        if donated:
            findings += check_donation(closed, donated, name, path)
    if "recompile" in run:
        findings += check_recompile(closed, name, path, example_args)
    if "collective-axis" in run:
        findings += check_collectives(closed, name, path, mesh_axes)
    if "pallas-block" in run:
        findings += check_pallas_blocks(closed, name, path, vmem_bytes)
    return findings
