"""Auto-sharding planner — search the mesh/layout space on the unified
sharding-flow cost model and emit executable PartitionSpecs (ISSUE 8
tentpole).

PR 4's :func:`~apex_tpu.analysis.sharding_flow.estimate_hbm_and_comms`
prices any (jaxpr, mesh, specs) triple; this module inverts it into a
GSPMD/Alpa-style layout search:

1. **Enumerate** candidate mesh shapes (pp, dp, tp factorizations of
   the device count that the model's shapes divide) and PartitionSpec
   layout templates per mesh (replicated baseline, Megatron TP).
2. **Trace once per pp** (stage depth changes the jaxpr; dp/tp/layout
   do not), then price every (dp, tp, layout) candidate against that
   one trace — the cheap inner loop the unified interpreter and the
   memoized liveness linearization exist for.
3. **Prune** candidates whose liveness-walk peak HBM exceeds the
   per-device budget (:func:`apex_tpu.ops.pallas_config.device_hbm_bytes`).
4. **Rank** survivors by a modeled step time: roofline compute
   (PaLM-appendix FLOPs over the device-generation peak, the same
   priors as the PR 6 tuning roofline) + HBM traffic over HBM
   bandwidth + comms bytes over ICI bandwidth, with the pipeline
   bubble factor (pp-1)/M on top.
5. **Verify** the winner by re-running all five sharding checks on the
   emitted specs — a plan that introduces an ``implicit-reshard`` or
   ``hbm-budget`` finding is rejected before it ships and the next
   survivor is tried.

The emitted :class:`Plan` is deterministic (byte-identical JSON for
identical inputs — no clocks, no RNG, stable tie-breaks) and
executable: :mod:`apex_tpu.parallel.auto_shard` turns it into a mesh +
``with_sharding_constraint``/``shard_map`` specs, and
``examples/llama_train.py --auto-shard`` consumes it end to end.

Comms terms the jaxpr estimator cannot see from a constraint-free
GSPMD trace are modeled analytically and documented in
docs/planner.md: per-layer Megatron activation allreduces (4/layer)
and the pipeline's per-microbatch boundary ppermutes.

CLI: ``python -m apex_tpu.analysis plan --target llama``.
"""

from __future__ import annotations

import dataclasses
import json

from apex_tpu.analysis.sharding_flow import (
    collective_bytes,
    shard_val_for_aval,
)

__all__ = [
    "Plan", "PlanError", "PLAN_MODELS", "plan", "plan_model",
    "publish_to_registry", "main",
]

PLAN_SCHEMA_VERSION = 1
PLAN_KIND = "apex_tpu.plan"

# Per-device HBM bandwidth (bytes/s) by generation, same substring
# scheme as pallas_config._HBM_BYTES. The v5e figure matches the PR 6
# tuning roofline (docs/kernel_cost_study.md); the others are public
# spec-sheet numbers. Ratios between candidates are what the ranking
# consumes, so one consistent table beats per-chip precision.
_HBM_BW = (
    ("v5p", 2765e9), ("v5 lite", 819e9), ("v5e", 819e9),
    ("v6", 1638e9), ("trillium", 1638e9), ("v4", 1228e9),
    ("v3", 900e9), ("v2", 700e9),
)
_HBM_BW_DEFAULT = 819e9

# Per-device ICI (inter-chip interconnect) bandwidth, bytes/s — the
# denominator under every modeled collective.
_ICI_BW = (
    ("v5p", 90e9), ("v5 lite", 45e9), ("v5e", 45e9),
    ("v6", 90e9), ("trillium", 90e9), ("v4", 50e9),
    ("v3", 70e9), ("v2", 70e9),
)
_ICI_BW_DEFAULT = 45e9

# Peak bf16 FLOP/s fallback when the observability table has no entry
# for the kind (CPU planning runs): the v5e prior from the PR 6 tuning
# roofline, so CPU plans reproduce the decisions a v5e plan would make.
_PEAK_FLOPS_DEFAULT = 197e12


def _by_kind(table, kind, default):
    kind = (kind or "").lower()
    for key, value in table:
        if key in kind:
            return value
    return default


def hbm_bandwidth(kind=None) -> float:
    """Modeled per-device HBM bandwidth (bytes/s) for ``kind``."""
    return _by_kind(_HBM_BW, kind, _HBM_BW_DEFAULT)


def interconnect_bandwidth(kind=None) -> float:
    """Modeled per-device ICI bandwidth (bytes/s) for ``kind``."""
    return _by_kind(_ICI_BW, kind, _ICI_BW_DEFAULT)


def planning_peak_flops(kind=None) -> float:
    """Peak bf16 FLOP/s for ``kind`` (observability table first, v5e
    planning prior for unknown/CPU kinds)."""
    from apex_tpu.observability.step_report import peak_flops

    return peak_flops(kind or "") or _PEAK_FLOPS_DEFAULT


class PlanError(RuntimeError):
    """No candidate survived pruning + verification (the message lists
    every candidate and why it fell)."""


# --------------------------------------------------------------- specs

def spec_entries(spec, ndim=None):
    """PartitionSpec -> JSON-safe per-dim entries (None | name |
    [names]), optionally padded to ``ndim``."""
    entries = []
    for e in tuple(spec or ()):
        if e is None:
            entries.append(None)
        elif isinstance(e, (tuple, list)):
            entries.append([str(a) for a in e])
        else:
            entries.append(str(e))
    if ndim is not None:
        while len(entries) < ndim:
            entries.append(None)
    return entries


def entries_to_spec(entries):
    """Inverse of :func:`spec_entries`."""
    from jax.sharding import PartitionSpec as P

    out = []
    for e in entries or ():
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(tuple(str(a) for a in e))
        else:
            out.append(str(e))
    return P(*out)


def _strip_axes(spec, active):
    """Drop mesh axes not in ``active`` (size-1 axes shard nothing and
    would fire spurious reshard findings)."""
    from jax.sharding import PartitionSpec as P

    out = []
    for e in tuple(spec or ()):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            kept = tuple(a for a in e if str(a) in active)
            out.append(kept if kept else None)
        else:
            out.append(str(e) if str(e) in active else None)
    return P(*out)


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "idx", None)
        if key is None:
            key = getattr(p, "name", None)
        parts.append(str(p) if key is None else str(key))
    return "/".join(parts)


def _flatten_with_names(tree, prefix):
    """[(name, leaf)] in ``tree_leaves`` order, names prefixed."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(f"{prefix}/{_path_str(path)}" if path else prefix, leaf)
            for path, leaf in flat]


def _flatten_spec_tree(tree):
    """Leaves of a PartitionSpec pytree in ``tree_leaves`` order."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree_util.tree_leaves(
        tree, is_leaf=lambda x: x is None or isinstance(x, P))


# --------------------------------------------------------------- model

@dataclasses.dataclass
class TracedStep:
    """One pp-depth's traced train step plus the analytic cost-model
    ingredients the jaxpr cannot carry."""

    closed: object                  # ClosedJaxpr
    donated: frozenset              # flat invar indices with donation
    flops_total: int                # whole-step FLOPs at pp=1 depth
    act_bytes_global: int           # boundary activations, global batch
    tp_collectives: int             # act-sized tp allreduces per step
    microbatches: int
    # dp grad-sync term (this stage's parameters): grads travel fp32,
    # the ZeRO-1 all-gather travels in the param storage dtype (see
    # apex_tpu.parallel.overlap.grad_sync_bytes_from_sizes)
    grad_bytes_global: int = 0
    param_store_bytes_global: int = 0


class PlanModel:
    """One searchable model family. Subclasses provide the trace and
    the layout templates; everything is deterministic."""

    name = "model"
    layouts = ("replicated", "megatron")

    def pp_candidates(self, devices):
        return (1,)

    def valid_tp(self, tp):
        return tp == 1

    def valid_dp(self, dp):
        return True

    def trace(self, pp) -> TracedStep:
        raise NotImplementedError

    def flat_specs(self, layout, traced, dp, tp):
        """One PartitionSpec per flat invar of ``traced.closed``."""
        raise NotImplementedError

    def layout_divides_tp(self, layout):
        return layout != "replicated"

    def emit_specs(self, layout, dp, tp):
        """The executable, JSON-safe spec table consumers apply."""
        raise NotImplementedError


PLAN_MODELS: dict = {}


def _check_grad_sync(mode):
    from apex_tpu.parallel.overlap import GRAD_SYNC_MODES

    if mode not in GRAD_SYNC_MODES:
        raise ValueError(
            f"grad_sync={mode!r} is not a known mode; valid: "
            f"{', '.join(GRAD_SYNC_MODES)}")
    return mode


def _tree_grad_param_bytes(params):
    """(fp32 grad bytes, storage-dtype param bytes) of a shaped param
    tree — the dp grad-sync term's inputs."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves(params)
    grad_b = sum(leaf.size * 4 for leaf in leaves)
    param_b = sum(leaf.size * np.dtype(str(leaf.dtype)).itemsize
                  for leaf in leaves)
    return int(grad_b), int(param_b)


def plan_model(name):
    """Register a :class:`PlanModel` subclass under ``name``."""
    def deco(cls):
        PLAN_MODELS[name] = cls
        cls.name = name
        return cls
    return deco


def _active_axes(dp, tp):
    active = set()
    if dp > 1:
        active.add("dp")
    if tp > 1:
        active.add("tp")
    return active


@plan_model("llama")
class LlamaPlanModel(PlanModel):
    """The llama decoder train step (fwd+bwd+FusedAdam), traced per
    stage depth with abstract avals only — shapes default small enough
    to plan on CPU in seconds while dividing every tp/pp candidate up
    to 8. Override via model_kw (layers/hidden/heads/kv_heads/
    intermediate/vocab/seq/batch/microbatches)."""

    def __init__(self, layers=8, hidden=64, heads=8, kv_heads=8,
                 intermediate=128, vocab=256, seq=32, batch=8,
                 microbatches=4, grad_sync="allreduce"):
        self.grad_sync = _check_grad_sync(grad_sync)
        self.layers = int(layers)
        self.hidden = int(hidden)
        self.heads = int(heads)
        self.kv_heads = int(kv_heads)
        self.intermediate = int(intermediate)
        self.vocab = int(vocab)
        self.seq = int(seq)
        self.batch = int(batch)
        self.microbatches = int(microbatches)
        self._traced: dict = {}
        self._shaped: dict = {}
        self._n_params_full = None

    def _cfg(self, n_layers):
        from apex_tpu.models import llama

        return llama.tiny(
            num_layers=n_layers, hidden_size=self.hidden,
            num_heads=self.heads, num_kv_heads=self.kv_heads,
            intermediate_size=self.intermediate, vocab_size=self.vocab,
            max_seq_len=self.seq)

    def pp_candidates(self, devices):
        return tuple(pp for pp in range(1, min(devices, self.layers) + 1)
                     if devices % pp == 0 and self.layers % pp == 0)

    def valid_tp(self, tp):
        return (self.heads % tp == 0 and self.kv_heads % tp == 0
                and self.vocab % tp == 0 and self.hidden % tp == 0
                and self.intermediate % tp == 0)

    def valid_dp(self, dp):
        return self.batch % dp == 0

    def _shapes(self, pp):
        # memoized per pp: flat_specs re-enters this once per (dp, tp,
        # layout) candidate and the eval_shape of the whole init is the
        # expensive part of the inner loop
        if pp in self._shaped:
            return self._shaped[pp]
        import jax
        import jax.numpy as jnp

        from apex_tpu.models import llama
        from apex_tpu.optimizers import fused_adam

        cfg = self._cfg(self.layers // pp)
        key = jax.random.PRNGKey(0)
        params = jax.eval_shape(lambda: llama.init_params(key, cfg))
        tx = fused_adam(lr=1e-3, flat=False)
        opt = jax.eval_shape(tx.init, params)
        tokens = jax.ShapeDtypeStruct((self.batch, self.seq), jnp.int32)
        self._shaped[pp] = (cfg, tx, params, opt, tokens)
        return self._shaped[pp]

    def trace(self, pp) -> TracedStep:
        if pp in self._traced:
            return self._traced[pp]
        import jax

        from apex_tpu.models import llama
        from apex_tpu.observability.step_report import (
            transformer_step_flops,
        )

        cfg, tx, params, opt, tokens = self._shapes(pp)

        def step(params, opt_state, tokens, targets):
            loss, grads = jax.value_and_grad(llama.loss_fn)(
                params, (tokens, targets), cfg, tp_axis=None,
                cp_axis=None, remat=False)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(jax.numpy.add, params,
                                            updates)
            return params, opt_state, loss

        closed = jax.make_jaxpr(step)(params, opt, tokens, tokens)
        # pp-invariant (whole-model param count); compute it once, not
        # once per stage depth
        if self._n_params_full is None:
            self._n_params_full = sum(
                leaf.size for leaf in jax.tree_util.tree_leaves(
                    jax.eval_shape(
                        lambda: llama.init_params(
                            jax.random.PRNGKey(0),
                            self._cfg(self.layers)))))
        n_params_full = self._n_params_full
        n_state = len(jax.tree_util.tree_leaves(params)) + len(
            jax.tree_util.tree_leaves(opt))
        grad_b, param_b = _tree_grad_param_bytes(params)
        traced = TracedStep(
            closed=closed,
            donated=frozenset(range(n_state)),
            flops_total=transformer_step_flops(
                n_params_full, self.layers, self.hidden, self.seq,
                self.batch),
            act_bytes_global=self.batch * self.seq * self.hidden * 4,
            # Megatron TP: 2 activation allreduces fwd (attention out +
            # MLP down row-parallel boundaries) and 2 bwd, per layer of
            # THIS stage depth
            tp_collectives=4 * (self.layers // pp),
            microbatches=self.microbatches,
            grad_bytes_global=grad_b,
            param_store_bytes_global=param_b,
        )
        self._traced[pp] = traced
        return traced

    def _spec_trees(self, pp, dp, tp):
        import jax

        from apex_tpu.models import llama
        from apex_tpu.optimizers import opt_partition_specs
        from jax.sharding import PartitionSpec as P

        cfg, tx, params, opt, tokens = self._shapes(pp)
        active = _active_axes(dp, tp)
        pspecs = jax.tree_util.tree_map(
            lambda s: _strip_axes(s, active), llama.param_specs(cfg),
            is_leaf=lambda x: isinstance(x, P))
        ospecs = opt_partition_specs(tx, params, pspecs)
        data = P("dp") if dp > 1 else P()
        return pspecs, ospecs, data

    def flat_specs(self, layout, traced, dp, tp):
        import jax
        from jax.sharding import PartitionSpec as P

        # the traced step knows its stage depth; recover it from the
        # trace cache rather than re-deriving it from shapes
        pp = next(pp_key for pp_key, cached in self._traced.items()
                  if cached is traced)
        pspecs, ospecs, data = self._spec_trees(pp, dp, tp)
        if layout == "replicated":
            pspecs = jax.tree_util.tree_map(
                lambda s: P(), pspecs,
                is_leaf=lambda x: isinstance(x, P))
            ospecs = jax.tree_util.tree_map(
                lambda s: P(), ospecs,
                is_leaf=lambda x: x is None or isinstance(x, P))
        return (_flatten_spec_tree(pspecs) + _flatten_spec_tree(ospecs)
                + [data, data])

    def emit_specs(self, layout, dp, tp):
        from jax.sharding import PartitionSpec as P

        cfg = self._cfg(self.layers)
        active = _active_axes(dp, tp) if layout != "replicated" \
            else (_active_axes(dp, tp) & {"dp"})
        from apex_tpu.models import llama

        specs = llama.param_specs(cfg)
        layer = {k: spec_entries(_strip_axes(v, active))
                 for k, v in specs["layers"].items()}
        io = {"embed": spec_entries(_strip_axes(specs["embed"], active)),
              "final_norm": spec_entries(specs["final_norm"]),
              "lm_head": spec_entries(_strip_axes(
                  specs.get("lm_head", P(None, None)), active))}
        return {"layers": layer, "io": io,
                "data": spec_entries(P("dp") if dp > 1 else P())}


@plan_model("mlp")
class MlpPlanModel(PlanModel):
    """Two-layer MLP + SGD — the deterministic test workhorse (also the
    smallest real customer: a Megatron column/row pair)."""

    def __init__(self, hidden=64, batch=32, grad_sync="allreduce",
                 dtype="float32"):
        import numpy as np

        self.grad_sync = _check_grad_sync(grad_sync)
        self.hidden = int(hidden)
        self.batch = int(batch)
        # param STORAGE dtype: with bf16 params + fp32 grads the zero1
        # grad-sync layout prices at exactly 0.75x the allreduce
        self.dtype = "bfloat16" if str(dtype) == "bfloat16" else \
            str(np.dtype(str(dtype)))
        self._traced: dict = {}

    def pp_candidates(self, devices):
        return (1,)

    def valid_tp(self, tp):
        return (4 * self.hidden) % tp == 0 and self.hidden % tp == 0

    def valid_dp(self, dp):
        return self.batch % dp == 0

    def trace(self, pp) -> TracedStep:
        if pp in self._traced:
            return self._traced[pp]
        import jax
        import jax.numpy as jnp

        h, b = self.hidden, self.batch
        w_dtype = jnp.dtype(self.dtype)
        params = {
            "w1": jax.ShapeDtypeStruct((h, 4 * h), w_dtype),
            "w2": jax.ShapeDtypeStruct((4 * h, h), w_dtype),
        }
        x = jax.ShapeDtypeStruct((b, h), jnp.float32)

        def step(params, x, y):
            # differentiate w.r.t. — and output — an fp32 MASTER copy
            # (the O2 pattern: master weights are the carried state,
            # storage dtype is the input format). The traced gradients,
            # the output resolution point, and therefore the
            # pending-psum allreduce the GSPMD estimate prices are all
            # fp32-wide regardless of storage dtype — the same
            # fp32-reduce baseline the zero1 delta in _candidate_comms
            # swaps against (the engine reduces fp32 and gathers in the
            # storage dtype). For float32 storage the cast is a no-op
            # and the jaxpr is unchanged.
            master = jax.tree_util.tree_map(
                lambda p: p.astype(jnp.float32), params)

            def loss_fn(m):
                out = jax.nn.relu(x @ m["w1"]) @ m["w2"]
                return jnp.mean(jnp.square(out - y))

            loss, grads = jax.value_and_grad(loss_fn)(master)
            new = jax.tree_util.tree_map(
                lambda m, g: m - 0.01 * g, master, grads)
            return new, loss

        closed = jax.make_jaxpr(step)(params, x, x)
        grad_b, param_b = _tree_grad_param_bytes(params)
        traced = TracedStep(
            closed=closed, donated=frozenset(range(len(params))),
            flops_total=2 * 3 * b * (h * 4 * h * 2),
            act_bytes_global=b * 4 * h * 4,
            tp_collectives=2, microbatches=1,
            grad_bytes_global=grad_b,
            param_store_bytes_global=param_b)
        self._traced[pp] = traced
        return traced

    def flat_specs(self, layout, traced, dp, tp):
        from jax.sharding import PartitionSpec as P

        data = P("dp") if dp > 1 else P()
        if layout == "megatron" and tp > 1:
            return [P(None, "tp"), P("tp", None), data, data]
        return [P(), P(), data, data]

    def emit_specs(self, layout, dp, tp):
        from jax.sharding import PartitionSpec as P

        if layout == "megatron" and tp > 1:
            w1, w2 = P(None, "tp"), P("tp", None)
        else:
            w1, w2 = P(), P()
        return {"params": {"w1": spec_entries(w1),
                           "w2": spec_entries(w2)},
                "data": spec_entries(P("dp") if dp > 1 else P())}


# ---------------------------------------------------------- evaluation

@dataclasses.dataclass
class Candidate:
    """One priced point of the search space, ranked-table-ready."""

    pp: int
    dp: int
    tp: int
    layout: str
    comms_bytes: int = 0
    peak_hbm_bytes: int = 0
    calibrated_hbm_bytes: int = 0  # modeled x calibration prior
    modeled_step_ms: float = 0.0
    status: str = "ok"        # ok | chosen | pruned:hbm | rejected:checks
    detail: str = ""

    @property
    def key(self) -> str:
        return f"pp{self.pp}.dp{self.dp}.tp{self.tp}/{self.layout}"

    @property
    def mesh(self) -> dict:
        return {"pp": self.pp, "dp": self.dp, "tp": self.tp}

    def row(self) -> dict:
        return {"candidate": self.key, "mesh": self.mesh,
                "layout": self.layout, "comms_bytes": self.comms_bytes,
                "peak_hbm_bytes": self.peak_hbm_bytes,
                "calibrated_hbm_bytes": self.calibrated_hbm_bytes,
                "modeled_step_ms": self.modeled_step_ms,
                "status": self.status, "detail": self.detail}


@dataclasses.dataclass
class Plan:
    """The planner's verdict: the chosen mesh + layout, its executable
    spec table, the prediction the choice rests on, and the full ranked
    candidate record (chosen/ok/pruned/rejected)."""

    model: str
    devices: int
    device_kind: str
    hbm_budget_bytes: int
    mesh: dict
    layout: str
    specs: dict
    predicted: dict
    candidates: list
    model_kw: dict
    hbm_prior: str = "none"  # calibration prior label the pruning used

    @property
    def chosen_key(self) -> str:
        return (f"pp{self.mesh['pp']}.dp{self.mesh['dp']}"
                f".tp{self.mesh['tp']}/{self.layout}")

    def to_dict(self) -> dict:
        return {
            "schema_version": PLAN_SCHEMA_VERSION,
            "kind": PLAN_KIND,
            "model": self.model,
            "devices": self.devices,
            "device_kind": self.device_kind,
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "hbm_prior": self.hbm_prior,
            "mesh": self.mesh,
            "layout": self.layout,
            "chosen": self.chosen_key,
            "specs": self.specs,
            "predicted": self.predicted,
            "candidates": [c.row() for c in self.candidates],
            "model_kw": self.model_kw,
        }

    def to_json(self) -> str:
        # deterministic on purpose: sorted keys, rounded floats, no
        # clocks — the same (model, devices) input must yield a
        # byte-identical plan across runs (regression-tested)
        return json.dumps(self.to_dict(), indent=2, sort_keys=True) + "\n"


def _modeled_step_s(model, traced, cand, kind, stats):
    """The documented cost model (docs/planner.md): roofline compute +
    HBM traffic + comms over ICI, times the pipeline bubble."""
    compute_ways = cand.pp * cand.dp * (
        cand.tp if model.layout_divides_tp(cand.layout) else 1)
    compute_s = traced.flops_total / compute_ways / planning_peak_flops(
        kind)
    mem_s = (stats["input_bytes"] + stats["output_bytes"]) \
        / hbm_bandwidth(kind)
    comms_s = cand.comms_bytes / interconnect_bandwidth(kind)
    bubble = (cand.pp - 1) / max(1, traced.microbatches)
    return (max(compute_s, mem_s) + comms_s) * (1.0 + bubble)


def _candidate_comms(model, traced, cand, stats):
    """GSPMD-estimated bytes plus the analytic terms a constraint-free
    trace cannot carry (per-layer Megatron activation allreduces, the
    pipeline's per-microbatch boundary hops, and the dp gradient sync
    — allreduce by default, or the ZeRO-1 reduce-scatter + all-gather
    layout at <= 0.75x the allreduce bytes when the model opts in via
    ``grad_sync="zero1"``; ISSUE 11)."""
    from apex_tpu.parallel.overlap import grad_sync_bytes_from_sizes

    comms = stats["comms_bytes"]
    if cand.tp > 1 and model.layout_divides_tp(cand.layout):
        act_local = traced.act_bytes_global // max(1, cand.dp)
        comms += traced.tp_collectives * collective_bytes(
            "psum", act_local, [cand.tp])
    if cand.pp > 1:
        comms += 2 * traced.act_bytes_global // max(1, cand.dp)
    if cand.dp > 1 and getattr(model, "grad_sync",
                               "allreduce") == "zero1":
        # the GSPMD estimate already prices the dp grad sync as a
        # pending-psum allreduce (the traced step folds the optimizer
        # update in, so the grad reduction is in the jaxpr); ZeRO-1
        # swaps that allreduce for reduce-scatter + storage-dtype
        # all-gather, so price the DELTA, not a second sync. The
        # stage's param slab shrinks with tp under a dividing layout.
        tp_div = cand.tp if model.layout_divides_tp(cand.layout) else 1
        g = traced.grad_bytes_global // tp_div
        p = traced.param_store_bytes_global // tp_div
        comms += (grad_sync_bytes_from_sizes(g, p, cand.dp, "zero1")
                  - grad_sync_bytes_from_sizes(g, p, cand.dp,
                                               "allreduce"))
    return int(comms)


def _enumerate(model, devices, min_mesh=None):
    min_mesh = dict(min_mesh or {})
    cands = []
    for pp in model.pp_candidates(devices):
        if pp < min_mesh.get("pp", 1):
            continue
        rest = devices // pp
        for dp in range(1, rest + 1):
            if rest % dp or not model.valid_dp(dp):
                continue
            if dp < min_mesh.get("dp", 1):
                continue
            tp = rest // dp
            if not model.valid_tp(tp) or tp < min_mesh.get("tp", 1):
                continue
            for layout in model.layouts:
                # every mesh axis must be EXPLOITED by the layout: a
                # tp>1 mesh under a replicated layout leaves tp-1 of
                # the machine idle while scoring zero comms — it would
                # always win and always be wrong
                if model.layout_divides_tp(layout) != (tp > 1):
                    continue
                cands.append(Candidate(pp=pp, dp=dp, tp=tp,
                                       layout=layout))
    return cands


def _in_vals_for(closed, flat_specs):
    vals = []
    for i, var in enumerate(closed.jaxpr.invars):
        spec = flat_specs[i] if i < len(flat_specs) else None
        vals.append(shard_val_for_aval(var.aval, spec))
    return vals


def _resolve_hbm_prior(hbm_prior):
    """(ratio or None, label) from the ``hbm_prior`` argument: None
    keeps modeled bytes uncorrected; a number is used verbatim; a
    string names a calibrated target whose measured/modeled ratio the
    committed analysis/hbm_priors.json carries (ISSUE 19) — unknown
    names resolve to None so the table can say ``prior:none`` loudly
    instead of silently inventing a correction."""
    if hbm_prior is None:
        return None, "none"
    if isinstance(hbm_prior, str):
        from apex_tpu.analysis.memory_checks import prior_for
        ratio = prior_for(hbm_prior)
        if ratio is None:
            return None, f"none ({hbm_prior}: no capture)"
        return ratio, f"{ratio:g} ({hbm_prior})"
    from apex_tpu.analysis.sharding_flow import prior_ratio_of
    ratio = prior_ratio_of(hbm_prior)
    return ratio, f"{ratio:g}"


def plan(model="llama", devices=None, device_kind=None,
         hbm_budget_bytes=None, registry=None, verify=True,
         min_mesh=None, hbm_prior=None, **model_kw) -> Plan:
    """Search the mesh/layout space for ``model`` over ``devices`` and
    return the verified :class:`Plan` (see module docstring for the
    pipeline). Raises :class:`PlanError` when nothing survives.

    ``min_mesh``: {axis: minimum size} executability floor from the
    consumer — e.g. a step whose collectives require a bound tp axis
    passes ``{"tp": 2}`` so the search never emits a mesh its runtime
    cannot execute.

    ``hbm_prior``: calibration correction for the HBM pruning gate —
    a measured/modeled ratio (number), the name of a calibrated target
    in analysis/hbm_priors.json (string), or None to prune on raw
    modeled bytes. With a prior, candidates are pruned on
    ``modeled x prior`` (the planner's best estimate of what the
    compiler will actually allocate — the fused-Adam master-weight
    target runs 3.4x its modeled peak), and the ranked table carries
    the calibrated column."""
    from apex_tpu.analysis.sharding_checks import analyze_sharding_jaxpr
    from apex_tpu.analysis.sharding_flow import estimate_hbm_and_comms

    if model not in PLAN_MODELS:
        raise ValueError(
            f"unknown plan model {model!r}; valid: "
            f"{sorted(PLAN_MODELS)}")
    if devices is None:
        import jax
        devices = len(jax.devices())
    devices = int(devices)
    if device_kind is None:
        import jax
        dev = jax.devices()[0]
        device_kind = dev.device_kind if dev.platform == "tpu" else "cpu"
    if hbm_budget_bytes is None:
        from apex_tpu.ops.pallas_config import device_hbm_bytes
        hbm_budget_bytes = device_hbm_bytes(device_kind)

    prior_ratio, prior_label = _resolve_hbm_prior(hbm_prior)

    mdl = PLAN_MODELS[model](**(model_kw or {}))
    candidates = _enumerate(mdl, devices, min_mesh=min_mesh)
    if not candidates:
        raise PlanError(
            f"no candidate meshes for model={model} over {devices} "
            f"device(s) (min_mesh={dict(min_mesh or {})}) — the "
            f"model's shapes divide none of the factorizations")

    seen_sigs = {}
    evaluated = []
    for cand in candidates:
        traced = mdl.trace(cand.pp)
        flat_specs = mdl.flat_specs(cand.layout, traced, cand.dp,
                                    cand.tp)
        in_vals = _in_vals_for(traced.closed, flat_specs)
        sig = (cand.pp, cand.dp, cand.tp,
               tuple(v.spec for v in in_vals))
        if sig in seen_sigs:
            # a layout that degenerates to an earlier one at this mesh
            # (megatron at tp=1 == replicated) would double-report the
            # same plan under two names
            continue
        seen_sigs[sig] = cand
        stats = estimate_hbm_and_comms(
            traced.closed, in_vals, donated=traced.donated,
            axis_sizes={"dp": cand.dp, "tp": cand.tp})
        cand.peak_hbm_bytes = stats["peak_hbm_bytes"]
        cand.calibrated_hbm_bytes = (
            int(round(cand.peak_hbm_bytes * prior_ratio))
            if prior_ratio is not None else cand.peak_hbm_bytes)
        cand.comms_bytes = _candidate_comms(mdl, traced, cand, stats)
        cand.modeled_step_ms = round(
            _modeled_step_s(mdl, traced, cand, device_kind, stats) * 1e3,
            6)
        # the pruning gate prices calibrated bytes: with no prior that
        # IS the modeled peak (back-compat); with one, a candidate the
        # raw model calls feasible can still be pruned (and vice versa)
        if cand.calibrated_hbm_bytes > hbm_budget_bytes:
            cand.status = "pruned:hbm"
            if prior_ratio is not None:
                cand.detail = (
                    f"calibrated HBM {cand.calibrated_hbm_bytes} B "
                    f"(modeled {cand.peak_hbm_bytes} B x prior "
                    f"{prior_ratio:g}) exceeds the {hbm_budget_bytes} B "
                    f"per-device budget")
            else:
                cand.detail = (
                    f"peak HBM {cand.peak_hbm_bytes} B exceeds the "
                    f"{hbm_budget_bytes} B per-device budget")
        evaluated.append((cand, traced, in_vals))

    # deterministic ranking: modeled time, then comms, then peak HBM,
    # then the candidate key — ties can never reorder across runs
    evaluated.sort(key=lambda e: (e[0].modeled_step_ms,
                                  e[0].comms_bytes,
                                  e[0].peak_hbm_bytes, e[0].key))

    chosen = None
    for cand, traced, in_vals in evaluated:
        if cand.status.startswith("pruned"):
            continue
        if verify:
            findings = analyze_sharding_jaxpr(
                traced.closed, in_vals, name=f"plan:{cand.key}",
                donated=traced.donated,
                axis_sizes={"dp": cand.dp, "tp": cand.tp},
                hbm_budget_bytes=hbm_budget_bytes)
            if findings:
                cand.status = "rejected:checks"
                cand.detail = "; ".join(
                    f"{f.check}: {f.message[:120]}" for f in findings)
                continue
        chosen = cand
        cand.status = "chosen"
        break

    ranked = [c for c, _t, _v in evaluated]
    if chosen is None:
        raise PlanError(
            f"no feasible plan for model={model} over {devices} "
            f"device(s) (budget {hbm_budget_bytes} B): "
            + "; ".join(f"{c.key} -> {c.status} ({c.detail})"
                        for c in ranked))

    result = Plan(
        model=model, devices=devices, device_kind=device_kind,
        hbm_budget_bytes=int(hbm_budget_bytes),
        mesh=chosen.mesh, layout=chosen.layout,
        specs=mdl.emit_specs(chosen.layout, chosen.dp, chosen.tp),
        predicted={
            "step_ms": chosen.modeled_step_ms,
            "comms_bytes": chosen.comms_bytes,
            "peak_hbm_bytes": chosen.peak_hbm_bytes,
            "calibrated_hbm_bytes": chosen.calibrated_hbm_bytes,
            # which dp grad-sync layout the comms term priced
            # (docs/parallel.md "Overlapped buckets & ZeRO-1")
            "grad_sync": getattr(mdl, "grad_sync", "allreduce"),
            # the chosen candidate survived every check by construction
            "findings": 0 if verify else None,
        },
        candidates=ranked,
        model_kw={k: model_kw[k] for k in sorted(model_kw)},
        hbm_prior=prior_label,
    )
    publish_to_registry(result, registry=registry)
    return result


def modeled_single_device_ms(model="llama", device_kind=None,
                             **model_kw) -> float:
    """Modeled step time of the unsharded single-device candidate —
    the number bench.py calibrates against its measured step."""
    p = plan(model=model, devices=1, device_kind=device_kind,
             registry=False, **model_kw)
    return p.predicted["step_ms"]


# ----------------------------------------------------------- reporting

def publish_to_registry(result: Plan, registry=None):
    """Publish the ranked table as the ``analysis/plan_*`` metric
    family (the bench JSONL rows tools/metrics_report.py renders and
    --compare gates plan flips on). ``registry=False`` skips."""
    if registry is False:
        return
    from apex_tpu.observability import get_registry

    reg = registry if registry is not None else get_registry()
    for cand in result.candidates:
        labels = {"model": result.model, "candidate": cand.key}
        reg.gauge("analysis/plan_modeled_step_ms", **labels).set(
            cand.modeled_step_ms)
        reg.gauge("analysis/plan_comms_bytes", **labels).set(
            cand.comms_bytes)
        reg.gauge("analysis/plan_peak_hbm_bytes", **labels).set(
            cand.peak_hbm_bytes)
        reg.gauge("analysis/plan_calibrated_hbm_bytes", **labels).set(
            cand.calibrated_hbm_bytes)
        reg.gauge("analysis/plan_chosen", **labels).set(
            1 if cand.status == "chosen" else 0)
    reg.event("plan", model=result.model, devices=result.devices,
              chosen=result.chosen_key,
              predicted_step_ms=result.predicted["step_ms"])


def render_table(result: Plan) -> str:
    from apex_tpu.analysis.sharding_checks import _fmt_bytes

    has_prior = not result.hbm_prior.startswith("none")
    lines = [
        f"auto-shard plan: {result.model} over {result.devices} "
        f"device(s) ({result.device_kind}), HBM budget "
        f"{_fmt_bytes(result.hbm_budget_bytes)}, "
        f"HBM prior {result.hbm_prior}",
        f"{'rank':>4s}  {'candidate':28s}  {'modeled':>12s}  "
        f"{'comms/step':>12s}  {'peak HBM':>10s}  {'cal HBM':>10s}  "
        f"status",
    ]
    for rank, cand in enumerate(result.candidates, 1):
        # the calibrated column is modeled x prior (what pruning
        # priced); with no capture it says so loudly instead of
        # repeating the modeled number as if it were calibrated
        cal = (_fmt_bytes(cand.calibrated_hbm_bytes) if has_prior
               else "prior:none")
        lines.append(
            f"{rank:>4d}  {cand.key:28s}  "
            f"{cand.modeled_step_ms:>9.3f} ms  "
            f"{_fmt_bytes(cand.comms_bytes):>12s}  "
            f"{_fmt_bytes(cand.peak_hbm_bytes):>10s}  "
            f"{cal:>10s}  {cand.status}")
    mesh = result.mesh
    verified = result.predicted["findings"]
    lines.append(
        f"chosen: pp={mesh['pp']} dp={mesh['dp']} tp={mesh['tp']} "
        f"layout={result.layout} — "
        + ("verification skipped (--no-verify)" if verified is None else
           f"winning specs pass all sharding checks "
           f"({verified} findings)"))
    return "\n".join(lines)


def main(argv=None):
    """``python -m apex_tpu.analysis plan`` — search, rank, emit."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis plan",
        description="auto-sharding planner: search mesh/layout space "
                    "on the sharding-flow cost model")
    ap.add_argument("--target", "--model", dest="model", default="llama",
                    help=f"plan model (valid: {sorted(PLAN_MODELS)})")
    ap.add_argument("--devices", type=int, default=None,
                    help="device count to plan for (default: visible)")
    ap.add_argument("--device-kind", default=None,
                    help="device generation for the cost tables "
                         "(default: detected; 'cpu' uses v5e priors)")
    ap.add_argument("--hbm-budget-bytes", type=int, default=None)
    ap.add_argument("--hbm-prior", default=None,
                    help="calibration prior for the HBM pruning gate: "
                         "a measured/modeled ratio (e.g. 3.43) or the "
                         "name of a calibrated target in "
                         "analysis/hbm_priors.json (e.g. "
                         "fused_adam_master_sharded_step); default "
                         "prunes on raw modeled bytes")
    ap.add_argument("--set", action="append", default=[],
                    metavar="KEY=INT",
                    help="model_kw override, e.g. --set layers=16")
    ap.add_argument("--grad-sync", choices=("allreduce", "zero1"),
                    default=None,
                    help="dp gradient-sync layout the comms model "
                         "prices (zero1 = reduce-scatter + all-gather, "
                         "<= 0.75x the allreduce bytes)")
    ap.add_argument("--no-verify", dest="verify", action="store_false",
                    help="skip the sharding-check vetting of the winner")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--out", default=None,
                    help="also write the plan JSON to this path")
    args = ap.parse_args(argv)

    model_kw = {}
    for entry in args.set:
        key, sep, value = entry.partition("=")
        if not sep or not key:
            print(f"--set expects KEY=INT, got {entry!r}",
                  file=sys.stderr)
            return 2
        try:
            model_kw[key] = int(value)
        except ValueError:
            print(f"--set {key} needs an integer, got {value!r}",
                  file=sys.stderr)
            return 2
    if args.grad_sync is not None:
        model_kw["grad_sync"] = args.grad_sync

    hbm_prior = args.hbm_prior
    if hbm_prior is not None:
        try:
            hbm_prior = float(hbm_prior)
        except ValueError:
            pass  # a target name — resolved against hbm_priors.json

    try:
        result = plan(model=args.model, devices=args.devices,
                      device_kind=args.device_kind,
                      hbm_budget_bytes=args.hbm_budget_bytes,
                      verify=args.verify, hbm_prior=hbm_prior,
                      **model_kw)
    except (ValueError, TypeError) as e:
        print(str(e), file=sys.stderr)
        return 2
    except PlanError as e:
        print(f"no feasible plan: {e}", file=sys.stderr)
        return 1

    if args.out:
        with open(args.out, "w") as f:
            f.write(result.to_json())
    if args.json:
        print(result.to_json(), end="")
    else:
        print(render_table(result))
        if args.out:
            print(f"plan -> {args.out}")
    return 0
