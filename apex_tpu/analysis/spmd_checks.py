"""SPMD rank-consistency checks — the static counterpart of the PR 11
fleet desync/straggler detectors (ISSUE 14 tentpole).

A multi-host step is one program run by every rank; the bug class that
kills fleets is the program *disagreeing with itself across ranks*: a
collective issued under a rank-divergent branch (some ranks enter, the
rest never arrive — deadlock, or a silent partial reduction), a
rank-derived value stored into state the out_specs claim is replicated
(the fingerprint desync PR 11 can only observe at runtime), RNG streams
that are coordinated when they must differ (or differ when they must
not), and host effects whose order the runtime never pinned. The fleet
observability tier makes these failures *visible*; this module makes
them *un-committable*, the same way the precision/sharding sanitizers
gate their bug classes at lint time.

The engine is :class:`RankConsistencyLattice`, a third value domain
plugged into the unified multi-lattice walk (:mod:`.interp`). Per jaxpr
``Var`` it tracks:

- ``distinct``      mesh axes across which the value can DIFFER between
  ranks — seeded by shard_map ``in_names`` (per-shard data),
  ``lax.axis_index`` (rank identity), and scatter-type collectives;
  cleared by reducing/gathering collectives (``psum``/``pmax``/
  ``all_gather`` make the value identical along their axes). This is
  the :mod:`.sharding_flow` ``distinct`` notion, re-derived here so the
  lattice also flows it through rank-indexed ``dynamic_slice``s and
  RNG, where the placement engine deliberately resets provenance.
- ``rank_origin``   the subset of ``distinct`` whose divergence traces
  to ``axis_index``/``process_index`` specifically — "this value IS a
  function of the rank id", the signature of the chaos one-rank-desync
  pattern (``where(rank == k, poisoned, x)``) as opposed to ordinary
  data parallelism.
- ``rng``           the value derives from a PRNG primitive
  (``threefry2x32``/``random_bits``/``random_fold_in``/...). Combined
  with ``distinct`` it distinguishes the two RNG failure modes below.
- ``leaked``        set only on shard_map OUTPUTS: the mesh axes the
  inner value was still distinct over although this output's
  ``out_names`` never mentions them — the out_spec claims replication
  the program does not establish.

Four checks ride the lattice (:data:`SPMD_CHECKS`; the fifth member of
the family, ``nondeterministic-collective-order``, is an AST check in
:mod:`.ast_checks` — collective ISSUE order is decided by host Python,
not by the jaxpr):

- ``collective-in-divergent-control``  a collective inside a ``cond``/
  ``while`` whose predicate is rank-distinct over an axis the
  collective rides: ranks disagree about whether (or how many times)
  the collective executes — the canonical SPMD deadlock. The interp
  walk carries the divergent-control stack (:attr:`MeshCtx.control`);
  this lattice pushes entries via :meth:`Lattice.divergent_axes`
  (while predicates are evaluated by running the ``cond_jaxpr`` under
  the same lattice).
- ``rank-divergent-update``  a shard_map output whose ``out_names``
  claim replication over an axis the value is still distinct on — no
  reducing collective intervened between the rank-divergent value and
  the store. Fired at the shard_map boundary (where the program itself
  declares the replication contract), plus optionally on declared
  ``replicated_outs`` slots for un-shard_mapped steps.
- ``uncoordinated-rng``  (a) a rank-distinct RNG-derived value reaching
  a replicated store — per-rank noise applied to supposedly-replicated
  state desyncs the fleet exactly like the update check, but the fix
  is different (fold the key identically everywhere, or reduce the
  noise); (b) a rank-INVARIANT random float (same stream on every
  rank) meeting rank-distinct data elementwise inside shard_map —
  every rank applies the same dropout/noise mask to different data,
  silently correlating what should be independent samples. Integer
  joins are exempt: ``fold_in(key, axis_index)`` — an integer op — IS
  the coordination idiom, not the bug.
- ``unordered-host-effect``  an ``io_callback(ordered=False)`` /
  ``debug_callback`` positioned between two collectives on the same
  axis with NO data dependency anchoring it to either (result unused
  by any collective operand, inputs not derived from any collective
  result): the runtime may interleave the host effect differently per
  rank, so cross-rank logs/telemetry disagree about which collective
  the effect preceded. The fleet probe's own call sites pass by
  construction — its enter token is barrier-tied INTO the collective
  operand and its exit callback is FED the collective's result.

Entry point: :func:`analyze_spmd` (mirrors ``analyze_sharding``); the
registered schedules live in :mod:`.targets` (``SPMD_TARGETS``) and the
per-run counts land in the ``analysis/spmd_*`` metric family.
"""

from __future__ import annotations

import dataclasses

from apex_tpu.analysis import interp
from apex_tpu.analysis.findings import Finding
from apex_tpu.analysis.sharding_flow import (
    COLLECTIVE_PRIMS,
    _axis_names_of as _axes_of,
)

SPMD_CHECKS = (
    "collective-in-divergent-control", "rank-divergent-update",
    "uncoordinated-rng", "unordered-host-effect",
)

#: collectives that make their output IDENTICAL across the ridden axes
#: (every rank holds the same reduced/gathered result)
_REDUCING_COLLECTIVES = frozenset({
    "psum", "psum2", "pmin", "pmax", "all_gather",
    "all_gather_invariant",
})

#: collectives whose output remains (or becomes) per-rank distinct
_SCATTER_COLLECTIVES = frozenset({"psum_scatter", "reduce_scatter"})

#: PRNG primitives (raw threefry keys AND new-style typed keys)
_RNG_PRIMS = frozenset({
    "threefry2x32", "random_bits", "random_seed", "random_wrap",
    "random_unwrap", "random_fold_in", "random_split", "random_gamma",
})

#: unordered host-effect primitives the ordering check governs
_HOST_EFFECT_PRIMS = frozenset({"io_callback", "debug_callback"})

#: re-typing prims that move no bytes: a pbroadcast/pvary never makes a
#: value distinct, and never launders distinctness away either
_IDENTITY_PRIMS = frozenset({"pbroadcast", "pvary", "stop_gradient",
                             "copy", "optimization_barrier"})

#: genuinely elementwise joins — the only place the shared-stream RNG
#: pattern (b) applies (a gather/concatenate legitimately mixes worlds)
_ELEMENTWISE_PRIMS = frozenset({
    "add", "sub", "mul", "div", "max", "min", "pow", "atan2",
    "nextafter", "add_any", "select_n",
})


@dataclasses.dataclass(frozen=True)
class RankVal:
    """One point of the rank-consistency lattice (module docstring)."""

    distinct: frozenset = frozenset()
    rank_origin: frozenset = frozenset()
    rng: bool = False
    leaked: frozenset = frozenset()
    leaked_origin: frozenset = frozenset()  # leaked ∩ rank-id-derived

    def with_(self, **kw) -> "RankVal":
        return dataclasses.replace(self, **kw)


_EMPTY = RankVal()


def _join(ins) -> RankVal:
    present = [v for v in ins if v is not None]
    if not present:
        return _EMPTY
    return RankVal(
        distinct=frozenset().union(*(v.distinct for v in present)),
        rank_origin=frozenset().union(
            *(v.rank_origin for v in present)),
        rng=any(v.rng for v in present))


class RankConsistencyLattice(interp.Lattice):
    """Rank-distinctness semantics over the unified walk. Scan/while
    carries run the warm fixpoint (a carry fed by a ppermute or a
    rank-indexed slice picks up distinctness on iteration 1);
    ``shard_map`` seeds distinctness from ``in_names`` on entry and
    audits the replication claim of ``out_names`` on exit (the
    ``leaked`` field the rank-divergent-update check reads)."""

    name = "rank"
    warm_carry_join = True

    def for_aval(self, aval):
        return _EMPTY

    def transfer(self, eqn, ins, out_avals, ctx):
        prim = eqn.primitive.name
        n_out = len(out_avals)

        if prim == "axis_index":
            axis = str(eqn.params.get("axis_name"))
            # a size-1 axis has exactly one rank: its index is the
            # constant 0 everywhere, never a divergence source (and
            # the default ctx size for an unknown axis is 1, so an
            # un-modeled mesh stays conservative-quiet, matching the
            # sharding engine's unknown-spec discipline)
            if ctx.size(axis) <= 1:
                return tuple(_EMPTY for _ in range(n_out))
            v = RankVal(distinct=frozenset({axis}),
                        rank_origin=frozenset({axis}))
            return tuple(v for _ in range(n_out))

        if prim in _IDENTITY_PRIMS:
            base = _join(ins)
            if prim == "optimization_barrier":
                # elementwise over the tuple: each output mirrors its
                # own operand, not the join (the probe token must not
                # taint the bucket it orders)
                return tuple(
                    (ins[i] if i < len(ins) and ins[i] is not None
                     else _EMPTY) for i in range(n_out))
            return tuple(base for _ in range(n_out))

        if prim in _REDUCING_COLLECTIVES:
            axes = frozenset(_axes_of(
                eqn.params.get(COLLECTIVE_PRIMS.get(prim, "axes"))))
            base = _join(ins)
            out = base.with_(distinct=base.distinct - axes,
                             rank_origin=base.rank_origin - axes)
            return tuple(out for _ in range(n_out))

        if prim in _SCATTER_COLLECTIVES:
            axes = frozenset(
                a for a in _axes_of(eqn.params.get(
                    COLLECTIVE_PRIMS.get(prim, "axis_name")))
                if ctx.size(a) > 1)  # a 1-rank scatter is the identity
            base = _join(ins)
            out = base.with_(distinct=base.distinct | axes)
            return tuple(out for _ in range(n_out))

        if prim in ("ppermute", "all_to_all"):
            # data moved between ranks is still per-rank data
            base = _join(ins)
            return tuple(base for _ in range(n_out))

        if prim in _RNG_PRIMS:
            base = _join(ins)
            out = base.with_(rng=True)
            return tuple(out for _ in range(n_out))

        # default: distinctness is contagious through every compute op
        # (incl. dynamic_slice with a rank-derived start: the slice
        # CONTENT differs per rank even when the operand is replicated)
        base = _join(ins)
        return tuple(base for _ in range(n_out))

    # ---- joins / structure ------------------------------------------

    def join_branch(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return RankVal(distinct=a.distinct | b.distinct,
                       rank_origin=a.rank_origin | b.rank_origin,
                       rng=a.rng or b.rng)

    join_carry = join_branch

    def divergent_axes(self, eqn, ins, ctx) -> frozenset:
        prim = eqn.primitive.name
        if prim == "cond":
            pred = ins[0] if ins else None
            return pred.distinct if pred is not None else frozenset()
        if prim == "while":
            # the main walk only enters the BODY; the predicate lives in
            # cond_jaxpr(cond_consts ++ carry) — run it under this
            # lattice to see which axes it can differ over
            subs = interp.closed_jaxprs_in(
                eqn.params.get("cond_jaxpr"))
            if not subs:
                return frozenset()
            n_cond = eqn.params.get("cond_nconsts", 0)
            n_body = eqn.params.get("body_nconsts", 0)
            cond_ins = list(ins[:n_cond]) + list(ins[n_cond + n_body:])
            try:
                outs = interp.run_lattice_silent(
                    self, subs[0], cond_ins, ctx)
            except Exception:  # noqa: BLE001 — a malformed cond_jaxpr
                # must degrade to "not provably divergent", never kill
                # the whole analysis run
                return frozenset()
            axes = frozenset()
            for o in outs:
                if o is not None:
                    axes |= o.distinct
            return axes
        return frozenset()

    # ---- shard_map boundary -----------------------------------------

    def shard_map_enter(self, eqn, ins, sub, ctx):
        in_names = eqn.params.get("in_names", ())
        sizes = interp.shard_map_axis_sizes(eqn)
        mapped = []
        for i, _var in enumerate(sub.invars):
            names = in_names[i] if i < len(in_names) else {}
            # in_names consumption of a size-1 axis cannot make
            # per-shard data differ (there is one shard) — leaving it
            # out keeps findings independent of the host device count
            # a degenerate mesh was built over
            consumed = frozenset(
                str(a) for axes in dict(names or {}).values()
                for a in axes if sizes.get(str(a), 1) > 1)
            outer = ins[i] if i < len(ins) else None
            base = outer if outer is not None else _EMPTY
            mapped.append(base.with_(
                distinct=base.distinct | consumed, leaked=frozenset()))
        return mapped

    def shard_map_exit(self, eqn, inner_outs, ctx):
        out_names = eqn.params.get("out_names", ())
        mesh_axes = frozenset(interp.shard_map_axis_sizes(eqn))
        outs = []
        for i, _var in enumerate(eqn.outvars):
            names = out_names[i] if i < len(out_names) else {}
            declared = frozenset(
                str(a) for axes in dict(names or {}).values()
                for a in axes)
            inner = inner_outs[i] if i < len(inner_outs) else None
            if inner is None:
                outs.append(_EMPTY)
                continue
            # the replication claim: every mesh axis this shard_map
            # binds that out_names does NOT lay the value out over
            leaked = (inner.distinct & mesh_axes) - declared
            outs.append(RankVal(
                distinct=inner.distinct - mesh_axes,
                rank_origin=inner.rank_origin - mesh_axes,
                rng=inner.rng, leaked=leaked,
                leaked_origin=inner.rank_origin & leaked))
        return outs


RANK_LATTICE = RankConsistencyLattice()


# ------------------------------------------------------------- findings


def _fmt_axes(axes):
    return "/".join(f"'{a}'" for a in sorted(axes))


class _Ctx:
    def __init__(self, name, path, checks=frozenset(SPMD_CHECKS)):
        self.name = name
        self.path = path
        self.checks = frozenset(checks)
        self.findings = []
        self.seen = set()
        self.collectives = 0
        self.host_effects = 0

    def add(self, check, severity, message, dedup_key=None):
        if check not in self.checks:
            return
        if dedup_key is not None:
            key = (check,) + tuple(dedup_key)
            if key in self.seen:
                return
            self.seen.add(key)
        self.findings.append(Finding(
            check, severity, self.path, 0, self.name, message))


def _visit_divergent_control(ctx, eqn, ins, outs, mctx):
    prim = eqn.primitive.name
    if prim not in COLLECTIVE_PRIMS:
        return
    axes = frozenset(_axes_of(eqn.params.get(COLLECTIVE_PRIMS[prim])))
    for control_prim, div_axes in mctx.control:
        hit = axes & div_axes
        if hit:
            ctx.add(
                "collective-in-divergent-control", "error",
                f"'{prim}' over {_fmt_axes(axes)} is issued inside a "
                f"'{control_prim}' whose predicate can differ across "
                f"{_fmt_axes(hit)}: ranks disagree about whether (or "
                f"how many times) this collective executes — some "
                f"arrive, the rest never do, and the fleet deadlocks "
                f"(or silently reduces a partial group). Hoist the "
                f"collective out of the branch, or make the predicate "
                f"rank-invariant (reduce it first: "
                f"psum/pmax the flag over {_fmt_axes(hit)})",
                dedup_key=(prim, tuple(sorted(axes)), control_prim))


def _visit_shard_map_exit(ctx, eqn, ins, outs, mctx):
    """The replication-claim audit: emits ``rank-divergent-update``,
    or ``uncoordinated-rng`` for the RNG-derived form when that check
    is enabled (a disabled specific check degrades to the generic one
    — the divergence is real either way; ``_Ctx.add`` drops whatever
    the caller's ``checks=`` excluded)."""
    if eqn.primitive.name != "shard_map":
        return
    for i, out in enumerate(outs):
        if out is None or not out.leaked:
            continue
        if out.rng and "uncoordinated-rng" in ctx.checks:
            ctx.add(
                "uncoordinated-rng", "error",
                f"shard_map output {i} carries RNG-derived data that "
                f"can differ across {_fmt_axes(out.leaked)} although "
                f"its out_specs claim replication over "
                f"{'that axis' if len(out.leaked) == 1 else 'those axes'}"
                f": every rank applies its own random stream to state "
                f"the program treats as replicated — the fleet desyncs "
                f"on the first step. Derive the key identically on "
                f"every rank (fold with the step, not axis_index), or "
                f"reduce the randomized update before storing",
                dedup_key=("rng-out", i, tuple(sorted(out.leaked))))
            continue
        origin = out.leaked_origin
        how = (f"derives from lax.axis_index over "
               f"{_fmt_axes(origin)} (the one-rank-desync shape: a "
               f"rank-conditional write)" if origin else
               f"is per-rank data (sharded input reached this store "
               f"with no reducing collective on the path)")
        ctx.add(
            "rank-divergent-update", "error",
            f"shard_map output {i} can differ across "
            f"{_fmt_axes(out.leaked)} although its out_specs claim "
            f"replication: the value {how}. Stored into params/"
            f"optimizer state this is the PR 11 fingerprint desync, "
            f"made static — insert the missing psum/pmean over "
            f"{_fmt_axes(out.leaked)} before the store, or declare the "
            f"output sharded if per-rank state is intended",
            dedup_key=("out", i, tuple(sorted(out.leaked))))


def _visit_uncoordinated_rng(ctx, eqn, ins, outs, mctx):
    """Pattern (b): a rank-invariant random FLOAT meets rank-distinct
    data elementwise inside the manual (shard_map) world."""
    prim = eqn.primitive.name
    if prim not in _ELEMENTWISE_PRIMS or not mctx.manual_axes:
        return
    present = [(v, iv) for v, iv in zip(ins, eqn.invars)
               if v is not None]
    if len(present) < 2:
        return
    import numpy as np

    def _is_float(var):
        try:
            return np.dtype(str(var.aval.dtype)).kind == "f"
        except Exception:  # noqa: BLE001 — exotic dtype: not a sample
            return False

    shared_rng = [
        (v, iv) for v, iv in present
        if v.rng and not (v.distinct & mctx.manual_axes)
        and _is_float(iv)]
    distinct_data = [
        v for v, _ in present if (v.distinct & mctx.manual_axes)]
    if shared_rng and distinct_data:
        axes = frozenset().union(*(v.distinct for v in distinct_data)) \
            & mctx.manual_axes
        ctx.add(
            "uncoordinated-rng", "warning",
            f"'{prim}' applies a rank-INVARIANT random sample to data "
            f"that differs across {_fmt_axes(axes)}: every rank draws "
            f"the identical stream (same dropout/noise mask against "
            f"different shards), silently correlating what should be "
            f"independent samples — fold the PRNG key with "
            f"lax.axis_index({_fmt_axes(axes)}) so each rank gets its "
            f"own stream",
            dedup_key=("shared-stream", prim, tuple(sorted(axes))))


def _visitors_for(run):
    """The eqn visitors an analyze run needs. The shard_map-exit audit
    serves BOTH update/rng check ids (emission is gated per id inside
    `_Ctx.add`), so requesting either installs it."""
    visitors = []
    if "collective-in-divergent-control" in run:
        visitors.append(_visit_divergent_control)
    if {"rank-divergent-update", "uncoordinated-rng"} & run:
        visitors.append(_visit_shard_map_exit)
    if "uncoordinated-rng" in run:
        visitors.append(_visit_uncoordinated_rng)
    return visitors


# --------------------------------------- unordered host effects (walk)


def _flatten_body(jaxpr, env, steps):
    """Call prims inlined (caller-world var identity), everything else
    one step — the same-body linear order the interleaving check
    reasons over. Control-flow/shard_map bodies are collected as
    separate bodies by the caller."""
    def canon(v):
        while v in env:
            v = env[v]
        return v

    for eqn in jaxpr.eqns:
        sub = None
        if eqn.primitive.name in interp.CALL_PRIMS:
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                if key in eqn.params:
                    subs = interp.closed_jaxprs_in(eqn.params[key])
                    if subs:
                        sub = interp.jaxpr_of(subs[0])
                        break
        if sub is not None and len(sub.invars) == len(eqn.invars):
            for iv, ov in zip(sub.invars, eqn.invars):
                if interp.is_var(ov):
                    env[iv] = canon(ov)
            _flatten_body(sub, env, steps)
            for inner_ov, outer_ov in zip(sub.outvars, eqn.outvars):
                if interp.is_var(inner_ov):
                    env[outer_ov] = canon(inner_ov)
            continue
        reads = [canon(v) if interp.is_var(v) else None
                 for v in eqn.invars]
        steps.append((eqn, reads))


def _iter_bodies(jaxpr):
    """Yield every distinct body (flattened step list) in the program:
    the top level, and each control-flow / shard_map sub-body."""
    env: dict = {}
    steps: list = []
    _flatten_body(jaxpr, env, steps)
    yield steps
    for eqn, _reads in steps:
        if eqn.primitive.name in interp.CALL_PRIMS:
            continue
        for value in eqn.params.values():
            for sub in interp.closed_jaxprs_in(value):
                yield from _iter_bodies(interp.jaxpr_of(sub))


def _is_unordered_effect(eqn) -> bool:
    prim = eqn.primitive.name
    if prim not in _HOST_EFFECT_PRIMS:
        return False
    if prim == "io_callback":
        return not bool(eqn.params.get("ordered", False))
    return True  # debug_callback carries no ordering guarantee


def _check_unordered_effects(ctx, closed):
    """Per body: unanchored unordered host effects positioned between
    two collectives on the same axis."""
    for steps in _iter_bodies(closed.jaxpr):
        collectives = []   # (pos, axes, eqn)
        effects = []       # (pos, eqn, reads)
        for pos, (eqn, reads) in enumerate(steps):
            prim = eqn.primitive.name
            if prim in COLLECTIVE_PRIMS:
                axes = frozenset(_axes_of(
                    eqn.params.get(COLLECTIVE_PRIMS[prim])))
                collectives.append((pos, axes, eqn))
            elif _is_unordered_effect(eqn):
                effects.append((pos, eqn, reads))
        ctx.collectives += len(collectives)
        ctx.host_effects += len(effects)
        if not effects or len(collectives) < 2:
            continue

        # forward: vars (transitively) derived from a collective result
        derived = set()
        # reverse: vars that (transitively) feed a collective operand
        feeds = set()
        for eqn, reads in steps:
            if any(r is not None and r in derived for r in reads) or \
                    eqn.primitive.name in COLLECTIVE_PRIMS:
                derived.update(v for v in eqn.outvars
                               if interp.is_var(v))
        for eqn, reads in reversed(steps):
            if eqn.primitive.name in COLLECTIVE_PRIMS or \
                    any(v in feeds for v in eqn.outvars
                        if interp.is_var(v)):
                feeds.update(r for r in reads if r is not None)

        for pos, eqn, reads in effects:
            anchored = any(r is not None and r in derived
                           for r in reads) or \
                any(interp.is_var(v) and v in feeds
                    for v in eqn.outvars)
            if anchored:
                continue
            between = sorted(
                axes_hit
                for (p0, a0, _e0) in collectives
                for (p1, a1, _e1) in collectives
                for axes_hit in (a0 & a1,)
                if p0 < pos < p1 and axes_hit)
            if not between:
                continue
            axes = between[0]
            ctx.add(
                "unordered-host-effect", "warning",
                f"'{eqn.primitive.name}' with no ordering guarantee "
                f"(ordered=False) sits between collectives over "
                f"{_fmt_axes(axes)} with no data dependency tying it "
                f"to either: the runtime may interleave the host "
                f"effect differently on each rank, so cross-rank "
                f"logs/telemetry disagree about which collective it "
                f"preceded — anchor it like the fleet probe does "
                f"(barrier-tie its token into the collective operand, "
                f"or feed it the collective's result), or pass "
                f"ordered=True",
                dedup_key=(eqn.primitive.name, pos))


# --------------------------------------------------------------- entry


def analyze_spmd(fn, *example_args, name=None, in_distinct=None,
                 replicated_outs=None, axis_sizes=None, checks=None,
                 stats_out=None):
    """Trace ``fn`` and run the rank-consistency checks over its jaxpr.

    ``in_distinct``: {argnum: iterable of mesh axes} marking positional
    arguments whose leaves already differ per rank when the traced fn
    is NOT a shard_map (inside one, ``in_names`` seed distinctness
    automatically). ``replicated_outs``: flat output slots that must be
    rank-invariant — a sequence of indices (no divergence allowed), or
    {index: allowed-axes} (divergence over the allowed axes is the
    declared sharding; anything else fires). shard_map outputs are
    audited against their own ``out_names`` regardless. ``stats_out``:
    optional dict receiving ``collectives`` / ``host_effects`` counts
    (UNORDERED host effects — the population the ordering check
    governs; the ``analysis/spmd_*`` gauges). Returns a list of
    :class:`Finding`.
    """
    import jax

    name = name or getattr(fn, "__name__", "fn")
    run = _validate_checks(checks)
    path = f"<jaxpr:{name}>"

    closed = jax.make_jaxpr(fn)(*example_args)

    in_vals = []
    flat_distinct = {}
    if in_distinct:
        idx = 0
        for argnum, arg in enumerate(example_args):
            n = len(jax.tree_util.tree_leaves(arg))
            if argnum in in_distinct:
                axes = frozenset(str(a) for a in in_distinct[argnum])
                for j in range(idx, idx + n):
                    flat_distinct[j] = axes
            idx += n
    for i, _var in enumerate(closed.jaxpr.invars):
        axes = flat_distinct.get(i)
        in_vals.append(RankVal(distinct=axes) if axes else None)

    ctx = _Ctx(name, path, checks=run)
    visitors = _visitors_for(run)

    def visit(eqn, ins, outs, mctx):
        for v in visitors:
            v(ctx, eqn, ins, outs, mctx)

    if axis_sizes is None:
        from apex_tpu.analysis.sharding_flow import live_mesh_axis_sizes
        axis_sizes = live_mesh_axis_sizes()
    (out_vals,) = interp.interpret_lattices(
        closed, [interp.LatticeRun(RANK_LATTICE, in_vals,
                                   visit if visitors else None)],
        axis_sizes=axis_sizes)

    if replicated_outs and ("rank-divergent-update" in run
                            or "uncoordinated-rng" in run):
        declared = (replicated_outs if isinstance(replicated_outs, dict)
                    else {i: () for i in replicated_outs})
        for i, allowed in sorted(declared.items()):
            if i >= len(out_vals) or out_vals[i] is None:
                continue
            bad = out_vals[i].distinct - frozenset(
                str(a) for a in allowed)
            if not bad:
                continue
            if out_vals[i].rng and "uncoordinated-rng" in run:
                ctx.add(
                    "uncoordinated-rng", "error",
                    f"output {i} is declared replicated but carries "
                    f"RNG-derived data that can differ across "
                    f"{_fmt_axes(bad)} — per-rank randomness reaching "
                    f"replicated state desyncs the fleet; coordinate "
                    f"the key or reduce before storing",
                    dedup_key=("declared-rng", i))
            elif "rank-divergent-update" in run:
                origin = out_vals[i].rank_origin & bad
                ctx.add(
                    "rank-divergent-update", "error",
                    f"output {i} is declared replicated but can differ "
                    f"across {_fmt_axes(bad)}"
                    + (f" (derives from lax.axis_index over "
                       f"{_fmt_axes(origin)})" if origin else "")
                    + " — insert the missing reducing collective "
                      "before the store",
                    dedup_key=("declared", i))

    if "unordered-host-effect" in run:
        _check_unordered_effects(ctx, closed)
    else:
        # stats stay populated either way (the gauges feed bench) —
        # counting the SAME predicate as the check path, so the
        # host_effects number never depends on which checks ran
        for steps in _iter_bodies(closed.jaxpr):
            for eqn, _reads in steps:
                if eqn.primitive.name in COLLECTIVE_PRIMS:
                    ctx.collectives += 1
                elif _is_unordered_effect(eqn):
                    ctx.host_effects += 1

    if stats_out is not None:
        stats_out.update({"collectives": ctx.collectives,
                          "host_effects": ctx.host_effects})
    return ctx.findings


def _validate_checks(checks):
    run = set(checks or SPMD_CHECKS)
    unknown = run - set(SPMD_CHECKS)
    if unknown:
        raise ValueError(
            f"unknown spmd check(s) {sorted(unknown)}; valid: "
            f"{list(SPMD_CHECKS)}")
    return run


def report_to_registry(results, registry=None):
    """Publish spmd findings + per-target collective counts as the
    ``analysis/spmd_*`` metric family.

    ``results``: {target name: (findings list, stats dict)}. Counters:
    ``analysis/spmd_findings{check=}``; gauges:
    ``analysis/spmd_findings_total``,
    ``analysis/spmd_collectives{target=}``,
    ``analysis/spmd_host_effects{target=}``. Returns {check id: count}.
    """
    from apex_tpu.observability import get_registry

    reg = registry if registry is not None else get_registry()
    counts = {c: 0 for c in SPMD_CHECKS}
    for target, (findings, stats) in sorted(results.items()):
        for f in findings:
            if f.check in counts:
                counts[f.check] += 1
        if stats:
            reg.gauge("analysis/spmd_collectives",
                      target=target).set(stats.get("collectives", 0))
            reg.gauge("analysis/spmd_host_effects",
                      target=target).set(stats.get("host_effects", 0))
    for check, n in counts.items():
        if n:
            reg.counter("analysis/spmd_findings", check=check).inc(n)
    reg.gauge("analysis/spmd_findings_total").set(sum(counts.values()))
    return counts
