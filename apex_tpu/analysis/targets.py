"""Registered jaxpr-engine analysis targets: the repo's real entry
points, traced with representative avals and run through every jaxpr
check. ``python -m apex_tpu.analysis`` and tests/run_analysis execute
all of them, so a regression in donation discipline, collective axis
wiring, or a kernel's BlockSpecs fails tier-1 without hardware.

Each target is a zero-arg callable returning a list of Findings. Keep
them cheap: tracing only (no compile, no execution) on the CPU backend.
"""

from __future__ import annotations

from apex_tpu.analysis.findings import Finding
from apex_tpu.analysis.jaxpr_checks import analyze_fn

TARGETS = {}

# Check ids produced by non-tracing targets (everything else emits the
# jaxpr_checks.JAXPR_CHECKS ids). The CLI derives --list-checks, check-id
# validation, and target narrowing from this — register new
# target-provided checks here, not in cli.py.
TARGET_CHECKS = ("kernel-auto-provenance", "step-record-schema")


def target(name):
    def deco(fn):
        TARGETS[name] = fn
        return fn
    return deco


@target("fused_adam_flat_step")
def _fused_adam_flat_step():
    """The flat-buffer Adam path behind a donated train step — the first
    customer the ISSUE names: its donated aliasing was never
    machine-checked."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.optimizers import fused_adam

    params = {"w": jnp.zeros((64, 128), jnp.float32),
              "b": jnp.zeros((128,), jnp.float32)}
    tx = fused_adam(lr=1e-3, weight_decay=0.01, flat=True)
    state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)

    def train_step(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, opt_state

    return analyze_fn(train_step, params, state, grads,
                      donate_argnums=(0, 1), name="fused_adam_flat_step")


@target("fused_adam_flat_kernel")
def _fused_adam_flat_kernel():
    """The Pallas flat-Adam kernel's BlockSpecs (scalar block + slab
    padding are the Mosaic-sensitive parts)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.optimizers import fused_adam
    from apex_tpu.ops import pallas_config

    params = {"w": jnp.zeros((4096,), jnp.float32)}
    tx = fused_adam(lr=1e-3, flat=True, use_kernel=True)
    state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    with pallas_config.force("interpret"):
        return analyze_fn(lambda g, s, p: tx.update(g, s, p),
                          grads, state, params,
                          name="fused_adam_flat_kernel")


@target("flash_attention_fwd")
def _flash_attention_fwd():
    import jax.numpy as jnp

    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.flash_attention import flash_attention

    q = jnp.zeros((2, 256, 4, 64), jnp.bfloat16)
    with pallas_config.force("on"):
        return analyze_fn(
            lambda q, k, v: flash_attention(q, k, v, causal=True),
            q, q, q, name="flash_attention_fwd")


@target("layer_norm_fwd")
def _layer_norm_fwd():
    import jax.numpy as jnp

    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.layer_norm import layer_norm

    x = jnp.zeros((256, 1024), jnp.bfloat16)
    w = jnp.ones((1024,), jnp.float32)
    b = jnp.zeros((1024,), jnp.float32)
    with pallas_config.force("on"):
        return analyze_fn(lambda x, w, b: layer_norm(x, w, b, (1024,)),
                          x, w, b, name="layer_norm_fwd")


@target("causal_softmax")
def _causal_softmax():
    import jax.numpy as jnp

    from apex_tpu.ops import pallas_config
    from apex_tpu.transformer.functional.fused_softmax import (
        scaled_upper_triang_masked_softmax,
    )

    x = jnp.zeros((8, 256, 256), jnp.bfloat16)
    with pallas_config.force("on"):
        return analyze_fn(
            lambda x: scaled_upper_triang_masked_softmax(x, None, 1.0),
            x, name="causal_softmax")


@target("tp_collectives")
def _tp_collectives():
    """Tensor-parallel allreduce wiring against the live parallel_state
    mesh — the collective-axis check's first customer."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state

    owned = not parallel_state.model_parallel_is_initialized()
    if owned:
        tp = 2 if len(jax.devices()) >= 2 else 1
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=tp)
    try:
        mesh = parallel_state.get_mesh()
        axis = parallel_state.get_tensor_model_parallel_group()
        tp = mesh.shape[axis]

        def allreduce(x):
            return jax.lax.psum(x, axis)

        fn = shard_map(allreduce, mesh=mesh, in_specs=P(axis),
                       out_specs=P())
        return analyze_fn(fn, jnp.zeros((tp * 8,), jnp.float32),
                          mesh_axes=mesh, name="tp_collectives")
    finally:
        if owned:
            parallel_state.destroy_model_parallel()


@target("kernel-auto-provenance")
def _kernel_auto_provenance():
    """Every pinned _KERNEL_AUTO verdict must name its evidence artifact
    (satellite: ops/pallas_config.py provenance)."""
    from apex_tpu.ops import pallas_config

    return [Finding("kernel-auto-provenance", "error",
                    "apex_tpu/ops/pallas_config.py", 0, "_KERNEL_AUTO",
                    problem)
            for problem in pallas_config.validate_kernel_auto_provenance()]


@target("step-record-schema")
def _step_record_schema():
    """The observability layer's own gate: a StepReporter record built
    from synthetic inputs must carry every STEP_RECORD_FIELDS key and
    survive a registry JSONL round-trip — the step-record schema is the
    evidence format every perf PR reads, so drift fails tier-1 here
    (ISSUE 2 satellite: the new module is registered and linted like
    any other entry point; the AST engine covers its sources via the
    default path set)."""
    import json as _json

    from apex_tpu.observability.registry import MetricRegistry
    from apex_tpu.observability.step_report import (
        STEP_RECORD_FIELDS, StepReporter,
    )

    findings = []

    def problem(msg):
        findings.append(Finding(
            "step-record-schema", "error",
            "apex_tpu/observability/step_report.py", 0, "StepReporter",
            msg))

    reg = MetricRegistry()
    rec = StepReporter("schema_check", registry=reg, tokens_per_step=1024,
                       flops_per_step=1e12, device_kind="cpu",
                       peak=1e15).step(0.01, loss=1.0)
    for field in STEP_RECORD_FIELDS:
        if field not in rec:
            problem(f"step record is missing documented field "
                    f"{field!r}")
    try:
        records = reg.to_records()
        _json.dumps(records)
    except (TypeError, ValueError) as e:
        problem(f"registry records are not JSON-serializable: {e}")
        return findings
    if not any(r.get("type") == "event" and r.get("name") == "step"
               for r in records):
        problem("StepReporter.step did not append a 'step' event to "
                "the registry")
    return findings


def run_targets(names=None):
    """Run the registered targets; returns (findings, errors) where
    errors maps target name -> repr of an exception that kept the target
    from tracing at all (itself a failure the caller should surface)."""
    findings, errors = [], {}
    for name, fn in TARGETS.items():
        if names is not None and name not in names:
            continue
        try:
            findings.extend(fn())
        except Exception as e:  # noqa: BLE001 — report, don't abort the scan
            errors[name] = repr(e)[:300]
    return findings, errors
