"""Registered jaxpr-engine analysis targets: the repo's real entry
points, traced with representative avals and run through every jaxpr
check. ``python -m apex_tpu.analysis`` and tests/run_analysis execute
all of them, so a regression in donation discipline, collective axis
wiring, or a kernel's BlockSpecs fails tier-1 without hardware.

Each target is a zero-arg callable returning a list of Findings. Keep
them cheap: tracing only (no compile, no execution) on the CPU backend.
"""

from __future__ import annotations

from apex_tpu.analysis.findings import Finding
from apex_tpu.analysis.jaxpr_checks import JAXPR_CHECKS, analyze_fn
from apex_tpu.analysis.precision_checks import (
    PRECISION_CHECKS,
    analyze_precision,
)
from apex_tpu.analysis.sharding_checks import (
    SHARDING_CHECKS,
    analyze_sharding,
)
from apex_tpu.analysis.memory_checks import MEMORY_CHECKS, analyze_memory
from apex_tpu.analysis.spmd_checks import SPMD_CHECKS, analyze_spmd
from apex_tpu.analysis.state_checks import STATE_CHECKS, analyze_state

TARGETS = {}

# Per-target comms-bytes / peak-HBM estimates from the last
# analyze_sharding run of each sharding target (filled as the targets
# execute; read by run_sharding_findings and bench.py).
SHARDING_STATS = {}

# Per-target grandfather lists (the jaxpr analog of `# apex-lint:
# disable`, which only reaches AST findings): @target(..., allow=(...))
# drops those check ids from that target's findings at the source, so a
# deliberate half-precision path doesn't need a global baseline slot.
# The CLI's --allow target:check lands here too (see run_targets).
TARGET_ALLOW = {}

# Check ids produced by non-tracing targets (everything else emits the
# jaxpr_checks.JAXPR_CHECKS ids). The CLI derives --list-checks, check-id
# validation, and target narrowing from this — register new
# target-provided checks here, not in cli.py.
TARGET_CHECKS = ("kernel-auto-provenance", "step-record-schema")

# Check ids that require running the tracing targets (the CLI runs the
# full target suite when any of these is requested).
TRACING_CHECKS = (tuple(JAXPR_CHECKS) + tuple(PRECISION_CHECKS)
                  + tuple(SHARDING_CHECKS) + tuple(SPMD_CHECKS)
                  + tuple(STATE_CHECKS) + tuple(MEMORY_CHECKS))

# Per-target collective/host-effect counts from the last analyze_spmd
# run of each spmd target (the analysis/spmd_* gauge family).
SPMD_STATS = {}

# Per-target carried/saved leaf counts from the last analyze_state run
# of each state target (the analysis/state_* gauge family).
STATE_STATS = {}

# Per-target peak/steady liveness numbers from the last analyze_memory
# run of each memory target (the analysis/memory_* gauge family).
MEMORY_STATS = {}


def target(name, allow=()):
    def deco(fn):
        TARGETS[name] = fn
        if allow:
            unknown = set(allow) - set(TRACING_CHECKS) - set(TARGET_CHECKS)
            if unknown:
                raise ValueError(
                    f"@target({name!r}) allows unknown check id(s) "
                    f"{sorted(unknown)}")
            TARGET_ALLOW[name] = frozenset(allow)
        return fn
    return deco


@target("fused_adam_flat_step")
def _fused_adam_flat_step():
    """The flat-buffer Adam path behind a donated train step — the first
    customer the ISSUE names: its donated aliasing was never
    machine-checked."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.optimizers import fused_adam

    params = {"w": jnp.zeros((64, 128), jnp.float32),
              "b": jnp.zeros((128,), jnp.float32)}
    tx = fused_adam(lr=1e-3, weight_decay=0.01, flat=True)
    state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)

    def train_step(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, opt_state

    return analyze_fn(train_step, params, state, grads,
                      donate_argnums=(0, 1), name="fused_adam_flat_step")


@target("fused_adam_flat_kernel")
def _fused_adam_flat_kernel():
    """The Pallas flat-Adam kernel's BlockSpecs (scalar block + slab
    padding are the Mosaic-sensitive parts)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.optimizers import fused_adam
    from apex_tpu.ops import pallas_config

    params = {"w": jnp.zeros((4096,), jnp.float32)}
    tx = fused_adam(lr=1e-3, flat=True, use_kernel=True)
    state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    with pallas_config.force("interpret"):
        return analyze_fn(lambda g, s, p: tx.update(g, s, p),
                          grads, state, params,
                          name="fused_adam_flat_kernel")


@target("flash_attention_fwd")
def _flash_attention_fwd():
    import jax.numpy as jnp

    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.flash_attention import flash_attention

    q = jnp.zeros((2, 256, 4, 64), jnp.bfloat16)
    with pallas_config.force("on"):
        return analyze_fn(
            lambda q, k, v: flash_attention(q, k, v, causal=True),
            q, q, q, name="flash_attention_fwd")


@target("layer_norm_fwd")
def _layer_norm_fwd():
    import jax.numpy as jnp

    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.layer_norm import layer_norm

    x = jnp.zeros((256, 1024), jnp.bfloat16)
    w = jnp.ones((1024,), jnp.float32)
    b = jnp.zeros((1024,), jnp.float32)
    with pallas_config.force("on"):
        return analyze_fn(lambda x, w, b: layer_norm(x, w, b, (1024,)),
                          x, w, b, name="layer_norm_fwd")


@target("causal_softmax")
def _causal_softmax():
    import jax.numpy as jnp

    from apex_tpu.ops import pallas_config
    from apex_tpu.transformer.functional.fused_softmax import (
        scaled_upper_triang_masked_softmax,
    )

    x = jnp.zeros((8, 256, 256), jnp.bfloat16)
    with pallas_config.force("on"):
        return analyze_fn(
            lambda x: scaled_upper_triang_masked_softmax(x, None, 1.0),
            x, name="causal_softmax")


@target("tp_collectives")
def _tp_collectives():
    """Tensor-parallel allreduce wiring against the live parallel_state
    mesh — the collective-axis check's first customer."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state

    owned = not parallel_state.model_parallel_is_initialized()
    if owned:
        tp = 2 if len(jax.devices()) >= 2 else 1
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=tp)
    try:
        mesh = parallel_state.get_mesh()
        axis = parallel_state.get_tensor_model_parallel_group()
        tp = mesh.shape[axis]

        def allreduce(x):
            return jax.lax.psum(x, axis)

        fn = shard_map(allreduce, mesh=mesh, in_specs=P(axis),
                       out_specs=P())
        return analyze_fn(fn, jnp.zeros((tp * 8,), jnp.float32),
                          mesh_axes=mesh, name="tp_collectives")
    finally:
        if owned:
            parallel_state.destroy_model_parallel()


@target("kernel-auto-provenance")
def _kernel_auto_provenance():
    """Every pinned _KERNEL_AUTO verdict must name its evidence artifact
    (satellite: ops/pallas_config.py provenance)."""
    from apex_tpu.ops import pallas_config

    return [Finding("kernel-auto-provenance", "error",
                    "apex_tpu/ops/pallas_config.py", 0, "_KERNEL_AUTO",
                    problem)
            for problem in pallas_config.validate_kernel_auto_provenance()]


@target("step-record-schema")
def _step_record_schema():
    """The observability layer's own gate: a StepReporter record built
    from synthetic inputs must carry every STEP_RECORD_FIELDS key and
    survive a registry JSONL round-trip — the step-record schema is the
    evidence format every perf PR reads, so drift fails tier-1 here
    (ISSUE 2 satellite: the new module is registered and linted like
    any other entry point; the AST engine covers its sources via the
    default path set)."""
    import json as _json

    from apex_tpu.observability.registry import MetricRegistry
    from apex_tpu.observability.step_report import (
        STEP_RECORD_FIELDS, StepReporter,
    )

    findings = []

    def problem(msg):
        findings.append(Finding(
            "step-record-schema", "error",
            "apex_tpu/observability/step_report.py", 0, "StepReporter",
            msg))

    reg = MetricRegistry()
    rec = StepReporter("schema_check", registry=reg, tokens_per_step=1024,
                       flops_per_step=1e12, device_kind="cpu",
                       peak=1e15).step(0.01, loss=1.0)
    for field in STEP_RECORD_FIELDS:
        if field not in rec:
            problem(f"step record is missing documented field "
                    f"{field!r}")
    try:
        records = reg.to_records()
        _json.dumps(records)
    except (TypeError, ValueError) as e:
        problem(f"registry records are not JSON-serializable: {e}")
        return findings
    if not any(r.get("type") == "event" and r.get("name") == "step"
               for r in records):
        problem("StepReporter.step did not append a 'step' event to "
                "the registry")
    return findings


# ----------------------------------------------- precision-flow targets
# (ISSUE 3): the amp/optimizer/normalization/transformer entry points
# whose documented precision discipline the dataflow checks enforce.
# All are trace-only on the CPU backend, like everything above.

def _leaf_count(tree):
    import jax
    return len(jax.tree_util.tree_leaves(tree))


@target("mlp_train_step")
def _mlp_train_step():
    """bf16 MLP forward+backward with an fp32 loss: every dot must pin
    an fp32 accumulator (mlp.py preferred_element_type) and the loss
    reduction must run in fp32 — the seeded-regression anchor the ISSUE
    names (drop the preferred_element_type and tier-1 fails here)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.mlp import mlp_function

    params = (jnp.zeros((128, 256), jnp.bfloat16),
              jnp.zeros((256,), jnp.bfloat16),
              jnp.zeros((256, 64), jnp.bfloat16),
              jnp.zeros((64,), jnp.bfloat16))
    x = jnp.zeros((32, 128), jnp.bfloat16)
    y = jnp.zeros((32, 64), jnp.float32)

    def loss_fn(params, x, y):
        out = mlp_function(True, "relu", x, *params)
        d = out.astype(jnp.float32) - y
        return jnp.mean(jnp.square(d))

    return analyze_precision(
        lambda p, x, y: jax.value_and_grad(loss_fn)(p, x, y),
        params, x, y, name="mlp_train_step")


@target("amp_o1_train_step")
def _amp_o1_train_step():
    """O1: fp32 params, bf16 boundary casting via the active policy,
    loss scaled before backward. The precision contract here is that
    boundary-cast matmuls still accumulate fp32 and the loss math stays
    fp32 — exactly what docs/amp.md promises for O1."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.amp import amp as amp_mod
    from apex_tpu.amp.frontend import Policy
    from apex_tpu.amp.scaler import LossScaler
    from apex_tpu.mlp import mlp_function

    params = (jnp.zeros((128, 256), jnp.float32),
              jnp.zeros((256,), jnp.float32),
              jnp.zeros((256, 64), jnp.float32),
              jnp.zeros((64,), jnp.float32))
    x = jnp.zeros((32, 128), jnp.float32)
    y = jnp.zeros((32, 64), jnp.float32)
    scaler = LossScaler("dynamic")
    sstate = scaler.init()
    policy = Policy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
                    output_dtype=jnp.float32)

    def scaled_loss(params, x, y, sstate):
        out = mlp_function(True, "relu", x, *params)
        loss = jnp.mean(jnp.square(out.astype(jnp.float32) - y))
        return scaler.scale_loss(loss, sstate)

    with amp_mod.casting(policy):
        return analyze_precision(
            lambda p, x, y, s: jax.value_and_grad(scaled_loss)(p, x, y, s),
            params, x, y, sstate, name="amp_o1_train_step")


@target("amp_o2_master_update")
def _amp_o2_master_update():
    """O2 update phase: bf16 model copy, fp32 master + moments, scaled
    bf16 grads through unscale -> overflow-gated FusedAdam -> master
    apply -> half re-materialization. Exercises master-weights (the
    fp32 path must never dip to half) and loss-scale-bypass (the grads
    must pass the scaler's unscale before touching state)."""
    import jax
    import jax.numpy as jnp
    import optax

    from apex_tpu.amp.scaler import LossScaler, scaled_update
    from apex_tpu.optimizers import fused_adam

    master = {"w": jnp.zeros((64, 128), jnp.float32),
              "b": jnp.zeros((128,), jnp.float32)}
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16), master)
    grads = jax.tree_util.tree_map(
        lambda p: jnp.ones_like(p, jnp.bfloat16), master)
    tx = fused_adam(lr=1e-3, weight_decay=0.01, flat=True)
    state = tx.init(master)
    scaler = LossScaler("dynamic")
    sstate = scaler.init()

    def update(grads, opt_state, master, params, sstate):
        updates, new_opt, new_ss, overflow = scaled_update(
            tx, scaler, grads, opt_state, master, sstate)
        new_master = optax.apply_updates(master, updates)
        new_params = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype), new_master, params)
        return new_master, new_opt, new_params, new_ss

    n_master = _leaf_count(master)
    n_state = _leaf_count(state)
    return analyze_precision(
        update, grads, state, master, params, sstate,
        roles={0: "grad", 1: "master", 2: "master", 3: "param",
               4: "scale"},
        master_outs=tuple(range(n_master + n_state)),
        name="amp_o2_master_update")


@target("fused_adam_tree_master_step")
def _fused_adam_tree_master_step():
    """Per-tensor FusedAdam over fp32 master params: the whole update
    chain (m, v, decay, apply) must stay fp32."""
    import jax
    import jax.numpy as jnp
    import optax

    from apex_tpu.optimizers import fused_adam

    master = {"w": jnp.zeros((64, 128), jnp.float32),
              "b": jnp.zeros((128,), jnp.float32)}
    tx = fused_adam(lr=1e-3, weight_decay=0.01, flat=False)
    state = tx.init(master)
    grads = jax.tree_util.tree_map(jnp.ones_like, master)

    def step(grads, state, master):
        updates, new_state = tx.update(grads, state, master)
        return optax.apply_updates(master, updates), new_state

    n_out = _leaf_count(master) + _leaf_count(state)
    return analyze_precision(
        step, grads, state, master,
        roles={1: "master", 2: "master"},
        master_outs=tuple(range(n_out)),
        name="fused_adam_tree_master_step")


@target("fused_lamb_master_step")
def _fused_lamb_master_step():
    """FusedLAMB over fp32 master params: grad-norm, trust ratio and
    moments are all reductions/chains that must accumulate fp32."""
    import jax
    import jax.numpy as jnp
    import optax

    from apex_tpu.optimizers import fused_lamb

    master = {"w": jnp.zeros((64, 128), jnp.float32),
              "b": jnp.zeros((128,), jnp.float32)}
    tx = fused_lamb(lr=1e-3, weight_decay=0.01)
    state = tx.init(master)
    grads = jax.tree_util.tree_map(jnp.ones_like, master)

    def step(grads, state, master):
        updates, new_state = tx.update(grads, state, master)
        return optax.apply_updates(master, updates), new_state

    n_out = _leaf_count(master) + _leaf_count(state)
    return analyze_precision(
        step, grads, state, master,
        roles={1: "master", 2: "master"},
        master_outs=tuple(range(n_out)),
        name="fused_lamb_master_step")


@target("fused_layer_norm_fwd_bwd")
def _fused_layer_norm_fwd_bwd():
    """FusedLayerNorm forward+backward on bf16 activations with fp32
    affine params (the Megatron mixed pattern): statistics and both
    backward reductions must be fp32 — the jnp fallback path is the one
    dataflow can see (the Pallas kernels are covered by their own unit
    tests and the pallas-block check)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.normalization import fused_layer_norm_affine

    x = jnp.zeros((256, 1024), jnp.bfloat16)
    w = jnp.ones((1024,), jnp.float32)
    b = jnp.zeros((1024,), jnp.float32)

    def loss(x, w, b):
        y = fused_layer_norm_affine(x, w, b, (1024,))
        return jnp.sum(y.astype(jnp.float32))

    return analyze_precision(
        lambda x, w, b: jax.grad(loss, argnums=(0, 1, 2))(x, w, b),
        x, w, b, name="fused_layer_norm_fwd_bwd")


@target("fused_rms_norm_fwd_bwd")
def _fused_rms_norm_fwd_bwd():
    import jax
    import jax.numpy as jnp

    from apex_tpu.normalization import fused_rms_norm_affine

    x = jnp.zeros((256, 1024), jnp.bfloat16)
    w = jnp.ones((1024,), jnp.float32)

    def loss(x, w):
        y = fused_rms_norm_affine(x, w, (1024,))
        return jnp.sum(y.astype(jnp.float32))

    return analyze_precision(
        lambda x, w: jax.grad(loss, argnums=(0, 1))(x, w),
        x, w, name="fused_rms_norm_fwd_bwd")


@target("fp8_matmul_delayed_scaling")
def _fp8_matmul_delayed_scaling():
    """The O4 epilogue end-to-end (ISSUE 13): one matmul site through
    the Fp8DelayedScaler context — scale-in, E4M3 cast, fp32-acc dot,
    scale-out, E5M2 grad cast, ring update. Both fp8 checks stay armed
    at 0 findings here because every cast sits behind a live,
    history-derived scale; drop the scale (or feed a constant) and
    tier-1 fails at the seeded regressions in
    tests/run_analysis/test_precision_checks.py."""
    import jax.numpy as jnp

    from apex_tpu.amp.scaler import Fp8DelayedScaler

    fp8 = Fp8DelayedScaler(["proj"], history=4)
    state = fp8.init()
    a = jnp.zeros((16, 32), jnp.bfloat16)
    b = jnp.zeros((32, 64), jnp.bfloat16)

    def step(a, b, state):
        with fp8.step(state) as ctx:
            def loss(a, b):
                y = ctx.matmul(a, b, name="proj")
                return jnp.sum(y.astype(jnp.float32))

            l, grads = ctx.value_and_grad(loss, argnums=(0, 1))(a, b)
        return l, grads, fp8.update(state, ctx)

    return analyze_precision(
        step, a, b, state,
        roles={2: ("fp8_scale", "amax_hist")},
        name="fp8_matmul_delayed_scaling")


@target("fp8_mlp_train_step")
def _fp8_mlp_train_step():
    """O4 over the mlp entry point: bf16 params, fp8 forward matmuls
    via the routed ``matmul_amp`` sites, fp32 loss — the whole fwd+bwd
    traced under the live context, so the fp8 casts inside the real
    library path (not a synthetic matmul) carry their scale provenance
    through the lattice. Also keeps lowprec-accum armed on the fp8
    path's de-scale/bias epilogue."""
    import jax.numpy as jnp

    from apex_tpu.amp.scaler import Fp8DelayedScaler
    from apex_tpu.mlp import mlp_function

    params = (jnp.zeros((64, 128), jnp.bfloat16),
              jnp.zeros((128,), jnp.bfloat16),
              jnp.zeros((128, 32), jnp.bfloat16),
              jnp.zeros((32,), jnp.bfloat16))
    x = jnp.zeros((16, 64), jnp.bfloat16)
    y = jnp.zeros((16, 32), jnp.float32)

    def loss(params, x, y):
        out = mlp_function(True, "relu", x, *params)
        return jnp.mean(jnp.square(out.astype(jnp.float32) - y))

    fp8 = Fp8DelayedScaler.for_step(loss, params, x, y, history=4)
    state = fp8.init()

    def step(params, x, y, state):
        with fp8.step(state) as ctx:
            l, grads = ctx.value_and_grad(loss)(params, x, y)
        return l, grads, fp8.update(state, ctx)

    return analyze_precision(
        step, params, x, y, state,
        roles={3: ("fp8_scale", "amax_hist")},
        name="fp8_mlp_train_step")


@target("tp_fused_softmax")
def _tp_fused_softmax():
    """Tensor-parallel fused softmax, jnp fallback path on bf16 logits:
    the exp must sit behind an fp32 upcast + max subtraction (the
    Pallas kernel keeps the same contract in VMEM)."""
    import jax.numpy as jnp

    from apex_tpu.transformer.functional.fused_softmax import (
        scaled_upper_triang_masked_softmax,
    )

    x = jnp.zeros((8, 256, 256), jnp.bfloat16)
    return analyze_precision(
        lambda x: scaled_upper_triang_masked_softmax(x, None, 1.0),
        x, name="tp_fused_softmax")


# ------------------------------------------------ sharding-flow targets
# (ISSUE 4): the parallelism entry points whose comms/HBM behavior the
# sharding checks pin down — TP layers fwd+bwd under GSPMD constraints,
# the shard_map collectives (PP 1F1B, DDP buckets, MoE all_to_all), and
# the TP-sharded optimizer master step. Trace-only, CPU backend.

def _world():
    import jax
    return len(jax.devices())


def _tp_size():
    world = _world()
    for tp in (4, 2):
        if world % tp == 0 and world >= tp:
            return tp
    return 1


def _owned_mesh(**kw):
    """(mesh, axis_sizes, owned) against parallel_state, honoring a mesh
    a caller already installed (same pattern as _tp_collectives)."""
    from apex_tpu.transformer import parallel_state

    owned = not parallel_state.model_parallel_is_initialized()
    if owned:
        parallel_state.initialize_model_parallel(**kw)
    mesh = parallel_state.get_mesh()
    sizes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    return mesh, sizes, owned


def _release_mesh(owned):
    if owned:
        from apex_tpu.transformer import parallel_state
        parallel_state.destroy_model_parallel()


def _tp_linear_fwd_bwd(kind, name):
    """Column/row-parallel fwd+bwd under GSPMD: partitioned params +
    the layers' own with_sharding_constraint boundaries. The propagated
    shardings must agree with every boundary (implicit-reshard), the
    params must actually shard (replicated-large), and the step must
    fit the HBM budget."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.tensor_parallel.layers import (
        ColumnParallelLinear,
        RowParallelLinear,
        param_partition_specs,
    )

    mesh, sizes, owned = _owned_mesh(
        tensor_model_parallel_size_=_tp_size())
    try:
        if kind == "column":
            mod = ColumnParallelLinear(output_size=64,
                                       gather_output=False,
                                       params_dtype=jnp.float32)
            x = jnp.zeros((8, 32), jnp.bfloat16)
        else:
            mod = RowParallelLinear(output_size=32,
                                    input_is_parallel=True,
                                    params_dtype=jnp.float32)
            x = jnp.zeros((8, 64), jnp.bfloat16)
        with jax.sharding.set_mesh(mesh):
            variables = mod.init(jax.random.PRNGKey(0), x)
            specs = param_partition_specs(variables)

            def loss(variables, x):
                y, _ = mod.apply(variables, x)
                return jnp.sum(y.astype(jnp.float32))

            stats = SHARDING_STATS.setdefault(name, {})
            return analyze_sharding(
                jax.value_and_grad(loss), variables, x,
                in_specs=[specs, P(None, None)], axis_sizes=sizes,
                stats_out=stats, name=name)
    finally:
        _release_mesh(owned)


@target("tp_column_parallel_fwd_bwd")
def _tp_column_parallel_fwd_bwd():
    return _tp_linear_fwd_bwd("column", "tp_column_parallel_fwd_bwd")


@target("tp_row_parallel_fwd_bwd")
def _tp_row_parallel_fwd_bwd():
    """Row-parallel: the tp-contracted gemm leaves partial sums that
    the output constraint must resolve (the allreduce shows up in the
    target's comms-bytes estimate, not as a finding)."""
    return _tp_linear_fwd_bwd("row", "tp_row_parallel_fwd_bwd")


@target("tp_fused_softmax_sharded")
def _tp_fused_softmax_sharded():
    """The TP fused softmax under shard_map with the batch/head dim
    sharded over tp — collective-free by construction; the sharding
    pass proves it stays that way (0 comms bytes)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.functional.fused_softmax import (
        scaled_upper_triang_masked_softmax,
    )

    mesh, sizes, owned = _owned_mesh(
        tensor_model_parallel_size_=_tp_size())
    try:
        fn = jax.shard_map(
            lambda x: scaled_upper_triang_masked_softmax(x, None, 1.0),
            mesh=mesh, in_specs=P("tp"), out_specs=P("tp"))
        stats = SHARDING_STATS.setdefault("tp_fused_softmax_sharded", {})
        return analyze_sharding(
            fn, jnp.zeros((8, 64, 64), jnp.bfloat16), axis_sizes=sizes,
            stats_out=stats, name="tp_fused_softmax_sharded")
    finally:
        _release_mesh(owned)


def _pp_1f1b(name, forward_only):
    """Shared builder for the two 1F1B pipeline targets (same stage
    model, shapes and mesh — one is the fwd+bwd step, the other the
    forward-only slice)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_without_interleaving,
    )

    world = _world()
    pp = 4 if world % 4 == 0 and world >= 4 else (
        2 if world % 2 == 0 else 1)
    mesh, sizes, owned = _owned_mesh(pipeline_model_parallel_size_=pp)
    try:
        dim, m_count, mb = 8, 4, 2

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        params = {"w": jnp.zeros((pp, dim, dim)),
                  "b": jnp.zeros((pp, dim))}
        x = jnp.zeros((m_count, mb, dim))
        tgt = jnp.zeros((m_count, mb, dim))

        def step(params, x, tgt):
            local = jax.tree_util.tree_map(lambda p: p[0], params)
            loss, grads = forward_backward_pipelining_without_interleaving(
                stage_fn, loss_fn, local, x, tgt,
                forward_only=forward_only, axis_name="pp")
            if forward_only:
                return loss
            return loss, jax.tree_util.tree_map(
                lambda g: g[None], grads)

        out_specs = P() if forward_only else (P(), P("pp"))
        fn = jax.shard_map(step, mesh=mesh,
                           in_specs=(P("pp"), P(), P()),
                           out_specs=out_specs)
        stats = SHARDING_STATS.setdefault(name, {})
        return analyze_sharding(fn, params, x, tgt, axis_sizes=sizes,
                                stats_out=stats, name=name)
    finally:
        _release_mesh(owned)


@target("pp_1f1b_microbatch_step", allow=("dead-collective",))
def _pp_1f1b_microbatch_step():
    """1F1B microbatch train step (fwd+bwd) over the 'pp' ring.

    allow=dead-collective: differentiating the collective schedule
    makes AD transpose pbroadcasts into psums of replicated cotangents
    (summing n identical per-device contributions IS the chain rule —
    a scale by axis size, statically resolvable but AD-emitted, not
    user-written). The check stays armed for hand-written code via the
    forward-only slice of this very schedule below."""
    return _pp_1f1b("pp_1f1b_microbatch_step", forward_only=False)


@target("pp_1f1b_forward")
def _pp_1f1b_forward():
    """Forward-only slice of the 1F1B schedule: every collective here
    is hand-written (the scan ppermutes, the last-stage loss psum), so
    dead-collective stays fully armed on the pipeline family."""
    return _pp_1f1b("pp_1f1b_forward", forward_only=True)


@target("ddp_bucket_allreduce_step")
def _ddp_bucket_allreduce_step():
    """DDP gradient sync over 'dp': per-leaf and flat-bucket allreduce.
    The axis-size probes must be static (the psum(ones) pattern this
    target caught in parallel/distributed.py was a dead collective
    riding every bucket)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.distributed import (
        sync_gradients,
        sync_gradients_flat,
    )

    world = _world()
    tp = 2 if world % 2 == 0 and world > 1 else 1
    mesh, sizes, owned = _owned_mesh(tensor_model_parallel_size_=tp)
    try:
        grads = {"w": jnp.zeros((128, 128)), "b": jnp.zeros((128,))}
        spec = {"w": P("dp"), "b": P("dp")}

        def step(grads):
            # both reduction paths over the SAME raw grads (chaining
            # them would double-reduce — which this target's own
            # dead-collective check correctly flags)
            flat = sync_gradients_flat(grads, axis_name="dp")
            plain = sync_gradients(grads, axis_name="dp",
                                   gradient_predivide_factor=2.0)
            return jax.tree_util.tree_map(jnp.add, flat, plain)

        fn = jax.shard_map(step, mesh=mesh, in_specs=(spec,),
                           out_specs=spec)
        stats = SHARDING_STATS.setdefault("ddp_bucket_allreduce_step", {})
        return analyze_sharding(fn, grads, axis_sizes=sizes,
                                stats_out=stats,
                                name="ddp_bucket_allreduce_step")
    finally:
        _release_mesh(owned)


def _ddp_grad_model():
    """Shared model for the two overlapped-DDP targets: a dp-sharded
    batch producing full-shaped, genuinely per-rank gradients inside
    the shard_map body (an input-specced grad tree would either shrink
    to the local shard — breaking the bucket plan — or arrive
    replicated and trip dead-collective on the reduce)."""
    import jax.numpy as jnp

    def grads_of(x):
        # x: the local (batch/dp, 256) shard
        return {"w": (x.T @ x).astype(jnp.float32),
                "b": jnp.sum(x, axis=0)}

    return grads_of


@target("ddp_overlap_bucket_step")
def _ddp_overlap_bucket_step():
    """Backward-interleaved bucket allreduce (ISSUE 11 tentpole): the
    barrier-chained per-bucket psums of sync_gradients_overlapped over
    'dp'. The optimization_barrier issue-order chain must add no comms
    of its own and no reshards; the estimated bytes are the allreduce
    baseline the zero1 target's 0.75x acceptance ratio is measured
    against."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.overlap import sync_gradients_overlapped

    mesh, sizes, owned = _owned_mesh()
    try:
        grads_of = _ddp_grad_model()

        def step(x):
            return sync_gradients_overlapped(
                grads_of(x), axis_name="dp", bucket_cap_mb=0.1,
                gradient_predivide_factor=2.0)

        fn = jax.shard_map(step, mesh=mesh, in_specs=(P("dp"),),
                           out_specs={"w": P(), "b": P()},
                           check_vma=False)
        stats = SHARDING_STATS.setdefault("ddp_overlap_bucket_step", {})
        return analyze_sharding(
            fn, jnp.zeros((8 * sizes.get("dp", 1), 256), jnp.float32),
            axis_sizes=sizes, stats_out=stats,
            name="ddp_overlap_bucket_step")
    finally:
        _release_mesh(owned)


@target("zero1_fused_adam_step")
def _zero1_fused_adam_step():
    """ZeRO-1 sharded-optimizer step (ISSUE 11 tentpole): per-bucket
    psum_scatter of the fp32 grads + all_gather of the updated bf16
    params, state shards donated. The sharding-flow estimate must price
    this at <= 0.75x the allreduce target above (fp32 grads at twice
    the bf16 param width: RS 1.0 + AG 0.5 vs allreduce 2.0), with all
    five checks at 0 findings."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.zero import Zero1FusedAdam

    mesh, sizes, owned = _owned_mesh()
    try:
        dp = sizes.get("dp", 1)
        params = {"w": jnp.zeros((256, 256), jnp.bfloat16),
                  "b": jnp.zeros((256,), jnp.bfloat16)}
        opt = Zero1FusedAdam(lr=1e-3, weight_decay=0.01, axis_name="dp",
                             num_shards=dp, bucket_cap_mb=0.1)
        state = opt.init(params)
        grads_of = _ddp_grad_model()

        def step(x, state, params):
            return opt.step(grads_of(x), state, params)

        state_specs = opt.state_specs(params)
        fn = jax.shard_map(
            step, mesh=mesh,
            in_specs=(P("dp"), state_specs, {"w": P(), "b": P()}),
            out_specs=({"w": P(), "b": P()}, state_specs),
            check_vma=False)
        stats = SHARDING_STATS.setdefault("zero1_fused_adam_step", {})
        return analyze_sharding(
            fn, jnp.zeros((8 * dp, 256), jnp.float32), state, params,
            donate_argnums=(1,), axis_sizes=sizes, stats_out=stats,
            name="zero1_fused_adam_step")
    finally:
        _release_mesh(owned)


@target("fused_adam_master_sharded_step")
def _fused_adam_master_sharded_step():
    """Per-tensor FusedAdam over tp-sharded fp32 master params under
    GSPMD, donated state: master/m/v shard like the params they mirror
    (replicated-large's canonical customer) and the donated buffers
    earn their HBM credit in the budget walk."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from apex_tpu.optimizers import fused_adam

    mesh, sizes, owned = _owned_mesh(
        tensor_model_parallel_size_=_tp_size())
    try:
        master = {"w": jnp.zeros((256, 1024), jnp.float32),
                  "b": jnp.zeros((1024,), jnp.float32)}
        tx = fused_adam(lr=1e-3, weight_decay=0.01, flat=False)
        state = tx.init(master)
        grads = jax.tree_util.tree_map(jnp.ones_like, master)

        def step(grads, state, master):
            updates, new_state = tx.update(grads, state, master)
            return optax.apply_updates(master, updates), new_state

        wspec = {"w": P(None, "tp"), "b": P("tp")}
        state_spec = jax.tree_util.tree_map(
            lambda s: (wspec["w"] if getattr(s, "ndim", 0) == 2 else
                       wspec["b"] if getattr(s, "ndim", 0) == 1 else P()),
            state, is_leaf=lambda s: hasattr(s, "shape"))
        with jax.sharding.set_mesh(mesh):
            stats = SHARDING_STATS.setdefault(
                "fused_adam_master_sharded_step", {})
            return analyze_sharding(
                step, grads, state, master,
                in_specs=[wspec, state_spec, wspec],
                donate_argnums=(1, 2), axis_sizes=sizes,
                stats_out=stats, name="fused_adam_master_sharded_step")
    finally:
        _release_mesh(owned)


@target("moe_dispatch")
def _moe_dispatch():
    """GShard MoE dispatch over 'ep': tokens shard over dp×ep so the
    all_to_all pair actually moves expert slabs (with replicated
    tokens it would be a dead collective — the seeded regression)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.transformer.moe import (
        MoEConfig,
        init_moe_params,
        moe_mlp,
    )

    world = _world()
    ep = 4 if world % 4 == 0 and world >= 4 else (
        2 if world % 2 == 0 else 1)
    dp = world // ep
    mesh = Mesh(np.asarray(jax.devices()).reshape(dp, ep), ("dp", "ep"))
    sizes = {"dp": dp, "ep": ep}
    cfg = MoEConfig(hidden_size=16, ffn_hidden_size=32,
                    num_experts=max(ep, 2), top_k=2)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)

    def step(p, x):
        y, aux = moe_mlp(p, x, cfg, ep_axis="ep")
        return y, jax.lax.pmean(aux, "dp")

    fn = jax.shard_map(
        step, mesh=mesh,
        in_specs=({"router": P(), "wi": P("ep"), "wo": P("ep")},
                  P(("dp", "ep"))),
        out_specs=(P(("dp", "ep")), P()), check_vma=False)
    stats = SHARDING_STATS.setdefault("moe_dispatch", {})
    return analyze_sharding(
        fn, params, jnp.zeros((8 * max(dp * ep, 1), 16)),
        axis_sizes=sizes, stats_out=stats, name="moe_dispatch")


SHARDING_TARGETS = (
    "tp_column_parallel_fwd_bwd", "tp_row_parallel_fwd_bwd",
    "tp_fused_softmax_sharded", "pp_1f1b_microbatch_step",
    "pp_1f1b_forward", "ddp_bucket_allreduce_step",
    "ddp_overlap_bucket_step", "zero1_fused_adam_step",
    "fused_adam_master_sharded_step", "moe_dispatch",
)


# --------------------------------------------- rank-consistency targets
# (ISSUE 14): the real grad-sync/pipeline/optimizer schedules run
# through the spmd rank-consistency checks — collectives under
# rank-divergent control, out_specs claiming replication the program
# does not establish, uncoordinated RNG, unordered host effects between
# collectives. Trace-only, CPU backend, like everything above.


def _analyze_spmd_target(name, fn, *args, **kw):
    stats = SPMD_STATS.setdefault(name, {})
    return analyze_spmd(fn, *args, name=name, stats_out=stats, **kw)


@target("spmd_ddp_sync_gradients")
def _spmd_ddp_sync_gradients():
    """The per-leaf + flat-bucket DDP grad sync (sync_gradients /
    sync_gradients_flat): grads born per-rank from the dp-sharded
    batch, psum-reduced, stored through P() out_specs — the exact
    replication contract rank-divergent-update audits. Drop a psum and
    tier-1 fails here."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.distributed import (
        sync_gradients,
        sync_gradients_flat,
    )

    mesh, sizes, owned = _owned_mesh()
    try:
        grads_of = _ddp_grad_model()

        def step(x):
            g = grads_of(x)
            flat = sync_gradients_flat(g, axis_name="dp")
            plain = sync_gradients(g, axis_name="dp",
                                   gradient_predivide_factor=2.0)
            return jax.tree_util.tree_map(jnp.add, flat, plain)

        fn = jax.shard_map(step, mesh=mesh, in_specs=(P("dp"),),
                           out_specs={"w": P(), "b": P()},
                           check_vma=False)
        return _analyze_spmd_target(
            "spmd_ddp_sync_gradients", fn,
            jnp.zeros((8 * sizes.get("dp", 1), 256), jnp.float32),
            axis_sizes=sizes)
    finally:
        _release_mesh(owned)


@target("spmd_ddp_overlap_bucket_step")
def _spmd_ddp_overlap_bucket_step():
    """The barrier-chained overlapped bucket allreduce (ISSUE 11's
    engine): the optimization_barrier issue chain must not launder
    distinctness or anchor-free host effects into the schedule."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.overlap import sync_gradients_overlapped

    mesh, sizes, owned = _owned_mesh()
    try:
        grads_of = _ddp_grad_model()

        def step(x):
            return sync_gradients_overlapped(
                grads_of(x), axis_name="dp", bucket_cap_mb=0.1,
                gradient_predivide_factor=2.0)

        fn = jax.shard_map(step, mesh=mesh, in_specs=(P("dp"),),
                           out_specs={"w": P(), "b": P()},
                           check_vma=False)
        return _analyze_spmd_target(
            "spmd_ddp_overlap_bucket_step", fn,
            jnp.zeros((8 * sizes.get("dp", 1), 256), jnp.float32),
            axis_sizes=sizes)
    finally:
        _release_mesh(owned)


@target("spmd_fleet_probe_grad_sync")
def _spmd_fleet_probe_grad_sync():
    """The overlapped grad sync with the PR 11 fleet barrier-wait probe
    ARMED: its io_callback enter marker is barrier-tied into the
    collective operand and its exit callback is fed the reduced result,
    so unordered-host-effect must hold the probe's own call sites at 0
    — the acceptance clause ISSUE 14 names."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.observability.fleet import probe
    from apex_tpu.parallel.overlap import sync_gradients_overlapped

    mesh, sizes, owned = _owned_mesh()
    was = probe._ENABLED
    probe.enable()
    try:
        grads_of = _ddp_grad_model()

        def step(x):
            return sync_gradients_overlapped(
                grads_of(x), axis_name="dp", bucket_cap_mb=0.1)

        fn = jax.shard_map(step, mesh=mesh, in_specs=(P("dp"),),
                           out_specs={"w": P(), "b": P()},
                           check_vma=False)
        findings = _analyze_spmd_target(
            "spmd_fleet_probe_grad_sync", fn,
            jnp.zeros((8 * sizes.get("dp", 1), 256), jnp.float32),
            axis_sizes=sizes)
        stats = SPMD_STATS["spmd_fleet_probe_grad_sync"]
        if not stats.get("host_effects"):
            # the probe silently tracing to nothing would hollow the
            # acceptance contract out — same loud-failure rule as a
            # typo'd target name
            raise RuntimeError(
                "fleet probe did not emit host callbacks into the "
                "traced grad sync — is probe.enable() broken?")
        return findings
    finally:
        probe._ENABLED = was
        _release_mesh(owned)


@target("spmd_zero1_fused_adam_step")
def _spmd_zero1_fused_adam_step():
    """ZeRO-1 scatter/gather: params must exit replicated (the
    all_gather), per-rank mu/nu shards must exit through P('dp')
    out_specs — a rank-indexed dynamic_slice feeding state is only
    legal because the out_names declare the dim-0 sharding."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.zero import Zero1FusedAdam

    mesh, sizes, owned = _owned_mesh()
    try:
        dp = sizes.get("dp", 1)
        params = {"w": jnp.zeros((256, 256), jnp.bfloat16),
                  "b": jnp.zeros((256,), jnp.bfloat16)}
        opt = Zero1FusedAdam(lr=1e-3, weight_decay=0.01, axis_name="dp",
                             num_shards=dp, bucket_cap_mb=0.1)
        state = opt.init(params)
        grads_of = _ddp_grad_model()

        def step(x, state, params):
            return opt.step(grads_of(x), state, params)

        state_specs = opt.state_specs(params)
        fn = jax.shard_map(
            step, mesh=mesh,
            in_specs=(P("dp"), state_specs, {"w": P(), "b": P()}),
            out_specs=({"w": P(), "b": P()}, state_specs),
            check_vma=False)
        return _analyze_spmd_target(
            "spmd_zero1_fused_adam_step", fn,
            jnp.zeros((8 * dp, 256), jnp.float32), state, params,
            axis_sizes=sizes)
    finally:
        _release_mesh(owned)


@target("spmd_pp_1f1b_microbatch_step")
def _spmd_pp_1f1b_microbatch_step():
    """The 1F1B pipeline train step: scan-carried ppermutes keep the
    activations pp-distinct, the last-stage loss select is rank-origin
    data — and the loss psum + P('pp') grad out_specs must account for
    every one of those axes."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_without_interleaving,
    )

    world = _world()
    pp = 4 if world % 4 == 0 and world >= 4 else (
        2 if world % 2 == 0 else 1)
    mesh, sizes, owned = _owned_mesh(pipeline_model_parallel_size_=pp)
    try:
        dim, m_count, mb = 8, 4, 2

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        params = {"w": jnp.zeros((pp, dim, dim)),
                  "b": jnp.zeros((pp, dim))}
        x = jnp.zeros((m_count, mb, dim))
        tgt = jnp.zeros((m_count, mb, dim))

        def step(params, x, tgt):
            local = jax.tree_util.tree_map(lambda p: p[0], params)
            loss, grads = \
                forward_backward_pipelining_without_interleaving(
                    stage_fn, loss_fn, local, x, tgt,
                    forward_only=False, axis_name="pp")
            return loss, jax.tree_util.tree_map(
                lambda g: g[None], grads)

        fn = jax.shard_map(step, mesh=mesh,
                           in_specs=(P("pp"), P(), P()),
                           out_specs=(P(), P("pp")))
        return _analyze_spmd_target(
            "spmd_pp_1f1b_microbatch_step", fn, params, x, tgt,
            axis_sizes=sizes)
    finally:
        _release_mesh(owned)


@target("spmd_llama_o4_step")
def _spmd_llama_o4_step():
    """The llama O4 train step (ISSUE 13's fp8 tier over the 3D mesh),
    mirroring examples/llama_train.py --opt-level O4: pipelined
    forward, vocab-parallel CE, fp8 delayed scaling pmax'd over every
    axis, dp-pmean'd grads — the largest real schedule in the gate.
    The fp8 state and loss exit through P() out_specs, so a missing
    reduce anywhere in that chain is a rank-divergent-update here."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.amp import Fp8DelayedScaler
    from apex_tpu.models import llama
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pipelined_forward,
    )
    from apex_tpu.transformer.tensor_parallel.cross_entropy import (
        vocab_parallel_cross_entropy,
    )
    from apex_tpu.transformer.tensor_parallel.mappings import _to_varying

    import numpy as np

    world = _world()
    if world >= 8:
        pp, dp, tp = 2, 2, 2
    elif world >= 4:
        pp, dp, tp = 1, 2, 2
    else:
        pp, dp, tp = 1, 1, max(world, 1)
    n_dev = pp * dp * tp
    mesh = Mesh(np.asarray(jax.devices()[:n_dev]).reshape(pp, dp, tp),
                ("pp", "dp", "tp"))
    sizes = {"pp": pp, "dp": dp, "tp": tp}
    sp = tp > 1
    M, mb, s = 2, 2, 16
    cfg = llama.tiny(num_layers=max(pp, 1), num_heads=2 * tp,
                     num_kv_heads=tp, hidden_size=32 * tp,
                     intermediate_size=64 * tp, vocab_size=128 * tp,
                     max_seq_len=s)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    stage_params = llama.split_stages(params, pp)
    io_params = {k: v for k, v in params.items() if k != "layers"}
    tx = fused_adam(lr=1e-3)
    fp8 = Fp8DelayedScaler(["lm_head"], history=4)

    def psum(t, ax):
        return jax.lax.psum(_to_varying(t, ax), ax)

    def pmean(t, ax):
        return jax.lax.pmean(_to_varying(t, ax), ax)

    def train_step(stage_params, io_params, opt_state, tokens, targets,
                   fp8_state):
        pp_rank = jax.lax.axis_index("pp")
        pp_size = jax.lax.axis_size("pp")

        def vary_all(t):
            for ax in ("pp", "dp", "tp"):
                t = jax.tree_util.tree_map(
                    lambda a, ax=ax: _to_varying(a, ax), t)
            return t

        def total_loss(trees):
            stage, io = trees
            stage = jax.tree_util.tree_map(lambda a: a[0], stage)
            stage, io = vary_all(stage), vary_all(io)
            x_mb = vary_all(jax.vmap(
                lambda tok: llama.embed(io, tok, cfg, tp_axis="tp",
                                        sequence_parallel=sp))(tokens))
            positions = llama._positions(mb, s, None)

            def stage_fn(sp_params, x):
                return llama.stage_fn(sp_params, x, cfg, positions,
                                      tp_axis="tp", cp_axis=None,
                                      sequence_parallel=sp)

            outs = pipelined_forward(stage_fn, stage, x_mb,
                                     axis_name="pp", remat=True)
            o2 = outs.reshape((M * mb,) + outs.shape[2:])
            t2 = targets.reshape((M * mb,) + targets.shape[2:])
            logits = llama.lm_head(io, o2, cfg, tp_axis="tp",
                                   sequence_parallel=sp)
            losses = jnp.mean(vocab_parallel_cross_entropy(
                logits, t2, axis_name="tp"))
            local = jnp.where(pp_rank == pp_size - 1, losses, 0.0)
            return jax.lax.psum(local, "pp")

        with fp8.step(fp8_state) as fp8_ctx:
            loss, (g_stage, g_io) = fp8_ctx.value_and_grad(
                total_loss)((stage_params, io_params))
        new_fp8 = fp8.update(fp8_state, fp8_ctx,
                             reduce_axes=("pp", "dp", "tp"))
        g_stage = jax.tree_util.tree_map(
            lambda g: pmean(g, "dp"), g_stage)
        g_io = jax.tree_util.tree_map(
            lambda g: pmean(psum(g, "pp"), "dp"), g_io)
        if sp:
            g_stage = {k: (psum(v, "tp") if k.endswith("norm") else v)
                       for k, v in g_stage.items()}
            g_io = {k: (psum(v, "tp") if k == "final_norm" else v)
                    for k, v in g_io.items()}
        grads = {"stage": g_stage, "io": g_io}
        updates, opt_state = tx.update(
            grads, opt_state, {"stage": stage_params, "io": io_params})
        new_stage = jax.tree_util.tree_map(
            jnp.add, stage_params, updates["stage"])
        new_io = jax.tree_util.tree_map(
            jnp.add, io_params, updates["io"])
        loss = jax.lax.pmean(jax.lax.pmean(loss, "dp"), "tp")
        return new_stage, new_io, opt_state, new_fp8, loss

    from apex_tpu.optimizers import opt_partition_specs

    lp = llama.param_specs(cfg)["layers"]
    io_specs = {"embed": P("tp", None), "final_norm": P(),
                "lm_head": P(None, "tp")}
    stage_specs = {k: P("pp", *lp[k]) for k in lp}
    with mesh:
        opt_state = tx.init({"stage": stage_params, "io": io_params})
        opt_specs = opt_partition_specs(
            tx, {"stage": stage_params, "io": io_params},
            {"stage": stage_specs, "io": io_specs})
        fp8_state0 = fp8.init()
        fp8_specs = jax.tree_util.tree_map(lambda _: P(), fp8_state0)
        fn = jax.shard_map(
            train_step, mesh=mesh,
            in_specs=(stage_specs, io_specs, opt_specs,
                      P(None, "dp", None), P(None, "dp", None),
                      fp8_specs),
            out_specs=(stage_specs, io_specs, opt_specs, fp8_specs,
                       P()),
            check_vma=False)
        tokens = jnp.zeros((M, mb * dp, s), jnp.int32)
        return _analyze_spmd_target(
            "spmd_llama_o4_step", fn, stage_params, io_params,
            opt_state, tokens, tokens, fp8_state0, axis_sizes=sizes)


@target("spmd_simple_distributed")
def _spmd_simple_distributed():
    """examples/simple_distributed.py's own train step (the satellite:
    the example now does its DDP reduction explicitly under
    check_rep=False, and THIS target is what keeps that pmean in
    place — remove it and tier-1 fails as a rank-divergent-update)."""
    import os
    import sys

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from examples.simple_distributed import make_train_step

    from apex_tpu.optimizers import fused_adam

    world = _world()
    mesh = Mesh(np.asarray(jax.devices()[:world]), ("data",))
    sizes = {"data": world}
    tx = fused_adam(lr=1e-2)
    w = jnp.zeros((16, 1))
    opt_state = tx.init(w)
    x = jnp.zeros((8 * world, 16))
    y = jnp.zeros((8 * world, 1))
    fn = jax.shard_map(
        make_train_step(tx), mesh=mesh,
        in_specs=(P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P(), P()), check_vma=False)
    return _analyze_spmd_target(
        "spmd_simple_distributed", fn, w, opt_state, x, y,
        axis_sizes=sizes)


SPMD_TARGETS = (
    "spmd_ddp_sync_gradients", "spmd_ddp_overlap_bucket_step",
    "spmd_fleet_probe_grad_sync", "spmd_zero1_fused_adam_step",
    "spmd_pp_1f1b_microbatch_step", "spmd_llama_o4_step",
    "spmd_simple_distributed", "spmd_serving_decode_step",
)


def run_targets(names=None, extra_allow=None, timings=None):
    """Run the registered targets; returns (findings, errors) where
    errors maps target name -> repr of an exception that kept the target
    from tracing at all (itself a failure the caller should surface).

    ``extra_allow``: {target name: set of check ids} merged over the
    ``@target(allow=...)`` lists — findings of an allowed check from
    that target are dropped (the per-target grandfather the CLI's
    ``--allow target:check`` feeds). ``timings``: optional dict that
    receives per-target wall seconds (the CLI rolls these up into the
    per-engine gate-latency summary)."""
    import time

    findings, errors = [], {}
    for name, fn in TARGETS.items():
        if names is not None and name not in names:
            continue
        allowed = set(TARGET_ALLOW.get(name, ()))
        if extra_allow:
            allowed |= set(extra_allow.get(name, ()))
        t0 = time.perf_counter()  # apex-lint: disable=raw-clock
        try:
            got = fn()
        except Exception as e:  # noqa: BLE001 — report, don't abort the scan
            errors[name] = repr(e)[:300]
            continue
        finally:
            if timings is not None:
                timings[name] = (
                    time.perf_counter() - t0)  # apex-lint: disable=raw-clock
        if allowed:
            got = [f for f in got if f.check not in allowed]
        findings.extend(got)
    return findings, errors


def run_precision_findings(registry=None, names=None):
    """Run only the precision-flow targets and publish their finding
    counts to the observability registry (``analysis/precision``
    counter family) — the hook bench.py reports through. Returns
    (findings, errors)."""
    from apex_tpu.analysis.precision_checks import report_to_registry

    wanted = names if names is not None else PRECISION_TARGETS
    findings, errors = run_targets(wanted)
    findings = [f for f in findings if f.check in PRECISION_CHECKS]
    report_to_registry(findings, registry=registry)
    return findings, errors


PRECISION_TARGETS = (
    "mlp_train_step", "amp_o1_train_step", "amp_o2_master_update",
    "fused_adam_tree_master_step", "fused_lamb_master_step",
    "fused_layer_norm_fwd_bwd", "fused_rms_norm_fwd_bwd",
    "tp_fused_softmax", "fp8_matmul_delayed_scaling",
    "fp8_mlp_train_step",
)


def run_sharding_findings(registry=None, names=None):
    """Run only the sharding-flow targets and publish finding counts +
    per-target comms-bytes / peak-HBM estimates to the observability
    registry (``analysis/sharding_*`` family) — the hook bench.py
    reports through. Returns (findings, errors, stats) where stats is
    {target: {"comms_bytes", "peak_hbm_bytes", ...}}."""
    from apex_tpu.analysis.sharding_checks import (
        SHARDING_CHECKS as _SC,
        report_to_registry,
    )

    wanted = tuple(names) if names is not None else SHARDING_TARGETS
    unknown = set(wanted) - set(TARGETS)
    if unknown:
        # a typo'd name silently yielding an all-zero stats row would
        # read as "analyzed and clean" forever — same loud-failure rule
        # as the CLI's unknown-check/path validation
        raise ValueError(
            f"unknown sharding target(s) {sorted(unknown)}; valid: "
            f"{sorted(SHARDING_TARGETS)}")
    findings, errors = run_targets(set(wanted))
    findings = [f for f in findings if f.check in _SC]
    results = {}
    for name in wanted:
        if name in errors:
            continue
        results[name] = (
            [f for f in findings if f.symbol == name],
            dict(SHARDING_STATS.get(name, {})),
        )
    report_to_registry(results, registry=registry)
    stats = {name: s for name, (_, s) in results.items()}
    return findings, errors, stats


def run_spmd_findings(registry=None, names=None):
    """Run only the rank-consistency targets and publish finding counts
    + per-target collective/host-effect counts to the observability
    registry (``analysis/spmd_*`` family) — the hook bench.py reports
    through. Returns (findings, errors, stats)."""
    from apex_tpu.analysis.spmd_checks import (
        SPMD_CHECKS as _SP,
        report_to_registry as _report,
    )

    wanted = tuple(names) if names is not None else SPMD_TARGETS
    unknown = set(wanted) - set(TARGETS)
    if unknown:
        raise ValueError(
            f"unknown spmd target(s) {sorted(unknown)}; valid: "
            f"{sorted(SPMD_TARGETS)}")
    findings, errors = run_targets(set(wanted))
    findings = [f for f in findings if f.check in _SP]
    results = {}
    for name in wanted:
        if name in errors:
            continue
        results[name] = (
            [f for f in findings if f.symbol == name],
            dict(SPMD_STATS.get(name, {})),
        )
    _report(results, registry=registry)
    stats = {name: s for name, (_, s) in results.items()}
    return findings, errors, stats


# ---- checkpoint/state-flow targets (ISSUE 18) ------------------------
# The resume-compatibility surface: each target is a train step in
# carry form (state as argnum 0, new state in the outputs) run through
# analyze_state — the step-carry fixpoint, save-tree coverage, the
# manifest schema round-trip, and (where state is dp-sharded) the
# elastic-reshard proof. All at 0 findings: every seeded regression
# lives in tests/run_analysis/test_state_checks.py.

@target("state_llama_o4_step")
def _state_llama_o4_step():
    """The llama O4 train step in carry form: params + fused-adam tree
    state + the fp8 delayed-scaling rings all round one step. The
    fixpoint must see every fp8 ring column and the adam moments as
    step-carried, and the identity save tree must cover them — drop
    any field from the carry's save path and this target turns red."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.amp import Fp8DelayedScaler
    from apex_tpu.models import llama
    from apex_tpu.optimizers import fused_adam

    cfg = llama.tiny(num_layers=1, num_heads=2, num_kv_heads=1,
                     hidden_size=32, intermediate_size=64,
                     vocab_size=128, max_seq_len=16)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tx = fused_adam(lr=1e-3)
    fp8 = Fp8DelayedScaler(["lm_head"], history=4)
    carry = (params, tx.init(params), fp8.init())
    tokens = jnp.zeros((2, 16), jnp.int32)

    def train_step(carry, tokens, targets):
        params, opt_state, fp8_state = carry

        def loss_fn(p):
            logits = llama.forward(p, tokens, cfg, tp_axis=None,
                                   cp_axis=None, ep_axis=None)
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            return -jnp.mean(jnp.take_along_axis(
                lp, targets[..., None], axis=-1))

        with fp8.step(fp8_state) as ctx:
            loss, grads = ctx.value_and_grad(loss_fn)(params)
        new_fp8 = fp8.update(fp8_state, ctx)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = jax.tree_util.tree_map(jnp.add, params, updates)
        return (new_params, new_opt, new_fp8), loss

    stats = STATE_STATS.setdefault("state_llama_o4_step", {})
    return analyze_state(train_step, carry, tokens, tokens,
                         name="state_llama_o4_step", stats_out=stats)


@target("state_zero1_fused_adam_step")
def _state_zero1_fused_adam_step():
    """ZeRO-1 carry step + the elastic-reshard proof: the dp-sharded
    mu/nu buckets must be step-carried, covered by the save tree,
    schema-stable through the format-2 manifest encoding, AND legally
    re-shardable onto every candidate the optimizer itself claims
    (state_layout/elastic_candidates) — the machine check on zero.py's
    pure-reshard contract."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.zero import Zero1FusedAdam

    mesh, sizes, owned = _owned_mesh()
    try:
        dp = sizes.get("dp", 1)
        params = {"w": jnp.zeros((256, 256), jnp.bfloat16),
                  "b": jnp.zeros((256,), jnp.bfloat16)}
        opt = Zero1FusedAdam(lr=1e-3, weight_decay=0.01, axis_name="dp",
                             num_shards=dp, bucket_cap_mb=0.1)
        state = opt.init(params)
        grads_of = _ddp_grad_model()

        def step(x, state, params):
            return opt.step(grads_of(x), state, params)

        state_specs = opt.state_specs(params)
        param_specs = {"w": P(), "b": P()}
        fn = jax.shard_map(
            step, mesh=mesh,
            in_specs=(P("dp"), state_specs, param_specs),
            out_specs=(param_specs, state_specs),
            check_vma=False)

        def train_step(carry, x):
            params, ostate = carry
            new_params, new_ostate = fn(x, ostate, params)
            return new_params, new_ostate

        stats = STATE_STATS.setdefault("state_zero1_fused_adam_step", {})
        return analyze_state(
            train_step, (params, state),
            jnp.zeros((8 * dp, 256), jnp.float32),
            name="state_zero1_fused_adam_step",
            specs=(param_specs, state_specs),
            reshard_layout=opt.state_layout(params),
            reshard_candidates=opt.elastic_candidates(params),
            axis_sizes=sizes, stats_out=stats)
    finally:
        _release_mesh(owned)


@target("state_ddp_overlap_step")
def _state_ddp_overlap_step():
    """Overlapped-DDP amp step: flat-adam state plus the LossScaleState
    counters round the carry through scaled_update's lax.cond skip —
    the fixpoint must prove both cond branches keep the opt state
    live, and every scaler counter saved."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.amp import LossScaler, scaled_update
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.parallel.overlap import sync_gradients_overlapped

    mesh, sizes, owned = _owned_mesh()
    try:
        dp = sizes.get("dp", 1)
        params = {"w": jnp.zeros((256, 256), jnp.float32),
                  "b": jnp.zeros((256,), jnp.float32)}
        tx = fused_adam(lr=1e-3, flat=True)
        scaler = LossScaler()
        carry = (params, tx.init(params), scaler.init())
        grads_of = _ddp_grad_model()

        def inner(x, params, opt_state, sstate):
            grads = sync_gradients_overlapped(
                grads_of(x), axis_name="dp", bucket_cap_mb=0.1)
            updates, new_opt, new_sstate, _ovf = scaled_update(
                tx, scaler, grads, opt_state, params, sstate,
                overflow_reduce_axes=("dp",))
            new_params = jax.tree_util.tree_map(
                jnp.add, params, updates)
            return new_params, new_opt, new_sstate

        fn = jax.shard_map(
            inner, mesh=mesh, in_specs=(P("dp"), P(), P(), P()),
            out_specs=(P(), P(), P()), check_vma=False)

        def train_step(carry, x):
            params, opt_state, sstate = carry
            return fn(x, params, opt_state, sstate)

        stats = STATE_STATS.setdefault("state_ddp_overlap_step", {})
        return analyze_state(
            train_step, carry,
            jnp.zeros((8 * dp, 256), jnp.float32),
            name="state_ddp_overlap_step", axis_sizes=sizes,
            stats_out=stats)
    finally:
        _release_mesh(owned)


@target("state_resilient_resume_path")
def _state_resilient_resume_path():
    """The ResilientTrainLoop resume composition: restore → first step
    with the restored reference retained as fallback_state
    (loop.resume_path mirrors run()'s real shape). The loop's step
    contract forbids donation, and this target is what enforces it —
    jit the step with donate_argnums=(0,) and restore-donation-hazard
    fires on the held fallback reference."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.resilience.loop import resume_path

    key = jax.random.PRNGKey(0)
    state = {"w": jnp.ones((16, 16), jnp.float32)}

    @jax.jit  # NON-donating: the loop's documented step contract
    def step_fn(state, step):
        g = jax.random.normal(jax.random.fold_in(key, step), (16, 16))
        w = state["w"] - 0.01 * (g + 0.1 * state["w"])
        return {"w": w}, {"loss": jnp.mean(w * w)}

    stats = STATE_STATS.setdefault("state_resilient_resume_path", {})
    return analyze_state(
        step_fn, state, jnp.int32(0),
        name="state_resilient_resume_path",
        save_tree_of=lambda s: {"state": s},  # the loop's save shape
        resume_fn=resume_path(step_fn), resume_args=(jnp.int32(0),),
        stats_out=stats)


STATE_TARGETS = (
    "state_llama_o4_step", "state_zero1_fused_adam_step",
    "state_ddp_overlap_step", "state_resilient_resume_path",
    "state_serving_decode_step",
)


def run_state_findings(registry=None, names=None):
    """Run only the checkpoint/state-flow targets and publish finding
    counts (zero-filled over every check id) + per-target carried/saved
    leaf counts to the observability registry (``analysis/state_*``
    family) — the hook bench.py reports through. Returns
    (findings, errors, stats)."""
    from apex_tpu.analysis.state_checks import (
        STATE_CHECKS as _ST,
        report_to_registry as _report,
    )

    wanted = tuple(names) if names is not None else STATE_TARGETS
    unknown = set(wanted) - set(TARGETS)
    if unknown:
        raise ValueError(
            f"unknown state target(s) {sorted(unknown)}; valid: "
            f"{sorted(STATE_TARGETS)}")
    findings, errors = run_targets(set(wanted))
    findings = [f for f in findings if f.check in _ST]
    results = {}
    for name in wanted:
        if name in errors:
            continue
        results[name] = (
            [f for f in findings if f.symbol == name],
            dict(STATE_STATS.get(name, {})),
        )
    _report(results, registry=registry)
    stats = {name: s for name, (_, s) in results.items()}
    return findings, errors, stats


@target("memory_llama_o4_step")
def _memory_llama_o4_step():
    """The llama O4 train step through the live-interval lattice: the
    carry is donated (the run loop's real calling convention), so every
    param/moment/fp8-ring buffer earns its donation credit and the
    peak is the transient working set — hold an activation across the
    backward or drop a donation and this target turns red."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.amp import Fp8DelayedScaler
    from apex_tpu.models import llama
    from apex_tpu.optimizers import fused_adam

    cfg = llama.tiny(num_layers=1, num_heads=2, num_kv_heads=1,
                     hidden_size=32, intermediate_size=64,
                     vocab_size=128, max_seq_len=16)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tx = fused_adam(lr=1e-3)
    fp8 = Fp8DelayedScaler(["lm_head"], history=4)
    carry = (params, tx.init(params), fp8.init())
    tokens = jnp.zeros((2, 16), jnp.int32)

    def train_step(carry, tokens, targets):
        params, opt_state, fp8_state = carry

        def loss_fn(p):
            logits = llama.forward(p, tokens, cfg, tp_axis=None,
                                   cp_axis=None, ep_axis=None)
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            return -jnp.mean(jnp.take_along_axis(
                lp, targets[..., None], axis=-1))

        with fp8.step(fp8_state) as ctx:
            loss, grads = ctx.value_and_grad(loss_fn)(params)
        new_fp8 = fp8.update(fp8_state, ctx)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = jax.tree_util.tree_map(jnp.add, params, updates)
        return (new_params, new_opt, new_fp8), loss

    stats = MEMORY_STATS.setdefault("memory_llama_o4_step", {})
    return analyze_memory(train_step, carry, tokens, tokens,
                          name="memory_llama_o4_step",
                          donate_argnums=(0,), state_argnums=(0,),
                          stats_out=stats)


@target("memory_zero1_fused_adam_step")
def _memory_zero1_fused_adam_step():
    """ZeRO-1 carry step under the liveness walk: the dp-sharded mu/nu
    buckets and params are donated carry, so the interval lattice must
    see their updates land in-place-shaped and charge only the
    reduce-scatter transients against the peak."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.parallel.zero import Zero1FusedAdam

    mesh, sizes, owned = _owned_mesh()
    try:
        dp = sizes.get("dp", 1)
        params = {"w": jnp.zeros((256, 256), jnp.bfloat16),
                  "b": jnp.zeros((256,), jnp.bfloat16)}
        opt = Zero1FusedAdam(lr=1e-3, weight_decay=0.01, axis_name="dp",
                             num_shards=dp, bucket_cap_mb=0.1)
        state = opt.init(params)
        grads_of = _ddp_grad_model()

        def step(x, state, params):
            return opt.step(grads_of(x), state, params)

        state_specs = opt.state_specs(params)
        param_specs = {"w": P(), "b": P()}
        fn = jax.shard_map(
            step, mesh=mesh,
            in_specs=(P("dp"), state_specs, param_specs),
            out_specs=(param_specs, state_specs),
            check_vma=False)

        def train_step(carry, x):
            params, ostate = carry
            new_params, new_ostate = fn(x, ostate, params)
            return new_params, new_ostate

        stats = MEMORY_STATS.setdefault("memory_zero1_fused_adam_step",
                                        {})
        return analyze_memory(
            train_step, (params, state),
            jnp.zeros((8 * dp, 256), jnp.float32),
            name="memory_zero1_fused_adam_step",
            donate_argnums=(0,), state_argnums=(0,),
            axis_sizes=sizes, stats_out=stats)
    finally:
        _release_mesh(owned)


@target("memory_ddp_overlap_step")
def _memory_ddp_overlap_step():
    """Overlapped-DDP amp step through the interval lattice: bucketed
    grad allreduce + scaled_update's cond must not hold the full grad
    tree and the bucket slabs live at once past the spike gate, and
    the donated carry (params, flat-adam state, scaler counters)
    collects its credit."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.amp import LossScaler, scaled_update
    from apex_tpu.optimizers import fused_adam
    from apex_tpu.parallel.overlap import sync_gradients_overlapped

    mesh, sizes, owned = _owned_mesh()
    try:
        dp = sizes.get("dp", 1)
        params = {"w": jnp.zeros((256, 256), jnp.float32),
                  "b": jnp.zeros((256,), jnp.float32)}
        tx = fused_adam(lr=1e-3, flat=True)
        scaler = LossScaler()
        carry = (params, tx.init(params), scaler.init())
        grads_of = _ddp_grad_model()

        def inner(x, params, opt_state, sstate):
            grads = sync_gradients_overlapped(
                grads_of(x), axis_name="dp", bucket_cap_mb=0.1)
            updates, new_opt, new_sstate, _ovf = scaled_update(
                tx, scaler, grads, opt_state, params, sstate,
                overflow_reduce_axes=("dp",))
            new_params = jax.tree_util.tree_map(
                jnp.add, params, updates)
            return new_params, new_opt, new_sstate

        fn = jax.shard_map(
            inner, mesh=mesh, in_specs=(P("dp"), P(), P(), P()),
            out_specs=(P(), P(), P()), check_vma=False)

        def train_step(carry, x):
            params, opt_state, sstate = carry
            return fn(x, params, opt_state, sstate)

        stats = MEMORY_STATS.setdefault("memory_ddp_overlap_step", {})
        return analyze_memory(
            train_step, carry,
            jnp.zeros((8 * dp, 256), jnp.float32),
            name="memory_ddp_overlap_step",
            donate_argnums=(0,), state_argnums=(0,),
            axis_sizes=sizes, stats_out=stats)
    finally:
        _release_mesh(owned)


@target("memory_fused_adam_master_sharded")
def _memory_fused_adam_master_sharded():
    """The calibration loop's 3.4x outlier (fused Adam over tp-sharded
    fp32 masters) under the liveness walk, fully donated: grads, state
    AND masters die into their updates, so every slab earns donation
    credit and the modeled peak is the number hbm_priors.json's ratio
    corrects. The grads slot is donated here where the sharding twin
    (fused_adam_master_sharded_step) historically was not — exactly
    the missed-donation pattern the check exists to catch."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from apex_tpu.optimizers import fused_adam

    mesh, sizes, owned = _owned_mesh(
        tensor_model_parallel_size_=_tp_size())
    try:
        master = {"w": jnp.zeros((256, 1024), jnp.float32),
                  "b": jnp.zeros((1024,), jnp.float32)}
        tx = fused_adam(lr=1e-3, weight_decay=0.01, flat=False)
        state = tx.init(master)
        grads = jax.tree_util.tree_map(jnp.ones_like, master)

        def step(grads, state, master):
            updates, new_state = tx.update(grads, state, master)
            return optax.apply_updates(master, updates), new_state

        wspec = {"w": P(None, "tp"), "b": P("tp")}
        state_spec = jax.tree_util.tree_map(
            lambda s: (wspec["w"] if getattr(s, "ndim", 0) == 2 else
                       wspec["b"] if getattr(s, "ndim", 0) == 1 else P()),
            state, is_leaf=lambda s: hasattr(s, "shape"))
        with jax.sharding.set_mesh(mesh):
            stats = MEMORY_STATS.setdefault(
                "memory_fused_adam_master_sharded", {})
            return analyze_memory(
                step, grads, state, master,
                in_specs=[wspec, state_spec, wspec],
                donate_argnums=(0, 1, 2), state_argnums=(1,),
                axis_sizes=sizes, stats_out=stats,
                name="memory_fused_adam_master_sharded")
    finally:
        _release_mesh(owned)


MEMORY_TARGETS = (
    "memory_llama_o4_step", "memory_zero1_fused_adam_step",
    "memory_ddp_overlap_step", "memory_fused_adam_master_sharded",
    "memory_serving_decode_step",
)


def run_memory_findings(registry=None, names=None):
    """Run only the memory-liveness targets and publish finding counts
    (zero-filled over every check id) + per-target peak/steady bytes to
    the observability registry (``analysis/memory_findings*`` +
    ``analysis/memory_peak_hbm_bytes`` family) — the hook bench.py
    reports through. Returns (findings, errors, stats)."""
    from apex_tpu.analysis.memory_checks import (
        MEMORY_CHECKS as _MC,
        report_to_registry as _report,
    )

    wanted = tuple(names) if names is not None else MEMORY_TARGETS
    unknown = set(wanted) - set(TARGETS)
    if unknown:
        raise ValueError(
            f"unknown memory target(s) {sorted(unknown)}; valid: "
            f"{sorted(MEMORY_TARGETS)}")
    findings, errors = run_targets(set(wanted))
    findings = [f for f in findings if f.check in _MC]
    results = {}
    for name in wanted:
        if name in errors:
            continue
        results[name] = (
            [f for f in findings if f.symbol == name],
            dict(MEMORY_STATS.get(name, {})),
        )
    _report(results, registry=registry)
    stats = {name: s for name, (_, s) in results.items()}
    return findings, errors, stats


# ------------------------------------------------------- serving targets
#
# The serving decode step (apex_tpu/serving/scheduler.py) as analysis
# targets: the same static-shape step the engine jits, proven through
# the state fixpoint (carried tokens/pages/positions), the memory
# liveness walk (donated page buffers), and — for fleet serving — the
# SPMD audit of the dp-replicated variant. They live in the state/
# memory/spmd family tuples (their checks ARE those families') but
# roll their wall time into the dedicated "serving" engine bucket
# (cli.target_engine checks SERVING_TARGETS first).


def _serving_decode_fixture():
    """Tiny-llama decode-step fixture shared by the serving targets:
    (cfg, params, decode_fn, carry, tables, active) with 2 slots over
    8 pages of 4 tokens (+ trash page), both rows mid-sequence."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models import llama
    from apex_tpu.serving.scheduler import build_decode_step

    cfg = llama.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    page_size, num_pages, batch, maxp = 4, 8, 2, 4
    decode = build_decode_step(cfg, page_size)
    shape = (cfg.num_layers, num_pages + 1, page_size,
             cfg.num_kv_heads, cfg.head_dim)
    carry = (jnp.zeros((batch,), jnp.int32),
             jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype),
             jnp.full((batch,), 5, jnp.int32))
    tables = jnp.asarray(
        np.arange(batch * maxp).reshape(batch, maxp), jnp.int32)
    active = jnp.ones((batch,), bool)

    def serve_step(carry, params, tables, active):
        tokens, k_pages, v_pages, pos = carry
        nxt, k_pages, v_pages = decode(params, {}, k_pages, v_pages,
                                       tokens, tables, pos, active)
        return nxt, k_pages, v_pages, pos + 1

    return cfg, params, serve_step, carry, tables, active


@target("state_serving_decode_step")
def _state_serving_decode_step():
    """The serving decode step through the state fixpoint: tokens,
    both page buffers and the position vector are the carry a
    continuous-batching server threads forever — every one must flow
    step-to-step (a dropped page buffer would silently serve from a
    stale cache)."""
    _cfg, params, serve_step, carry, tables, active = \
        _serving_decode_fixture()
    stats = STATE_STATS.setdefault("state_serving_decode_step", {})
    return analyze_state(serve_step, carry, params, tables, active,
                         name="state_serving_decode_step",
                         stats_out=stats)


@target("memory_serving_decode_step")
def _memory_serving_decode_step():
    """The serving decode step through the liveness walk with the
    carry donated — the engine's jit donates both page buffers every
    step, so the lattice must see the scatter updates land
    in-place-shaped and charge only the per-step activations (not a
    second cache) against the peak."""
    _cfg, params, serve_step, carry, tables, active = \
        _serving_decode_fixture()
    stats = MEMORY_STATS.setdefault("memory_serving_decode_step", {})
    return analyze_memory(serve_step, carry, params, tables, active,
                          name="memory_serving_decode_step",
                          donate_argnums=(0,), state_argnums=(0,),
                          stats_out=stats)


@target("spmd_serving_decode_step")
def _spmd_serving_decode_step():
    """Fleet serving: dp-replicated decode shards the slot arrays and
    page buffers over 'dp' (replica-private caches), params
    replicated. There are NO collectives by design — each replica
    serves its own requests — and the SPMD audit is what keeps that
    true (an accidental cross-replica reduction would both corrupt
    tokens and serialize the fleet)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models import llama
    from apex_tpu.serving.scheduler import build_decode_step

    mesh, sizes, owned = _owned_mesh()
    try:
        dp = sizes.get("dp", 1)
        cfg = llama.tiny()
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        page_size, num_pages, batch, maxp = 4, 8, 2, 4
        decode = build_decode_step(cfg, page_size)

        def local_step(params, k_pages, v_pages, tokens, tables, pos,
                       active):
            return decode(params, {}, k_pages, v_pages, tokens,
                          tables, pos, active)

        shape = (cfg.num_layers, dp * (num_pages + 1), page_size,
                 cfg.num_kv_heads, cfg.head_dim)
        k_pages = jnp.zeros(shape, cfg.dtype)
        v_pages = jnp.zeros(shape, cfg.dtype)
        tokens = jnp.zeros((dp * batch,), jnp.int32)
        tables = jnp.asarray(
            np.tile(np.arange(batch * maxp).reshape(batch, maxp),
                    (dp, 1)), jnp.int32)
        pos = jnp.full((dp * batch,), 5, jnp.int32)
        active = jnp.ones((dp * batch,), bool)
        fn = jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P(None, "dp"), P(None, "dp"), P("dp"),
                      P("dp"), P("dp"), P("dp")),
            out_specs=(P("dp"), P(None, "dp"), P(None, "dp")),
            check_vma=False)
        return _analyze_spmd_target(
            "spmd_serving_decode_step", fn, params, k_pages, v_pages,
            tokens, tables, pos, active, axis_sizes=sizes)
    finally:
        _release_mesh(owned)


# The dedicated wall-time bucket (cli.ENGINE_NAMES "serving"): checked
# FIRST by cli.target_engine, so these names bucket here even though
# they also belong to the state/memory/spmd family tuples above.
SERVING_TARGETS = (
    "state_serving_decode_step", "memory_serving_decode_step",
    "spmd_serving_decode_step",
)
