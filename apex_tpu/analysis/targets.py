"""Registered jaxpr-engine analysis targets: the repo's real entry
points, traced with representative avals and run through every jaxpr
check. ``python -m apex_tpu.analysis`` and tests/run_analysis execute
all of them, so a regression in donation discipline, collective axis
wiring, or a kernel's BlockSpecs fails tier-1 without hardware.

Each target is a zero-arg callable returning a list of Findings. Keep
them cheap: tracing only (no compile, no execution) on the CPU backend.
"""

from __future__ import annotations

from apex_tpu.analysis.findings import Finding
from apex_tpu.analysis.jaxpr_checks import JAXPR_CHECKS, analyze_fn
from apex_tpu.analysis.precision_checks import (
    PRECISION_CHECKS,
    analyze_precision,
)

TARGETS = {}

# Per-target grandfather lists (the jaxpr analog of `# apex-lint:
# disable`, which only reaches AST findings): @target(..., allow=(...))
# drops those check ids from that target's findings at the source, so a
# deliberate half-precision path doesn't need a global baseline slot.
# The CLI's --allow target:check lands here too (see run_targets).
TARGET_ALLOW = {}

# Check ids produced by non-tracing targets (everything else emits the
# jaxpr_checks.JAXPR_CHECKS ids). The CLI derives --list-checks, check-id
# validation, and target narrowing from this — register new
# target-provided checks here, not in cli.py.
TARGET_CHECKS = ("kernel-auto-provenance", "step-record-schema")

# Check ids that require running the tracing targets (the CLI runs the
# full target suite when any of these is requested).
TRACING_CHECKS = tuple(JAXPR_CHECKS) + tuple(PRECISION_CHECKS)


def target(name, allow=()):
    def deco(fn):
        TARGETS[name] = fn
        if allow:
            unknown = set(allow) - set(TRACING_CHECKS) - set(TARGET_CHECKS)
            if unknown:
                raise ValueError(
                    f"@target({name!r}) allows unknown check id(s) "
                    f"{sorted(unknown)}")
            TARGET_ALLOW[name] = frozenset(allow)
        return fn
    return deco


@target("fused_adam_flat_step")
def _fused_adam_flat_step():
    """The flat-buffer Adam path behind a donated train step — the first
    customer the ISSUE names: its donated aliasing was never
    machine-checked."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.optimizers import fused_adam

    params = {"w": jnp.zeros((64, 128), jnp.float32),
              "b": jnp.zeros((128,), jnp.float32)}
    tx = fused_adam(lr=1e-3, weight_decay=0.01, flat=True)
    state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)

    def train_step(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(jnp.add, params, updates)
        return params, opt_state

    return analyze_fn(train_step, params, state, grads,
                      donate_argnums=(0, 1), name="fused_adam_flat_step")


@target("fused_adam_flat_kernel")
def _fused_adam_flat_kernel():
    """The Pallas flat-Adam kernel's BlockSpecs (scalar block + slab
    padding are the Mosaic-sensitive parts)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.optimizers import fused_adam
    from apex_tpu.ops import pallas_config

    params = {"w": jnp.zeros((4096,), jnp.float32)}
    tx = fused_adam(lr=1e-3, flat=True, use_kernel=True)
    state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    with pallas_config.force("interpret"):
        return analyze_fn(lambda g, s, p: tx.update(g, s, p),
                          grads, state, params,
                          name="fused_adam_flat_kernel")


@target("flash_attention_fwd")
def _flash_attention_fwd():
    import jax.numpy as jnp

    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.flash_attention import flash_attention

    q = jnp.zeros((2, 256, 4, 64), jnp.bfloat16)
    with pallas_config.force("on"):
        return analyze_fn(
            lambda q, k, v: flash_attention(q, k, v, causal=True),
            q, q, q, name="flash_attention_fwd")


@target("layer_norm_fwd")
def _layer_norm_fwd():
    import jax.numpy as jnp

    from apex_tpu.ops import pallas_config
    from apex_tpu.ops.layer_norm import layer_norm

    x = jnp.zeros((256, 1024), jnp.bfloat16)
    w = jnp.ones((1024,), jnp.float32)
    b = jnp.zeros((1024,), jnp.float32)
    with pallas_config.force("on"):
        return analyze_fn(lambda x, w, b: layer_norm(x, w, b, (1024,)),
                          x, w, b, name="layer_norm_fwd")


@target("causal_softmax")
def _causal_softmax():
    import jax.numpy as jnp

    from apex_tpu.ops import pallas_config
    from apex_tpu.transformer.functional.fused_softmax import (
        scaled_upper_triang_masked_softmax,
    )

    x = jnp.zeros((8, 256, 256), jnp.bfloat16)
    with pallas_config.force("on"):
        return analyze_fn(
            lambda x: scaled_upper_triang_masked_softmax(x, None, 1.0),
            x, name="causal_softmax")


@target("tp_collectives")
def _tp_collectives():
    """Tensor-parallel allreduce wiring against the live parallel_state
    mesh — the collective-axis check's first customer."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state

    owned = not parallel_state.model_parallel_is_initialized()
    if owned:
        tp = 2 if len(jax.devices()) >= 2 else 1
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=tp)
    try:
        mesh = parallel_state.get_mesh()
        axis = parallel_state.get_tensor_model_parallel_group()
        tp = mesh.shape[axis]

        def allreduce(x):
            return jax.lax.psum(x, axis)

        fn = shard_map(allreduce, mesh=mesh, in_specs=P(axis),
                       out_specs=P())
        return analyze_fn(fn, jnp.zeros((tp * 8,), jnp.float32),
                          mesh_axes=mesh, name="tp_collectives")
    finally:
        if owned:
            parallel_state.destroy_model_parallel()


@target("kernel-auto-provenance")
def _kernel_auto_provenance():
    """Every pinned _KERNEL_AUTO verdict must name its evidence artifact
    (satellite: ops/pallas_config.py provenance)."""
    from apex_tpu.ops import pallas_config

    return [Finding("kernel-auto-provenance", "error",
                    "apex_tpu/ops/pallas_config.py", 0, "_KERNEL_AUTO",
                    problem)
            for problem in pallas_config.validate_kernel_auto_provenance()]


@target("step-record-schema")
def _step_record_schema():
    """The observability layer's own gate: a StepReporter record built
    from synthetic inputs must carry every STEP_RECORD_FIELDS key and
    survive a registry JSONL round-trip — the step-record schema is the
    evidence format every perf PR reads, so drift fails tier-1 here
    (ISSUE 2 satellite: the new module is registered and linted like
    any other entry point; the AST engine covers its sources via the
    default path set)."""
    import json as _json

    from apex_tpu.observability.registry import MetricRegistry
    from apex_tpu.observability.step_report import (
        STEP_RECORD_FIELDS, StepReporter,
    )

    findings = []

    def problem(msg):
        findings.append(Finding(
            "step-record-schema", "error",
            "apex_tpu/observability/step_report.py", 0, "StepReporter",
            msg))

    reg = MetricRegistry()
    rec = StepReporter("schema_check", registry=reg, tokens_per_step=1024,
                       flops_per_step=1e12, device_kind="cpu",
                       peak=1e15).step(0.01, loss=1.0)
    for field in STEP_RECORD_FIELDS:
        if field not in rec:
            problem(f"step record is missing documented field "
                    f"{field!r}")
    try:
        records = reg.to_records()
        _json.dumps(records)
    except (TypeError, ValueError) as e:
        problem(f"registry records are not JSON-serializable: {e}")
        return findings
    if not any(r.get("type") == "event" and r.get("name") == "step"
               for r in records):
        problem("StepReporter.step did not append a 'step' event to "
                "the registry")
    return findings


# ----------------------------------------------- precision-flow targets
# (ISSUE 3): the amp/optimizer/normalization/transformer entry points
# whose documented precision discipline the dataflow checks enforce.
# All are trace-only on the CPU backend, like everything above.

def _leaf_count(tree):
    import jax
    return len(jax.tree_util.tree_leaves(tree))


@target("mlp_train_step")
def _mlp_train_step():
    """bf16 MLP forward+backward with an fp32 loss: every dot must pin
    an fp32 accumulator (mlp.py preferred_element_type) and the loss
    reduction must run in fp32 — the seeded-regression anchor the ISSUE
    names (drop the preferred_element_type and tier-1 fails here)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.mlp import mlp_function

    params = (jnp.zeros((128, 256), jnp.bfloat16),
              jnp.zeros((256,), jnp.bfloat16),
              jnp.zeros((256, 64), jnp.bfloat16),
              jnp.zeros((64,), jnp.bfloat16))
    x = jnp.zeros((32, 128), jnp.bfloat16)
    y = jnp.zeros((32, 64), jnp.float32)

    def loss_fn(params, x, y):
        out = mlp_function(True, "relu", x, *params)
        d = out.astype(jnp.float32) - y
        return jnp.mean(jnp.square(d))

    return analyze_precision(
        lambda p, x, y: jax.value_and_grad(loss_fn)(p, x, y),
        params, x, y, name="mlp_train_step")


@target("amp_o1_train_step")
def _amp_o1_train_step():
    """O1: fp32 params, bf16 boundary casting via the active policy,
    loss scaled before backward. The precision contract here is that
    boundary-cast matmuls still accumulate fp32 and the loss math stays
    fp32 — exactly what docs/amp.md promises for O1."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.amp import amp as amp_mod
    from apex_tpu.amp.frontend import Policy
    from apex_tpu.amp.scaler import LossScaler
    from apex_tpu.mlp import mlp_function

    params = (jnp.zeros((128, 256), jnp.float32),
              jnp.zeros((256,), jnp.float32),
              jnp.zeros((256, 64), jnp.float32),
              jnp.zeros((64,), jnp.float32))
    x = jnp.zeros((32, 128), jnp.float32)
    y = jnp.zeros((32, 64), jnp.float32)
    scaler = LossScaler("dynamic")
    sstate = scaler.init()
    policy = Policy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
                    output_dtype=jnp.float32)

    def scaled_loss(params, x, y, sstate):
        out = mlp_function(True, "relu", x, *params)
        loss = jnp.mean(jnp.square(out.astype(jnp.float32) - y))
        return scaler.scale_loss(loss, sstate)

    with amp_mod.casting(policy):
        return analyze_precision(
            lambda p, x, y, s: jax.value_and_grad(scaled_loss)(p, x, y, s),
            params, x, y, sstate, name="amp_o1_train_step")


@target("amp_o2_master_update")
def _amp_o2_master_update():
    """O2 update phase: bf16 model copy, fp32 master + moments, scaled
    bf16 grads through unscale -> overflow-gated FusedAdam -> master
    apply -> half re-materialization. Exercises master-weights (the
    fp32 path must never dip to half) and loss-scale-bypass (the grads
    must pass the scaler's unscale before touching state)."""
    import jax
    import jax.numpy as jnp
    import optax

    from apex_tpu.amp.scaler import LossScaler, scaled_update
    from apex_tpu.optimizers import fused_adam

    master = {"w": jnp.zeros((64, 128), jnp.float32),
              "b": jnp.zeros((128,), jnp.float32)}
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16), master)
    grads = jax.tree_util.tree_map(
        lambda p: jnp.ones_like(p, jnp.bfloat16), master)
    tx = fused_adam(lr=1e-3, weight_decay=0.01, flat=True)
    state = tx.init(master)
    scaler = LossScaler("dynamic")
    sstate = scaler.init()

    def update(grads, opt_state, master, params, sstate):
        updates, new_opt, new_ss, overflow = scaled_update(
            tx, scaler, grads, opt_state, master, sstate)
        new_master = optax.apply_updates(master, updates)
        new_params = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype), new_master, params)
        return new_master, new_opt, new_params, new_ss

    n_master = _leaf_count(master)
    n_state = _leaf_count(state)
    return analyze_precision(
        update, grads, state, master, params, sstate,
        roles={0: "grad", 1: "master", 2: "master", 3: "param",
               4: "scale"},
        master_outs=tuple(range(n_master + n_state)),
        name="amp_o2_master_update")


@target("fused_adam_tree_master_step")
def _fused_adam_tree_master_step():
    """Per-tensor FusedAdam over fp32 master params: the whole update
    chain (m, v, decay, apply) must stay fp32."""
    import jax
    import jax.numpy as jnp
    import optax

    from apex_tpu.optimizers import fused_adam

    master = {"w": jnp.zeros((64, 128), jnp.float32),
              "b": jnp.zeros((128,), jnp.float32)}
    tx = fused_adam(lr=1e-3, weight_decay=0.01, flat=False)
    state = tx.init(master)
    grads = jax.tree_util.tree_map(jnp.ones_like, master)

    def step(grads, state, master):
        updates, new_state = tx.update(grads, state, master)
        return optax.apply_updates(master, updates), new_state

    n_out = _leaf_count(master) + _leaf_count(state)
    return analyze_precision(
        step, grads, state, master,
        roles={1: "master", 2: "master"},
        master_outs=tuple(range(n_out)),
        name="fused_adam_tree_master_step")


@target("fused_lamb_master_step")
def _fused_lamb_master_step():
    """FusedLAMB over fp32 master params: grad-norm, trust ratio and
    moments are all reductions/chains that must accumulate fp32."""
    import jax
    import jax.numpy as jnp
    import optax

    from apex_tpu.optimizers import fused_lamb

    master = {"w": jnp.zeros((64, 128), jnp.float32),
              "b": jnp.zeros((128,), jnp.float32)}
    tx = fused_lamb(lr=1e-3, weight_decay=0.01)
    state = tx.init(master)
    grads = jax.tree_util.tree_map(jnp.ones_like, master)

    def step(grads, state, master):
        updates, new_state = tx.update(grads, state, master)
        return optax.apply_updates(master, updates), new_state

    n_out = _leaf_count(master) + _leaf_count(state)
    return analyze_precision(
        step, grads, state, master,
        roles={1: "master", 2: "master"},
        master_outs=tuple(range(n_out)),
        name="fused_lamb_master_step")


@target("fused_layer_norm_fwd_bwd")
def _fused_layer_norm_fwd_bwd():
    """FusedLayerNorm forward+backward on bf16 activations with fp32
    affine params (the Megatron mixed pattern): statistics and both
    backward reductions must be fp32 — the jnp fallback path is the one
    dataflow can see (the Pallas kernels are covered by their own unit
    tests and the pallas-block check)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.normalization import fused_layer_norm_affine

    x = jnp.zeros((256, 1024), jnp.bfloat16)
    w = jnp.ones((1024,), jnp.float32)
    b = jnp.zeros((1024,), jnp.float32)

    def loss(x, w, b):
        y = fused_layer_norm_affine(x, w, b, (1024,))
        return jnp.sum(y.astype(jnp.float32))

    return analyze_precision(
        lambda x, w, b: jax.grad(loss, argnums=(0, 1, 2))(x, w, b),
        x, w, b, name="fused_layer_norm_fwd_bwd")


@target("fused_rms_norm_fwd_bwd")
def _fused_rms_norm_fwd_bwd():
    import jax
    import jax.numpy as jnp

    from apex_tpu.normalization import fused_rms_norm_affine

    x = jnp.zeros((256, 1024), jnp.bfloat16)
    w = jnp.ones((1024,), jnp.float32)

    def loss(x, w):
        y = fused_rms_norm_affine(x, w, (1024,))
        return jnp.sum(y.astype(jnp.float32))

    return analyze_precision(
        lambda x, w: jax.grad(loss, argnums=(0, 1))(x, w),
        x, w, name="fused_rms_norm_fwd_bwd")


@target("tp_fused_softmax")
def _tp_fused_softmax():
    """Tensor-parallel fused softmax, jnp fallback path on bf16 logits:
    the exp must sit behind an fp32 upcast + max subtraction (the
    Pallas kernel keeps the same contract in VMEM)."""
    import jax.numpy as jnp

    from apex_tpu.transformer.functional.fused_softmax import (
        scaled_upper_triang_masked_softmax,
    )

    x = jnp.zeros((8, 256, 256), jnp.bfloat16)
    return analyze_precision(
        lambda x: scaled_upper_triang_masked_softmax(x, None, 1.0),
        x, name="tp_fused_softmax")


def run_targets(names=None, extra_allow=None):
    """Run the registered targets; returns (findings, errors) where
    errors maps target name -> repr of an exception that kept the target
    from tracing at all (itself a failure the caller should surface).

    ``extra_allow``: {target name: set of check ids} merged over the
    ``@target(allow=...)`` lists — findings of an allowed check from
    that target are dropped (the per-target grandfather the CLI's
    ``--allow target:check`` feeds)."""
    findings, errors = [], {}
    for name, fn in TARGETS.items():
        if names is not None and name not in names:
            continue
        allowed = set(TARGET_ALLOW.get(name, ()))
        if extra_allow:
            allowed |= set(extra_allow.get(name, ()))
        try:
            got = fn()
        except Exception as e:  # noqa: BLE001 — report, don't abort the scan
            errors[name] = repr(e)[:300]
            continue
        if allowed:
            got = [f for f in got if f.check not in allowed]
        findings.extend(got)
    return findings, errors


def run_precision_findings(registry=None, names=None):
    """Run only the precision-flow targets and publish their finding
    counts to the observability registry (``analysis/precision``
    counter family) — the hook bench.py reports through. Returns
    (findings, errors)."""
    from apex_tpu.analysis.precision_checks import report_to_registry

    wanted = names if names is not None else PRECISION_TARGETS
    findings, errors = run_targets(wanted)
    findings = [f for f in findings if f.check in PRECISION_CHECKS]
    report_to_registry(findings, registry=registry)
    return findings, errors


PRECISION_TARGETS = (
    "mlp_train_step", "amp_o1_train_step", "amp_o2_master_update",
    "fused_adam_tree_master_step", "fused_lamb_master_step",
    "fused_layer_norm_fwd_bwd", "fused_rms_norm_fwd_bwd",
    "tp_fused_softmax",
)
