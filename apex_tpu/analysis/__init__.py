"""apex_tpu.analysis — static TPU lint (SURVEY: sanitizer/pyprof-adjacent
correctness tooling, rebuilt as compile-time analysis).

Two engines, one CLI, one pytest gate:

- **jaxpr engine** (:mod:`.jaxpr_checks`): trace a function with
  abstract avals on any backend and walk the closed jaxpr for donation
  races, retrace hazards, collective-axis mismatches against the live
  ``parallel_state`` mesh, and Pallas BlockSpec tiling/VMEM problems.
  On top of it, the **dataflow engine** (:mod:`.dataflow`) runs a
  forward abstract interpretation (dtype/cast/taint lattice) powering
  the **precision-flow checks** (:mod:`.precision_checks`):
  low-precision accumulation, master-weight discipline, unsafe exp,
  cast churn, loss-scale bypass. The **sharding engine**
  (:mod:`.sharding_flow`) runs the placement analog (PartitionSpec /
  distinctness lattice + liveness walk) powering the **sharding-flow
  checks** (:mod:`.sharding_checks`): implicit reshards, replicated
  large inputs, psum→slice reduce-scatter opportunities, dead
  collectives, and the per-device peak-HBM budget — plus the
  per-target comms-bytes/peak-HBM estimates bench.py reports. The
  **rank-consistency engine** (:mod:`.spmd_checks`) proves the SPMD
  contracts over the same walk: no collective under rank-divergent
  control, no rank-distinct value stored where out_specs claim
  replication, coordinated RNG, anchored host effects. The
  **checkpoint/state-flow engine** (:mod:`.state_checks`) closes the
  resume loop: a step-carry fixpoint over the train-step jaxpr proves
  every live state leaf reaches the checkpoint save tree, matches the
  manifest's format-2 ``state_schema``, restores without dtype
  narrowing, re-shards legally onto every elastic candidate mesh, and
  is never read after being donated on the resume path. The
  **memory-liveness engine** (:mod:`.memory_checks`) rides the same
  walk with a live-interval lattice — every value gets a birth/death
  step, donation credit, and peak-composition record — powering
  missed-donation, remat-opportunity (roofline-priced), peak-spike,
  live-range-upcast, and offload-candidate, plus the calibrated HBM
  priors (``hbm_priors.json``) the planner prunes on.
- **AST engine** (:mod:`.ast_checks`): lint driver code (apex_tpu,
  examples/, tools/, bench.py) for host-sync anti-patterns — the
  ``block_until_ready``-as-timing bug that produced r5's impossible
  MFU=330, host pulls and Python RNG inside jit, mutable defaults.

CLI: ``python -m apex_tpu.analysis`` (see :mod:`.cli`). Gate:
``tools/lint.sh`` + ``tests/run_analysis/`` with a checked-in baseline.
Docs: ``docs/analysis.md``.
"""

from apex_tpu.analysis.ast_checks import (
    AST_CHECKS,
    lint_paths,
    lint_source,
)
from apex_tpu.analysis.concurrency_checks import (
    CONCURRENCY_CHECKS,
    run_concurrency_findings,
)
from apex_tpu.analysis.findings import (
    Finding,
    load_baseline,
    new_findings,
    save_baseline,
)
from apex_tpu.analysis.jaxpr_checks import JAXPR_CHECKS, analyze_fn
from apex_tpu.analysis.memory_checks import (
    MEMORY_CHECKS,
    analyze_memory,
    analyze_memory_jaxpr,
    load_hbm_priors,
    prior_for,
)
from apex_tpu.analysis.precision_checks import (
    PRECISION_CHECKS,
    analyze_precision,
)
from apex_tpu.analysis.sharding_checks import (
    SHARDING_CHECKS,
    analyze_sharding,
    analyze_sharding_jaxpr,
)
from apex_tpu.analysis.planner import (
    PLAN_MODELS,
    Plan,
    PlanError,
    plan,
)
from apex_tpu.analysis.spmd_checks import (
    SPMD_CHECKS,
    analyze_spmd,
)
from apex_tpu.analysis.state_checks import (
    STATE_CHECKS,
    analyze_state,
)
from apex_tpu.analysis.targets import (
    TARGETS,
    run_memory_findings,
    run_precision_findings,
    run_sharding_findings,
    run_spmd_findings,
    run_state_findings,
    run_targets,
)

__all__ = [
    "AST_CHECKS", "CONCURRENCY_CHECKS", "Finding", "JAXPR_CHECKS",
    "MEMORY_CHECKS",
    "PLAN_MODELS",
    "PRECISION_CHECKS", "Plan", "PlanError",
    "SHARDING_CHECKS", "SPMD_CHECKS", "STATE_CHECKS", "TARGETS",
    "analyze_fn",
    "analyze_memory", "analyze_memory_jaxpr",
    "analyze_precision",
    "analyze_sharding", "analyze_sharding_jaxpr", "analyze_spmd",
    "analyze_state",
    "lint_paths", "lint_source", "load_baseline", "load_hbm_priors",
    "new_findings", "plan", "prior_for", "run_concurrency_findings",
    "run_memory_findings",
    "run_precision_findings",
    "run_sharding_findings", "run_spmd_findings", "run_state_findings",
    "run_targets",
    "save_baseline",
]
