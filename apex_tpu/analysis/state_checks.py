"""Checkpoint/state-flow checks — static resume compatibility (ISSUE 18).

Every training tier this repo grew (amp scaler state, fused-optimizer
master state, fp8 amax rings, ZeRO-1 moment shards) rides one unproven
assumption: that the state a train step carries round-trips through
:mod:`apex_tpu.checkpoint` and can be re-laid-out on a different mesh.
``_APEX_COMMIT.json`` is a file-level manifest (size + crc32): a field
silently dropped from the save tree, a dtype-narrowed restore slot, or
a ZeRO-1 bucket whose padding quantum does not divide the new dp count
are all runtime-or-never discoveries. This engine makes them static
errors, the way the spmd/concurrency engines did for rank desync and
host races.

The engine derives the *expected* state schema from code:

- a **step-carry fixpoint** over the train-step jaxpr via the unified
  interpreter (:mod:`.interp`) — :class:`StateFlowLattice` tracks, per
  jaxpr ``Var``, the set of flat state-input leaves the value derives
  from (``warm_carry_join`` runs scan/while bodies to their
  steady-state, so a leaf read only through a carried loop still
  registers). A state leaf whose value reaches ANY step output is
  *step-carried*: its restored value determines the post-resume
  trajectory, so it must round-trip through the checkpoint;
- **joined with the registered state constructors** — the known state
  NamedTuples (``LossScaleState``, ``Fp8ScalingState``,
  ``AmaxHistoryState``, ``Zero1AdamState``, fused-optimizer flat/tree
  state) tag each leaf with its constructor kind, so findings and the
  manifest's ``state_schema`` block both name the field that drifted,
  not just a flat index.

Five checks (:data:`STATE_CHECKS`):

- ``unsaved-train-state``  a step-carried leaf never reaches the
  checkpoint save tree (the save fn's jaxpr is origin-traced the same
  way) — silent state loss on resume. The chaos harness can only catch
  this per-field at runtime; the fixpoint proves it for all fields.
- ``ckpt-schema-drift``  the code-derived treedef/shape/dtype/spec
  fingerprint disagrees with the manifest's ``state_schema`` block
  (commit-marker format 2, :func:`apex_tpu.checkpoint.state_schema_of`)
  — the checkpoint on disk is not the state the code expects to
  restore. Format-1 manifests carry no schema and pass (back-compat).
- ``dtype-narrowing-restore``  a saved dtype wider than the restore
  slot (fp32 master state restored into a bf16 slot): orbax casts
  silently and the master-weight discipline dies on resume.
- ``reshard-illegal``  for each saved dim-0-sharded buffer and every
  candidate mesh size the planner would propose on shrink/grow, prove
  dim-0 divisibility AND shard-quantum compatibility (the ZeRO-1
  bucket padding ``_pad_up(total, n)`` must be invariant under the new
  shard count, or the saved flat buffer cannot be re-laid-out
  bit-for-bit) — the static gate elastic re-mesh needs before it
  exists (ROADMAP items 2–3).
- ``restore-donation-hazard``  a restored buffer feeds a donated
  argnum on the resume path and is read again (or returned) after the
  donating call with no copy in between — use-after-donate that only
  fires on real TPU, where donation actually invalidates the buffer.

Entry point: :func:`analyze_state` (mirrors ``analyze_spmd``); the
registered step/save/resume compositions live in :mod:`.targets`
(``STATE_TARGETS``) and per-run counts land in the
``analysis/state_findings{check=}`` metric family — zero-filled (every
check id is emitted every run), so the binary ``--compare`` gate in
``tools/metrics_report.py`` sees an explicit 0, not an absent series.
"""

from __future__ import annotations

import dataclasses
import json

from apex_tpu.analysis import interp
from apex_tpu.analysis.findings import Finding

STATE_CHECKS = (
    "unsaved-train-state", "ckpt-schema-drift",
    "dtype-narrowing-restore", "reshard-illegal",
    "restore-donation-hazard",
)


# ------------------------------------------------------- origin lattice


@dataclasses.dataclass(frozen=True)
class OriginVal:
    """One point of the state-flow lattice: the set of flat state-input
    leaf indices this value derives from."""

    origins: frozenset = frozenset()


_EMPTY = OriginVal()


def _join(ins):
    present = [v for v in ins if v is not None]
    if not present:
        return _EMPTY
    return OriginVal(origins=frozenset().union(
        *(v.origins for v in present)))


class StateFlowLattice(interp.Lattice):
    """Origin provenance over the unified walk: which state leaves can
    influence each value. Union-join everywhere (provenance is
    contagious through every compute op); scan/while carries run the
    warm fixpoint so a leaf read only on iteration >= 1 of a carried
    loop still registers as live."""

    name = "state"
    warm_carry_join = True

    def for_aval(self, aval):
        return _EMPTY

    def transfer(self, eqn, ins, out_avals, ctx):
        if eqn.primitive.name == "optimization_barrier":
            # elementwise over the tuple: each output mirrors its own
            # operand (a chain token must not taint the bucket it
            # orders — same rule as the rank lattice)
            return tuple(
                (ins[i] if i < len(ins) and ins[i] is not None
                 else _EMPTY) for i in range(len(out_avals)))
        base = _join(ins)
        return tuple(base for _ in out_avals)

    def join_branch(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return OriginVal(origins=a.origins | b.origins)

    join_carry = join_branch


STATE_LATTICE = StateFlowLattice()


# ----------------------------------------------------- schema derivation


#: Known state-constructor NamedTuples: leaves under one of these nodes
#: are tagged ``Kind.field`` in the schema, so a drift finding names
#: the constructor field, not a flat index. Import paths are lazy —
#: a missing module just loses the tag, never the check.
_CONSTRUCTOR_IMPORTS = (
    ("apex_tpu.amp.scaler", "LossScaleState"),
    ("apex_tpu.amp.scaler", "Fp8ScalingState"),
    ("apex_tpu.observability.numerics.history", "AmaxHistoryState"),
    ("apex_tpu.parallel.zero", "Zero1AdamState"),
)


#: (module, class) pairs whose lazy import failed: the schema loses the
#: constructor tag but every check still runs — counted here so the
#: degradation is inspectable, never silent.
_MISSING_CONSTRUCTORS = set()


def _constructor_classes():
    import importlib

    out = []
    for mod, cls in _CONSTRUCTOR_IMPORTS:
        try:
            out.append(getattr(importlib.import_module(mod), cls))
        except Exception:  # noqa: BLE001 — optional tags only
            _MISSING_CONSTRUCTORS.add((mod, cls))
    return tuple(out)


def leaf_kinds(tree):
    """Per-flat-leaf constructor tag (``"Zero1AdamState.mu"`` /
    ``"LossScaleState.loss_scale"`` / None) for ``tree``, in
    ``tree_leaves`` order — the registered-constructor join."""
    import jax

    classes = _constructor_classes()
    kinds = []

    def walk(node, tag):
        if isinstance(node, classes):
            for field, child in zip(type(node)._fields, node):
                walk(child, f"{type(node).__name__}.{field}")
            return
        leaves_here = jax.tree_util.tree_structure(node).num_leaves
        if leaves_here == 0:
            return
        children, _treedef = jax.tree_util.tree_flatten(
            node, is_leaf=lambda x: x is not node and (
                isinstance(x, classes)
                or jax.tree_util.treedef_is_leaf(
                    jax.tree_util.tree_structure(x))))
        if len(children) == 1 and children[0] is node:
            kinds.append(tag)
            return
        for child in children:
            walk(child, tag)

    walk(tree, None)
    return tuple(kinds)


@dataclasses.dataclass(frozen=True)
class StateLeaf:
    """One flat leaf of the derived schema."""

    path: str            # jax.tree_util.keystr of the leaf
    shape: tuple
    dtype: str
    spec: object         # encoded PartitionSpec dims, or None (unknown)
    kind: object = None  # constructor tag ("Zero1AdamState.mu") or None
    carried: bool = False  # the step-carry fixpoint says the step reads it


@dataclasses.dataclass(frozen=True)
class StateSchema:
    """Code-derived expected state schema (treedef + typed leaves)."""

    treedef: str
    leaves: tuple

    def to_manifest(self) -> dict:
        """The commit-marker ``state_schema`` encoding this schema
        expects on disk — same shape :func:`apex_tpu.checkpoint.
        state_schema_of` writes, so drift compares real encodings."""
        from apex_tpu.checkpoint import schema_fingerprint

        body = {
            "treedef": self.treedef,
            "leaves": [
                {"path": lf.path, "shape": list(lf.shape),
                 "dtype": lf.dtype, "spec": lf.spec, "kind": lf.kind}
                for lf in self.leaves],
        }
        body["fingerprint"] = schema_fingerprint(body)
        return body


def _flatten_with_paths(tree):
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = tuple(jax.tree_util.keystr(kp) for kp, _ in flat)
    leaves = tuple(leaf for _, leaf in flat)
    return paths, leaves, treedef


def _spec_leaves(specs, n, context):
    """Flatten a PartitionSpec pytree to ``n`` encoded entries (None =
    unknown); loud on a structure mismatch — a silently-misaligned
    spec tree would attach the wrong axis to every leaf."""
    if specs is None:
        return (None,) * n
    import jax
    from jax.sharding import PartitionSpec

    flat = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda s: s is None
        or isinstance(s, PartitionSpec))[0]
    if len(flat) != n:
        raise ValueError(
            f"{context}: specs pytree has {len(flat)} leaves, state "
            f"has {n} — spec and state trees diverged")
    from apex_tpu.checkpoint import encode_spec

    return tuple(encode_spec(s) for s in flat)


def derive_state_schema(step_fn, state, *args, specs=None, name=None,
                        axis_sizes=None) -> StateSchema:
    """Trace ``step_fn(state, *args)`` and derive the expected state
    schema: per-leaf path/shape/dtype/spec/constructor-kind plus the
    step-carry verdict (does the leaf's value influence any output —
    the fixpoint over the jaxpr via :class:`StateFlowLattice`)."""
    import jax

    name = name or getattr(step_fn, "__name__", "step")
    paths, leaves, treedef = _flatten_with_paths(state)
    closed = jax.make_jaxpr(step_fn)(state, *args)

    n_state = len(leaves)
    in_vals = [OriginVal(origins=frozenset({j})) for j in range(n_state)]
    in_vals += [None] * (len(closed.jaxpr.invars) - n_state)
    (out_vals,) = interp.interpret_lattices(
        closed, [interp.LatticeRun(STATE_LATTICE, in_vals)],
        axis_sizes=axis_sizes or {})

    live = frozenset().union(
        *(v.origins for v in out_vals if v is not None)) \
        if any(v is not None for v in out_vals) else frozenset()

    spec_flat = _spec_leaves(specs, n_state, f"derive_state_schema "
                                            f"({name})")
    kinds = leaf_kinds(state)
    schema_leaves = tuple(
        StateLeaf(path=paths[j], shape=tuple(leaves[j].shape),
                  dtype=_dtype_name(leaves[j]), spec=spec_flat[j],
                  kind=kinds[j] if j < len(kinds) else None,
                  carried=j in live)
        for j in range(n_state))
    return StateSchema(treedef=str(treedef), leaves=schema_leaves)


def _dtype_name(leaf):
    import numpy as np

    dt = getattr(leaf, "dtype", None)
    if dt is None:
        dt = np.asarray(leaf).dtype
    return np.dtype(dt).name


def trace_save_coverage(save_tree_of, state):
    """Origin-trace the save fn: which flat state leaves reach the
    saved tree, and per saved slot, which state leaf it mirrors.

    Returns ``(covered, saved_paths, saved_shapes, slot_origins)``:
    ``covered`` is the frozenset of state-leaf indices present in the
    save tree; ``slot_origins[i]`` is the origin set of saved flat
    slot ``i`` (a singleton for plain restructuring saves)."""
    import jax

    closed, saved_shape = jax.make_jaxpr(
        save_tree_of, return_shape=True)(state)
    n_state = len(jax.tree_util.tree_leaves(state))
    in_vals = [OriginVal(origins=frozenset({j})) for j in range(n_state)]
    in_vals += [None] * (len(closed.jaxpr.invars) - n_state)
    (out_vals,) = interp.interpret_lattices(
        closed, [interp.LatticeRun(STATE_LATTICE, in_vals)],
        axis_sizes={})
    slot_origins = tuple(
        (v.origins if v is not None else frozenset())
        for v in out_vals)
    covered = frozenset().union(*slot_origins) if slot_origins \
        else frozenset()
    saved_paths, saved_leaves, saved_treedef = _flatten_with_paths(
        saved_shape)
    return covered, saved_paths, saved_leaves, saved_treedef, \
        slot_origins


# ------------------------------------------------------------- findings


class _Ctx:
    def __init__(self, name, path, checks=frozenset(STATE_CHECKS)):
        self.name = name
        self.path = path
        self.checks = frozenset(checks)
        self.findings = []
        self.seen = set()

    def add(self, check, severity, message, dedup_key=None):
        if check not in self.checks:
            return
        if dedup_key is not None:
            key = (check,) + tuple(dedup_key)
            if key in self.seen:
                return
            self.seen.add(key)
        self.findings.append(Finding(
            check, severity, self.path, 0, self.name, message))


# -------------------------------------------------- per-check evaluators


def _check_unsaved(ctx, schema, covered):
    for j, lf in enumerate(schema.leaves):
        if not lf.carried or j in covered:
            continue
        kind = f" ({lf.kind})" if lf.kind else ""
        ctx.add(
            "unsaved-train-state", "error",
            f"state leaf {lf.path}{kind} is step-carried (its value "
            f"flows into the next step's outputs) but never reaches "
            f"the checkpoint save tree: on resume it silently "
            f"re-initializes and the run is no longer the run that "
            f"was saved — add the leaf to the save tree, or prove it "
            f"derivable and drop it from the carry",
            dedup_key=(lf.path,))


def _manifest_leaves(manifest_schema):
    out = {}
    for lf in manifest_schema.get("leaves", ()):
        out[lf.get("path", "?")] = lf
    return out


def _check_schema_drift(ctx, code_manifest, disk_manifest):
    code_by = _manifest_leaves(code_manifest)
    disk_by = _manifest_leaves(disk_manifest)
    if code_manifest.get("treedef") != disk_manifest.get("treedef"):
        ctx.add(
            "ckpt-schema-drift", "error",
            f"saved treedef does not match the code-derived save "
            f"tree: manifest has {disk_manifest.get('treedef')!r}, "
            f"code expects {code_manifest.get('treedef')!r} — the "
            f"checkpoint on disk is not the state this step restores",
            dedup_key=("treedef",))
    for path in sorted(set(code_by) - set(disk_by)):
        ctx.add(
            "ckpt-schema-drift", "error",
            f"save-tree leaf {path} is missing from the manifest's "
            f"state_schema — the checkpoint predates (or dropped) "
            f"this field and restore will not populate it",
            dedup_key=("missing", path))
    for path in sorted(set(disk_by) - set(code_by)):
        ctx.add(
            "ckpt-schema-drift", "warning",
            f"manifest carries leaf {path} the code-derived save "
            f"tree no longer has — stale state rides every restore "
            f"(or the save tree silently shrank)",
            dedup_key=("extra", path))
    for path in sorted(set(code_by) & set(disk_by)):
        want, got = code_by[path], disk_by[path]
        for field in ("shape", "dtype", "spec"):
            w = want.get(field)
            g = got.get(field)
            if field == "shape":
                w, g = list(w or ()), list(g or ())
            if w != g:
                ctx.add(
                    "ckpt-schema-drift", "error",
                    f"leaf {path} {field} drifted: manifest has "
                    f"{g!r}, code expects {w!r} — restore would "
                    f"reinterpret the saved bytes",
                    dedup_key=(path, field))


_FLOAT_WIDTH = {
    "float64": 8, "float32": 4, "bfloat16": 2, "float16": 2,
    "float8_e4m3fn": 1, "float8_e5m2": 1, "float8_e4m3fnuz": 1,
    "float8_e5m2fnuz": 1, "float8_e4m3b11fnuz": 1,
}


def _check_dtype_narrowing(ctx, saved_manifest, template_paths,
                           template_leaves):
    slot_by_path = {p: lf for p, lf in zip(template_paths,
                                           template_leaves)}
    for lf in saved_manifest.get("leaves", ()):
        path = lf.get("path", "?")
        slot = slot_by_path.get(path)
        if slot is None:
            continue
        saved_dt = str(lf.get("dtype"))
        slot_dt = _dtype_name(slot)
        sw = _FLOAT_WIDTH.get(saved_dt)
        tw = _FLOAT_WIDTH.get(slot_dt)
        if sw is None or tw is None or sw <= tw:
            continue
        kind = f" ({lf.get('kind')})" if lf.get("kind") else ""
        ctx.add(
            "dtype-narrowing-restore", "error",
            f"leaf {path}{kind} was saved as {saved_dt} but the "
            f"restore slot is {slot_dt}: orbax casts silently and "
            f"the wide master copy is lost on resume — restore into "
            f"a {saved_dt} slot (the master-weight discipline the "
            f"precision engine enforces in-step applies across the "
            f"checkpoint boundary too)",
            dedup_key=(path,))


def _pad_up(total, k):
    return total + ((-total) % max(1, k))


def _spec_dim0_axes(spec):
    """Mesh axis names the encoded spec shards dim 0 over."""
    if not spec:
        return ()
    dim0 = spec[0]
    if dim0 is None:
        return ()
    if isinstance(dim0, (list, tuple)):
        return tuple(str(a) for a in dim0)
    return (str(dim0),)


def _check_reshard(ctx, saved_manifest, layout, candidates):
    candidates = tuple(int(n) for n in candidates)
    axis = (layout or {}).get("axis")
    for lf in saved_manifest.get("leaves", ()):
        axes = _spec_dim0_axes(lf.get("spec"))
        if not axes or (axis is not None and axis not in axes):
            continue
        shape = tuple(lf.get("shape") or ())
        if not shape:
            continue
        for n in candidates:
            if n > 0 and shape[0] % n == 0:
                continue
            ctx.add(
                "reshard-illegal", "error",
                f"leaf {lf.get('path', '?')} is saved dim-0-sharded "
                f"over {'/'.join(axes)} with shape[0]={shape[0]}, "
                f"which does not divide into {n} shards — the "
                f"planner's candidate mesh ({'/'.join(axes)}={n}) "
                f"cannot re-lay this buffer out; re-pad the saved "
                f"buffer or drop {n} from the elastic candidate set",
                dedup_key=(lf.get("path", "?"), n))
    for k, bucket in enumerate((layout or {}).get("buckets", ())):
        total = int(bucket.get("total", 0))
        padded = int(bucket.get("padded", 0))
        for n in candidates:
            if n <= 0:
                continue
            if padded % n != 0:
                ctx.add(
                    "reshard-illegal", "error",
                    f"ZeRO-1 bucket {k} ({bucket.get('dtype')}) has "
                    f"padded length {padded}, not divisible by "
                    f"candidate shard count {n} — the saved moment "
                    f"shards cannot be re-scattered onto that mesh",
                    dedup_key=("bucket-div", k, n))
            elif _pad_up(total, n) != padded:
                ctx.add(
                    "reshard-illegal", "error",
                    f"ZeRO-1 bucket {k} ({bucket.get('dtype')}) was "
                    f"padded to {padded} for "
                    f"{(layout or {}).get('num_shards')} shards, but "
                    f"re-planning for {n} shards pads "
                    f"{total} -> {_pad_up(total, n)}: the saved flat "
                    f"buffer and the new plan disagree on the shard "
                    f"quantum, so a restore onto that mesh "
                    f"misaligns every leaf after the first pad — "
                    f"only shard counts with _pad_up(total, n) == "
                    f"{padded} are pure reshards",
                    dedup_key=("bucket-quantum", k, n))


def check_restore_donation(resume_fn, state, *resume_args, name=None,
                           checks=None):
    """Trace the resume path (``resume_fn(restored_state, *args)``)
    and flag restored buffers that feed a donated argnum of an inner
    jitted call and are then read again (or returned) — on real TPU
    the donation invalidated the buffer, so the later read is
    use-after-free the CPU backend never surfaces.

    A copy (``jnp.copy`` / ``+ 0``) before the donating call creates a
    fresh buffer and clears the hazard for the original; so does
    simply not touching the restored reference after the call."""
    import jax

    name = name or getattr(resume_fn, "__name__", "resume")
    ctx = _Ctx(name, f"<jaxpr:{name}>",
               checks=_validate_checks(checks))
    if "restore-donation-hazard" not in ctx.checks:
        return ctx.findings
    closed = jax.make_jaxpr(resume_fn)(state, *resume_args)
    jaxpr = closed.jaxpr
    n_state = len(jax.tree_util.tree_leaves(state))

    # forward origin pass over the TOP-LEVEL eqns (donation happens at
    # jit boundaries, which appear here as pjit eqns)
    restored = {v for v in jaxpr.invars[:n_state]}
    derives = dict.fromkeys(restored, True)
    donated_at = []  # (position, eqn, donated restored vars)
    for pos, eqn in enumerate(jaxpr.eqns):
        flags = eqn.params.get("donated_invars")
        if flags:
            hit = [v for v, flag in zip(eqn.invars, flags)
                   if flag and interp.is_var(v) and derives.get(v)]
            if hit:
                donated_at.append((pos, eqn, hit))
        tainted = any(interp.is_var(v) and derives.get(v)
                      for v in eqn.invars)
        for v in eqn.outvars:
            if interp.is_var(v):
                derives[v] = tainted
    out_vars = {v for v in jaxpr.outvars if interp.is_var(v)}
    for pos, eqn, hit in donated_at:
        later_reads = set()
        for later in jaxpr.eqns[pos + 1:]:
            later_reads.update(v for v in later.invars
                               if interp.is_var(v))
        for v in hit:
            read_after = v in later_reads
            returned = v in out_vars
            if not read_after and not returned:
                continue
            how = "read again after the donating call" if read_after \
                else "returned to the caller"
            ctx.add(
                "restore-donation-hazard", "error",
                f"a restored buffer is donated into "
                f"'{eqn.primitive.name}' (donate_argnums on the first "
                f"post-resume step) and then {how}: on TPU the "
                f"donation invalidated the buffer, so the resume path "
                f"holds a dead reference (the ResilientTrainLoop "
                f"fallback_state pattern) — jnp.copy the restored "
                f"state before the donating step, or drop the stale "
                f"reference",
                dedup_key=(str(v), pos))
    return ctx.findings


# ----------------------------------------------------------------- entry


def analyze_state(step_fn, state, *args, name=None, save_tree_of=None,
                  restore_template=None, specs=None, manifest=None,
                  reshard_layout=None, reshard_candidates=None,
                  resume_fn=None, resume_args=None, checks=None,
                  stats_out=None, axis_sizes=None):
    """Run the checkpoint/state-flow checks over one train step.

    ``step_fn(state, *args)``: the train step, state as argnum 0; its
    outputs define liveness for the step-carry fixpoint.
    ``save_tree_of``: state -> the pytree the checkpoint path actually
    persists (default: identity — save everything).
    ``restore_template``: the pytree restore populates (default: the
    save tree itself — no narrowing). ``specs``: PartitionSpec pytree
    matching ``state``. ``manifest``: a commit-marker ``state_schema``
    dict, a full marker payload, or a checkpoint dir path — when None,
    the drift check round-trips the code-derived schema through the
    manifest encoding (the arming self-check). ``reshard_layout`` /
    ``reshard_candidates``: the :meth:`Zero1FusedAdam.state_layout`
    export and the candidate shard counts to prove (e.g.
    :meth:`Zero1FusedAdam.elastic_candidates`). ``resume_fn`` /
    ``resume_args``: the resume-path composition for the donation
    check (skipped when absent). Returns a list of :class:`Finding`.
    """
    name = name or getattr(step_fn, "__name__", "step")
    run = _validate_checks(checks)
    ctx = _Ctx(name, f"<jaxpr:{name}>", checks=run)

    schema = derive_state_schema(step_fn, state, *args, specs=specs,
                                 name=name, axis_sizes=axis_sizes)
    save_fn = save_tree_of if save_tree_of is not None \
        else (lambda s: s)
    covered, saved_paths, saved_leaves, _saved_treedef, slot_origins \
        = trace_save_coverage(save_fn, state)

    if "unsaved-train-state" in run:
        _check_unsaved(ctx, schema, covered)

    # schema of the SAVED tree (what the manifest describes): spec and
    # kind carry over from the state leaf a slot mirrors (singleton
    # origin — plain restructuring saves)
    saved_schema_leaves = []
    for i, (path, leaf) in enumerate(zip(saved_paths, saved_leaves)):
        spec = kind = None
        origins = slot_origins[i] if i < len(slot_origins) \
            else frozenset()
        if len(origins) == 1:
            (j,) = origins
            if j < len(schema.leaves):
                spec = schema.leaves[j].spec
                kind = schema.leaves[j].kind
        saved_schema_leaves.append(StateLeaf(
            path=path, shape=tuple(leaf.shape),
            dtype=_dtype_name(leaf), spec=spec, kind=kind))
    code_saved = StateSchema(treedef=str(_saved_treedef),
                             leaves=tuple(saved_schema_leaves))
    code_manifest = code_saved.to_manifest()

    disk_manifest = _resolve_manifest(manifest)
    if "ckpt-schema-drift" in run:
        if disk_manifest is not None:
            _check_schema_drift(ctx, code_manifest, disk_manifest)
        else:
            # arming round-trip: the encode/decode path itself is under
            # test, so a broken encoder fails the clean targets loudly
            _check_schema_drift(
                ctx, code_manifest,
                json.loads(json.dumps(code_manifest)))

    if "dtype-narrowing-restore" in run:
        saved_for_narrowing = disk_manifest if disk_manifest is not None \
            else code_manifest
        if restore_template is not None:
            tpaths, tleaves, _ = _flatten_with_paths(restore_template)
        else:
            tpaths, tleaves = saved_paths, saved_leaves
        _check_dtype_narrowing(ctx, saved_for_narrowing, tpaths,
                               tleaves)

    if "reshard-illegal" in run and reshard_candidates:
        _check_reshard(ctx, code_manifest, reshard_layout,
                       reshard_candidates)

    if "restore-donation-hazard" in run and resume_fn is not None:
        ctx.findings.extend(check_restore_donation(
            resume_fn, state, *(resume_args or ()), name=name,
            checks=("restore-donation-hazard",)))

    if stats_out is not None:
        stats_out.update({
            "carried": sum(1 for lf in schema.leaves if lf.carried),
            "saved_leaves": len(saved_leaves),
            "reshard_candidates": len(tuple(reshard_candidates or ())),
        })
    return ctx.findings


def _resolve_manifest(manifest):
    """Normalize ``manifest`` to a ``state_schema`` dict (or None):
    accepts the schema dict itself, a full commit-marker payload, or a
    checkpoint step-dir path. A format-1 dir (no schema) resolves to
    None — back-compat, not drift."""
    if manifest is None:
        return None
    if isinstance(manifest, str):
        from apex_tpu.checkpoint import manifest_state_schema

        return manifest_state_schema(manifest)
    if isinstance(manifest, dict):
        if "leaves" in manifest:
            return manifest
        return manifest.get("state_schema")
    raise TypeError(
        f"manifest must be a dict or checkpoint dir path, got "
        f"{type(manifest).__name__}")


def _validate_checks(checks):
    run = set(checks or STATE_CHECKS)
    unknown = run - set(STATE_CHECKS)
    if unknown:
        raise ValueError(
            f"unknown state check(s) {sorted(unknown)}; valid: "
            f"{list(STATE_CHECKS)}")
    return run


def report_to_registry(results, registry=None):
    """Publish state findings + per-target carry/save stats as the
    ``analysis/state_*`` metric family.

    ``results``: {target name: (findings list, stats dict)}. Counters:
    ``analysis/state_findings{check=}`` — ZERO-FILLED: every check id
    is emitted every run (an explicit 0, not an absent series), so the
    binary ``--compare`` gate distinguishes "clean" from "never ran".
    Gauges: ``analysis/state_findings_total``,
    ``analysis/state_carried_leaves{target=}``,
    ``analysis/state_saved_leaves{target=}``. Returns {check: count}.
    """
    from apex_tpu.observability import get_registry

    reg = registry if registry is not None else get_registry()
    counts = {c: 0 for c in STATE_CHECKS}
    for target, (findings, stats) in sorted(results.items()):
        for f in findings:
            if f.check in counts:
                counts[f.check] += 1
        if stats:
            reg.gauge("analysis/state_carried_leaves",
                      target=target).set(stats.get("carried", 0))
            reg.gauge("analysis/state_saved_leaves",
                      target=target).set(stats.get("saved_leaves", 0))
    for check, n in counts.items():
        reg.counter("analysis/state_findings", check=check).inc(n)
    reg.gauge("analysis/state_findings_total").set(sum(counts.values()))
    return counts
