"""Unified multi-lattice forward abstract interpretation over closed
jaxprs — the one traversal under the precision AND sharding engines
(ISSUE 8 prerequisite refactor).

:mod:`.dataflow` (dtype/taint lattice, precision checks) and
:mod:`.sharding_flow` (PartitionSpec/distinctness lattice, sharding
checks) used to carry two near-identical interpreters: the same env
bookkeeping, the same ``pjit``/``remat``/``scan``/``while``/``cond``/
``shard_map`` structural walk, duplicated and drifting independently.
This module owns that walk ONCE; each engine plugs in as a
:class:`Lattice` — a bundle of value semantics (initial values, the
per-equation transfer function, branch/carry joins, call-boundary
coercions, the shard_map world rule). Several lattices ride the same
traversal: one pass over the jaxpr computes every engine's values and
fires every engine's visitors, which is what makes the auto-sharding
planner's inner loop (many spec candidates x one jaxpr) and the
combined lint gate cheap.

Structural semantics are lattice-selectable where the engines
legitimately differ:

- ``warm_carry_join``   scan/while bodies run once silently first and
  the output carries are joined into the input carries (the sharding
  engine's steady-state fixpoint); lattices that opt out (precision —
  every check there fires on iteration 1) keep their original inputs,
  so a mixed run changes neither engine's verdicts. The silent warm
  pass is skipped entirely when no participating lattice wants it.
- ``shard_map_enter/exit``  the sharding engine treats shard_map as a
  world boundary (specs stripped to distinctness, outer spec rebuilt
  from out_names); the precision engine enters it like any call. Both
  are expressed as lattice hooks over the same single body traversal.

Entry point: :func:`interpret_lattices`. The single-engine entry
points (``dataflow.interpret``, ``sharding_flow.interpret_sharding``)
are thin wrappers that pass exactly one lattice.

ISSUE 9 adds a third domain: :class:`NonFiniteLattice`, the
non-finite taint lattice under
:mod:`apex_tpu.observability.numerics.nan_probe`. Unlike the precision
and sharding lattices it carries CONCRETE values when it can (the
probe replays the failing step's jaxpr with the actual tensors), so
"did this primitive produce the first NaN?" is answered by evaluating
the primitive, not by approximating it — with a pure taint fallback
(any non-finite input taints every output) wherever concrete replay is
impossible (pallas kernels, shape-changing structural re-entries).
"""

from __future__ import annotations

__all__ = ["Lattice", "LatticeRun", "MeshCtx", "NFVal",
           "NonFiniteLattice", "interpret_lattices",
           "run_lattice_silent"]

# Call-like primitives whose bodies run in the caller's value world.
CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "custom_jvp_call",
    "custom_vjp_call", "custom_vjp_call_jaxpr", "remat", "checkpoint",
})

_SUB_JAXPR_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")


def is_var(v):
    import jax.core as core
    return isinstance(v, core.Var)


def closed_jaxprs_in(value):
    import jax.core as core
    out = []
    if isinstance(value, (core.ClosedJaxpr, core.Jaxpr)):
        out.append(value)
    elif isinstance(value, (tuple, list)):
        for v in value:
            out.extend(closed_jaxprs_in(v))
    return out


def jaxpr_of(obj):
    import jax.core as core
    return obj.jaxpr if isinstance(obj, core.ClosedJaxpr) else obj


def consts_of(obj):
    import jax.core as core
    return obj.consts if isinstance(obj, core.ClosedJaxpr) else ()


class MeshCtx:
    """Axis universe the interpretation runs under: name -> size, plus
    the manual (shard_map-consumed) axes at the current depth.

    ``control`` is the divergent-control stack (ISSUE 14): one
    ``(prim, axes)`` entry per enclosing ``cond``/``while`` whose
    predicate some participating lattice declared rank-divergent
    (:meth:`Lattice.divergent_axes`). A visitor that sees a collective
    while the stack carries a non-empty entry knows the collective's
    issue is conditional on a value that differs across those mesh
    axes — the deadlock/desync shape the rank-consistency checks
    exist for."""

    def __init__(self, axis_sizes=None, manual_axes=frozenset(),
                 control=()):
        self.axis_sizes = dict(axis_sizes or {})
        self.manual_axes = frozenset(manual_axes)
        self.control = tuple(control)

    def size(self, axis, default=1) -> int:
        return int(self.axis_sizes.get(axis, default))

    def child(self, extra_sizes=None, extra_manual=()):
        sizes = dict(self.axis_sizes)
        if extra_sizes:
            sizes.update({str(k): int(v) for k, v in extra_sizes.items()})
        return MeshCtx(sizes, self.manual_axes | frozenset(extra_manual),
                       self.control)

    def control_child(self, prim, axes):
        """The context for a ``cond``/``while`` body whose predicate
        can differ across ``axes``."""
        return MeshCtx(self.axis_sizes, self.manual_axes,
                       self.control + ((str(prim), frozenset(axes)),))

    def divergent_axes(self) -> frozenset:
        """Union of the control stack's divergent axes."""
        out = frozenset()
        for _prim, axes in self.control:
            out |= axes
        return out


def shard_map_axis_sizes(eqn) -> dict:
    """The mesh axis sizes a shard_map equation introduces."""
    shape = getattr(eqn.params.get("mesh"), "shape", None)
    return {str(k): int(v) for k, v in dict(shape).items()} \
        if shape else {}


class Lattice:
    """Value semantics for one analysis domain (see module docstring).

    Subclasses must implement :meth:`for_aval` and :meth:`transfer`;
    everything else has call-transparent defaults matching the
    precision engine's behavior."""

    name = "lattice"
    # scan/while: run the body once silently and join the output
    # carries into the input carries before the visited pass.
    warm_carry_join = False

    # ---- values ------------------------------------------------------

    def for_aval(self, aval):
        raise NotImplementedError

    def for_const(self, var, const):
        return self.for_aval(getattr(var, "aval", None))

    def transfer(self, eqn, ins, out_avals, ctx):
        raise NotImplementedError

    # ---- call boundaries ---------------------------------------------

    def bind_sub(self, aval, val):
        """Coerce a caller value onto a sub-jaxpr invar (None = derive
        from the aval)."""
        return self.for_aval(aval) if val is None else val

    def fix_out(self, aval, val, restack=False):
        """Coerce a sub-jaxpr output onto the caller's out aval.
        ``restack`` marks stacked scan ys (which grow a leading dim)."""
        return self.for_aval(aval) if val is None else val

    # ---- joins -------------------------------------------------------

    def join_branch(self, a, b):
        """Join the same output slot across cond branches."""
        return a if a is not None else b

    def cond_branch_index(self, ins):
        """Index of the branch this lattice KNOWS will run (from its
        abstract view of the cond's index operand), or None to walk
        and join every branch. The walk honors it only when every
        participating lattice names the same branch — the abstract
        engines (precision/sharding) return None by design: their
        verdicts must cover all paths."""
        return None

    def join_carry(self, orig, warm):
        """Join a warm-pass output carry into the input carry; the
        default keeps the original (no fixpoint)."""
        return orig

    def divergent_axes(self, eqn, ins, ctx) -> frozenset:
        """Mesh axes across which this ``cond``/``while`` equation's
        predicate can DIFFER between ranks, in this lattice's view —
        the walk pushes the union onto :attr:`MeshCtx.control` for the
        body traversal. The default (every abstract engine that does
        not model rank distinctness) declares none."""
        return frozenset()

    # ---- scan / shard_map structure ----------------------------------

    def map_scan_xs(self, val):
        """Map an xs value across the scan boundary (the body sees it
        without the leading scan dim)."""
        return val

    def shard_map_enter(self, eqn, ins, sub, ctx):
        """Values bound to the shard_map body invars; the default enters
        like a call."""
        n = len(sub.invars)
        bound = list(ins[:n]) + [None] * max(0, n - len(ins))
        return [self.bind_sub(var.aval, val)
                for var, val in zip(sub.invars, bound)]

    def shard_map_exit(self, eqn, inner_outs, ctx):
        """Caller-world values for the shard_map outputs; the default
        exits like a call."""
        outs = []
        for i, var in enumerate(eqn.outvars):
            o = inner_outs[i] if i < len(inner_outs) else None
            outs.append(self.fix_out(var.aval, o))
        return outs


class LatticeRun:
    """One lattice's participation in a traversal: the lattice, its
    per-invar input values, and an optional
    ``visit(eqn, ins, outs, mesh_ctx)`` callback."""

    def __init__(self, lattice, in_vals=(), visit=None):
        self.lattice = lattice
        self.in_vals = list(in_vals or ())
        self.visit = visit


class _Walk:
    def __init__(self, lattices, visits):
        self.lattices = lattices
        self.visits = visits

    def _silent(self):
        return _Walk(self.lattices, [None] * len(self.lattices))

    def run(self, jaxpr, consts, in_cols, ctx):
        lats = self.lattices
        n_lat = len(lats)
        env: dict = {}

        def write(var, vals):
            if is_var(var):
                env[var] = vals

        consts = list(consts or ())
        for i, var in enumerate(jaxpr.constvars):
            if i < len(consts):
                write(var, [lat.for_const(var, consts[i])
                            for lat in lats])
            else:
                write(var, [lat.for_aval(var.aval) for lat in lats])
        for j, var in enumerate(jaxpr.invars):
            vals = []
            for k, lat in enumerate(lats):
                v = in_cols[k][j] if j < len(in_cols[k]) else None
                vals.append(v if v is not None else lat.for_aval(var.aval))
            write(var, vals)

        for eqn in jaxpr.eqns:
            rows = [env.get(v) if is_var(v) else None for v in eqn.invars]
            ins_cols = [tuple(row[k] if row is not None else None
                              for row in rows) for k in range(n_lat)]
            outs_cols = self._structured(eqn, ins_cols, ctx)
            if outs_cols is None:
                out_avals = tuple(v.aval for v in eqn.outvars)
                outs_cols = [lats[k].transfer(eqn, ins_cols[k],
                                              out_avals, ctx)
                             for k in range(n_lat)]
            for k, visit in enumerate(self.visits):
                if visit is not None:
                    visit(eqn, ins_cols[k], outs_cols[k], ctx)
            for j, var in enumerate(eqn.outvars):
                write(var, [outs_cols[k][j] for k in range(n_lat)])

        results = []
        for k, lat in enumerate(lats):
            out = []
            for v in jaxpr.outvars:
                row = env.get(v) if is_var(v) else None
                out.append(row[k] if row is not None
                           else lat.for_aval(getattr(v, "aval", None)))
            results.append(tuple(out))
        return results

    # ---- structured primitives ---------------------------------------

    def _structured(self, eqn, ins_cols, ctx):
        prim = eqn.primitive.name
        params = eqn.params

        if prim in CALL_PRIMS:
            for key in _SUB_JAXPR_KEYS:
                if key in params:
                    subs = closed_jaxprs_in(params[key])
                    if subs:
                        return self._run_sub(subs[0], ins_cols, eqn, ctx)
            return None

        if prim == "scan":
            subs = closed_jaxprs_in(params.get("jaxpr"))
            if not subs:
                return None
            n_consts = params.get("num_consts", 0)
            n_carry = params.get("num_carry", 0)
            mapped_cols = []
            for k, lat in enumerate(self.lattices):
                col = list(ins_cols[k])
                for i in range(n_consts + n_carry, len(col)):
                    if col[i] is not None:
                        col[i] = lat.map_scan_xs(col[i])
                mapped_cols.append(col)
            self._warm_carries(subs[0], mapped_cols, eqn, ctx,
                               carry_at=n_consts, n_carry=n_carry,
                               restack_from=n_carry)
            return self._run_sub(subs[0], mapped_cols, eqn, ctx,
                                 restack_from=n_carry)

        if prim == "while":
            subs = closed_jaxprs_in(params.get("body_jaxpr"))
            if not subs:
                return None
            n_cond = params.get("cond_nconsts", 0)
            body_cols = [list(col[n_cond:]) for col in ins_cols]
            n_body = params.get("body_nconsts", 0)
            self._warm_carries(subs[0], body_cols, eqn, ctx,
                               carry_at=n_body, n_carry=None)
            # divergence must be judged on the WARMED carries: a
            # predicate that only becomes rank-divergent through the
            # loop carry (per-rank early exit) is invisible on the
            # initial values. The warm pass itself is silent, so no
            # visitor misses the control context.
            warmed_ins = [
                list(ins_cols[k][:n_cond + n_body])
                + list(body_cols[k][n_body:])
                for k in range(len(self.lattices))]
            sub_ctx = self._control_ctx(eqn, warmed_ins, ctx)
            return self._run_sub(subs[0], body_cols, eqn, sub_ctx)

        if prim == "cond":
            branches = closed_jaxprs_in(params.get("branches", ()))
            if not branches:
                return None
            ctx = self._control_ctx(eqn, ins_cols, ctx)
            pred_less = [col[1:] for col in ins_cols]
            # concrete-replay lattices can name the branch that will
            # actually run; walking (and joining) the untaken branch
            # would blame its primitives for values that never existed
            picks = {lat.cond_branch_index(ins_cols[k])
                     for k, lat in enumerate(self.lattices)}
            if len(picks) == 1:
                pick = picks.pop()
                if pick is not None and 0 <= pick < len(branches):
                    return self._run_sub(branches[pick], pred_less,
                                         eqn, ctx)
            outs_cols = None
            for br in branches:
                br_cols = self._run_sub(br, pred_less, eqn, ctx)
                if outs_cols is None:
                    outs_cols = [list(c) for c in br_cols]
                else:
                    for k, lat in enumerate(self.lattices):
                        outs_cols[k] = [
                            lat.join_branch(a, b)
                            for a, b in zip(outs_cols[k], br_cols[k])]
            return [tuple(c) for c in outs_cols]

        if prim == "shard_map":
            subs = closed_jaxprs_in(params.get("jaxpr", ()))
            if not subs:
                return None
            sizes = shard_map_axis_sizes(eqn)
            inner_ctx = ctx.child(sizes, sizes.keys())
            sub = jaxpr_of(subs[0])
            inner_cols = [lat.shard_map_enter(eqn, ins_cols[k], sub, ctx)
                          for k, lat in enumerate(self.lattices)]
            inner_outs = _Walk(self.lattices, self.visits).run(
                sub, consts_of(subs[0]), inner_cols, inner_ctx)
            return [tuple(lat.shard_map_exit(eqn, inner_outs[k], ctx))
                    for k, lat in enumerate(self.lattices)]

        return None

    def _control_ctx(self, eqn, ins_cols, ctx):
        """Push a divergent-control entry for a cond/while body when any
        participating lattice declares the predicate rank-divergent
        (no-op context otherwise — the common case costs one call)."""
        axes = frozenset()
        for k, lat in enumerate(self.lattices):
            axes |= lat.divergent_axes(eqn, ins_cols[k], ctx)
        if not axes:
            return ctx
        return ctx.control_child(eqn.primitive.name, axes)

    def _warm_carries(self, sub, cols, eqn, ctx, carry_at, n_carry,
                      restack_from=None):
        """Silent warm pass + per-lattice carry join (in place) for the
        lattices that want the fixpoint. No-op when none do."""
        if not any(lat.warm_carry_join for lat in self.lattices):
            return
        warm_cols = self._silent()._run_sub(sub, cols, eqn, ctx,
                                            restack_from=restack_from)
        for k, lat in enumerate(self.lattices):
            if not lat.warm_carry_join:
                continue
            warm = warm_cols[k]
            stop = len(warm) if n_carry is None else min(n_carry,
                                                         len(warm))
            for c in range(stop):
                i = carry_at + c
                if i < len(cols[k]):
                    cols[k][i] = lat.join_carry(cols[k][i], warm[c])

    def _run_sub(self, closed_or_jaxpr, ins_cols, eqn, ctx,
                 restack_from=None):
        jaxpr = jaxpr_of(closed_or_jaxpr)
        consts = consts_of(closed_or_jaxpr)
        n = len(jaxpr.invars)
        mapped_cols = []
        for k, lat in enumerate(self.lattices):
            col = list(ins_cols[k][:n]) + [None] * max(
                0, n - len(ins_cols[k]))
            mapped_cols.append([lat.bind_sub(var.aval, val)
                                for var, val in zip(jaxpr.invars, col)])
        outs_cols = self.run(jaxpr, consts, mapped_cols, ctx)
        out_avals = tuple(v.aval for v in eqn.outvars)
        fixed_cols = []
        for k, lat in enumerate(self.lattices):
            outs = outs_cols[k]
            fixed = []
            for i, aval in enumerate(out_avals):
                o = outs[i] if i < len(outs) else None
                restack = restack_from is not None and i >= restack_from
                fixed.append(lat.fix_out(aval, o, restack=restack))
            fixed_cols.append(tuple(fixed))
        return fixed_cols


def run_lattice_silent(lattice, closed_or_jaxpr, in_vals, ctx):
    """Run ONE lattice over a (closed) jaxpr with no visitors and
    return its abstract outputs — the hook a lattice's own
    :meth:`Lattice.divergent_axes` uses to evaluate a while-loop's
    ``cond_jaxpr`` (which the main walk never enters: only the body
    carries values forward)."""
    jaxpr = jaxpr_of(closed_or_jaxpr)
    cols = [list(in_vals[:len(jaxpr.invars)])]
    cols[0] += [None] * (len(jaxpr.invars) - len(cols[0]))
    (outs,) = _Walk([lattice], [None]).run(
        jaxpr, consts_of(closed_or_jaxpr), cols, ctx)
    return outs


def interpret_lattices(closed, runs, axis_sizes=None):
    """Run every :class:`LatticeRun` in ``runs`` over ``closed`` (a
    ``ClosedJaxpr``) in ONE traversal.

    Each run's ``in_vals`` holds one abstract value (or None for
    "derive from the aval") per flat invar; its ``visit`` fires for
    every equation at every depth with that lattice's values. Returns
    one tuple of abstract output values per run, in order."""
    ctx = MeshCtx(axis_sizes or {})
    jaxpr = closed.jaxpr
    in_cols = []
    for run in runs:
        col = list(run.in_vals) + [None] * max(
            0, len(jaxpr.invars) - len(run.in_vals))
        in_cols.append(col)
    walk = _Walk([run.lattice for run in runs],
                 [run.visit for run in runs])
    return walk.run(jaxpr, closed.consts, in_cols, ctx)


# ------------------------------------------------- non-finite taint


class NFVal:
    """One point of the non-finite lattice: ``finite`` is True (proven
    finite), False (contains NaN/Inf), or None (unknown); ``val`` is
    the concrete array when the replay still has one."""

    __slots__ = ("finite", "val")

    def __init__(self, finite=None, val=None):
        self.finite = finite
        self.val = val

    @classmethod
    def known(cls, val):
        return cls(finite=_finite_of(val), val=val)

    def __repr__(self):
        return (f"NFVal(finite={self.finite}, "
                f"concrete={self.val is not None})")


def _finite_of(val):
    """True/False for arrays whose finiteness is checkable, None
    otherwise (opaque objects, exotic dtypes). Integer/bool values are
    finite by construction."""
    import numpy as np
    try:
        arr = np.asarray(val)
    except Exception:  # noqa: BLE001 — not an array-like
        return None
    if arr.dtype.kind in ("i", "u", "b"):
        return True
    if arr.dtype.kind not in ("f", "c"):
        return None
    try:
        if arr.dtype.itemsize < 4:  # bf16/f16/fp8: widen for the ufunc
            arr = arr.astype(np.float32)
        return bool(np.isfinite(arr).all())
    except Exception:  # noqa: BLE001 — ml_dtypes gap etc.
        return None


# Primitives concrete replay must not execute: kernels (a replay is a
# host-side post-mortem — running a device kernel eagerly from it can
# itself fail or hang) and effectful I/O.
_NO_EVAL_PRIMS = frozenset({
    "pallas_call", "infeed", "outfeed", "io_callback", "pure_callback",
    "custom_partitioning",
})


class NonFiniteLattice(Lattice):
    """Concrete-replay non-finite taint (see module docstring).

    ``transfer`` evaluates the equation with the concrete input values
    when every input is available (``prim.bind`` outside any trace =
    eager evaluation) and derives each output's finite flag from the
    result. When replay is impossible — an opaque kernel, a value
    already degraded to taint, a bind error from a structural
    approximation upstream — it falls back to the taint join: any
    known-non-finite input marks every output non-finite ("the taint
    reached this op"), all-finite inputs mark outputs finite only for
    NaN-incapable output dtypes, else unknown.
    """

    name = "nonfinite"

    def for_aval(self, aval):
        return NFVal()

    def for_const(self, var, const):
        return NFVal.known(const)

    def _literal_vals(self, eqn, ins):
        """Concrete input list, pulling Literal values straight off the
        equation (the walk hands None for non-Var inputs)."""
        vals = []
        for i, var in enumerate(eqn.invars):
            nf = ins[i] if i < len(ins) else None
            if nf is not None and nf.val is not None:
                vals.append(nf.val)
            elif nf is None and hasattr(var, "val"):
                vals.append(var.val)
            else:
                return None
        return vals

    def _taint_join(self, eqn, ins, out_avals):
        import numpy as np
        flags = []
        for i, var in enumerate(eqn.invars):
            nf = ins[i] if i < len(ins) else None
            if nf is not None:
                flags.append(nf.finite)
            elif hasattr(var, "val"):
                flags.append(_finite_of(var.val))
            else:
                flags.append(None)
        if any(f is False for f in flags):
            out = False
        elif all(f is True for f in flags):
            out = True
        else:
            out = None
        res = []
        for aval in out_avals:
            kind = np.dtype(getattr(aval, "dtype", np.float32)).kind \
                if hasattr(aval, "dtype") else "f"
            if kind in ("i", "u", "b"):
                res.append(NFVal(finite=True))
            else:
                res.append(NFVal(finite=out))
        return tuple(res)

    def transfer(self, eqn, ins, out_avals, ctx):
        prim = eqn.primitive
        if prim.name in _NO_EVAL_PRIMS:
            return self._taint_join(eqn, ins, out_avals)
        vals = self._literal_vals(eqn, ins)
        if vals is None:
            return self._taint_join(eqn, ins, out_avals)
        try:
            out = prim.bind(*vals, **eqn.params)
        except Exception:  # noqa: BLE001 — replay is best-effort; a
            # bind error (shape drift from a structural approximation,
            # an unsupported eager prim) degrades to taint, never kills
            # the probe
            return self._taint_join(eqn, ins, out_avals)
        outs = list(out) if prim.multiple_results else [out]
        if len(outs) != len(out_avals):
            return self._taint_join(eqn, ins, out_avals)
        return tuple(NFVal.known(o) for o in outs)

    # structural coercions: concrete values whose shape no longer
    # matches the target aval drop to flag-only (the finite verdict
    # still flows; downstream binds fall back to taint)

    def _coerce(self, aval, nf):
        if nf is None:
            return NFVal()
        if nf.val is not None and hasattr(aval, "shape") and \
                tuple(getattr(nf.val, "shape", ())) != tuple(aval.shape):
            return NFVal(finite=nf.finite)
        return nf

    def bind_sub(self, aval, val):
        return self._coerce(aval, val)

    def fix_out(self, aval, val, restack=False):
        if restack:
            return NFVal(finite=None if val is None else val.finite)
        return self._coerce(aval, val)

    def map_scan_xs(self, val):
        """The body sees one slice of the xs. A whole-array non-finite
        flag must survive the slicing: element 0 can be clean while
        the poison sits in a later row, and replaying the body with
        the clean slice would launder the taint — drop to flag-only so
        the body's first consuming primitive is still named."""
        if val is None or val.val is None:
            return val
        if val.finite is False:
            return NFVal(finite=False)
        try:
            return NFVal.known(val.val[0])
        except Exception:  # noqa: BLE001 — 0-d or exotic container
            return NFVal(finite=val.finite)

    def cond_branch_index(self, ins):
        """The cond's index operand (invar 0, an i32 after jax's
        bool→index conversion) is usually concrete in a replay: name
        the branch that actually runs so join_branch never blames the
        untaken one."""
        nf = ins[0] if ins else None
        if nf is None or nf.val is None:
            return None
        try:
            import numpy as np
            idx = np.asarray(nf.val)
            if idx.ndim != 0:
                return None
            return int(idx)
        except Exception:  # noqa: BLE001 — exotic index value
            return None

    def join_branch(self, a, b):
        if a is None or b is None:
            return a if b is None else b
        if a.finite is False or b.finite is False:
            return NFVal(finite=False)
        if a.finite is True and b.finite is True:
            return NFVal(finite=True)
        return NFVal()
